"""Worker process for the real multi-process distributed tests.

Launched by tests/test_distributed.py in N separate OS processes joined via
``jax.distributed.initialize`` on the CPU platform — the TPU answer to
"multi-node tests without a cluster" (SURVEY.md §4), but with *actual*
process boundaries: striding, fixed step counts, and collective pairing run
for real, which single-process virtual-device tests cannot exercise.

Writes one JSON record (eval metrics + a few train facts) to ``--out``.
"""
from __future__ import annotations

import argparse
import json


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--coordinator', required=True)
    parser.add_argument('--process_id', type=int, required=True)
    parser.add_argument('--num_processes', type=int, required=True)
    parser.add_argument('--prefix', required=True)
    parser.add_argument('--out', required=True)
    parser.add_argument('--train_epochs', type=int, default=0,
                        help='0 = evaluate the seed-42 init params only')
    parser.add_argument('--data_cache', type=int, default=1,
                        help='1 = per-process token cache, 0 = streaming')
    parser.add_argument('--model_axis', type=int, default=1,
                        help='mesh model-axis size (TP across processes)')
    parser.add_argument('--lr', type=float, default=0.01,
                        help='0 freezes params: mid-train evals then see '
                             'the seed-42 init on every process count')
    args = parser.parse_args()

    import jax
    jax.config.update('jax_platforms', 'cpu')
    # Bounded join: under heavy host load a sibling worker can start late;
    # 120s is the barrier deadline — a missed join fails THIS process fast
    # with a clear error instead of wedging until the harness's outer
    # timeout, and the harness retries the whole cluster once.
    jax.distributed.initialize(coordinator_address=args.coordinator,
                               num_processes=args.num_processes,
                               process_id=args.process_id,
                               initialization_timeout=120)

    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel

    config = Config(
        TRAIN_DATA_PATH_PREFIX=args.prefix,
        TEST_DATA_PATH=args.prefix + '.val.c2v',
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32',
        MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=8, TEST_BATCH_SIZE=8,
        NUM_TRAIN_EPOCHS=max(args.train_epochs, 1),
        SAVE_EVERY_EPOCHS=1000, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False, LEARNING_RATE=args.lr,
        # 1 exercises the per-process token cache (.tokcache.p<i>of<n>),
        # 0 the streaming fixed-step multi-host path
        TRAIN_DATA_CACHE=bool(args.data_cache),
        # model_axis > 1: row-sharded tables + sharded softmax/top-k with
        # collectives that cross the process boundary (PARAM_ROW_ALIGNMENT
        # must divide evenly; 8 covers the tiny test vocabs)
        MESH_MODEL_AXIS_SIZE=args.model_axis, PARAM_ROW_ALIGNMENT=8)
    model = Code2VecModel(config)

    record = {
        'process_id': args.process_id,
        'process_count': jax.process_count(),
        'n_global_devices': jax.device_count(),
        'n_local_devices': jax.local_device_count(),
    }
    if args.train_epochs > 0:
        model.train()  # includes the per-epoch multi-host evaluate
        record['trained_epochs'] = args.train_epochs
        # the merged in-training eval numbers the training loop itself saw
        record['eval_history'] = model.eval_history

    results = model.evaluate()
    record.update({
        'topk_acc': [float(x) for x in results.topk_acc],
        'precision': results.subtoken_precision,
        'recall': results.subtoken_recall,
        'f1': results.subtoken_f1,
        'loss': results.loss,
    })
    with open(args.out, 'w') as f:
        json.dump(record, f)


if __name__ == '__main__':
    main()
