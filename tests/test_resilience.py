"""Resilience layer tests (ISSUE 3): fault-spec grammar and plan
semantics, watchdog/preemption unit behavior, and the e2e pillars on a
tiny CPU corpus — NaN rewind + recovery, SIGTERM snapshot + mid-epoch
resume with a monotonic metric step axis, corrupt-snapshot restore
fallback with quarantine, and the subprocess hang-abort drill."""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults
from code2vec_tpu.resilience.guard import (DivergenceError, DivergenceGuard,
                                           batch_stats)
from code2vec_tpu.resilience.preempt import PreemptionHandler
from code2vec_tpu.resilience.watchdog import STACKS_FILE_NAME, HangWatchdog
from tests.test_train_overfit import make_dataset


@pytest.fixture(autouse=True)
def clear_fault_plan():
    """The plan is process-global by design (like the telemetry
    registry): every test starts and ends disarmed."""
    faults.configure('')
    yield
    faults.configure('')


def _train_config(tmp_path, prefix, **overrides):
    defaults = dict(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_EPOCHS=1000,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MODEL_SAVE_PATH=str(tmp_path / 'models' / 'saved_model'),
        TELEMETRY_DIR=str(tmp_path / 'tele'))
    defaults.update(overrides)
    return Config(**defaults)


# ------------------------------------------------------------- fault plan
def test_parse_spec_grammar():
    assert faults.parse_spec('') == {}
    assert faults.parse_spec('nan_loss@step=120') == {'nan_loss': 120}
    assert faults.parse_spec('nan_loss@step=120, sigterm@step=50') == \
        {'nan_loss': 120, 'sigterm': 50}
    assert faults.parse_spec('corrupt_snapshot@save=2') == \
        {'corrupt_snapshot': 2}
    with pytest.raises(ValueError, match='unknown fault point'):
        faults.parse_spec('definitely_not_a_point@step=1')
    with pytest.raises(ValueError, match='not <point>@<trigger>'):
        faults.parse_spec('nan_loss=3')
    with pytest.raises(ValueError, match='not <point>@<trigger>'):
        faults.parse_spec('nan_loss@step=abc')


def test_config_verify_rejects_bad_fault_spec():
    config = Config(TRAIN_DATA_PATH_PREFIX='x',
                    FAULT_INJECT='bogus@step=1')
    with pytest.raises(ValueError, match='unknown fault point'):
        config.verify()


def test_cli_flags_fill_resilience_knobs(monkeypatch):
    config = Config().load_from_args(
        ['--data', 'x', '--fault-inject', 'nan_loss@step=3',
         '--watchdog-secs', '5.5', '--max-divergence-rewinds', '7',
         '--no-divergence-guard'])
    assert config.FAULT_INJECT == 'nan_loss@step=3'
    assert config.HANG_WATCHDOG_SECS == 5.5
    assert config.MAX_DIVERGENCE_REWINDS == 7
    assert not config.DIVERGENCE_GUARD
    # env fallback, like TELEMETRY_TRACE_AT_STEP
    monkeypatch.setenv('FAULT_INJECT', 'sigterm@step=9')
    config2 = Config().load_from_args(['--data', 'x'])
    assert config2.FAULT_INJECT == 'sigterm@step=9'
    # the explicit flag wins over the env var
    config3 = Config().load_from_args(
        ['--data', 'x', '--fault-inject', 'sigterm@step=2'])
    assert config3.FAULT_INJECT == 'sigterm@step=2'
    # and an explicit '' DISABLES injection despite the env var (the
    # control arm of a drill)
    config4 = Config().load_from_args(['--data', 'x', '--fault-inject', ''])
    assert config4.FAULT_INJECT == ''


def test_fault_plan_fires_once_at_step():
    faults.configure('nan_loss@step=3')
    assert not faults.maybe_fire('nan_loss', step=2)
    assert faults.maybe_fire('nan_loss', step=3)
    assert not faults.maybe_fire('nan_loss', step=4)  # single-shot
    assert not faults.maybe_fire('sigterm', step=3)   # not in the plan


def test_fault_plan_fires_late_when_exact_step_was_skipped():
    """Resumed runs can start past the configured trigger: >= matching
    still fires the fault at the first opportunity."""
    faults.configure('nan_loss@step=3')
    assert faults.maybe_fire('nan_loss', step=10)


def test_fault_plan_site_counter_mode():
    """Sites with no natural step counter (hang_input counts batches)
    trigger on their own invocation count."""
    faults.configure('hang_input@step=2')
    assert not faults.maybe_fire('hang_input')   # call 0
    assert not faults.maybe_fire('hang_input')   # call 1
    assert faults.maybe_fire('hang_input')       # call 2
    assert not faults.maybe_fire('hang_input')   # single-shot


def test_disarmed_plan_is_inert():
    faults.configure('')
    assert not faults.active()
    assert not faults.maybe_fire('nan_loss', step=0)


# --------------------------------------------------------------- watchdog
def test_watchdog_expires_dumps_stacks_and_aborts(tmp_path):
    aborted = threading.Event()
    wd = HangWatchdog(0.2, str(tmp_path), abort=aborted.set, poll_s=0.02)
    wd.arm('unit-test wait')
    assert aborted.wait(timeout=5.0), 'watchdog never fired'
    wd.shutdown()
    stacks = (tmp_path / STACKS_FILE_NAME).read_text()
    assert 'unit-test wait' in stacks
    # faulthandler dumped THIS (test) thread's frames too
    assert 'test_resilience' in stacks


def test_watchdog_disarm_prevents_expiry(tmp_path):
    fired = threading.Event()
    wd = HangWatchdog(0.1, str(tmp_path), abort=fired.set, poll_s=0.02)
    with wd.watch('quick wait'):
        pass
    time.sleep(0.3)
    wd.shutdown()
    assert not fired.is_set()
    assert not (tmp_path / STACKS_FILE_NAME).exists()


def test_watchdog_rearm_resets_deadline(tmp_path):
    fired = threading.Event()
    wd = HangWatchdog(0.25, str(tmp_path), abort=fired.set, poll_s=0.02)
    for _ in range(4):  # 0.4s of short watched waits: never overdue
        with wd.watch('short wait'):
            time.sleep(0.1)
    assert not fired.is_set()
    wd.shutdown()


# -------------------------------------------------------------- preempt
def test_preemption_handler_flag_and_restore():
    previous = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as handler:
        assert not handler.requested
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython delivers at the next bytecode boundary
        for _ in range(100):
            if handler.requested:
                break
            time.sleep(0.01)
        assert handler.requested
        assert handler.signal_name == 'SIGTERM'
    assert signal.getsignal(signal.SIGTERM) is previous


# ----------------------------------------------------------------- guard
class _FakeState:
    step = 7


def test_guard_aborts_without_restore_target(tmp_path):
    guard = DivergenceGuard(3, restore=None, dump_dir=str(tmp_path))
    with pytest.raises(DivergenceError, match='no checkpoint'):
        guard.handle(4, [float('nan')], None)
    dump = json.loads((tmp_path / 'divergence_step4.json').read_text())
    assert dump['batch_num'] == 4


def test_guard_budget_exhaustion(tmp_path):
    guard = DivergenceGuard(1, restore=lambda b: _FakeState(),
                            dump_dir=str(tmp_path))
    state = guard.handle(2, [float('inf')], None)
    assert state.step == 7
    with pytest.raises(DivergenceError, match='budget'):
        guard.handle(4, [float('nan')], None)


def test_batch_stats_tolerates_batch_types():
    from code2vec_tpu.data.reader import Batch
    batch = Batch(source=np.ones((2, 3), np.int32),
                  path=np.zeros((2, 3), np.int32),
                  target=np.ones((2, 3), np.int32),
                  mask=np.ones((2, 3), np.float32),
                  label=np.arange(2, dtype=np.int32),
                  weight=np.ones((2,), np.float32))
    stats = batch_stats(batch)
    assert stats['label'] == {'shape': [2], 'dtype': 'int32',
                              'min': 0.0, 'max': 1.0}
    assert batch_stats(None) == {}


def test_quarantine_picks_unique_destination(tmp_path):
    """A repeat rewind can quarantine the same step number twice (the
    key was re-saved after the first purge); the rename must not fail
    against the existing `.rewound` dir and leave the artifact behind."""
    import types

    from code2vec_tpu.checkpoints import CheckpointStore
    store = CheckpointStore(str(tmp_path / 'm'))
    manager = types.SimpleNamespace(directory=str(tmp_path))
    for _ in range(2):
        (tmp_path / '6').mkdir()
        (tmp_path / '6' / 'x').write_text('data')
        store._quarantine(manager, 6, suffix='.rewound')
    assert (tmp_path / '6.rewound').is_dir()
    assert (tmp_path / '6.rewound.2').is_dir()
    assert not (tmp_path / '6').exists()


# ---------------------------------------------------------- e2e: pillars
def test_nan_loss_rewinds_and_recovers(tmp_path):
    """Acceptance: a CPU fit with FAULT_INJECT=nan_loss@step=k rewinds to
    the prior snapshot, skips the poisoned window, and finishes healthy
    (finite eval loss, step axis reflecting exactly one rewound
    window)."""
    prefix = make_dataset(tmp_path)
    kwargs = dict(NUM_TRAIN_EPOCHS=8, LEARNING_RATE=0.01,
                  TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
                  SAVE_EVERY_N_STEPS=2, NUM_BATCHES_TO_LOG_PROGRESS=2)
    config = _train_config(tmp_path, prefix,
                           FAULT_INJECT='nan_loss@step=5', **kwargs)
    from code2vec_tpu.model_api import Code2VecModel
    model = Code2VecModel(config)
    model.train()
    # 8 epochs x 4 steps = 32 batches consumed; the poisoned window
    # ([4, 5], synced at batch 6) rewound to the step-4 snapshot, so the
    # final step counter is 32 - 2
    assert int(model.state.step) == 30
    results = model.evaluate()
    assert results.loss is not None and np.isfinite(results.loss)

    # uninjected twin (same seeds -> same batch order, 2 more effective
    # steps): the recovered run must land in the same final-loss ballpark
    twin_config = _train_config(
        tmp_path, prefix,
        MODEL_SAVE_PATH=str(tmp_path / 'models_twin' / 'saved_model'),
        **kwargs)
    twin = Code2VecModel(twin_config)
    twin.train()
    twin_results = twin.evaluate()
    assert results.loss < twin_results.loss * 1.5 + 0.1, \
        (results.loss, twin_results.loss)
    # the diagnostic dump landed next to the telemetry artifacts
    dump_path = tmp_path / 'tele' / 'divergence_step6.json'
    assert dump_path.exists()
    dump = json.loads(dump_path.read_text())
    assert dump['batch_num'] == 6
    assert any(not np.isfinite(x) for x in dump['window_losses'])
    assert 'label' in dump['last_batch']  # offending-batch stats


def test_rewind_purges_poisoned_window_snapshots(tmp_path):
    """A snapshot saved BETWEEN the first NaN and its detection holds
    suspect params: the rewind must purge it (rename `<step>.rewound`)
    so it neither shadows the rewound state as 'newest' for a later
    resume nor blocks orbax from re-saving its step key (orbax silently
    skips saves at `step <= latest_step`)."""
    prefix = make_dataset(tmp_path)
    config = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=3, SAVE_EVERY_N_STEPS=2,
        NUM_BATCHES_TO_LOG_PROGRESS=4, FAULT_INJECT='nan_loss@step=5')
    from code2vec_tpu.model_api import Code2VecModel
    model = Code2VecModel(config)
    model.train()
    # NaN at step 5 -> snapshot at step 6 lands inside the poisoned
    # window -> detection at the batch-8 sync rewinds to step 4 (first
    # bad step = 5) and purges step 6; 12 batches minus the 4 rewound
    # steps end the run at state.step 8
    assert int(model.state.step) == 8
    snapshot_dir = tmp_path / 'models' / 'saved_model__step-snapshots'
    assert (snapshot_dir / '6.rewound').is_dir()
    # the RE-TRAINED step 6 was saved again after the purge (orbax did
    # not skip its key), so resume restores the healthy step-6 state
    assert (snapshot_dir / '6').is_dir()
    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=3,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert int(model2.state.step) == 6


def test_nan_loss_without_snapshot_aborts_with_diagnostics(tmp_path):
    """No checkpoint to rewind to -> the guard fails loud with the dump
    path instead of training on NaN."""
    prefix = make_dataset(tmp_path)
    config = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=1, MODEL_SAVE_PATH=None,
        NUM_BATCHES_TO_LOG_PROGRESS=2, FAULT_INJECT='nan_loss@step=1')
    from code2vec_tpu.model_api import Code2VecModel
    model = Code2VecModel(config)
    with pytest.raises(DivergenceError, match='no checkpoint'):
        model.train()
    assert (tmp_path / 'tele' / 'divergence_step2.json').exists()


def test_sigterm_preempts_saves_and_resumes_monotonically(tmp_path):
    """Acceptance + satellite: sigterm@step=k exits cleanly with a
    durable snapshot at exactly step k; --load resume restarts the
    interrupted epoch from it and the metric step axis stays monotonic
    across the kill/resume boundary."""
    prefix = make_dataset(tmp_path)
    kwargs = dict(NUM_TRAIN_EPOCHS=4, SAVE_EVERY_EPOCHS=1,
                  TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
                  NUM_BATCHES_TO_LOG_PROGRESS=2, USE_TENSORBOARD=True)
    config = _train_config(tmp_path, prefix,
                           FAULT_INJECT='sigterm@step=5', **kwargs)
    from code2vec_tpu.model_api import Code2VecModel
    model = Code2VecModel(config)
    model.train()  # returns early, cleanly, after the preemption save
    assert int(model.state.step) == 5
    snapshot_dir = tmp_path / 'models' / 'saved_model__step-snapshots'
    assert (snapshot_dir / '5').is_dir()
    marker = json.loads((snapshot_dir / 'PREEMPTED.json').read_text())
    assert marker['step'] == 5
    # step 5 is inside epoch 1 (4 steps/epoch): last complete epoch is 0
    assert marker['last_complete_epoch'] == 0

    config2 = _train_config(
        tmp_path, prefix,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'),
        **kwargs)
    model2 = Code2VecModel(config2)
    assert int(model2.state.step) == 5
    assert model2._start_epoch == 1  # restart the interrupted epoch
    assert not (snapshot_dir / 'PREEMPTED.json').exists()  # consumed
    model2.train()  # completes epochs 1..3
    assert int(model2.state.step) > 5
    # eval history resumed on the global batch axis
    assert model2.eval_history, 'resumed run ran no evals'

    # satellite: the writer's metric streams (same summaries dir, append
    # mode) must carry a monotone non-decreasing step axis across the
    # preemption/resume boundary, per tag
    metrics_path = tmp_path / 'models' / 'summaries' / 'metrics.jsonl'
    by_tag = {}
    for line in metrics_path.read_text().splitlines():
        record = json.loads(line)
        by_tag.setdefault(record['tag'], []).append(record['step'])
    assert 'train/loss' in by_tag and 'eval/top1_acc' in by_tag
    for tag, steps in by_tag.items():
        assert steps == sorted(steps), (tag, steps)


def test_corrupt_snapshot_restore_falls_back_and_quarantines(tmp_path):
    """Satellite + corrupt_snapshot drill: the newest snapshot is
    truncated on disk (disk-full shape); restore must log, quarantine
    that step, and fall back to the next-older retained snapshot instead
    of failing the run."""
    prefix = make_dataset(tmp_path)
    config = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_N_STEPS=2,
        FAULT_INJECT='corrupt_snapshot@save=2')
    from code2vec_tpu.model_api import Code2VecModel
    Code2VecModel(config).train()
    snapshot_dir = tmp_path / 'models' / 'saved_model__step-snapshots'
    # snapshots landed at steps 2, 4, 6 (retention keeps the last two);
    # the third save (index 2 -> step 6) was corrupted after finalize
    assert (snapshot_dir / '6').is_dir()

    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert int(model2.state.step) == 4  # fell back past the corrupt 6
    assert (snapshot_dir / '6.corrupt').is_dir()  # quarantined, kept
    assert not (snapshot_dir / '6').exists()
    model2.train()  # the fallback state trains on without error


def test_all_snapshots_corrupt_raises_clearly(tmp_path):
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
                           SAVE_EVERY_N_STEPS=2)
    from code2vec_tpu.model_api import Code2VecModel
    Code2VecModel(config).train()
    snapshot_dir = tmp_path / 'models' / 'saved_model__step-snapshots'
    for step_dir in snapshot_dir.iterdir():
        if step_dir.is_dir():
            faults.corrupt_directory(str(step_dir))
    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    with pytest.raises(ValueError, match='could be restored'):
        Code2VecModel(config2)


def test_hang_input_watchdog_aborts_subprocess(tmp_path):
    """Acceptance: hang_input@step=k wedges the input pipeline; the
    watchdog must dump thread stacks to disk and hard-abort the process
    within the deadline — asserted against a REAL training process,
    since SIGABRT cannot be faked in-process."""
    prefix = make_dataset(tmp_path)
    tele_dir = tmp_path / 'tele'
    cmd = [sys.executable, '-m', 'code2vec_tpu.cli',
           '--data', str(prefix), '--epochs', '1', '--batch-size', '16',
           '--dtype', 'float32', '--no-data-cache',
           '--fault-inject', 'hang_input@step=1',
           '--watchdog-secs', '5', '--telemetry-dir', str(tele_dir),
           '-v', '0']
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'PYTHONPATH': repo + os.pathsep + os.environ.get('PYTHONPATH',
                                                            '')}
    t0 = time.time()
    result = subprocess.run(cmd, capture_output=True, text=True, env=env,
                            timeout=240, cwd=str(tmp_path))
    wall = time.time() - t0
    assert result.returncode != 0, (result.stdout, result.stderr)
    stacks_path = tele_dir / STACKS_FILE_NAME
    assert stacks_path.exists(), (result.stdout, result.stderr, wall)
    stacks = stacks_path.read_text()
    assert 'next staged batch' in stacks  # the wait that expired
    assert 'Thread' in stacks             # all-threads faulthandler dump
