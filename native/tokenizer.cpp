// Native .c2v tokenizer: the host input pipeline's hot loop.
//
// Replaces the Python per-token dict lookups in
// code2vec_tpu/data/reader.py::tokenize_rows with a multithreaded C++
// implementation (the reference leaned on tf.data's C++ CsvDataset for the
// same reason, path_context_reader.py:122-125). Semantics are identical:
//
//   line   := label ' ' ctx (' ' ctx)*            (trailing spaces = padding)
//   ctx    := source ',' path ',' target           (missing parts -> PAD)
//   lookup := vocab.get(word, OOV); empty -> PAD
//   mask   := any of the three indices != its PAD index
//
// Exposed as a C API for ctypes (no pybind11 in this image).
#include <cstdint>
#include <deque>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Vocab {
  // keys are views into `storage` (one retained copy of the words blob):
  // lookups take a string_view with NO per-token heap allocation
  std::unordered_map<std::string_view, int32_t> word_to_index;
  std::deque<std::string> storage;  // deque: elements never move
  int32_t oov = 0;
  int32_t pad = 0;

  // context parts: the reference's CSV default substitutes the PAD word
  // for empty fields before the hashtable lookup
  int32_t lookup(std::string_view word) const {
    if (word.empty()) return pad;
    auto it = word_to_index.find(word);
    return it == word_to_index.end() ? oov : it->second;
  }

  // labels: the reference's CSV default for the label column is the OOV
  // word (path_context_reader.py:82), so an empty label is OOV, not PAD
  int32_t lookup_label(std::string_view word) const {
    if (word.empty()) return oov;
    auto it = word_to_index.find(word);
    return it == word_to_index.end() ? oov : it->second;
  }
};

struct Tokenizer {
  Vocab token;
  Vocab path;
  Vocab target;
};

// Tokenize rows [row_begin, row_end) of the line buffer.
void tokenize_range(const Tokenizer* tok, const char* buf,
                    const int64_t* offsets, int32_t row_begin,
                    int32_t row_end, int32_t max_contexts, int32_t* src,
                    int32_t* path, int32_t* tgt, float* mask,
                    int32_t* label) {
  const int32_t token_pad = tok->token.pad;
  const int32_t path_pad = tok->path.pad;
  for (int32_t r = row_begin; r < row_end; ++r) {
    std::string_view line(buf + offsets[r],
                          static_cast<size_t>(offsets[r + 1] - offsets[r]));
    while (!line.empty() && (line.back() == '\n' || line.back() == '\r'))
      line.remove_suffix(1);

    int32_t* row_src = src + static_cast<int64_t>(r) * max_contexts;
    int32_t* row_path = path + static_cast<int64_t>(r) * max_contexts;
    int32_t* row_tgt = tgt + static_cast<int64_t>(r) * max_contexts;
    float* row_mask = mask + static_cast<int64_t>(r) * max_contexts;

    size_t pos = line.find(' ');
    std::string_view label_sv =
        pos == std::string_view::npos ? line : line.substr(0, pos);
    label[r] = tok->target.lookup_label(label_sv);

    int32_t c = 0;
    size_t start = pos == std::string_view::npos ? line.size() : pos + 1;
    while (c < max_contexts) {
      if (start > line.size()) break;
      size_t end = line.find(' ', start);
      if (end == std::string_view::npos) end = line.size();
      std::string_view ctx = line.substr(start, end - start);
      int32_t s_idx = token_pad, p_idx = path_pad, t_idx = token_pad;
      if (!ctx.empty()) {
        size_t c1 = ctx.find(',');
        if (c1 == std::string_view::npos) {
          s_idx = tok->token.lookup(ctx);
        } else {
          s_idx = tok->token.lookup(ctx.substr(0, c1));
          size_t c2 = ctx.find(',', c1 + 1);
          if (c2 == std::string_view::npos) {
            p_idx = tok->path.lookup(ctx.substr(c1 + 1));
          } else {
            p_idx = tok->path.lookup(ctx.substr(c1 + 1, c2 - c1 - 1));
            t_idx = tok->token.lookup(ctx.substr(c2 + 1));
          }
        }
      }
      row_src[c] = s_idx;
      row_path[c] = p_idx;
      row_tgt[c] = t_idx;
      row_mask[c] =
          (s_idx != token_pad || p_idx != path_pad || t_idx != token_pad)
              ? 1.0f
              : 0.0f;
      ++c;
      start = end + 1;
    }
    for (; c < max_contexts; ++c) {
      row_src[c] = token_pad;
      row_path[c] = path_pad;
      row_tgt[c] = token_pad;
      row_mask[c] = 0.0f;
    }
  }
}

Vocab* vocab_by_id(Tokenizer* tok, int32_t vocab_id) {
  switch (vocab_id) {
    case 0:
      return &tok->token;
    case 1:
      return &tok->path;
    case 2:
      return &tok->target;
  }
  return nullptr;
}

}  // namespace

extern "C" {

void* c2v_tok_create() { return new Tokenizer(); }

void c2v_tok_destroy(void* handle) {
  delete static_cast<Tokenizer*>(handle);
}

// words: '\n'-separated word list; indices: per-word vocab index.
void c2v_tok_add_words(void* handle, int32_t vocab_id, const char* words,
                       int64_t words_len, const int32_t* indices,
                       int32_t n_words) {
  Vocab* vocab = vocab_by_id(static_cast<Tokenizer*>(handle), vocab_id);
  if (!vocab) return;
  vocab->word_to_index.reserve(static_cast<size_t>(n_words) * 2);
  // retain one copy of the blob; map keys are views into it
  vocab->storage.emplace_back(words, static_cast<size_t>(words_len));
  std::string_view buf(vocab->storage.back());
  size_t start = 0;
  for (int32_t i = 0; i < n_words; ++i) {
    size_t end = buf.find('\n', start);
    if (end == std::string_view::npos) end = buf.size();
    vocab->word_to_index.emplace(buf.substr(start, end - start),
                                 indices[i]);
    start = end + 1;
  }
}

void c2v_tok_set_special(void* handle, int32_t vocab_id, int32_t oov,
                         int32_t pad) {
  Vocab* vocab = vocab_by_id(static_cast<Tokenizer*>(handle), vocab_id);
  if (!vocab) return;
  vocab->oov = oov;
  vocab->pad = pad;
}

// buf: concatenated lines; offsets: n_rows+1 offsets into buf.
// Output arrays must be preallocated: src/path/tgt/mask (n_rows,
// max_contexts) C-contiguous, label (n_rows,).
void c2v_tok_tokenize(void* handle, const char* buf, const int64_t* offsets,
                      int32_t n_rows, int32_t max_contexts,
                      int32_t num_threads, int32_t* src, int32_t* path,
                      int32_t* tgt, float* mask, int32_t* label) {
  const Tokenizer* tok = static_cast<Tokenizer*>(handle);
  if (num_threads <= 1 || n_rows < 64) {
    tokenize_range(tok, buf, offsets, 0, n_rows, max_contexts, src, path,
                   tgt, mask, label);
    return;
  }
  std::vector<std::thread> threads;
  int32_t chunk = (n_rows + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int32_t begin = t * chunk;
    int32_t end = std::min(n_rows, begin + chunk);
    if (begin >= end) break;
    threads.emplace_back(tokenize_range, tok, buf, offsets, begin, end,
                         max_contexts, src, path, tgt, mask, label);
  }
  for (auto& thread : threads) thread.join();
}

}  // extern "C"
