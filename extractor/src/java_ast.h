// AST for the native Java path-context extractor.
//
// Node `type` names follow javaparser's class simple names (NameExpr,
// MethodCallExpr, BlockStmt, ...) so the emitted path vocabulary lines up
// with the reference extractor's (reference Property.java:28-31 uses the
// class simple name as the node type).
#pragma once

#include <memory>
#include <string>
#include <vector>

namespace c2v {

struct Node {
  std::string type;       // e.g. "BinaryExpr:plus" (operator-augmented)
  std::string raw_type;   // e.g. "BinaryExpr" (no operator suffix)
  std::string code;       // source text (leaf naming / normalization)
  Node* parent = nullptr;
  std::vector<Node*> children;
  int child_id = 0;       // index among parent's children
  bool is_statement = false;  // statements are never leaves
                              // (reference LeavesCollectorVisitor.java:50-52)
  size_t src_begin = 0;       // source span (set for method body blocks,
  size_t src_end = 0;         // used for the method-length filter)

  void add(Node* child) {
    if (child == nullptr) return;
    child->parent = this;
    child->child_id = static_cast<int>(children.size());
    children.push_back(child);
  }
};

// Bump allocator: nodes live exactly as long as one file's extraction.
class Arena {
 public:
  Node* make(std::string type, std::string code = std::string(),
             bool is_statement = false) {
    nodes_.push_back(std::make_unique<Node>());
    Node* node = nodes_.back().get();
    node->raw_type = type;
    node->type = std::move(type);
    node->code = std::move(code);
    node->is_statement = is_statement;
    return node;
  }

  Node* make_op(const std::string& type, const std::string& op,
                std::string code = std::string()) {
    Node* node = make(type, std::move(code));
    node->type = type + ":" + op;
    return node;
  }

 private:
  std::vector<std::unique_ptr<Node>> nodes_;
};

}  // namespace c2v
