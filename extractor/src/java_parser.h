// Recursive-descent Java parser for the native extractor.
//
// This is NOT a full Java compiler frontend: it parses the constructs that
// dominate real-world method bodies (declarations, statements, the full
// expression grammar with precedence, generics, annotations, lambdas,
// method references, switch, try/catch) and produces an AST whose node
// types/structure mirror javaparser's, so paths line up with the reference
// extractor's vocabulary. Unparseable members are skipped (the reference
// skips whole files on parse failure after its wrap-retries,
// FeatureExtractor.java:51-75; per-member recovery is strictly better).
//
// Operator spellings use javaparser 3.0.0-alpha.4 enum names (plus, assign,
// preIncrement, ...) — extracted from the enum constant pools of the
// reference's checked-in fat JAR (JavaExtractor-0.0.1-SNAPSHOT.jar:
// com/github/javaparser/ast/expr/{Binary,Unary,Assign}Expr$Operator.class;
// no toString override, so Operator.toString() == the enum constant name).
// Reference Property.java:33-42 appends them to the node type as
// "BinaryExpr:plus", which flows into the path vocabulary — exact spellings
// are required for drop-in compatibility with reference-extracted datasets.
#pragma once

#include <optional>
#include <set>
#include <stdexcept>
#include <string>
#include <vector>

#include "java_ast.h"
#include "java_lexer.h"

namespace c2v {

struct ParseError : std::runtime_error {
  explicit ParseError(const std::string& what) : std::runtime_error(what) {}
};

// Recursion ceiling: pathological nesting (tens of thousands deep) would
// otherwise overflow the C stack and SEGFAULT the whole process — in --dir
// mode that kills every worker's output. A graceful ParseError lets the
// file be skipped like any other unparseable input.
constexpr int kMaxParseDepth = 2000;

struct DepthGuard {
  int* depth;
  explicit DepthGuard(int* d) : depth(d) {
    if (++*depth > kMaxParseDepth) {
      --*depth;
      throw ParseError("maximum nesting depth exceeded");
    }
  }
  DepthGuard(const DepthGuard&) = delete;
  DepthGuard& operator=(const DepthGuard&) = delete;
  ~DepthGuard() { --*depth; }
};

class Parser {
 public:
  Parser(std::vector<Token> tokens, Arena* arena)
      : toks_(std::move(tokens)), arena_(arena) {}

  // Parse a compilation unit; returns the root node.
  Node* parse_compilation_unit() {
    Node* root = arena_->make("CompilationUnit");
    skip_package_and_imports();
    while (!at_end()) {
      if (accept_punct(";")) continue;
      Node* type_decl = parse_type_declaration();
      if (type_decl) root->add(type_decl);
    }
    return root;
  }

  // True if member-level error recovery skipped any input: the tree is
  // usable but incomplete, so a zero-method result must not be trusted
  // as "this file genuinely has no methods".
  bool had_recovery() const { return recovered_; }

 private:
  std::vector<Token> toks_;
  Arena* arena_;
  size_t i_ = 0;
  std::vector<std::pair<size_t, std::string>> mutations_;
  int depth_ = 0;
  bool recovered_ = false;

  static const std::set<std::string>& modifiers() {
    static const std::set<std::string> kMods = {
        "public", "protected", "private", "static",   "final",
        "abstract", "native",  "synchronized", "transient", "volatile",
        "strictfp", "default"};
    return kMods;
  }

  static const std::set<std::string>& primitive_types() {
    static const std::set<std::string> kPrims = {
        "boolean", "byte", "char", "short", "int", "long", "float",
        "double"};
    return kPrims;
  }

  // ----------------------------------------------------------- token utils
  const Token& cur() const { return toks_[i_]; }
  const Token& ahead(size_t n) const {
    size_t j = i_ + n;
    return j < toks_.size() ? toks_[j] : toks_.back();
  }
  bool at_end() const { return cur().kind == Tok::kEnd; }
  void advance() {
    if (!at_end()) ++i_;
  }
  size_t mark() const { return i_; }
  void rewind(size_t m) {
    // undo any token mutations (the '>>' split in parse_type_arguments)
    // made past the mark — a tentative parse must leave no trace
    while (!mutations_.empty() && mutations_.back().first >= m) {
      toks_[mutations_.back().first].text = mutations_.back().second;
      mutations_.pop_back();
    }
    i_ = m;
  }
  void mutate_token(const std::string& new_text) {
    mutations_.emplace_back(i_, toks_[i_].text);
    toks_[i_].text = new_text;
  }

  bool is_punct(const std::string& p, size_t n = 0) const {
    return ahead(n).kind == Tok::kPunct && ahead(n).text == p;
  }
  bool is_ident(const std::string& word, size_t n = 0) const {
    return ahead(n).kind == Tok::kIdent && ahead(n).text == word;
  }
  bool accept_punct(const std::string& p) {
    if (is_punct(p)) {
      advance();
      return true;
    }
    return false;
  }
  bool accept_ident(const std::string& word) {
    if (is_ident(word)) {
      advance();
      return true;
    }
    return false;
  }
  void expect_punct(const std::string& p) {
    if (!accept_punct(p))
      throw ParseError("expected '" + p + "' got '" + cur().text + "'");
  }
  std::string expect_ident() {
    if (cur().kind != Tok::kIdent)
      throw ParseError("expected identifier, got '" + cur().text + "'");
    std::string name = cur().text;
    advance();
    return name;
  }

  void skip_balanced(const std::string& open, const std::string& close) {
    int depth = 0;
    while (!at_end()) {
      if (is_punct(open)) ++depth;
      if (is_punct(close)) {
        --depth;
        if (depth == 0) {
          advance();
          return;
        }
      }
      advance();
    }
  }

  void skip_annotations() {
    // '@interface' introduces an annotation DECLARATION, not an
    // annotation use — leave it for parse_type_declaration
    while (is_punct("@") && !is_ident("interface", 1)) {
      advance();
      expect_ident();
      while (accept_punct(".")) expect_ident();
      if (is_punct("(")) skip_balanced("(", ")");
    }
  }

  void skip_modifiers() {
    while (true) {
      skip_annotations();
      if (cur().kind == Tok::kIdent && modifiers().count(cur().text)) {
        advance();
        continue;
      }
      break;
    }
  }

  void skip_package_and_imports() {
    skip_annotations();
    if (accept_ident("package")) {
      while (!at_end() && !accept_punct(";")) advance();
    }
    while (is_ident("import")) {
      while (!at_end() && !accept_punct(";")) advance();
    }
  }

  void skip_type_parameters() {
    if (!is_punct("<")) return;
    int depth = 0;
    while (!at_end()) {
      if (is_punct("<")) ++depth;
      else if (is_punct(">")) --depth;
      else if (is_punct(">>")) depth -= 2;
      else if (is_punct(">>>")) depth -= 3;
      advance();
      if (depth <= 0) return;
    }
  }

  // -------------------------------------------------------- declarations
  Node* parse_type_declaration() {
    skip_modifiers();
    if (at_end()) return nullptr;
    if (is_ident("class") || is_ident("interface")) {
      return parse_class_or_interface();
    }
    if (is_ident("enum")) return parse_enum();
    if (is_punct("@") || is_ident("record")) {
      // annotation decl / record: skip body. A record body can hold real
      // methods, so skipping one counts as recovery — a file whose ONLY
      // type is a record must not pass as "valid Java with no methods"
      // (the reference's JavaParser predates records and errors on them).
      // @interface members are not MethodDeclarations, so that skip drops
      // nothing the reference would have extracted.
      if (is_ident("record")) recovered_ = true;
      while (!at_end() && !is_punct("{")) advance();
      if (is_punct("{")) skip_balanced("{", "}");
      return nullptr;
    }
    // unknown top-level construct: skip one token to make progress (and
    // mark the parse recovered — input was dropped, so "no methods found"
    // can no longer be trusted as a property of valid Java)
    recovered_ = true;
    advance();
    return nullptr;
  }

  Node* parse_class_or_interface() {
    DepthGuard depth_guard(&depth_);  // nested/anonymous class cycle
    bool is_interface = is_ident("interface");
    advance();  // class/interface
    std::string name = expect_ident();
    Node* decl = arena_->make("ClassOrInterfaceDeclaration", name);
    decl->add(arena_->make("NameExpr", name));
    skip_type_parameters();
    while (is_ident("extends") || is_ident("implements")) {
      advance();
      parse_type();  // discard
      while (accept_punct(",")) parse_type();
    }
    if (accept_ident("permits")) {
      parse_type();
      while (accept_punct(",")) parse_type();
    }
    expect_punct("{");
    parse_class_body(decl, is_interface);
    return decl;
  }

  Node* parse_enum() {
    advance();  // enum
    std::string name = expect_ident();
    Node* decl = arena_->make("EnumDeclaration", name);
    decl->add(arena_->make("NameExpr", name));
    while (is_ident("implements")) {
      advance();
      parse_type();
      while (accept_punct(",")) parse_type();
    }
    expect_punct("{");
    // enum constants: Ident [(args)] [{body}] separated by ','
    while (!at_end() && !is_punct(";") && !is_punct("}")) {
      skip_annotations();
      if (cur().kind == Tok::kIdent) {
        Node* constant =
            arena_->make("EnumConstantDeclaration", cur().text);
        advance();
        if (is_punct("(")) skip_balanced("(", ")");
        if (is_punct("{")) skip_balanced("{", "}");
        decl->add(constant);
      }
      if (!accept_punct(",")) break;
    }
    if (accept_punct(";")) parse_class_body(decl, false);
    else expect_punct("}");
    return decl;
  }

  void parse_class_body(Node* decl, bool is_interface) {
    while (!at_end() && !is_punct("}")) {
      size_t member_start = mark();
      try {
        parse_member(decl, is_interface);
      } catch (const ParseError&) {
        // recovery: skip this member — to the next ';' at depth 0 or past
        // one balanced '{...}' block
        recovered_ = true;
        rewind(member_start);
        skip_member();
      }
      if (mark() == member_start) skip_member();  // ensure progress
    }
    accept_punct("}");
  }

  void skip_member() {
    while (!at_end() && !is_punct("}")) {
      if (is_punct(";")) {
        advance();
        return;
      }
      if (is_punct("{")) {
        skip_balanced("{", "}");
        return;
      }
      advance();
    }
  }

  void parse_member(Node* decl, bool /*is_interface*/) {
    skip_modifiers();
    if (accept_punct(";")) return;
    if (is_punct("{")) {  // instance/static initializer
      Node* init = arena_->make("InitializerDeclaration");
      init->add(parse_block());
      decl->add(init);
      return;
    }
    if (is_ident("class") || is_ident("interface")) {
      decl->add(parse_class_or_interface());
      return;
    }
    if (is_ident("enum")) {
      decl->add(parse_enum());
      return;
    }
    skip_type_parameters();
    skip_annotations();

    // constructor: Ident '('  (same name as class, but name match isn't
    // required for parsing)
    if (cur().kind == Tok::kIdent && is_punct("(", 1)) {
      decl->add(parse_constructor());
      return;
    }

    // method or field: Type Ident ...
    Node* type = parse_type();
    if (is_ident("void", 0)) advance();  // defensive; handled in parse_type
    std::string name = expect_ident();
    if (is_punct("(")) {
      decl->add(parse_method_rest(type, name));
    } else {
      decl->add(parse_field_rest(type, name));
    }
  }

  Node* parse_constructor() {
    std::string name = expect_ident();
    Node* ctor = arena_->make("ConstructorDeclaration", name);
    ctor->add(arena_->make("NameExpr", name));
    parse_parameters(ctor);
    if (accept_ident("throws")) {
      parse_type();
      while (accept_punct(",")) parse_type();
    }
    if (is_punct("{")) ctor->add(parse_block());
    else expect_punct(";");
    return ctor;
  }

  // MethodDeclaration children mirror javaparser: return type, NameExpr
  // (the method-name leaf the reference renames to METHOD_NAME,
  // Common.java:69-75), parameters, body block.
  Node* parse_method_rest(Node* return_type, const std::string& name) {
    Node* method = arena_->make("MethodDeclaration", name);
    method->add(return_type);
    method->add(arena_->make("NameExpr", name));
    parse_parameters(method);
    while (accept_punct("[")) expect_punct("]");  // archaic int f()[] {}
    if (accept_ident("throws")) {
      parse_type();
      while (accept_punct(",")) parse_type();
    }
    if (is_punct("{")) {
      method->add(parse_block());
    } else {
      expect_punct(";");  // abstract/interface method: no body
    }
    return method;
  }

  Node* parse_field_rest(Node* type, const std::string& first_name) {
    Node* field = arena_->make("FieldDeclaration");
    field->add(type);
    field->add(parse_variable_declarator(first_name));
    while (accept_punct(",")) {
      std::string name = expect_ident();
      field->add(parse_variable_declarator(name));
    }
    expect_punct(";");
    return field;
  }

  Node* parse_variable_declarator(const std::string& name) {
    Node* declarator = arena_->make("VariableDeclarator", name);
    declarator->add(arena_->make("VariableDeclaratorId", name));
    while (accept_punct("[")) expect_punct("]");
    if (accept_punct("=")) {
      declarator->add(is_punct("{") ? parse_array_initializer()
                                    : parse_expression());
    }
    return declarator;
  }

  void parse_parameters(Node* owner) {
    expect_punct("(");
    if (accept_punct(")")) return;
    do {
      skip_modifiers();  // final, annotations
      Node* parameter = arena_->make("Parameter");
      Node* type = parse_type();
      parameter->add(type);
      accept_punct("...");  // varargs
      if (cur().kind == Tok::kIdent) {
        std::string name = expect_ident();
        parameter->add(arena_->make("VariableDeclaratorId", name));
        while (accept_punct("[")) expect_punct("]");
      }
      owner->add(parameter);
    } while (accept_punct(","));
    expect_punct(")");
  }

  // --------------------------------------------------------------- types
  Node* parse_type() {
    DepthGuard depth_guard(&depth_);
    skip_annotations();
    if (is_ident("void")) {
      advance();
      Node* type = arena_->make("VoidType", "void");
      return maybe_array(type);
    }
    if (cur().kind == Tok::kIdent && primitive_types().count(cur().text)) {
      Node* type = arena_->make("PrimitiveType", cur().text);
      advance();
      return maybe_array(type);
    }
    if (cur().kind != Tok::kIdent)
      throw ParseError("expected type, got '" + cur().text + "'");
    return maybe_array(parse_class_type());
  }

  Node* parse_class_type() {
    std::string name = expect_ident();
    while (is_punct(".") && ahead(1).kind == Tok::kIdent &&
           !is_ident("class", 1)) {
      advance();
      name += "." + expect_ident();
    }
    Node* type = arena_->make("ClassOrInterfaceType", name);
    if (is_punct("<")) parse_type_arguments(type);
    return type;
  }

  void parse_type_arguments(Node* owner) {
    expect_punct("<");
    if (accept_punct(">")) return;  // diamond <>
    while (true) {
      if (is_punct("?")) {
        advance();
        Node* wildcard = arena_->make("WildcardType", "?");
        if (accept_ident("extends") || accept_ident("super"))
          wildcard->add(parse_type());
        owner->add(wildcard);
      } else {
        owner->add(parse_type());
      }
      if (accept_punct(",")) continue;
      if (accept_punct(">")) return;
      // '>>' / '>>>' closing nested generics: split them (journaled so a
      // rewound tentative parse restores the original token)
      if (is_punct(">>")) {
        mutate_token(">");
        return;
      }
      if (is_punct(">>>")) {
        mutate_token(">>");
        return;
      }
      throw ParseError("bad type arguments near '" + cur().text + "'");
    }
  }

  Node* maybe_array(Node* type) {
    while (is_punct("[") && is_punct("]", 1)) {
      advance();
      advance();
      Node* array = arena_->make("ArrayType");
      array->add(type);
      type = array;
    }
    return type;
  }

  // ---------------------------------------------------------- statements
  Node* parse_block() {
    DepthGuard depth_guard(&depth_);
    size_t begin = cur().pos;
    expect_punct("{");
    Node* block = arena_->make("BlockStmt", "", /*is_statement=*/true);
    block->src_begin = begin;
    while (!at_end() && !is_punct("}")) {
      block->add(parse_statement());
    }
    block->src_end = cur().pos;
    expect_punct("}");
    return block;
  }

  Node* parse_statement() {
    DepthGuard depth_guard(&depth_);
    skip_annotations();
    if (is_punct("{")) return parse_block();
    if (accept_punct(";"))
      return arena_->make("EmptyStmt", "", true);
    if (is_ident("if")) return parse_if();
    if (is_ident("while")) return parse_while();
    if (is_ident("do")) return parse_do();
    if (is_ident("for")) return parse_for();
    if (is_ident("return")) return parse_return();
    if (is_ident("throw")) return parse_throw();
    if (is_ident("try")) return parse_try();
    if (is_ident("switch")) return parse_switch();
    if (is_ident("break")) {
      advance();
      Node* stmt = arena_->make("BreakStmt", "", true);
      if (cur().kind == Tok::kIdent) advance();  // label
      expect_punct(";");
      return stmt;
    }
    if (is_ident("continue")) {
      advance();
      Node* stmt = arena_->make("ContinueStmt", "", true);
      if (cur().kind == Tok::kIdent) advance();
      expect_punct(";");
      return stmt;
    }
    if (is_ident("synchronized")) {
      advance();
      Node* stmt = arena_->make("SynchronizedStmt", "", true);
      expect_punct("(");
      stmt->add(parse_expression());
      expect_punct(")");
      stmt->add(parse_block());
      return stmt;
    }
    if (is_ident("assert")) {
      advance();
      Node* stmt = arena_->make("AssertStmt", "", true);
      stmt->add(parse_expression());
      if (accept_punct(":")) stmt->add(parse_expression());
      expect_punct(";");
      return stmt;
    }
    if ((is_ident("class") || is_ident("final") || is_ident("abstract")) &&
        !is_punct(".", 1)) {
      // local class
      Node* stmt =
          arena_->make("TypeDeclarationStmt", "", true);
      skip_modifiers();
      stmt->add(parse_class_or_interface());
      return stmt;
    }
    // labeled statement: Ident ':'
    if (cur().kind == Tok::kIdent && is_punct(":", 1) &&
        !is_ident("default")) {
      Node* stmt = arena_->make("LabeledStmt", cur().text, true);
      advance();
      advance();
      stmt->add(parse_statement());
      return stmt;
    }
    // local variable declaration?
    {
      size_t m = mark();
      Node* decl = try_parse_local_variable_declaration();
      if (decl) {
        expect_punct(";");
        Node* stmt = arena_->make("ExpressionStmt", "", true);
        stmt->add(decl);
        return stmt;
      }
      rewind(m);
    }
    Node* stmt = arena_->make("ExpressionStmt", "", true);
    stmt->add(parse_expression());
    expect_punct(";");
    return stmt;
  }

  // VariableDeclarationExpr: [type, VariableDeclarator...]
  Node* try_parse_local_variable_declaration() {
    try {
      skip_modifiers();  // final / annotations
      if (cur().kind != Tok::kIdent) return nullptr;
      // `var` needs no special case: parse_type() yields the same
      // ClassOrInterfaceType("var") node alpha.4 would produce
      Node* type = parse_type();
      if (cur().kind != Tok::kIdent) return nullptr;
      // next after name must be one of = ; , [ to be a declaration
      const Token& after = ahead(1);
      if (!(after.kind == Tok::kPunct &&
            (after.text == "=" || after.text == ";" || after.text == "," ||
             after.text == "[" || after.text == ":")))
        return nullptr;
      if (after.text == ":") return nullptr;  // foreach handled in for
      Node* decl = arena_->make("VariableDeclarationExpr");
      decl->add(type);
      std::string name = expect_ident();
      decl->add(parse_variable_declarator(name));
      while (accept_punct(",")) {
        std::string next_name = expect_ident();
        decl->add(parse_variable_declarator(next_name));
      }
      return decl;
    } catch (const ParseError&) {
      return nullptr;
    }
  }

  Node* parse_if() {
    advance();
    Node* stmt = arena_->make("IfStmt", "", true);
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    stmt->add(parse_statement());
    if (accept_ident("else")) stmt->add(parse_statement());
    return stmt;
  }

  Node* parse_while() {
    advance();
    Node* stmt = arena_->make("WhileStmt", "", true);
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    stmt->add(parse_statement());
    return stmt;
  }

  Node* parse_do() {
    advance();
    Node* stmt = arena_->make("DoStmt", "", true);
    stmt->add(parse_statement());
    if (!accept_ident("while")) throw ParseError("expected while after do");
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    expect_punct(";");
    return stmt;
  }

  Node* parse_for() {
    advance();
    expect_punct("(");
    // foreach? "[final] Type Ident :"
    size_t m = mark();
    {
      skip_modifiers();
      try {
        if (cur().kind == Tok::kIdent) {
          Node* type = parse_type();
          if (cur().kind == Tok::kIdent && is_punct(":", 1)) {
            Node* stmt = arena_->make("ForeachStmt", "", true);
            Node* decl = arena_->make("VariableDeclarationExpr");
            decl->add(type);
            std::string name = expect_ident();
            decl->add(parse_variable_declarator(name));
            stmt->add(decl);
            expect_punct(":");
            stmt->add(parse_expression());
            expect_punct(")");
            stmt->add(parse_statement());
            return stmt;
          }
        }
      } catch (const ParseError&) {
      }
      rewind(m);
    }
    Node* stmt = arena_->make("ForStmt", "", true);
    if (!is_punct(";")) {
      Node* init = try_parse_local_variable_declaration();
      if (init) {
        stmt->add(init);
      } else {
        stmt->add(parse_expression());
        while (accept_punct(",")) stmt->add(parse_expression());
      }
    }
    expect_punct(";");
    if (!is_punct(";")) stmt->add(parse_expression());
    expect_punct(";");
    if (!is_punct(")")) {
      stmt->add(parse_expression());
      while (accept_punct(",")) stmt->add(parse_expression());
    }
    expect_punct(")");
    stmt->add(parse_statement());
    return stmt;
  }

  Node* parse_return() {
    advance();
    Node* stmt = arena_->make("ReturnStmt", "", true);
    if (!is_punct(";")) stmt->add(parse_expression());
    expect_punct(";");
    return stmt;
  }

  Node* parse_throw() {
    advance();
    Node* stmt = arena_->make("ThrowStmt", "", true);
    stmt->add(parse_expression());
    expect_punct(";");
    return stmt;
  }

  Node* parse_try() {
    advance();
    Node* stmt = arena_->make("TryStmt", "", true);
    if (is_punct("(")) {  // try-with-resources
      advance();
      while (!is_punct(")") && !at_end()) {
        Node* resource = try_parse_local_variable_declaration();
        stmt->add(resource ? resource : parse_expression());
        if (!accept_punct(";")) break;
      }
      expect_punct(")");
    }
    stmt->add(parse_block());
    while (is_ident("catch")) {
      advance();
      Node* clause = arena_->make("CatchClause");
      expect_punct("(");
      skip_modifiers();
      Node* parameter = arena_->make("Parameter");
      parameter->add(parse_type());
      while (accept_punct("|")) parse_type();  // multi-catch: keep first
      if (cur().kind == Tok::kIdent) {
        parameter->add(
            arena_->make("VariableDeclaratorId", expect_ident()));
      }
      clause->add(parameter);
      expect_punct(")");
      clause->add(parse_block());
      stmt->add(clause);
    }
    if (accept_ident("finally")) stmt->add(parse_block());
    return stmt;
  }

  Node* parse_switch() {
    advance();
    Node* stmt = arena_->make("SwitchStmt", "", true);
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    expect_punct("{");
    while (!at_end() && !is_punct("}")) {
      Node* entry = arena_->make("SwitchEntryStmt", "", true);
      if (accept_ident("case")) {
        entry->add(parse_expression());
        while (accept_punct(",")) entry->add(parse_expression());
      } else if (!accept_ident("default")) {
        throw ParseError("expected case/default in switch");
      }
      if (accept_punct("->")) {  // arrow form
        if (is_punct("{")) entry->add(parse_block());
        else {
          entry->add(parse_statement());
        }
      } else {
        expect_punct(":");
        while (!at_end() && !is_punct("}") && !is_ident("case") &&
               !is_ident("default")) {
          entry->add(parse_statement());
        }
      }
      stmt->add(entry);
    }
    expect_punct("}");
    return stmt;
  }

  Node* parse_array_initializer() {
    expect_punct("{");
    Node* init = arena_->make("ArrayInitializerExpr");
    while (!at_end() && !is_punct("}")) {
      init->add(is_punct("{") ? parse_array_initializer()
                              : parse_expression());
      if (!accept_punct(",")) break;
    }
    expect_punct("}");
    return init;
  }

  // --------------------------------------------------------- expressions
  Node* parse_expression() { return parse_assignment(); }

  Node* parse_assignment() {
    DepthGuard depth_guard(&depth_);
    Node* left = parse_ternary();
    // AssignExpr$Operator constants, javaparser 3.0.0-alpha.4
    static const std::pair<const char*, const char*> kAssignOps[] = {
        {"=", "assign"},       {"+=", "plus"},
        {"-=", "minus"},       {"*=", "star"},
        {"/=", "slash"},       {"%=", "rem"},
        {"&=", "and"},         {"|=", "or"},
        {"^=", "xor"},         {"<<=", "lShift"},
        {">>=", "rSignedShift"}, {">>>=", "rUnsignedShift"}};
    for (const auto& [text, name] : kAssignOps) {
      if (is_punct(text)) {
        advance();
        Node* assign = arena_->make_op("AssignExpr", name);
        assign->add(left);
        assign->add(is_punct("{") ? parse_array_initializer()
                                  : parse_assignment());
        return assign;
      }
    }
    return left;
  }

  Node* parse_ternary() {
    Node* condition = parse_binary(0);
    if (is_punct("?")) {
      advance();
      Node* ternary = arena_->make("ConditionalExpr");
      ternary->add(condition);
      ternary->add(parse_expression());
      expect_punct(":");
      ternary->add(parse_expression());
      return ternary;
    }
    return condition;
  }

  struct BinOp {
    const char* text;
    const char* name;
    int prec;
  };

  static const std::vector<BinOp>& binary_ops() {
    // BinaryExpr$Operator constants, javaparser 3.0.0-alpha.4
    static const std::vector<BinOp> kOps = {
        {"||", "or", 1},           {"&&", "and", 2},
        {"|", "binOr", 3},         {"^", "xor", 4},
        {"&", "binAnd", 5},        {"==", "equals", 6},
        {"!=", "notEquals", 6},    {"<", "less", 7},
        {">", "greater", 7},       {"<=", "lessEquals", 7},
        {">=", "greaterEquals", 7},
        {"<<", "lShift", 8},       {">>", "rSignedShift", 8},
        {">>>", "rUnsignedShift", 8},
        {"+", "plus", 9},          {"-", "minus", 9},
        {"*", "times", 10},        {"/", "divide", 10},
        {"%", "remainder", 10}};
    return kOps;
  }

  const BinOp* current_binop(int min_prec) {
    if (cur().kind != Tok::kPunct) return nullptr;
    for (const auto& op : binary_ops()) {
      if (cur().text == op.text && op.prec >= min_prec) return &op;
    }
    return nullptr;
  }

  Node* parse_binary(int min_prec) {
    Node* left = parse_unary();
    while (true) {
      if (is_ident("instanceof")) {
        advance();
        Node* check = arena_->make("InstanceOfExpr");
        check->add(left);
        check->add(parse_type());
        if (cur().kind == Tok::kIdent) advance();  // pattern variable
        left = check;
        continue;
      }
      const BinOp* op = current_binop(min_prec + 1);
      if (!op) return left;
      advance();
      Node* right = parse_binary(op->prec);
      Node* binary = arena_->make_op("BinaryExpr", op->name);
      binary->add(left);
      binary->add(right);
      left = binary;
    }
  }

  Node* parse_unary() {
    DepthGuard depth_guard(&depth_);
    // UnaryExpr$Operator constants, javaparser 3.0.0-alpha.4
    static const std::pair<const char*, const char*> kPrefix[] = {
        {"+", "positive"},
        {"-", "negative"},
        {"!", "not"},
        {"~", "inverse"},
        {"++", "preIncrement"},
        {"--", "preDecrement"}};
    for (const auto& [text, name] : kPrefix) {
      if (is_punct(text)) {
        advance();
        // negative literal folding like javaparser: -5 is an
        // IntegerLiteralExpr("-5")? javaparser keeps UnaryExpr(minus);
        // we do the same.
        Node* unary = arena_->make_op("UnaryExpr", name);
        unary->add(parse_unary());
        return unary;
      }
    }
    // cast: '(' Type ')' unary  — tentative
    if (is_punct("(")) {
      size_t m = mark();
      advance();
      try {
        Node* type = parse_type();
        if (accept_punct(")")) {
          bool cast_target = cur().kind == Tok::kIdent ||
                             cur().kind == Tok::kIntLit ||
                             cur().kind == Tok::kFloatLit ||
                             cur().kind == Tok::kCharLit ||
                             cur().kind == Tok::kStringLit ||
                             is_punct("(") || is_punct("!") ||
                             is_punct("~");
          if (cast_target) {
            Node* cast = arena_->make("CastExpr");
            cast->add(type);
            cast->add(parse_unary());
            return parse_postfix_ops(cast);
          }
        }
      } catch (const ParseError&) {
      }
      rewind(m);
    }
    return parse_postfix();
  }

  Node* parse_postfix() {
    Node* expr = parse_primary();
    expr = parse_postfix_ops(expr);
    if (is_punct("++")) {
      advance();
      Node* unary = arena_->make_op("UnaryExpr", "posIncrement");
      unary->add(expr);
      return unary;
    }
    if (is_punct("--")) {
      advance();
      Node* unary = arena_->make_op("UnaryExpr", "posDecrement");
      unary->add(expr);
      return unary;
    }
    return expr;
  }

  // selectors: .name, .name(args), [index], ::ref
  Node* parse_postfix_ops(Node* expr) {
    while (true) {
      if (is_punct(".")) {
        advance();
        if (accept_ident("new")) {  // inner class creation: treat as call
          Node* creation = parse_object_creation(expr);
          expr = creation;
          continue;
        }
        if (is_punct("<")) skip_type_parameters();  // explicit type args
        if (is_ident("class")) {
          advance();
          Node* access = arena_->make("ClassExpr");
          access->add(expr);
          expr = access;
          continue;
        }
        if (is_ident("this")) {
          advance();
          Node* access = arena_->make("FieldAccessExpr");
          access->add(expr);
          access->add(arena_->make("ThisExpr", "this"));
          expr = access;
          continue;
        }
        std::string name = expect_ident();
        if (is_punct("(")) {
          Node* call = arena_->make("MethodCallExpr", name);
          call->add(expr);  // scope
          call->add(arena_->make("NameExpr", name));
          parse_arguments(call);
          expr = call;
        } else {
          Node* access = arena_->make("FieldAccessExpr", name);
          access->add(expr);
          access->add(arena_->make("NameExpr", name));
          expr = access;
        }
        continue;
      }
      if (is_punct("[") && !is_punct("]", 1)) {
        advance();
        Node* index = parse_expression();
        expect_punct("]");
        Node* access = arena_->make("ArrayAccessExpr");
        access->add(expr);
        access->add(index);
        expr = access;
        continue;
      }
      if (is_punct("::")) {
        advance();
        std::string name =
            is_ident("new") ? (advance(), "new") : expect_ident();
        Node* ref = arena_->make("MethodReferenceExpr", name);
        ref->add(expr);
        ref->add(arena_->make("NameExpr", name));
        expr = ref;
        continue;
      }
      return expr;
    }
  }

  void parse_arguments(Node* call) {
    expect_punct("(");
    if (accept_punct(")")) return;
    do {
      call->add(parse_expression());
    } while (accept_punct(","));
    expect_punct(")");
  }

  Node* parse_object_creation(Node* scope) {
    // after 'new'
    Node* creation = arena_->make("ObjectCreationExpr");
    if (scope) creation->add(scope);
    if (cur().kind == Tok::kIdent &&
        primitive_types().count(cur().text)) {
      // new int[...]
      Node* type = arena_->make("PrimitiveType", cur().text);
      advance();
      return parse_array_creation(type);
    }
    Node* type = parse_class_type();
    if (is_punct("[")) return parse_array_creation(type);
    creation->add(type);
    parse_arguments(creation);
    if (is_punct("{")) {  // anonymous class body
      Node* body = arena_->make("ClassOrInterfaceDeclaration");
      advance();  // consume '{'
      parse_class_body(body, false);
      creation->add(body);
    }
    return creation;
  }

  Node* parse_array_creation(Node* element_type) {
    Node* creation = arena_->make("ArrayCreationExpr");
    creation->add(element_type);
    while (is_punct("[")) {
      advance();
      if (!is_punct("]")) creation->add(parse_expression());
      expect_punct("]");
    }
    if (is_punct("{")) creation->add(parse_array_initializer());
    return creation;
  }

  bool lambda_ahead() {
    // Ident '->'  or  '(' params ')' '->'
    if (cur().kind == Tok::kIdent && is_punct("->", 1)) return true;
    if (!is_punct("(")) return false;
    int depth = 0;
    size_t j = 0;
    while (ahead(j).kind != Tok::kEnd) {
      if (ahead(j).kind == Tok::kPunct) {
        if (ahead(j).text == "(") ++depth;
        if (ahead(j).text == ")") {
          --depth;
          if (depth == 0) return ahead(j + 1).kind == Tok::kPunct &&
                                 ahead(j + 1).text == "->";
        }
      }
      ++j;
    }
    return false;
  }

  Node* parse_lambda() {
    Node* lambda = arena_->make("LambdaExpr");
    if (cur().kind == Tok::kIdent) {
      Node* parameter = arena_->make("Parameter");
      parameter->add(
          arena_->make("VariableDeclaratorId", expect_ident()));
      lambda->add(parameter);
    } else {
      expect_punct("(");
      while (!is_punct(")") && !at_end()) {
        skip_modifiers();
        Node* parameter = arena_->make("Parameter");
        size_t m = mark();
        // typed param?
        try {
          Node* type = parse_type();
          if (cur().kind == Tok::kIdent) {
            parameter->add(type);
            parameter->add(
                arena_->make("VariableDeclaratorId", expect_ident()));
          } else {
            throw ParseError("untyped");
          }
        } catch (const ParseError&) {
          rewind(m);
          parameter->add(
              arena_->make("VariableDeclaratorId", expect_ident()));
        }
        lambda->add(parameter);
        if (!accept_punct(",")) break;
      }
      expect_punct(")");
    }
    expect_punct("->");
    lambda->add(is_punct("{") ? parse_block() : parse_expression());
    return lambda;
  }

  Node* parse_primary() {
    if (lambda_ahead()) return parse_lambda();
    const Token& token = cur();
    switch (token.kind) {
      case Tok::kIntLit: {
        advance();
        return arena_->make("IntegerLiteralExpr", token.text);
      }
      case Tok::kFloatLit: {
        advance();
        return arena_->make("DoubleLiteralExpr", token.text);
      }
      case Tok::kCharLit: {
        advance();
        return arena_->make("CharLiteralExpr", token.text);
      }
      case Tok::kStringLit: {
        advance();
        return arena_->make("StringLiteralExpr", token.text);
      }
      case Tok::kIdent:
        break;
      case Tok::kPunct:
        if (is_punct("(")) {
          advance();
          Node* enclosed = arena_->make("EnclosedExpr");
          enclosed->add(parse_expression());
          expect_punct(")");
          return enclosed;
        }
        throw ParseError("unexpected token '" + token.text + "'");
      default:
        throw ParseError("unexpected end of input");
    }
    // identifier-led primaries
    if (is_ident("new")) {
      advance();
      return parse_object_creation(nullptr);
    }
    if (is_ident("true") || is_ident("false")) {
      Node* literal = arena_->make("BooleanLiteralExpr", token.text);
      advance();
      return literal;
    }
    if (is_ident("null")) {
      advance();
      return arena_->make("NullLiteralExpr", "null");
    }
    if (is_ident("this")) {
      advance();
      if (is_punct("(")) {  // this(...) constructor call
        Node* call = arena_->make("ExplicitConstructorInvocationStmt");
        parse_arguments(call);
        return call;
      }
      return arena_->make("ThisExpr", "this");
    }
    if (is_ident("super")) {
      advance();
      if (is_punct("(")) {
        Node* call = arena_->make("ExplicitConstructorInvocationStmt");
        parse_arguments(call);
        return call;
      }
      return arena_->make("SuperExpr", "super");
    }
    if (cur().kind == Tok::kIdent &&
        primitive_types().count(cur().text)) {
      // int.class / int[]::new etc: treat as type expression
      Node* type = arena_->make("PrimitiveType", cur().text);
      advance();
      return maybe_array(type);
    }
    // plain name or unqualified call
    std::string name = expect_ident();
    if (is_punct("(")) {
      Node* call = arena_->make("MethodCallExpr", name);
      call->add(arena_->make("NameExpr", name));
      parse_arguments(call);
      return call;
    }
    return arena_->make("NameExpr", name);
  }
};

}  // namespace c2v
