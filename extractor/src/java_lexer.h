// Hand-written Java lexer for the native extractor. Comments are consumed
// here and never reach the parser (the reference's visitor likewise drops
// Comment nodes, LeavesCollectorVisitor.java:21-23).
#pragma once

#include <cctype>
#include <stdexcept>
#include <string>
#include <string_view>
#include <vector>

namespace c2v {

enum class Tok {
  kEnd,
  kIdent,       // identifiers and keywords
  kIntLit,      // 123, 0x1F, 10L
  kFloatLit,    // 1.5, 2e3, 1.5f
  kCharLit,     // 'a'
  kStringLit,   // "abc"
  kPunct,       // operators and punctuation, longest-match
};

struct Token {
  Tok kind = Tok::kEnd;
  std::string text;   // raw text (string/char literals keep quotes)
  size_t pos = 0;
};

struct LexError : std::runtime_error {
  explicit LexError(const std::string& what) : std::runtime_error(what) {}
};

class Lexer {
 public:
  // csharp mode adds @identifiers, @"verbatim" and $"interpolated" strings
  explicit Lexer(std::string_view src, bool csharp = false)
      : src_(src), csharp_(csharp) {}

  // when set, comment text is captured here instead of dropped (the C#
  // extractor emits COMMENT contexts from comment trivia)
  void capture_comments(std::vector<std::string>* sink) {
    comments_ = sink;
  }

  std::vector<Token> run() {
    std::vector<Token> out;
    while (true) {
      Token token = next();
      bool end = token.kind == Tok::kEnd;
      out.push_back(std::move(token));
      if (end) break;
    }
    return out;
  }

 private:
  std::string_view src_;
  size_t pos_ = 0;
  bool csharp_ = false;
  std::vector<std::string>* comments_ = nullptr;

  char peek(size_t ahead = 0) const {
    return pos_ + ahead < src_.size() ? src_[pos_ + ahead] : '\0';
  }

  void skip_space_and_comments() {
    while (pos_ < src_.size()) {
      char c = src_[pos_];
      if (std::isspace(static_cast<unsigned char>(c))) {
        ++pos_;
      } else if (c == '/' && peek(1) == '/') {
        size_t start = pos_;
        while (pos_ < src_.size() && src_[pos_] != '\n') ++pos_;
        if (comments_)
          comments_->push_back(
              std::string(src_.substr(start, pos_ - start)));
      } else if (c == '/' && peek(1) == '*') {
        size_t start = pos_;
        pos_ += 2;
        while (pos_ + 1 < src_.size() &&
               !(src_[pos_] == '*' && src_[pos_ + 1] == '/'))
          ++pos_;
        pos_ = pos_ + 2 <= src_.size() ? pos_ + 2 : src_.size();
        if (comments_)
          comments_->push_back(
              std::string(src_.substr(start, pos_ - start)));
      } else {
        return;
      }
    }
  }

  Token next() {
    skip_space_and_comments();
    Token token;
    token.pos = pos_;
    if (pos_ >= src_.size()) return token;

    char c = src_[pos_];
    if (csharp_ && (c == '$' || c == '@')) {
      // must run before the identifier branch: '$' would otherwise start
      // a Java-style identifier
      if (c == '@' && peek(1) == '"') return lex_verbatim_string();
      if (c == '@' &&
          (std::isalpha(static_cast<unsigned char>(peek(1))) ||
           peek(1) == '_')) {
        ++pos_;  // @identifier: drop the '@'
        return next();
      }
      if (c == '$' && peek(1) == '"') {
        ++pos_;  // interpolated string lexed as one string token
        Token token = lex_string();
        token.pos -= 1;
        return token;
      }
      if (c == '$' && peek(1) == '@' && peek(2) == '"') {
        ++pos_;
        Token token = lex_verbatim_string();
        token.pos -= 1;
        return token;
      }
    }
    if (std::isalpha(static_cast<unsigned char>(c)) || c == '_' || c == '$') {
      size_t start = pos_;
      while (pos_ < src_.size() &&
             (std::isalnum(static_cast<unsigned char>(src_[pos_])) ||
              src_[pos_] == '_' || src_[pos_] == '$'))
        ++pos_;
      token.kind = Tok::kIdent;
      token.text = std::string(src_.substr(start, pos_ - start));
      return token;
    }
    if (std::isdigit(static_cast<unsigned char>(c)) ||
        (c == '.' && std::isdigit(static_cast<unsigned char>(peek(1))))) {
      return lex_number();
    }
    if (c == '"') return lex_string();
    if (c == '\'') return lex_char();
    return lex_punct();
  }

  Token lex_verbatim_string() {
    // @"..."; quotes escaped by doubling
    Token token;
    token.pos = pos_;
    size_t start = pos_;
    pos_ += 2;  // @"
    while (pos_ < src_.size()) {
      if (src_[pos_] == '"') {
        if (peek(1) == '"') {
          pos_ += 2;
          continue;
        }
        ++pos_;
        break;
      }
      ++pos_;
    }
    token.kind = Tok::kStringLit;
    token.text = std::string(src_.substr(start, pos_ - start));
    return token;
  }

  Token lex_number() {
    Token token;
    token.pos = pos_;
    size_t start = pos_;
    bool is_float = false;
    if (peek() == '0' && (peek(1) == 'x' || peek(1) == 'X')) {
      pos_ += 2;
      while (std::isxdigit(static_cast<unsigned char>(peek())) ||
             peek() == '_')
        ++pos_;
    } else if (peek() == '0' && (peek(1) == 'b' || peek(1) == 'B')) {
      pos_ += 2;
      while (peek() == '0' || peek() == '1' || peek() == '_') ++pos_;
    } else {
      while (std::isdigit(static_cast<unsigned char>(peek())) ||
             peek() == '_')
        ++pos_;
      if (peek() == '.' &&
          std::isdigit(static_cast<unsigned char>(peek(1)))) {
        is_float = true;
        ++pos_;
        while (std::isdigit(static_cast<unsigned char>(peek())) ||
               peek() == '_')
          ++pos_;
      }
      if (peek() == 'e' || peek() == 'E') {
        is_float = true;
        ++pos_;
        if (peek() == '+' || peek() == '-') ++pos_;
        while (std::isdigit(static_cast<unsigned char>(peek()))) ++pos_;
      }
    }
    if (peek() == 'f' || peek() == 'F' || peek() == 'd' || peek() == 'D') {
      is_float = true;
      ++pos_;
    } else if (peek() == 'l' || peek() == 'L') {
      ++pos_;
    }
    token.kind = is_float ? Tok::kFloatLit : Tok::kIntLit;
    token.text = std::string(src_.substr(start, pos_ - start));
    return token;
  }

  Token lex_string() {
    Token token;
    token.pos = pos_;
    size_t start = pos_;
    ++pos_;  // opening quote
    while (pos_ < src_.size() && src_[pos_] != '"') {
      if (src_[pos_] == '\\') ++pos_;
      ++pos_;
    }
    if (pos_ >= src_.size()) throw LexError("unterminated string literal");
    ++pos_;  // closing quote
    token.kind = Tok::kStringLit;
    token.text = std::string(src_.substr(start, pos_ - start));
    return token;
  }

  Token lex_char() {
    Token token;
    token.pos = pos_;
    size_t start = pos_;
    ++pos_;
    while (pos_ < src_.size() && src_[pos_] != '\'') {
      if (src_[pos_] == '\\') ++pos_;
      ++pos_;
    }
    if (pos_ >= src_.size()) throw LexError("unterminated char literal");
    ++pos_;
    token.kind = Tok::kCharLit;
    token.text = std::string(src_.substr(start, pos_ - start));
    return token;
  }

  Token lex_punct() {
    static const char* three[] = {">>>", "<<=", ">>=", "..."};
    static const char* two[] = {"==", "!=", "<=", ">=", "&&", "||", "++",
                                "--", "+=", "-=", "*=", "/=", "%=", "&=",
                                "|=", "^=", "<<", ">>", "->", "::"};
    // C#-only tokens, gated so Java tokenization is untouched
    // (e.g. Java `cond?.5:1.0` must lex '?' then '.5')
    static const char* three_cs[] = {"?\?="};
    static const char* two_cs[] = {"=>", "??", "?."};
    Token token;
    token.pos = pos_;
    token.kind = Tok::kPunct;
    std::string_view rest = src_.substr(pos_);
    auto try_ops = [&](auto& ops, size_t len) -> bool {
      for (const char* op : ops) {
        if (rest.size() >= len && rest.substr(0, len) == op) {
          token.text = op;
          pos_ += len;
          return true;
        }
      }
      return false;
    };
    if (rest.size() >= 4 && rest.substr(0, 4) == ">>>=") {
      token.text = ">>>=";
      pos_ += 4;
      return token;
    }
    if (csharp_ && try_ops(three_cs, 3)) return token;
    if (try_ops(three, 3)) return token;
    if (csharp_ && try_ops(two_cs, 2)) return token;
    if (try_ops(two, 2)) return token;
    token.text = std::string(1, src_[pos_]);
    ++pos_;
    return token;
  }
};

}  // namespace c2v
