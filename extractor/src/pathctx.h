// Path-context extraction over the parsed AST.
//
// Reference semantics implemented here:
// - leaf predicate, per-node childId (LeavesCollectorVisitor.java:20-37,
//   57-68);
// - node properties: type (+ :OP), normalized name, METHOD_NAME
//   substitution, boxed-type renaming, 50-char truncation
//   (Property.java:28-76, Common.java:36-76);
// - all-pairs i<j path generation with MaxPathLength prune on node count
//   and MaxPathWidth prune on LCA child-index delta, and the exact childId
//   rendering rules — including the reference's asymmetric set-membership
//   check (parent type on the way up, own type on the way down)
//   (FeatureExtractor.java:95-195);
// - output: "label src,path,tgt ..." with Java String#hashCode path hashing
//   unless --no_hash (ProgramRelation.java:18-33).
#pragma once

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <set>
#include <sstream>
#include <string>
#include <vector>

#include "java_ast.h"

namespace c2v {

struct ExtractorOptions {
  int max_path_length = 8;
  int max_path_width = 2;
  int max_child_id = 2147483647;  // reference default: Integer.MAX_VALUE
  int min_code_len = 1;
  int max_code_len = 10000;
  bool no_hash = false;
  // C# frontend: reservoir-sample cap on variable pairs
  // (reference Utilities.cs:30-32, default 30000)
  int max_contexts_cs = 30000;
};

// ---------------------------------------------------------- normalization
// reference Common.java:36-53
inline std::string normalize_name(const std::string& original,
                                  const std::string& fallback) {
  std::string cleaned;
  cleaned.reserve(original.size());
  for (size_t i = 0; i < original.size(); ++i) {
    char c = original[i];
    if (c == '\\' && i + 1 < original.size() && original[i + 1] == 'n') {
      ++i;  // escaped newline
      continue;
    }
    if (c == '"' || c == '\'' || c == ',') continue;
    unsigned char uc = static_cast<unsigned char>(c);
    if (uc < 0x20 || uc >= 0x7F) continue;  // non-printables / non-ascii
    cleaned.push_back(
        static_cast<char>(std::tolower(static_cast<unsigned char>(c))));
  }
  std::string stripped;
  for (char c : cleaned)
    if (std::isalpha(static_cast<unsigned char>(c))) stripped.push_back(c);
  if (!stripped.empty()) return stripped;
  std::string careful;
  for (char c : cleaned) careful.push_back(c == ' ' ? '_' : c);
  if (!careful.empty()) return careful;
  return fallback;
}

// reference Common.java:71-76: split on aA boundaries, '_', digits,
// AAb boundaries and whitespace; normalize parts; drop empties.
inline std::vector<std::string> split_subtokens(const std::string& input) {
  std::string trimmed = input;
  while (!trimmed.empty() &&
         std::isspace(static_cast<unsigned char>(trimmed.front())))
    trimmed.erase(trimmed.begin());
  while (!trimmed.empty() &&
         std::isspace(static_cast<unsigned char>(trimmed.back())))
    trimmed.pop_back();

  std::vector<std::string> parts;
  std::string current;
  auto flush = [&]() {
    if (!current.empty()) {
      std::string normalized = normalize_name(current, "");
      if (!normalized.empty()) parts.push_back(normalized);
      current.clear();
    }
  };
  for (size_t i = 0; i < trimmed.size(); ++i) {
    char c = trimmed[i];
    if (c == '_' || std::isdigit(static_cast<unsigned char>(c)) ||
        std::isspace(static_cast<unsigned char>(c))) {
      flush();  // separator chars are dropped
      continue;
    }
    bool lower_to_upper =
        i > 0 && std::islower(static_cast<unsigned char>(trimmed[i - 1])) &&
        std::isupper(static_cast<unsigned char>(c));
    bool acronym_end = i + 1 < trimmed.size() &&
                       std::isupper(static_cast<unsigned char>(c)) &&
                       i > 0 &&
                       std::isupper(static_cast<unsigned char>(trimmed[i - 1])) &&
                       std::islower(static_cast<unsigned char>(trimmed[i + 1]));
    if (lower_to_upper || acronym_end) flush();
    current.push_back(c);
  }
  flush();
  return parts;
}

inline std::string join(const std::vector<std::string>& parts,
                        const std::string& sep) {
  std::string out;
  for (size_t i = 0; i < parts.size(); ++i) {
    if (i) out += sep;
    out += parts[i];
  }
  return out;
}

// Java String#hashCode (reference ProgramRelation.java:25 uses
// String.hashCode via Integer.toString).
inline int32_t java_hash(const std::string& s) {
  uint32_t h = 0;
  for (unsigned char c : s) h = 31u * h + c;
  return static_cast<int32_t>(h);
}

// ------------------------------------------------------------- properties
inline bool is_boxed_type(const Node* node) {
  static const std::set<std::string> kBoxed = {
      "Boolean", "Byte", "Character", "Double",
      "Float",   "Integer", "Long",   "Short"};
  return node->raw_type == "ClassOrInterfaceType" && kBoxed.count(node->code);
}

inline std::string unboxed_name(const std::string& boxed) {
  if (boxed == "Integer") return "int";
  if (boxed == "Character") return "char";
  std::string lower = boxed;
  for (char& c : lower)
    c = static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
  return lower;  // boolean byte double float long short
}

struct Property {
  std::string type;  // path-rendering type (may be rewritten)
  std::string name;  // emitted terminal token
};

constexpr int kMaxLabelLength = 50;  // reference Common.java:32

// reference Property.java:28-76
inline Property compute_property(const Node* node, bool is_leaf) {
  Property property;
  property.type = node->type;
  bool boxed = is_boxed_type(node);
  if (boxed) property.type = "PrimitiveType";

  bool generic_parent = node->raw_type == "ClassOrInterfaceType" &&
                        !node->children.empty();
  if (generic_parent && is_leaf) property.type = "GenericClass";

  property.name = normalize_name(node->code, "BLANK");
  if (static_cast<int>(property.name.size()) > kMaxLabelLength) {
    property.name = property.name.substr(0, kMaxLabelLength);
  } else if (boxed) {
    property.name = unboxed_name(node->code);
  }
  // METHOD_NAME substitution (Common.java:69-75)
  if (node->raw_type == "NameExpr" && node->parent != nullptr &&
      node->parent->raw_type == "MethodDeclaration") {
    property.name = "METHOD_NAME";
  }
  return property;
}

// ---------------------------------------------------------------- leaves
// reference LeavesCollectorVisitor.java:20-37
inline bool is_leaf(const Node* node) {
  if (!node->children.empty()) return false;
  if (node->is_statement) return false;
  if (node->code.empty()) return false;
  if (node->code == "null" && node->raw_type != "NullLiteralExpr")
    return false;
  return true;
}

inline void collect_leaves(Node* node, std::vector<Node*>* leaves) {
  if (is_leaf(node)) leaves->push_back(node);
  for (Node* child : node->children) collect_leaves(child, leaves);
}

// ----------------------------------------------------------------- paths
inline const std::set<std::string>& child_id_parent_types() {
  // reference FeatureExtractor.java:26-28
  static const std::set<std::string> kTypes = {
      "AssignExpr", "ArrayAccessExpr", "FieldAccessExpr", "MethodCallExpr"};
  return kTypes;
}

inline std::vector<const Node*> tree_stack(const Node* node) {
  std::vector<const Node*> stack;
  for (const Node* current = node; current != nullptr;
       current = current->parent)
    stack.push_back(current);
  return stack;
}

// reference FeatureExtractor.java:120-191. Empty string = pruned.
inline std::string generate_path(const Node* source, const Node* target,
                                 const ExtractorOptions& options) {
  std::vector<const Node*> source_stack = tree_stack(source);
  std::vector<const Node*> target_stack = tree_stack(target);

  int common_prefix = 0;
  int si = static_cast<int>(source_stack.size()) - 1;
  int ti = static_cast<int>(target_stack.size()) - 1;
  while (si >= 0 && ti >= 0 && source_stack[si] == target_stack[ti]) {
    ++common_prefix;
    --si;
    --ti;
  }
  int path_length = static_cast<int>(source_stack.size()) +
                    static_cast<int>(target_stack.size()) -
                    2 * common_prefix;
  if (path_length > options.max_path_length) return std::string();
  if (si >= 0 && ti >= 0) {
    int path_width =
        target_stack[ti]->child_id - source_stack[si]->child_id;
    if (path_width > options.max_path_width) return std::string();
  }

  auto saturate = [&](int child_id) {
    return std::min(child_id, options.max_child_id);
  };

  std::string out;
  int source_nodes = static_cast<int>(source_stack.size()) - common_prefix;
  for (int i = 0; i < source_nodes; ++i) {
    const Node* current = source_stack[i];
    std::string child_id;
    // up-walk: childId appended for the leaf itself or when the PARENT's
    // raw type is in the set (FeatureExtractor.java:157-161)
    const std::string& parent_raw =
        current->parent ? current->parent->raw_type : std::string();
    if (i == 0 || child_id_parent_types().count(parent_raw)) {
      child_id = std::to_string(saturate(current->child_id));
    }
    out += '(';
    out += compute_property(current, i == 0 && is_leaf(current)).type;
    out += child_id;
    out += ')';
    out += '^';
  }

  const Node* common_node = source_stack[source_nodes];
  std::string common_child_id;
  const std::string common_parent_raw =
      common_node->parent ? common_node->parent->raw_type : std::string();
  if (child_id_parent_types().count(common_parent_raw)) {
    common_child_id = std::to_string(saturate(common_node->child_id));
  }
  out += '(';
  out += compute_property(common_node, false).type;
  out += common_child_id;
  out += ')';

  for (int i = static_cast<int>(target_stack.size()) - common_prefix - 1;
       i >= 0; --i) {
    const Node* current = target_stack[i];
    std::string child_id;
    // down-walk: the reference checks the CURRENT node's own raw type here
    // (FeatureExtractor.java:182) — asymmetric with the up-walk; kept
    // verbatim for parity
    if (i == 0 || child_id_parent_types().count(current->raw_type)) {
      child_id = std::to_string(saturate(current->child_id));
    }
    out += '_';
    out += '(';
    out += compute_property(current, i == 0 && is_leaf(current)).type;
    out += child_id;
    out += ')';
  }
  return out;
}

// ------------------------------------------------------------ per method
struct MethodFeatures {
  std::string label;
  std::vector<std::string> contexts;  // "src,path-or-hash,tgt"
};

inline void find_methods(Node* node, std::vector<Node*>* methods) {
  if (node->raw_type == "MethodDeclaration") methods->push_back(node);
  for (Node* child : node->children) find_methods(child, methods);
}

inline long method_length_lines(const Node* method,
                                const std::string& source) {
  // reference FunctionVisitor.java:44-57: count body source lines minus
  // comment-only lines; its brace/blank filters are no-ops (string
  // reference comparison), so only the comment filter is effective.
  const Node* body = nullptr;
  for (const Node* child : method->children)
    if (child->raw_type == "BlockStmt") body = child;
  if (body == nullptr || body->children.empty()) return 0;
  size_t begin = body->src_begin, end = body->src_end;
  if (end <= begin || end > source.size()) return 1;
  long lines = 0;
  size_t line_start = begin;
  auto count_line = [&](size_t line_end) {
    std::string_view line(source.data() + line_start,
                          line_end - line_start);
    size_t first = line.find_first_not_of(" \t\r");
    if (first == std::string_view::npos) {
      ++lines;  // blank lines ARE counted (the reference's blank filter
                // never fires)
      return;
    }
    if (line[first] == '/' || line[first] == '*') return;  // comment line
    ++lines;
  };
  for (size_t i = begin; i < end; ++i) {
    if (source[i] == '\n') {
      count_line(i);
      line_start = i + 1;
    }
  }
  if (line_start < end) count_line(end);
  return lines;
}

inline MethodFeatures extract_method(Node* method,
                                     const ExtractorOptions& options) {
  MethodFeatures features;
  // label: subtoken-split method name (FunctionVisitor.java:30-38)
  std::vector<std::string> parts = split_subtokens(method->code);
  features.label = parts.empty() ? normalize_name(method->code, "BLANK")
                                 : join(parts, "|");

  std::vector<Node*> leaves;
  collect_leaves(method, &leaves);
  // properties computed once per leaf, not once per pair (the reference
  // similarly computes Property once per node in its visitor)
  std::vector<std::string> leaf_names;
  leaf_names.reserve(leaves.size());
  for (const Node* leaf : leaves)
    leaf_names.push_back(compute_property(leaf, true).name);
  for (size_t i = 0; i < leaves.size(); ++i) {
    for (size_t j = i + 1; j < leaves.size(); ++j) {
      std::string path = generate_path(leaves[i], leaves[j], options);
      if (path.empty()) continue;
      const std::string path_out =
          options.no_hash ? path : std::to_string(java_hash(path));
      features.contexts.push_back(leaf_names[i] + ',' + path_out + ',' +
                                  leaf_names[j]);
    }
  }
  return features;
}

inline std::vector<MethodFeatures> extract_all(
    Node* root, const std::string& source, const ExtractorOptions& options) {
  std::vector<Node*> methods;
  find_methods(root, &methods);
  std::vector<MethodFeatures> all;
  for (Node* method : methods) {
    long length = method_length_lines(method, source);
    if (length < options.min_code_len || length > options.max_code_len)
      continue;
    MethodFeatures features = extract_method(method, options);
    if (!features.contexts.empty()) all.push_back(std::move(features));
  }
  return all;
}

}  // namespace c2v
