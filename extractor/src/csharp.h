// C# frontend for the native extractor.
//
// Reimplements the reference CSharpExtractor (a Roslyn-based C# program,
// reference CSharpExtractor/): per method, group leaf TOKENS into
// variables by name, enumerate variable pairs (plus self-pairs), reservoir-
// sample up to --max_contexts pairs, and emit token-level AST paths rendered
// with Roslyn SyntaxKind names — `Kind^Kind^...Kind_Kind`, childIds
// (truncated at 3) appended under six parent kinds (Extractor.cs:23-24,
// 90-99), plus COMMENT contexts from the file's comment trivia in
// 5-subtoken batches (Extractor.cs:204-218).
//
// The parser is a pragmatic C# grammar (namespaces, classes, properties,
// the full expression grammar incl. lambdas, ?. ?? is/as, object
// initializers) producing Roslyn-style node kinds so paths line up with the
// reference's vocabulary. Known deviations are listed in
// extractor/README.md.
#pragma once

#include <algorithm>
#include <map>
#include <random>
#include <set>
#include <string>
#include <vector>

#include "java_ast.h"
#include "java_lexer.h"
#include "java_parser.h"  // ParseError
#include "pathctx.h"      // java_hash, ExtractorOptions

namespace c2v {
namespace cs {

// A leaf token: text + the node the path starts from (token.Parent in
// Roslyn terms; the method-name token hangs directly off MethodDeclaration,
// Variable.cs:63-67).
struct CsToken {
  std::string text;
  Node* parent = nullptr;
  bool is_identifier = false;
  bool is_literal = false;
  bool is_predefined_type = false;
};

// ----------------------------------------------------------------- parser
class CsParser {
 public:
  CsParser(std::vector<Token> tokens, Arena* arena)
      : toks_(std::move(tokens)), arena_(arena) {}

  Node* parse_compilation_unit() {
    Node* root = arena_->make("CompilationUnit");
    while (!at_end()) {
      if (accept_punct(";")) continue;
      parse_top_level(root);
    }
    return root;
  }

  // leaf tokens in DFS order, restricted to `scope`'s subtree
  void collect_tokens(Node* scope, std::vector<CsToken>* out) const {
    auto it = tokens_by_node_.find(scope);
    if (it != tokens_by_node_.end())
      out->insert(out->end(), it->second.begin(), it->second.end());
    for (Node* child : scope->children) collect_tokens(child, out);
  }

  const std::vector<std::string>& comments() const { return comments_; }
  void set_comments(std::vector<std::string> comments) {
    comments_ = std::move(comments);
  }

 private:
  std::vector<Token> toks_;
  Arena* arena_;
  size_t i_ = 0;
  std::map<Node*, std::vector<CsToken>> tokens_by_node_;
  int depth_ = 0;
  std::vector<std::string> comments_;

  static const std::set<std::string>& modifiers() {
    static const std::set<std::string> kMods = {
        "public", "protected", "private", "internal", "static", "readonly",
        "sealed", "abstract", "virtual", "override", "async", "partial",
        "const", "new", "extern", "unsafe", "volatile"};
    return kMods;
  }

  static const std::set<std::string>& predefined_types() {
    static const std::set<std::string> kPredef = {
        "bool", "byte", "sbyte", "char", "decimal", "double", "float",
        "int", "uint", "long", "ulong", "short", "ushort", "object",
        "string", "void", "dynamic"};
    return kPredef;
  }

  void add_token(Node* parent, const std::string& text, bool ident,
                 bool literal, bool predefined) {
    tokens_by_node_[parent].push_back(
        CsToken{text, parent, ident, literal, predefined});
  }

  // ------------------------------------------------------- token helpers
  const Token& cur() const { return toks_[i_]; }
  const Token& ahead(size_t n) const {
    size_t j = i_ + n;
    return j < toks_.size() ? toks_[j] : toks_.back();
  }
  bool at_end() const { return cur().kind == Tok::kEnd; }
  void advance() {
    if (!at_end()) ++i_;
  }
  size_t mark() const { return i_; }
  void rewind(size_t m) { i_ = m; }
  bool is_punct(const std::string& p, size_t n = 0) const {
    return ahead(n).kind == Tok::kPunct && ahead(n).text == p;
  }
  bool is_ident(const std::string& w, size_t n = 0) const {
    return ahead(n).kind == Tok::kIdent && ahead(n).text == w;
  }
  bool accept_punct(const std::string& p) {
    if (is_punct(p)) {
      advance();
      return true;
    }
    return false;
  }
  bool accept_ident(const std::string& w) {
    if (is_ident(w)) {
      advance();
      return true;
    }
    return false;
  }
  void expect_punct(const std::string& p) {
    if (!accept_punct(p))
      throw ParseError("expected '" + p + "' got '" + cur().text + "'");
  }
  std::string expect_ident() {
    if (cur().kind != Tok::kIdent)
      throw ParseError("expected identifier, got '" + cur().text + "'");
    std::string name = cur().text;
    advance();
    return name;
  }
  void skip_balanced(const std::string& open, const std::string& close) {
    int depth = 0;
    while (!at_end()) {
      if (is_punct(open)) ++depth;
      if (is_punct(close)) {
        --depth;
        if (depth == 0) {
          advance();
          return;
        }
      }
      advance();
    }
  }

  void skip_attributes() {
    while (is_punct("[")) {
      // attribute lists only appear at declaration positions; statement-
      // level callers never route '[' here
      skip_balanced("[", "]");
    }
  }

  void skip_modifiers() {
    // only called at declaration positions, where every modifier keyword
    // (including 'new' as a hiding modifier) is safe to consume
    while (cur().kind == Tok::kIdent && modifiers().count(cur().text))
      advance();
  }

  void skip_generic_args() {
    if (!is_punct("<")) return;
    int depth = 0;
    while (!at_end()) {
      if (is_punct("<")) ++depth;
      else if (is_punct(">")) --depth;
      else if (is_punct(">>")) depth -= 2;
      advance();
      if (depth <= 0) return;
    }
  }

  // disambiguate `F<int>(x)` from `a < b`: a generic argument list holds
  // only type-shaped tokens and is followed by '('
  bool generic_call_ahead() const {
    if (!is_punct("<")) return false;
    int depth = 0;
    size_t j = 0;
    while (ahead(j).kind != Tok::kEnd && j < 64) {
      const Token& token = ahead(j);
      if (token.kind == Tok::kPunct) {
        if (token.text == "<") ++depth;
        else if (token.text == ">") --depth;
        else if (token.text == ">>") depth -= 2;
        else if (token.text != "," && token.text != "." &&
                 token.text != "?" && token.text != "[" &&
                 token.text != "]")
          return false;
        if (depth <= 0) return is_punct("(", j + 1);
      } else if (token.kind != Tok::kIdent) {
        return false;
      }
      ++j;
    }
    return false;
  }

  void skip_where_clauses() {
    while (is_ident("where")) {
      advance();  // 'where'
      while (!at_end() && !is_punct("{") && !is_punct(";") &&
             !is_punct("=>") && !is_ident("where"))
        advance();
    }
  }

  // ---------------------------------------------------------- top level
  void parse_top_level(Node* root) {
    DepthGuard depth_guard(&depth_);  // nested-namespace cycle
    skip_attributes();
    skip_modifiers();
    if (at_end()) return;
    if (accept_ident("using")) {
      while (!at_end() && !accept_punct(";")) advance();
      return;
    }
    if (accept_ident("namespace")) {
      expect_ident();
      while (accept_punct(".")) expect_ident();
      if (accept_punct(";")) {  // file-scoped namespace
        Node* ns = arena_->make("NamespaceDeclaration");
        root->add(ns);
        while (!at_end()) parse_top_level(ns);
        return;
      }
      Node* ns = arena_->make("NamespaceDeclaration");
      root->add(ns);
      expect_punct("{");
      while (!at_end() && !is_punct("}")) parse_top_level(ns);
      accept_punct("}");
      return;
    }
    if (is_ident("class") || is_ident("struct") || is_ident("interface") ||
        is_ident("record")) {
      root->add(parse_class());
      return;
    }
    if (is_ident("enum")) {
      advance();
      expect_ident();
      while (!at_end() && !is_punct("{")) advance();
      if (is_punct("{")) skip_balanced("{", "}");
      return;
    }
    advance();  // unknown: make progress
  }

  Node* parse_class() {
    DepthGuard depth_guard(&depth_);  // nested-type cycle
    advance();  // class/struct/interface/record
    std::string name = expect_ident();
    Node* decl = arena_->make("ClassDeclaration", name);
    skip_generic_args();
    if (accept_punct(":")) {  // base list
      parse_type();
      while (accept_punct(",")) parse_type();
    }
    skip_where_clauses();
    expect_punct("{");
    while (!at_end() && !is_punct("}")) {
      size_t member_start = mark();
      try {
        parse_member(decl);
      } catch (const ParseError&) {
        rewind(member_start);
        skip_member();
      }
      if (mark() == member_start) skip_member();
    }
    accept_punct("}");
    return decl;
  }

  void skip_member() {
    while (!at_end() && !is_punct("}")) {
      if (is_punct(";")) {
        advance();
        return;
      }
      if (is_punct("{")) {
        skip_balanced("{", "}");
        return;
      }
      advance();
    }
  }

  void parse_member(Node* decl) {
    skip_attributes();
    skip_modifiers();
    if (accept_punct(";")) return;
    if (is_ident("class") || is_ident("struct") || is_ident("interface")) {
      decl->add(parse_class());
      return;
    }
    if (is_ident("enum")) {
      advance();
      expect_ident();
      while (!at_end() && !is_punct("{")) advance();
      if (is_punct("{")) skip_balanced("{", "}");
      return;
    }
    // constructor: Ident '('
    if (cur().kind == Tok::kIdent && is_punct("(", 1)) {
      std::string name = expect_ident();
      Node* ctor = arena_->make("ConstructorDeclaration", name);
      parse_parameter_list(ctor);
      if (accept_punct(":")) {  // : base(...) / this(...)
        expect_ident();
        if (is_punct("(")) skip_balanced("(", ")");
      }
      if (is_punct("{")) ctor->add(parse_block());
      else if (accept_punct("=>")) {
        ctor->add(parse_expression());
        expect_punct(";");
      } else
        expect_punct(";");
      decl->add(ctor);
      return;
    }
    Node* type = parse_type();
    std::string name = expect_ident();
    skip_generic_args();  // generic method type params
    if (is_punct("(")) {
      decl->add(parse_method_rest(type, name));
      return;
    }
    if (is_punct("{") || is_punct("=>")) {
      // property: Type Name { get ... set ... } or expression-bodied
      Node* property = arena_->make("PropertyDeclaration", name);
      property->add(type);
      if (accept_punct("=>")) {
        property->add(parse_expression());
        expect_punct(";");
      } else {
        advance();  // '{'
        while (!at_end() && !is_punct("}")) {
          skip_attributes();
          skip_modifiers();
          if (accept_ident("get") || accept_ident("set") ||
              accept_ident("init") || accept_ident("add") ||
              accept_ident("remove")) {
            if (is_punct("{")) property->add(parse_block());
            else if (accept_punct("=>")) {
              property->add(parse_expression());
              expect_punct(";");
            } else
              accept_punct(";");
          } else {
            advance();
          }
        }
        accept_punct("}");
        if (accept_punct("=")) {  // auto-property initializer
          property->add(parse_expression());
          expect_punct(";");
        }
      }
      decl->add(property);
      return;
    }
    // field
    Node* field = arena_->make("FieldDeclaration");
    Node* var_decl = arena_->make("VariableDeclaration");
    var_decl->add(type);
    field->add(var_decl);
    var_decl->add(parse_variable_declarator(name));
    while (accept_punct(",")) {
      var_decl->add(parse_variable_declarator(expect_ident()));
    }
    expect_punct(";");
    decl->add(field);
  }

  // MethodDeclaration: name token hangs directly off the method node
  // (Roslyn), children = [return type, ParameterList, Block]
  Node* parse_method_rest(Node* return_type, const std::string& name) {
    Node* method = arena_->make("MethodDeclaration", name);
    method->add(return_type);
    add_token(method, name, /*ident=*/true, false, false);
    parse_parameter_list(method);
    skip_where_clauses();
    if (is_punct("{")) {
      method->add(parse_block());
    } else if (accept_punct("=>")) {  // expression-bodied
      Node* arrow = arena_->make("ArrowExpressionClause");
      arrow->add(parse_expression());
      method->add(arrow);
      expect_punct(";");
    } else {
      expect_punct(";");
    }
    return method;
  }

  void parse_parameter_list(Node* owner) {
    Node* parameter_list = arena_->make("ParameterList");
    owner->add(parameter_list);
    expect_punct("(");
    if (accept_punct(")")) return;
    do {
      skip_attributes();
      while (accept_ident("ref") || accept_ident("out") ||
             accept_ident("in") || accept_ident("params") ||
             accept_ident("this"))
        ;
      Node* parameter = arena_->make("Parameter");
      parameter->add(parse_type());
      if (cur().kind == Tok::kIdent) {
        std::string name = expect_ident();
        add_token(parameter, name, true, false, false);
        if (accept_punct("=")) {
          Node* default_value = arena_->make("EqualsValueClause");
          default_value->add(parse_expression());
          parameter->add(default_value);
        }
      }
      parameter_list->add(parameter);
    } while (accept_punct(","));
    expect_punct(")");
  }

  // --------------------------------------------------------------- types
  Node* parse_type() {
    DepthGuard depth_guard(&depth_);
    if (is_punct("(")) {
      // tuple type `(int, string name)` — Roslyn TupleType with
      // TupleElement children (element name is an identifier token)
      advance();
      Node* tuple = arena_->make("TupleType");
      do {
        Node* element = arena_->make("TupleElement");
        element->add(parse_type());
        if (cur().kind == Tok::kIdent &&
            (is_punct(",", 1) || is_punct(")", 1)))
          add_token(element, expect_ident(), true, false, false);
        tuple->add(element);
      } while (accept_punct(","));
      expect_punct(")");
      if (tuple->children.size() < 2)
        throw ParseError("tuple type needs >= 2 elements");
      return maybe_type_suffix(tuple);
    }
    if (cur().kind == Tok::kIdent && predefined_types().count(cur().text)) {
      Node* type = arena_->make("PredefinedType");
      add_token(type, cur().text, false, false, /*predefined=*/true);
      advance();
      return maybe_type_suffix(type);
    }
    if (cur().kind != Tok::kIdent)
      throw ParseError("expected type, got '" + cur().text + "'");
    Node* type = parse_name_for_type();
    return maybe_type_suffix(type);
  }

  Node* parse_name_for_type() {
    std::string name = expect_ident();
    Node* node = arena_->make("IdentifierName");
    add_token(node, name, true, false, false);
    skip_generic_args();
    while (is_punct(".") && ahead(1).kind == Tok::kIdent) {
      advance();
      std::string next_name = expect_ident();
      Node* qualified = arena_->make("QualifiedName");
      Node* right = arena_->make("IdentifierName");
      add_token(right, next_name, true, false, false);
      qualified->add(node);
      qualified->add(right);
      skip_generic_args();
      node = qualified;
    }
    return node;
  }

  Node* maybe_type_suffix(Node* type) {
    while (true) {
      if (accept_punct("?")) {
        Node* nullable = arena_->make("NullableType");
        nullable->add(type);
        type = nullable;
        continue;
      }
      if (is_punct("[") &&
          (is_punct("]", 1) || (is_punct(",", 1) && is_punct("]", 2)))) {
        skip_balanced("[", "]");
        Node* array = arena_->make("ArrayType");
        array->add(type);
        type = array;
        continue;
      }
      return type;
    }
  }

  // ---------------------------------------------------------- statements
  Node* parse_block() {
    DepthGuard depth_guard(&depth_);
    expect_punct("{");
    Node* block = arena_->make("Block", "", true);
    while (!at_end() && !is_punct("}")) block->add(parse_statement());
    expect_punct("}");
    return block;
  }

  Node* parse_statement() {
    DepthGuard depth_guard(&depth_);
    if (is_punct("{")) return parse_block();
    if (accept_punct(";")) return arena_->make("EmptyStatement", "", true);
    if (is_ident("if")) return parse_if();
    if (is_ident("while")) return parse_while();
    if (is_ident("do")) return parse_do();
    if (is_ident("for")) return parse_for();
    if (is_ident("foreach")) return parse_foreach();
    if (is_ident("return")) {
      advance();
      Node* stmt = arena_->make("ReturnStatement", "", true);
      if (!is_punct(";")) stmt->add(parse_expression());
      expect_punct(";");
      return stmt;
    }
    if (is_ident("throw")) {
      advance();
      Node* stmt = arena_->make("ThrowStatement", "", true);
      if (!is_punct(";")) stmt->add(parse_expression());
      expect_punct(";");
      return stmt;
    }
    if (is_ident("break")) {
      advance();
      expect_punct(";");
      return arena_->make("BreakStatement", "", true);
    }
    if (is_ident("continue")) {
      advance();
      expect_punct(";");
      return arena_->make("ContinueStatement", "", true);
    }
    if (is_ident("try")) return parse_try();
    if (is_ident("switch")) return parse_switch();
    if (is_ident("using") && is_punct("(", 1)) {
      advance();
      Node* stmt = arena_->make("UsingStatement", "", true);
      expect_punct("(");
      Node* decl = try_parse_variable_declaration();
      stmt->add(decl ? decl : parse_expression());
      expect_punct(")");
      stmt->add(parse_statement());
      return stmt;
    }
    if (is_ident("using") && ahead(1).kind == Tok::kIdent) {
      // C# 8 using DECLARATION `using var f = Open(p);` — Roslyn kind is
      // still LocalDeclarationStatement (using is just a token on it)
      advance();
      Node* decl = try_parse_variable_declaration();
      if (decl && accept_punct(";")) {
        Node* stmt = arena_->make("LocalDeclarationStatement", "", true);
        stmt->add(decl);
        return stmt;
      }
      throw ParseError("malformed using declaration");
    }
    if (is_ident("lock")) {
      advance();
      Node* stmt = arena_->make("LockStatement", "", true);
      expect_punct("(");
      stmt->add(parse_expression());
      expect_punct(")");
      stmt->add(parse_statement());
      return stmt;
    }
    if (is_ident("var") || cur().kind == Tok::kIdent || is_punct("(")) {
      size_t m = mark();
      Node* fn = try_parse_local_function();
      if (fn) return fn;
      rewind(m);
      Node* decl = try_parse_variable_declaration();
      if (decl && accept_punct(";")) {
        Node* stmt = arena_->make("LocalDeclarationStatement", "", true);
        stmt->add(decl);
        return stmt;
      }
      rewind(m);
    }
    Node* stmt = arena_->make("ExpressionStatement", "", true);
    stmt->add(parse_expression());
    expect_punct(";");
    return stmt;
  }

  // Local function `int Local(int k) { ... }` inside a block — Roslyn
  // LocalFunctionStatement: NOT a MethodDeclaration, so its leaves stay
  // inside the enclosing method's bag (the reference's visitor descends
  // MethodDeclarationSyntax only). Returns nullptr (caller rewinds) when
  // the statement is not a local function.
  Node* try_parse_local_function() {
    try {
      while (is_ident("async") || is_ident("static") || is_ident("unsafe"))
        advance();
      if (cur().kind != Tok::kIdent && !is_punct("(")) return nullptr;
      Node* type;
      if (is_ident("var")) return nullptr;  // `var f = ...` is a decl
      type = parse_type();
      if (cur().kind != Tok::kIdent) return nullptr;
      if (!is_punct("(", 1) && !is_punct("<", 1)) return nullptr;
      std::string name = expect_ident();
      skip_generic_args();
      if (!is_punct("(")) return nullptr;
      Node* fn = arena_->make("LocalFunctionStatement", name, true);
      fn->add(type);
      add_token(fn, name, /*ident=*/true, false, false);
      parse_parameter_list(fn);
      skip_where_clauses();
      if (is_punct("{")) {
        fn->add(parse_block());
      } else if (accept_punct("=>")) {
        Node* arrow = arena_->make("ArrowExpressionClause");
        arrow->add(parse_expression());
        fn->add(arrow);
        expect_punct(";");
      } else {
        return nullptr;
      }
      return fn;
    } catch (const ParseError&) {
      return nullptr;
    }
  }

  // VariableDeclaration: [type, VariableDeclarator...]; 'var' is NOT a
  // leaf token (reference Tree.cs:168-175)
  Node* try_parse_variable_declaration() {
    try {
      if (cur().kind != Tok::kIdent && !is_punct("(")) return nullptr;
      Node* type;
      if (is_ident("var") && ahead(1).kind == Tok::kIdent) {
        advance();
        type = arena_->make("IdentifierName", "var");  // no leaf token
      } else {
        type = parse_type();  // handles tuple types `(int, string) p`
      }
      if (cur().kind != Tok::kIdent) return nullptr;
      const Token& after = ahead(1);
      if (!(after.kind == Tok::kPunct &&
            (after.text == "=" || after.text == ";" || after.text == ",")))
        return nullptr;
      Node* decl = arena_->make("VariableDeclaration");
      decl->add(type);
      decl->add(parse_variable_declarator(expect_ident()));
      while (accept_punct(","))
        decl->add(parse_variable_declarator(expect_ident()));
      return decl;
    } catch (const ParseError&) {
      return nullptr;
    }
  }

  Node* parse_variable_declarator(const std::string& name) {
    Node* declarator = arena_->make("VariableDeclarator", name);
    add_token(declarator, name, true, false, false);
    if (accept_punct("=")) {
      Node* init = arena_->make("EqualsValueClause");
      init->add(is_punct("{") ? parse_array_initializer()
                              : parse_expression());
      declarator->add(init);
    }
    return declarator;
  }

  Node* parse_if() {
    advance();
    Node* stmt = arena_->make("IfStatement", "", true);
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    stmt->add(parse_statement());
    if (accept_ident("else")) {
      Node* else_clause = arena_->make("ElseClause");
      else_clause->add(parse_statement());
      stmt->add(else_clause);
    }
    return stmt;
  }

  Node* parse_while() {
    advance();
    Node* stmt = arena_->make("WhileStatement", "", true);
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    stmt->add(parse_statement());
    return stmt;
  }

  Node* parse_do() {
    advance();
    Node* stmt = arena_->make("DoStatement", "", true);
    stmt->add(parse_statement());
    if (!accept_ident("while")) throw ParseError("expected while");
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    expect_punct(";");
    return stmt;
  }

  Node* parse_for() {
    advance();
    Node* stmt = arena_->make("ForStatement", "", true);
    expect_punct("(");
    if (!is_punct(";")) {
      Node* init = try_parse_variable_declaration();
      if (init) stmt->add(init);
      else {
        stmt->add(parse_expression());
        while (accept_punct(",")) stmt->add(parse_expression());
      }
    }
    expect_punct(";");
    if (!is_punct(";")) stmt->add(parse_expression());
    expect_punct(";");
    if (!is_punct(")")) {
      stmt->add(parse_expression());
      while (accept_punct(",")) stmt->add(parse_expression());
    }
    expect_punct(")");
    stmt->add(parse_statement());
    return stmt;
  }

  Node* parse_foreach() {
    advance();
    // `foreach (var (a, b) in ...)` — Roslyn ForEachVariableStatement
    // with a ParenthesizedVariableDesignation holding the names
    Node* stmt = arena_->make("ForEachStatement", "", true);
    expect_punct("(");
    if (is_ident("var")) {
      advance();
      if (is_punct("(")) {
        stmt->raw_type = "ForEachVariableStatement";
        stmt->type = "ForEachVariableStatement";
        advance();
        // Roslyn wraps the designation in a DeclarationExpression whose
        // type is IdentifierName("var") — `var` is not a leaf token
        // (reference Tree.cs:168-175), matching the typed branch's shape
        Node* declaration = arena_->make("DeclarationExpression");
        declaration->add(arena_->make("IdentifierName", "var"));
        Node* designation =
            arena_->make("ParenthesizedVariableDesignation");
        do {
          Node* single = arena_->make("SingleVariableDesignation");
          add_token(single, expect_ident(), true, false, false);
          designation->add(single);
        } while (accept_punct(","));
        expect_punct(")");
        declaration->add(designation);
        stmt->add(declaration);
        if (!accept_ident("in")) throw ParseError("expected in");
        stmt->add(parse_expression());
        expect_punct(")");
        stmt->add(parse_statement());
        return stmt;
      }
    } else {
      if (is_punct("(")) {
        // explicitly-typed deconstruction `foreach ((int a, int b) in
        // xs)` — Roslyn: ForEachVariableStatement whose variable is a
        // TupleExpression of DeclarationExpressions
        size_t m = mark();
        try {
          advance();
          Node* tuple = arena_->make("TupleExpression");
          do {
            Node* argument = arena_->make("Argument");
            Node* declaration = arena_->make("DeclarationExpression");
            declaration->add(parse_type());
            Node* single = arena_->make("SingleVariableDesignation");
            add_token(single, expect_ident(), true, false, false);
            declaration->add(single);
            argument->add(declaration);
            tuple->add(argument);
          } while (accept_punct(","));
          expect_punct(")");
          if (!accept_ident("in")) throw ParseError("expected in");
          stmt->raw_type = "ForEachVariableStatement";
          stmt->type = "ForEachVariableStatement";
          stmt->add(tuple);
          stmt->add(parse_expression());
          expect_punct(")");
          stmt->add(parse_statement());
          return stmt;
        } catch (const ParseError&) {
          rewind(m);
        }
      }
      stmt->add(parse_type());
    }
    std::string name = expect_ident();
    add_token(stmt, name, true, false, false);
    if (!accept_ident("in")) throw ParseError("expected in");
    stmt->add(parse_expression());
    expect_punct(")");
    stmt->add(parse_statement());
    return stmt;
  }

  Node* parse_try() {
    advance();
    Node* stmt = arena_->make("TryStatement", "", true);
    stmt->add(parse_block());
    while (is_ident("catch")) {
      advance();
      Node* clause = arena_->make("CatchClause");
      if (is_punct("(")) {
        advance();
        Node* decl = arena_->make("CatchDeclaration");
        decl->add(parse_type());
        if (cur().kind == Tok::kIdent)
          add_token(decl, expect_ident(), true, false, false);
        clause->add(decl);
        expect_punct(")");
      }
      if (accept_ident("when")) {
        expect_punct("(");
        clause->add(parse_expression());
        expect_punct(")");
      }
      clause->add(parse_block());
      stmt->add(clause);
    }
    if (accept_ident("finally")) {
      Node* fin = arena_->make("FinallyClause");
      fin->add(parse_block());
      stmt->add(fin);
    }
    return stmt;
  }

  Node* parse_switch() {
    advance();
    Node* stmt = arena_->make("SwitchStatement", "", true);
    expect_punct("(");
    stmt->add(parse_expression());
    expect_punct(")");
    expect_punct("{");
    while (!at_end() && !is_punct("}")) {
      Node* section = arena_->make("SwitchSection", "", true);
      while (is_ident("case") || is_ident("default")) {
        if (accept_ident("case")) {
          section->add(parse_expression());
          if (accept_ident("when")) section->add(parse_expression());
        } else {
          advance();  // default
        }
        expect_punct(":");
      }
      while (!at_end() && !is_punct("}") && !is_ident("case") &&
             !is_ident("default"))
        section->add(parse_statement());
      stmt->add(section);
    }
    expect_punct("}");
    return stmt;
  }

  // SwitchExpressionArm patterns — the pragmatic subset the corpus
  // actually hits (Roslyn kinds): DiscardPattern `_`, RelationalPattern
  // `> 5`, NotPattern `not null`, DeclarationPattern `int n`,
  // ConstantPattern everything-else.
  Node* parse_switch_pattern() {
    // `,` / `)` lookahead: a discard inside a positional pattern —
    // `(_, 0) => ...` — is a DiscardPattern subpattern (Roslyn emits no
    // identifier leaf for it); without these it fell through to
    // ConstantPattern with a spurious `_` leaf (ADVICE r5).
    if (is_ident("_") && (is_punct("=>", 1) || is_ident("when", 1) ||
                          is_punct(",", 1) || is_punct(")", 1)))
      { advance(); return arena_->make("DiscardPattern"); }
    static const char* kRel[] = {">=", "<=", ">", "<"};
    for (const char* op : kRel) {
      if (is_punct(op)) {
        advance();
        Node* rel = arena_->make("RelationalPattern");
        rel->add(parse_binary(0));
        return rel;
      }
    }
    if (is_ident("not")) {
      advance();
      Node* not_pattern = arena_->make("NotPattern");
      not_pattern->add(parse_switch_pattern());
      return not_pattern;
    }
    if (is_punct("(")) {
      // positional pattern `(0, 0)` — Roslyn RecursivePattern with a
      // PositionalPatternClause of Subpatterns. MUST be handled here:
      // the ConstantPattern fallback's expression parse would see
      // `(0, 0) =>` as a parenthesized LAMBDA and die on the literal
      // "parameters", dropping the method.
      advance();
      Node* recursive = arena_->make("RecursivePattern");
      Node* positional = arena_->make("PositionalPatternClause");
      do {
        Node* sub = arena_->make("Subpattern");
        sub->add(parse_switch_pattern());
        positional->add(sub);
      } while (accept_punct(","));
      expect_punct(")");
      recursive->add(positional);
      return recursive;
    }
    size_t m = mark();
    try {
      Node* type = parse_type();
      if (cur().kind == Tok::kIdent && !is_ident("when") &&
          !predefined_types().count(cur().text)) {
        Node* decl_pattern = arena_->make("DeclarationPattern");
        decl_pattern->add(type);
        add_token(decl_pattern, expect_ident(), true, false, false);
        return decl_pattern;
      }
      throw ParseError("not a declaration pattern");
    } catch (const ParseError&) {
      rewind(m);
    }
    Node* constant = arena_->make("ConstantPattern");
    constant->add(parse_binary(0));
    return constant;
  }

  Node* parse_switch_expression(Node* governed) {
    advance();  // 'switch'
    Node* sw = arena_->make("SwitchExpression");
    sw->add(governed);
    expect_punct("{");
    while (!at_end() && !is_punct("}")) {
      Node* arm = arena_->make("SwitchExpressionArm");
      arm->add(parse_switch_pattern());
      if (accept_ident("when")) {
        Node* when = arena_->make("WhenClause");
        when->add(parse_expression());
        arm->add(when);
      }
      expect_punct("=>");
      arm->add(parse_expression());
      sw->add(arm);
      if (!accept_punct(",")) break;
    }
    expect_punct("}");
    return sw;
  }

  // LINQ query syntax — Roslyn QueryExpression: FromClause + QueryBody
  // holding Where/Let/OrderBy/Join/Select/Group clauses (and
  // QueryContinuation after `into`). The reference's Roslyn parse puts
  // all of these node kinds on paths; clause keywords are contextual,
  // so this is only entered behind parse_primary's from-lookahead.
  Node* parse_from_clause() {
    advance();  // 'from'
    Node* from = arena_->make("FromClause");
    if (!(cur().kind == Tok::kIdent && is_ident("in", 1)))
      from->add(parse_type());  // `from int x in ...`
    add_token(from, expect_ident(), true, false, false);
    if (!accept_ident("in")) throw ParseError("expected 'in' in query");
    from->add(parse_expression());
    return from;
  }

  Node* parse_query_expression() {
    Node* query = arena_->make("QueryExpression");
    query->add(parse_from_clause());
    Node* body = arena_->make("QueryBody");
    query->add(body);
    while (true) {
      if (is_ident("from") && ahead(1).kind == Tok::kIdent) {
        body->add(parse_from_clause());
      } else if (is_ident("where")) {
        advance();
        Node* where = arena_->make("WhereClause");
        where->add(parse_expression());
        body->add(where);
      } else if (is_ident("let")) {
        advance();
        Node* let = arena_->make("LetClause");
        add_token(let, expect_ident(), true, false, false);
        expect_punct("=");
        let->add(parse_expression());
        body->add(let);
      } else if (is_ident("orderby")) {
        advance();
        Node* orderby = arena_->make("OrderByClause");
        do {
          Node* key = parse_expression();
          const char* kind = "AscendingOrdering";
          if (accept_ident("descending")) kind = "DescendingOrdering";
          else accept_ident("ascending");
          Node* ordering = arena_->make(kind);
          ordering->add(key);
          orderby->add(ordering);
        } while (accept_punct(","));
        body->add(orderby);
      } else if (is_ident("join")) {
        advance();
        Node* join = arena_->make("JoinClause");
        if (!(cur().kind == Tok::kIdent && is_ident("in", 1)))
          join->add(parse_type());
        add_token(join, expect_ident(), true, false, false);
        if (!accept_ident("in")) throw ParseError("join needs 'in'");
        join->add(parse_expression());
        if (!accept_ident("on")) throw ParseError("join needs 'on'");
        join->add(parse_expression());
        if (!accept_ident("equals")) throw ParseError("join needs 'equals'");
        join->add(parse_expression());
        if (accept_ident("into")) {
          Node* into = arena_->make("JoinIntoClause");
          add_token(into, expect_ident(), true, false, false);
          join->add(into);
        }
        body->add(join);
      } else if (is_ident("select")) {
        advance();
        Node* select = arena_->make("SelectClause");
        select->add(parse_expression());
        body->add(select);
        if (accept_ident("into")) {
          // Roslyn nests post-`into` clauses under the continuation's
          // OWN QueryBody — mirror that so `into` paths match
          Node* continuation = arena_->make("QueryContinuation");
          add_token(continuation, expect_ident(), true, false, false);
          body->add(continuation);
          body = arena_->make("QueryBody");
          continuation->add(body);
          continue;
        }
        break;
      } else if (is_ident("group")) {
        advance();
        Node* group = arena_->make("GroupClause");
        group->add(parse_expression());
        if (!accept_ident("by")) throw ParseError("group needs 'by'");
        group->add(parse_expression());
        body->add(group);
        if (accept_ident("into")) {
          Node* continuation = arena_->make("QueryContinuation");
          add_token(continuation, expect_ident(), true, false, false);
          body->add(continuation);
          body = arena_->make("QueryBody");
          continuation->add(body);
          continue;
        }
        break;
      } else {
        break;
      }
    }
    return query;
  }

  Node* parse_array_initializer() {
    expect_punct("{");
    Node* init = arena_->make("InitializerExpression");
    while (!at_end() && !is_punct("}")) {
      init->add(is_punct("{") ? parse_array_initializer()
                              : parse_expression());
      if (!accept_punct(",")) break;
    }
    expect_punct("}");
    return init;
  }

  // --------------------------------------------------------- expressions
  Node* parse_expression() { return parse_assignment(); }

  Node* parse_assignment() {
    DepthGuard depth_guard(&depth_);
    Node* left = parse_ternary();
    static const std::pair<const char*, const char*> kAssign[] = {
        {"=", "SimpleAssignmentExpression"},
        {"+=", "AddAssignmentExpression"},
        {"-=", "SubtractAssignmentExpression"},
        {"*=", "MultiplyAssignmentExpression"},
        {"/=", "DivideAssignmentExpression"},
        {"%=", "ModuloAssignmentExpression"},
        {"&=", "AndAssignmentExpression"},
        {"|=", "OrAssignmentExpression"},
        {"^=", "ExclusiveOrAssignmentExpression"},
        {"<<=", "LeftShiftAssignmentExpression"},
        {">>=", "RightShiftAssignmentExpression"},
        {"?\?=", "CoalesceAssignmentExpression"}};
    for (const auto& [text, kind] : kAssign) {
      if (is_punct(text)) {
        advance();
        Node* assign = arena_->make(kind);
        assign->add(left);
        assign->add(is_punct("{") ? parse_array_initializer()
                                  : parse_assignment());
        return assign;
      }
    }
    return left;
  }

  Node* parse_ternary() {
    Node* condition = parse_binary(0);
    if (is_punct("?") && !is_punct("?.")) {
      advance();
      Node* ternary = arena_->make("ConditionalExpression");
      ternary->add(condition);
      ternary->add(parse_expression());
      expect_punct(":");
      ternary->add(parse_expression());
      return ternary;
    }
    return condition;
  }

  struct BinOp {
    const char* text;
    const char* kind;
    int prec;
  };

  static const std::vector<BinOp>& binary_ops() {
    // precedence starts at 1: parse_binary(0) matches ops with prec >= 1
    static const std::vector<BinOp> kOps = {
        {"??", "CoalesceExpression", 1},
        {"||", "LogicalOrExpression", 2},
        {"&&", "LogicalAndExpression", 3},
        {"|", "BitwiseOrExpression", 4},
        {"^", "ExclusiveOrExpression", 5},
        {"&", "BitwiseAndExpression", 6},
        {"==", "EqualsExpression", 7},
        {"!=", "NotEqualsExpression", 7},
        {"<", "LessThanExpression", 8},
        {">", "GreaterThanExpression", 8},
        {"<=", "LessThanOrEqualExpression", 8},
        {">=", "GreaterThanOrEqualExpression", 8},
        {"<<", "LeftShiftExpression", 9},
        {">>", "RightShiftExpression", 9},
        {"+", "AddExpression", 10},
        {"-", "SubtractExpression", 10},
        {"*", "MultiplyExpression", 11},
        {"/", "DivideExpression", 11},
        {"%", "ModuloExpression", 11}};
    return kOps;
  }

  const BinOp* current_binop(int min_prec) {
    if (cur().kind != Tok::kPunct) return nullptr;
    for (const auto& op : binary_ops())
      if (cur().text == op.text && op.prec >= min_prec) return &op;
    return nullptr;
  }

  Node* parse_binary(int min_prec) {
    Node* left = parse_unary();
    // postfix `expr switch { pattern => value, ... }` (C# 8) — Roslyn
    // binds the switch to the UNARY operand (`a + b switch {...}` is
    // `a + (b switch {...})`), so the hook sits before the binary loop
    while (is_ident("switch") && is_punct("{", 1))
      left = parse_switch_expression(left);
    while (true) {
      if (is_ident("is") || is_ident("as")) {
        bool is_is = is_ident("is");
        advance();
        Node* check =
            arena_->make(is_is ? "IsExpression" : "AsExpression");
        check->add(left);
        check->add(parse_type());
        if (is_is && cur().kind == Tok::kIdent &&
            !is_ident("is") && !is_ident("as"))
          add_token(check, expect_ident(), true, false, false);  // pattern
        left = check;
        continue;
      }
      const BinOp* op = current_binop(min_prec + 1);
      if (!op) return left;
      advance();
      Node* right = parse_binary(op->prec);
      Node* binary = arena_->make(op->kind);
      binary->add(left);
      binary->add(right);
      left = binary;
    }
  }

  Node* parse_unary() {
    DepthGuard depth_guard(&depth_);
    // `await expr` — contextual keyword: only when a unary expression
    // can actually start at the next token (a bare `await;` or
    // `await + 1` where await is a variable keeps parsing as an
    // identifier use)
    if (is_ident("await")) {
      const Token& next = ahead(1);
      bool starts_unary =
          next.kind == Tok::kIdent || next.kind == Tok::kIntLit ||
          next.kind == Tok::kFloatLit || next.kind == Tok::kStringLit ||
          next.kind == Tok::kCharLit ||
          (next.kind == Tok::kPunct &&
           (next.text == "(" || next.text == "!" || next.text == "~" ||
            next.text == "++" || next.text == "--" ||
            // prefix sign: `await -Fetch(id)` is
            // AwaitExpression(UnaryMinus(...)), not a SubtractExpression
            // with an `await` identifier leaf (ADVICE r5). The traded-
            // away reading — a VARIABLE named await in `await - x` — is
            // far rarer than the keyword in async-heavy corpora.
            next.text == "-" || next.text == "+"));
      if (starts_unary) {
        advance();
        Node* await_expr = arena_->make("AwaitExpression");
        await_expr->add(parse_unary());
        return await_expr;
      }
    }
    static const std::pair<const char*, const char*> kPrefix[] = {
        {"+", "UnaryPlusExpression"},
        {"-", "UnaryMinusExpression"},
        {"!", "LogicalNotExpression"},
        {"~", "BitwiseNotExpression"},
        {"++", "PreIncrementExpression"},
        {"--", "PreDecrementExpression"}};
    for (const auto& [text, kind] : kPrefix) {
      if (is_punct(text)) {
        advance();
        Node* unary = arena_->make(kind);
        unary->add(parse_unary());
        return unary;
      }
    }
    if (is_punct("(")) {  // tentative cast
      size_t m = mark();
      advance();
      try {
        Node* type = parse_type();
        if (accept_punct(")")) {
          bool target = cur().kind == Tok::kIdent ||
                        cur().kind == Tok::kIntLit ||
                        cur().kind == Tok::kFloatLit ||
                        cur().kind == Tok::kStringLit ||
                        cur().kind == Tok::kCharLit || is_punct("(");
          // `(a, b)` parses as a TupleType of identifier "types", so a
          // tuple LITERAL followed by a contextual keyword (`(a, b)
          // switch {...}`, `(a, b) is ...`) would commit as a cast and
          // blow up at the keyword, dropping the method. Tuple casts
          // require double parens and are vanishingly rare; never
          // commit a cast from a TupleType — the rewind lands in the
          // tuple-literal path below.
          if (type->raw_type == "TupleType") target = false;
          if (target) {
            Node* cast = arena_->make("CastExpression");
            cast->add(type);
            cast->add(parse_unary());
            return parse_postfix_ops(cast);
          }
        }
      } catch (const ParseError&) {
      }
      rewind(m);
    }
    Node* expr = parse_primary();
    expr = parse_postfix_ops(expr);
    if (is_punct("++")) {
      advance();
      Node* unary = arena_->make("PostIncrementExpression");
      unary->add(expr);
      return unary;
    }
    if (is_punct("--")) {
      advance();
      Node* unary = arena_->make("PostDecrementExpression");
      unary->add(expr);
      return unary;
    }
    return expr;
  }

  void parse_argument_list(Node* owner, const std::string& kind,
                           const std::string& open,
                           const std::string& close) {
    Node* argument_list = arena_->make(kind);
    owner->add(argument_list);
    expect_punct(open);
    if (accept_punct(close)) return;
    do {
      while (accept_ident("ref") || accept_ident("out") ||
             accept_ident("in"))
        if (is_ident("var")) advance();
      Node* argument = arena_->make("Argument");
      if (cur().kind == Tok::kIdent && is_punct(":", 1) &&
          !is_punct("::", 1)) {
        advance();  // named argument label
        advance();
      }
      argument->add(parse_expression());
      argument_list->add(argument);
    } while (accept_punct(","));
    expect_punct(close);
  }

  Node* parse_postfix_ops(Node* expr) {
    while (true) {
      if (is_punct(".") || is_punct("?.")) {
        bool conditional = is_punct("?.");
        advance();
        std::string name = expect_ident();
        if (generic_call_ahead()) skip_generic_args();
        Node* name_node = arena_->make("IdentifierName");
        add_token(name_node, name, true, false, false);
        Node* access = arena_->make(
            conditional ? "ConditionalAccessExpression"
                        : "SimpleMemberAccessExpression");
        access->add(expr);
        access->add(name_node);
        if (is_punct("(")) {
          Node* call = arena_->make("InvocationExpression");
          call->add(access);
          parse_argument_list(call, "ArgumentList", "(", ")");
          expr = call;
        } else {
          expr = access;
        }
        continue;
      }
      if (is_punct("(")) {
        Node* call = arena_->make("InvocationExpression");
        call->add(expr);
        parse_argument_list(call, "ArgumentList", "(", ")");
        expr = call;
        continue;
      }
      if (is_punct("[")) {
        Node* access = arena_->make("ElementAccessExpression");
        access->add(expr);
        parse_argument_list(access, "BracketedArgumentList", "[", "]");
        expr = access;
        continue;
      }
      return expr;
    }
  }

  bool lambda_ahead() {
    if (cur().kind == Tok::kIdent && is_punct("=>", 1)) return true;
    if (!is_punct("(")) return false;
    int depth = 0;
    size_t j = 0;
    while (ahead(j).kind != Tok::kEnd) {
      if (ahead(j).kind == Tok::kPunct) {
        if (ahead(j).text == "(") ++depth;
        if (ahead(j).text == ")") {
          --depth;
          if (depth == 0)
            return ahead(j + 1).kind == Tok::kPunct &&
                   ahead(j + 1).text == "=>";
        }
      }
      ++j;
    }
    return false;
  }

  Node* parse_lambda() {
    if (cur().kind == Tok::kIdent) {
      Node* lambda = arena_->make("SimpleLambdaExpression");
      Node* parameter = arena_->make("Parameter");
      add_token(parameter, expect_ident(), true, false, false);
      lambda->add(parameter);
      expect_punct("=>");
      lambda->add(is_punct("{") ? parse_block() : parse_expression());
      return lambda;
    }
    Node* lambda = arena_->make("ParenthesizedLambdaExpression");
    expect_punct("(");
    while (!is_punct(")") && !at_end()) {
      Node* parameter = arena_->make("Parameter");
      size_t m = mark();
      try {
        Node* type = parse_type();
        if (cur().kind == Tok::kIdent) {
          parameter->add(type);
          add_token(parameter, expect_ident(), true, false, false);
        } else {
          throw ParseError("untyped");
        }
      } catch (const ParseError&) {
        rewind(m);
        add_token(parameter, expect_ident(), true, false, false);
      }
      lambda->add(parameter);
      if (!accept_punct(",")) break;
    }
    expect_punct(")");
    expect_punct("=>");
    lambda->add(is_punct("{") ? parse_block() : parse_expression());
    return lambda;
  }

  Node* parse_primary() {
    if (lambda_ahead()) return parse_lambda();
    // LINQ query: `from [Type] x in ...` — tentative parse so a plain
    // identifier named `from` keeps parsing as an identifier, while
    // arbitrarily-shaped range-variable types (qualified, generic,
    // array) still enter the query path (parse_from_clause throws when
    // no `in` follows, which rewinds us out)
    if (is_ident("from") && ahead(1).kind == Tok::kIdent) {
      size_t m = mark();
      try {
        return parse_query_expression();
      } catch (const ParseError&) {
        rewind(m);
      }
    }
    const Token& token = cur();
    switch (token.kind) {
      case Tok::kIntLit:
      case Tok::kFloatLit: {
        advance();
        Node* literal = arena_->make("NumericLiteralExpression");
        add_token(literal, token.text, false, true, false);
        return literal;
      }
      case Tok::kCharLit: {
        advance();
        Node* literal = arena_->make("CharacterLiteralExpression");
        add_token(literal, token.text, false, true, false);
        return literal;
      }
      case Tok::kStringLit: {
        advance();
        Node* literal = arena_->make("StringLiteralExpression");
        add_token(literal, token.text, false, true, false);
        return literal;
      }
      case Tok::kIdent:
        break;
      case Tok::kPunct:
        if (is_punct("(")) {
          advance();
          Node* first = parse_expression();
          if (is_punct(",")) {
            // tuple literal `(a, b)` — Roslyn TupleExpression with
            // Argument children
            Node* tuple = arena_->make("TupleExpression");
            Node* first_arg = arena_->make("Argument");
            first_arg->add(first);
            tuple->add(first_arg);
            while (accept_punct(",")) {
              Node* argument = arena_->make("Argument");
              argument->add(parse_expression());
              tuple->add(argument);
            }
            expect_punct(")");
            return tuple;
          }
          Node* enclosed = arena_->make("ParenthesizedExpression");
          enclosed->add(first);
          expect_punct(")");
          return enclosed;
        }
        throw ParseError("unexpected token '" + token.text + "'");
      default:
        throw ParseError("unexpected end of input");
    }
    if (is_ident("new")) {
      advance();
      Node* creation = arena_->make("ObjectCreationExpression");
      if (cur().kind == Tok::kIdent) creation->add(parse_type());
      if (is_punct("("))
        parse_argument_list(creation, "ArgumentList", "(", ")");
      if (is_punct("[")) skip_balanced("[", "]");  // array ranks
      if (is_punct("{")) creation->add(parse_array_initializer());
      return creation;
    }
    if (is_ident("true") || is_ident("false")) {
      Node* literal = arena_->make(is_ident("true")
                                       ? "TrueLiteralExpression"
                                       : "FalseLiteralExpression");
      advance();
      return literal;
    }
    if (is_ident("null")) {
      advance();
      return arena_->make("NullLiteralExpression");
    }
    if (is_ident("this")) {
      advance();
      return arena_->make("ThisExpression");
    }
    if (is_ident("base")) {
      advance();
      return arena_->make("BaseExpression");
    }
    if (is_ident("typeof") || is_ident("nameof") || is_ident("default") ||
        is_ident("sizeof")) {
      std::string which = cur().text;
      advance();
      Node* expr = arena_->make(
          which == "typeof" ? "TypeOfExpression"
          : which == "nameof" ? "InvocationExpression"
          : which == "default" ? "DefaultExpression"
                               : "SizeOfExpression");
      if (is_punct("(")) {
        advance();
        if (!is_punct(")")) {
          size_t m = mark();
          try {
            expr->add(parse_type());
            if (!is_punct(")")) throw ParseError("not a type");
          } catch (const ParseError&) {
            rewind(m);
            expr->add(parse_expression());
          }
        }
        expect_punct(")");
      }
      return expr;
    }
    if (predefined_types().count(cur().text)) {
      Node* type = arena_->make("PredefinedType");
      add_token(type, cur().text, false, false, true);
      advance();
      return type;
    }
    std::string name = expect_ident();
    if (generic_call_ahead()) skip_generic_args();
    Node* node = arena_->make("IdentifierName");
    add_token(node, name, true, false, false);
    return node;
  }
};

// ------------------------------------------------------------- extraction
// reference Utilities.cs NormalizeName (C# variant: NUM whitelist
// {0,1,2,3,4,5,10}, no careful-strip fallback)
inline std::string cs_normalize_name(const std::string& original) {
  static const std::set<std::string> kKeep = {"0", "1", "2", "3",
                                              "4", "5", "10"};
  std::string partially;
  for (size_t i = 0; i < original.size(); ++i) {
    char c = original[i];
    if (c == '\\' && i + 1 < original.size() && original[i + 1] == 'n') {
      ++i;
      continue;
    }
    if (std::isspace(static_cast<unsigned char>(c))) continue;
    unsigned char uc = static_cast<unsigned char>(c);
    if (uc >= 0x80) continue;  // non-ascii dropped
    partially.push_back(
        static_cast<char>(std::tolower(static_cast<unsigned char>(c))));
  }
  std::string completely;
  for (char c : partially)
    if (std::isalpha(static_cast<unsigned char>(c)))
      completely.push_back(c);
  if (!completely.empty()) return completely;
  bool all_digits = !partially.empty();
  for (char c : partially)
    if (!std::isdigit(static_cast<unsigned char>(c))) all_digits = false;
  if (all_digits) return kKeep.count(partially) ? partially : "NUM";
  return std::string();
}

inline std::vector<std::string> cs_split_subtokens(const std::string& name) {
  // same boundaries as the Java splitter, but parts normalized with the C#
  // rules (Utilities.cs:92-101)
  std::vector<std::string> parts;
  std::string current;
  std::string trimmed = name;
  auto flush = [&]() {
    if (!current.empty()) {
      std::string normalized = cs_normalize_name(current);
      if (!normalized.empty()) parts.push_back(normalized);
      current.clear();
    }
  };
  for (size_t i = 0; i < trimmed.size(); ++i) {
    char c = trimmed[i];
    if (c == '_' || std::isdigit(static_cast<unsigned char>(c)) ||
        std::isspace(static_cast<unsigned char>(c))) {
      flush();
      continue;
    }
    bool lower_to_upper =
        i > 0 && std::islower(static_cast<unsigned char>(trimmed[i - 1])) &&
        std::isupper(static_cast<unsigned char>(c));
    bool acronym_end =
        i + 1 < trimmed.size() &&
        std::isupper(static_cast<unsigned char>(c)) && i > 0 &&
        std::isupper(static_cast<unsigned char>(trimmed[i - 1])) &&
        std::islower(static_cast<unsigned char>(trimmed[i + 1]));
    if (lower_to_upper || acronym_end) flush();
    current.push_back(c);
  }
  flush();
  return parts;
}

// reference Extractor.cs:139-162
inline std::string cs_split_name_unless_empty(const std::string& original) {
  std::vector<std::string> subtokens = cs_split_subtokens(original);
  std::string name = join(subtokens, "|");
  if (name.empty()) name = cs_normalize_name(original);
  if (name.empty()) {
    bool all_space = !original.empty();
    for (char c : original)
      if (!std::isspace(static_cast<unsigned char>(c))) all_space = false;
    name = all_space ? "SPACE" : "BLANK";
  }
  if (original == "METHOD_NAME") name = original;
  return name;
}

inline const std::set<std::string>& cs_child_id_parent_kinds() {
  // reference Extractor.cs:23-24
  static const std::set<std::string> kKinds = {
      "SimpleAssignmentExpression", "ElementAccessExpression",
      "SimpleMemberAccessExpression", "InvocationExpression",
      "BracketedArgumentList", "ArgumentList"};
  return kKinds;
}

inline int cs_depth(const Node* node, const Node* root) {
  int depth = 0;
  while (node != root && node != nullptr) {
    node = node->parent;
    ++depth;
  }
  return depth;
}

// reference PathFinder.cs:82-111 + Extractor.cs:46-99
inline std::string cs_find_path(const CsToken& left, const CsToken& right,
                                const Node* method_root,
                                const ExtractorOptions& options) {
  const Node* l = left.parent;
  const Node* r = right.parent;
  int dl = cs_depth(l, method_root);
  int dr = cs_depth(r, method_root);
  // LCA by depth equalization
  const Node* a = l;
  const Node* b = r;
  int da = dl, db = dr;
  while (a != b) {
    if (da >= db) {
      a = a->parent;
      --da;
    } else {
      b = b->parent;
      --db;
    }
  }
  const Node* lca = a;
  int dlca = da;
  if (dl + dr - 2 * dlca + 2 > options.max_path_length) return std::string();

  std::vector<const Node*> left_side, right_side;
  for (const Node* n = l; n != lca; n = n->parent) left_side.push_back(n);
  for (const Node* n = r; n != lca; n = n->parent) right_side.push_back(n);
  std::reverse(right_side.begin(), right_side.end());

  if (!left_side.empty() && !right_side.empty()) {
    int li = left_side.back()->child_id;
    int ri = right_side.front()->child_id;
    if (std::abs(li - ri) >= options.max_path_width) return std::string();
  }

  auto child_id_suffix = [&](const Node* n) -> std::string {
    if (n->parent != nullptr &&
        cs_child_id_parent_kinds().count(n->parent->raw_type)) {
      return std::to_string(std::min(n->child_id, 3));  // truncated at 3
    }
    return std::string();
  };

  std::string out;
  for (size_t i = 0; i < left_side.size(); ++i) {
    out += left_side[i]->raw_type;
    out += child_id_suffix(left_side[i]);
    out += '^';
  }
  out += lca->raw_type;
  for (size_t i = 0; i < right_side.size(); ++i) {
    out += '_';
    out += right_side[i]->raw_type;
    out += child_id_suffix(right_side[i]);
  }
  return out;
}

// variables: leaves grouped by token text; METHOD_NAME for the method-name
// token (reference Variable.cs:63-108)
struct CsVariable {
  std::string name;
  std::vector<int> token_indices;
};

inline std::vector<MethodFeatures> cs_extract_all(
    CsParser& parser, Node* root, const ExtractorOptions& options) {
  std::vector<Node*> methods;
  std::vector<Node*> stack{root};
  while (!stack.empty()) {
    Node* node = stack.back();
    stack.pop_back();
    if (node->raw_type == "MethodDeclaration") methods.push_back(node);
    for (Node* child : node->children) stack.push_back(child);
  }
  std::reverse(methods.begin(), methods.end());

  // file-level comment contexts, appended to every method
  // (reference Extractor.cs:204-218 iterates the FULL tree's trivia inside
  // the per-method loop)
  std::vector<std::string> comment_contexts;
  for (const std::string& comment : parser.comments()) {
    std::string trimmed = comment;
    auto is_trim = [](char c) {
      return c == ' ' || c == '/' || c == '*' || c == '{' || c == '}';
    };
    while (!trimmed.empty() && is_trim(trimmed.front()))
      trimmed.erase(trimmed.begin());
    while (!trimmed.empty() && is_trim(trimmed.back())) trimmed.pop_back();
    std::string normalized = cs_split_name_unless_empty(trimmed);
    std::vector<std::string> parts;
    size_t start = 0;
    while (start <= normalized.size()) {
      size_t end = normalized.find('|', start);
      if (end == std::string::npos) end = normalized.size();
      parts.push_back(normalized.substr(start, end - start));
      start = end + 1;
    }
    for (size_t i = 0; i * 5 < parts.size(); ++i) {
      std::vector<std::string> batch(
          parts.begin() + i * 5,
          parts.begin() + std::min(parts.size(), (i + 1) * 5));
      std::string joined = join(batch, "|");
      comment_contexts.push_back(joined + ",COMMENT," + joined);
    }
  }

  std::vector<MethodFeatures> all;
  std::mt19937 rng(0);  // deterministic (reference uses unseeded Random())
  for (Node* method : methods) {
    std::vector<CsToken> tokens;
    parser.collect_tokens(method, &tokens);
    // keep only leaf tokens (identifiers/literals/predefined-type)
    std::vector<CsToken> leaves;
    for (auto& token : tokens) {
      if (token.is_identifier || token.is_literal ||
          token.is_predefined_type)
        leaves.push_back(token);
    }

    MethodFeatures features;
    std::vector<std::string> label_parts = cs_split_subtokens(method->code);
    features.label = label_parts.empty() ? cs_normalize_name(method->code)
                                         : join(label_parts, "|");

    // group into variables by name; method-name token -> METHOD_NAME
    std::vector<CsVariable> variables;
    std::map<std::string, int> variable_index;
    for (size_t t = 0; t < leaves.size(); ++t) {
      std::string name = leaves[t].text;
      if (leaves[t].is_identifier && leaves[t].parent == method)
        name = "METHOD_NAME";
      auto [it, inserted] =
          variable_index.emplace(name, variables.size());
      if (inserted) variables.push_back(CsVariable{name, {}});
      variables[it->second].token_indices.push_back(
          static_cast<int>(t));
    }

    // variable pairs: Choose2 + self-pairs, reservoir-sampled
    // (reference Extractor.cs:111-117)
    std::vector<std::pair<int, int>> pairs;
    for (size_t i = 0; i < variables.size(); ++i)
      for (size_t j = i + 1; j < variables.size(); ++j)
        pairs.emplace_back(static_cast<int>(i), static_cast<int>(j));
    for (size_t i = 0; i < variables.size(); ++i)
      pairs.emplace_back(static_cast<int>(i), static_cast<int>(i));
    if (static_cast<int>(pairs.size()) > options.max_contexts_cs) {
      // reservoir sample
      std::vector<std::pair<int, int>> sample;
      sample.reserve(options.max_contexts_cs);
      for (size_t seen = 0; seen < pairs.size(); ++seen) {
        if (static_cast<int>(sample.size()) < options.max_contexts_cs) {
          sample.push_back(pairs[seen]);
        } else {
          std::uniform_int_distribution<size_t> dist(0, seen);
          size_t position = dist(rng);
          if (position < sample.size()) sample[position] = pairs[seen];
        }
      }
      pairs = std::move(sample);
    }

    for (const auto& [vi, vj] : pairs) {
      const CsVariable& left_var = variables[vi];
      const CsVariable& right_var = variables[vj];
      for (int rt : right_var.token_indices) {
        for (int lt : left_var.token_indices) {
          if (lt == rt) continue;
          std::string path =
              cs_find_path(leaves[lt], leaves[rt], method, options);
          if (path.empty()) continue;
          std::string path_out =
              options.no_hash ? path : std::to_string(java_hash(path));
          features.contexts.push_back(
              cs_split_name_unless_empty(left_var.name) + ',' + path_out +
              ',' + cs_split_name_unless_empty(right_var.name));
        }
      }
    }
    features.contexts.insert(features.contexts.end(),
                             comment_contexts.begin(),
                             comment_contexts.end());
    if (!features.contexts.empty()) all.push_back(std::move(features));
  }
  return all;
}

}  // namespace cs
}  // namespace c2v
