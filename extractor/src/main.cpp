// c2v-extract — native Java path-context extractor CLI.
//
// Flag-compatible with the reference JavaExtractor
// (Common/CommandLineValues.java:12-40): --file | --dir, --max_path_length,
// --max_path_width, --no_hash, --num_threads, --min_code_len,
// --max_code_len, --max_child_id. Output: one "label ctx ctx ..." line per
// method on stdout (App.java / ExtractFeaturesTask.java), with the
// reference's 3-stage parse retry (plain → class+method wrap → class wrap,
// FeatureExtractor.java:51-75). Per-file failures go to stderr and are
// skipped; lines are printed atomically under a mutex.
#include <atomic>
#include <filesystem>
#include <fstream>
#include <iostream>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "csharp.h"
#include "java_ast.h"
#include "java_lexer.h"
#include "java_parser.h"
#include "pathctx.h"

namespace fs = std::filesystem;

namespace {

struct CliOptions {
  std::string file;
  std::string dir;
  std::string lang;  // "java" | "csharp" | "" (auto by file extension)
  int num_threads = 32;
  c2v::ExtractorOptions extractor;
};

bool parse_int_flag(const std::string& value, int* out) {
  try {
    *out = std::stoi(value);
    return true;
  } catch (...) {
    return false;
  }
}

bool parse_cli(int argc, char** argv, CliOptions* options) {
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) return nullptr;
      return argv[++i];
    };
    if (arg == "--file") {
      const char* v = next();
      if (!v) return false;
      options->file = v;
    } else if (arg == "--dir") {
      const char* v = next();
      if (!v) return false;
      options->dir = v;
    } else if (arg == "--max_path_length") {
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->extractor.max_path_length))
        return false;
    } else if (arg == "--max_path_width") {
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->extractor.max_path_width))
        return false;
    } else if (arg == "--max_child_id") {
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->extractor.max_child_id))
        return false;
    } else if (arg == "--min_code_len") {
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->extractor.min_code_len))
        return false;
    } else if (arg == "--max_code_len") {
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->extractor.max_code_len))
        return false;
    } else if (arg == "--num_threads") {
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->num_threads)) return false;
    } else if (arg == "--no_hash") {
      options->extractor.no_hash = true;
    } else if (arg == "--lang") {
      const char* v = next();
      if (!v) return false;
      options->lang = v;
      if (options->lang != "java" && options->lang != "csharp") {
        std::cerr << "--lang must be java or csharp\n";
        return false;
      }
    } else if (arg == "--max_contexts") {
      // C# frontend: reservoir cap (reference Utilities.cs:30-32)
      const char* v = next();
      if (!v || !parse_int_flag(v, &options->extractor.max_contexts_cs))
        return false;
    } else if (arg == "--pretty_print") {
      // accepted for flag compatibility; no-op
    } else {
      std::cerr << "unknown flag: " << arg << "\n";
      return false;
    }
  }
  if (options->file.empty() == options->dir.empty()) {
    std::cerr << "exactly one of --file or --dir is required\n";
    return false;
  }
  return true;
}

c2v::Node* parse_with_retries(const std::string& code, c2v::Arena* arena,
                              std::string* parsed_source) {
  // reference FeatureExtractor.java:51-75
  const std::string class_prefix = "public class Test {";
  const std::string class_suffix = "}";
  const std::string method_prefix = "SomeUnknownReturnType f() {";
  const std::string method_suffix = "return noSuchReturnValue; }";
  const std::string candidates[3] = {
      code,
      class_prefix + method_prefix + code + method_suffix + class_suffix,
      class_prefix + code + class_suffix,
  };
  // a candidate that parses cleanly but holds no methods is NOT a parse
  // failure: the reference only retries on a parse exception
  // (FeatureExtractor.java:51-75) and emits nothing, without error, for
  // valid Java whose only function members are constructors (its visitor
  // walks MethodDeclaration nodes only). Keep trying later wrappings for
  // one that yields methods, but remember the first clean parse so such
  // files produce zero rows instead of a spurious "could not parse".
  c2v::Node* first_parsed = nullptr;
  std::string first_parsed_source;
  for (const std::string& candidate : candidates) {
    try {
      c2v::Lexer lexer(candidate);
      c2v::Parser parser(lexer.run(), arena);
      c2v::Node* root = parser.parse_compilation_unit();
      std::vector<c2v::Node*> methods;
      c2v::find_methods(root, &methods);
      if (!methods.empty()) {
        *parsed_source = candidate;
        return root;
      }
      // only a RECOVERY-FREE parse proves the file is valid Java with no
      // methods; a recovered parse of garbage also reaches here with an
      // empty method list and must still count as a failure
      if (first_parsed == nullptr && !parser.had_recovery()) {
        first_parsed = root;
        first_parsed_source = candidate;
      }
    } catch (const std::exception&) {
      // fall through to the next wrapping
    }
  }
  if (first_parsed != nullptr) {
    *parsed_source = first_parsed_source;
    return first_parsed;
  }
  return nullptr;
}

std::string render_methods(const std::vector<c2v::MethodFeatures>& methods) {
  std::string out;
  for (const auto& method : methods) {
    out += method.label;
    for (const auto& context : method.contexts) {
      out += ' ';
      out += context;
    }
    out += '\n';
  }
  return out;
}

bool is_csharp(const CliOptions& cli, const std::string& path) {
  if (!cli.lang.empty()) return cli.lang == "csharp";
  return fs::path(path).extension() == ".cs";
}

std::string extract_csharp(const std::string& code,
                           const c2v::ExtractorOptions& options,
                           std::string* error) {
  // plain parse, then a class-wrap retry for bare method snippets (the
  // reference parses with dummy wraps too, Tree.cs DummyMethodName/Type).
  // A clean parse that simply contains no methods (DTOs, interfaces) is
  // SUCCESS with empty output, not an error.
  const std::string candidates[2] = {
      code, "public class Test {" + code + "}"};
  bool plain_parse_ok = false;
  for (size_t attempt = 0; attempt < 2; ++attempt) {
    try {
      c2v::Arena arena;
      std::vector<std::string> comments;
      c2v::Lexer lexer(candidates[attempt], /*csharp=*/true);
      lexer.capture_comments(&comments);
      c2v::cs::CsParser parser(lexer.run(), &arena);
      c2v::Node* root = parser.parse_compilation_unit();
      parser.set_comments(std::move(comments));
      std::vector<c2v::MethodFeatures> methods =
          c2v::cs::cs_extract_all(parser, root, options);
      if (!methods.empty()) return render_methods(methods);
      if (attempt == 0) plain_parse_ok = true;  // maybe the wrap finds more
    } catch (const std::exception&) {
    }
  }
  if (plain_parse_ok) return std::string();  // valid but method-less file
  *error = "could not parse C# input";
  return std::string();
}

std::string extract_file_to_string(const CliOptions& cli,
                                   const std::string& path,
                                   const c2v::ExtractorOptions& options,
                                   std::string* error) {
  std::ifstream in(path, std::ios::binary);
  if (!in) {
    *error = "cannot open file: " + path;
    return std::string();
  }
  std::ostringstream buffer;
  buffer << in.rdbuf();
  std::string code = buffer.str();

  if (is_csharp(cli, path)) {
    std::string result = extract_csharp(code, options, error);
    if (!error->empty()) *error += ": " + path;
    return result;
  }

  c2v::Arena arena;
  std::string parsed_source;
  c2v::Node* root = parse_with_retries(code, &arena, &parsed_source);
  if (root == nullptr) {
    *error = "could not parse: " + path;
    return std::string();
  }
  return render_methods(c2v::extract_all(root, parsed_source, options));
}

}  // namespace

int main(int argc, char** argv) {
  std::ios::sync_with_stdio(false);
  CliOptions options;
  if (!parse_cli(argc, argv, &options)) return 2;

  if (!options.file.empty()) {
    std::string error;
    std::string out = extract_file_to_string(options, options.file,
                                             options.extractor, &error);
    if (!error.empty()) {
      std::cerr << error << "\n";
      return 1;
    }
    std::cout << out;
    return 0;
  }

  // --dir: recursive walk over .java files with a worker pool
  // (reference App.java:39-59 used a ThreadPoolExecutor the same way)
  std::vector<std::string> files;
  std::error_code ec;
  for (auto it = fs::recursive_directory_iterator(
           options.dir, fs::directory_options::skip_permission_denied, ec);
       it != fs::recursive_directory_iterator(); it.increment(ec)) {
    if (ec) break;
    if (it->is_regular_file(ec) &&
        (it->path().extension() == ".java" ||
         it->path().extension() == ".cs")) {
      files.push_back(it->path().string());
    }
  }
  if (ec) {
    std::cerr << "error walking directory " << options.dir << ": "
              << ec.message() << "\n";
    return 1;
  }

  std::atomic<size_t> next_file{0};
  std::mutex out_mutex;
  int num_threads =
      std::max(1, std::min<int>(options.num_threads,
                                static_cast<int>(files.size())));
  std::vector<std::thread> workers;
  for (int t = 0; t < num_threads; ++t) {
    workers.emplace_back([&]() {
      while (true) {
        size_t index = next_file.fetch_add(1);
        if (index >= files.size()) return;
        std::string error;
        std::string out = extract_file_to_string(
            options, files[index], options.extractor, &error);
        std::lock_guard<std::mutex> lock(out_mutex);
        if (!error.empty()) {
          std::cerr << error << "\n";
        } else {
          std::cout << out;
        }
      }
    });
  }
  for (auto& worker : workers) worker.join();
  std::cout.flush();
  return 0;
}
