"""Latency attribution from the serving span log (telemetry/tracing.py).

Reads the flat span records of ``spans.jsonl`` (or any
``flight_<event>.jsonl`` flight-recorder dump — header lines are
skipped; a flight path transparently merges its replica-namespaced
``flight_<event>_r<N>.jsonl`` siblings, the worker-process form, with
cross-file deduplication) and reports:

- **phase x bucket x tier x replica breakdown**: p50/p95/p99
  (nearest-rank) and count per span name, keyed by the trace's output
  tier, the batch bucket it dispatched on, and — for serving-mesh
  traffic — WHICH replica served it (the ``replica`` attribute the
  mesh dispatcher stamps on the pack span; '-' for single-engine
  traffic);
- **queue-wait vs device-time decomposition**: where end-to-end latency
  actually went (the micro-batcher's direct tuning signal:
  queue-dominated -> lower SERVING_MAX_DELAY_MS / raise buckets /
  add replicas; device-dominated -> the model is the bottleneck), as a
  FLEET view plus a per-replica x tier table — the "which replica is
  slow" question under a mesh is read straight off it;
- **terminal statuses**: how many traces ended ok / shed / expired /
  closed / error — shed storms and deadline expiries show up here;
- **top-K slowest traces** as full indented span trees, for the "why is
  p99 like that" question;
- with ``--fleet``: the cross-process view over STITCHED traces
  (OBSERVABILITY.md "Fleet observability") — true
  queue-vs-WIRE-vs-device decomposition per replica for worker-mode
  mesh traffic (the wire residual is the transport cost no
  single-process span can show), plus the count of delivered traces
  whose worker-side spans never stitched (``scripts/mesh_soak.py``
  asserts that count to zero).

``--perfetto out.json`` converts the spans to the Chrome trace-event
format, so serving traces open in the same Perfetto/chrome://tracing
tooling as the ``jax.profiler`` captures that
``benchmarks/analyze_trace.py`` decomposes.  ``--json`` emits one JSON
line per phase row for machine consumers (benchmarks/capture_all.sh
folds these into the capture trajectory).

Usage:
    python scripts/latency_report.py --spans <dir>/spans.jsonl \
        [--top 5] [--json] [--perfetto out.json]

Dependency-free (stdlib only), like the rest of the tracing layer.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: span names whose interval overlaps other phases by design (the
#: coalescing window contains its members' queue_wait); reported, but
#: excluded from phase-sum / decomposition arithmetic
OVERLAPPING = frozenset(('serving.coalesce',))

#: the disjoint per-request phase chain, in lifecycle order — these tile
#: the root span (small scheduler gaps aside), so their sums approximate
#: end-to-end latency (asserted in tests/test_tracing.py)
PHASE_CHAIN = (
    'serving.admission', 'serving.tokenize', 'serving.queue_wait',
    'serving.stall', 'serving.pack', 'serving.h2d', 'serving.dispatch',
    'serving.device_execute', 'serving.decode', 'serving.deliver',
)


#: flight-recorder dump filename, with the optional replica-instance
#: namespace a worker-mode mesh replica writes under
#: (flight_<event>_r<N>.jsonl — telemetry/tracing.py): the parent and
#: its workers share one telemetry dir, so a postmortem must read BOTH
#: forms
FLIGHT_RE = re.compile(
    r'^flight_(?P<event>.+?)(?:_(?P<inst>r\d+))?\.jsonl$')


def collect_span_paths(path: str) -> List[str]:
    """Expand one span-log path into every sibling that belongs to the
    same story: a ``flight_<event>.jsonl`` (or a replica-namespaced
    ``flight_<event>_r<N>.jsonl``) pulls in every other dump of that
    event in the directory.  A plain spans.jsonl stays itself."""
    match = FLIGHT_RE.match(os.path.basename(path))
    if match is None:
        return [path]
    dirname = os.path.dirname(path) or '.'
    event = match.group('event')
    paths = {path}
    try:
        siblings = sorted(os.listdir(dirname))
    except OSError:
        siblings = []
    for candidate in siblings:
        sibling = FLIGHT_RE.match(candidate)
        if sibling is not None and sibling.group('event') == event:
            paths.add(os.path.join(dirname, candidate))
    return sorted(paths)


def load_spans(path: str) -> List[dict]:
    """Flat span records from a spans.jsonl or flight_<event>.jsonl
    (flight header lines and garbage lines are skipped).  Flight paths
    transparently merge their replica-namespaced siblings; records
    appearing in several files (a trace in both the span log and a
    flight ring) are deduplicated."""
    records = []
    seen = set()
    for one_path in collect_span_paths(path):
        # only GLOBBED siblings may be absent (raced away); the
        # caller's own path stays strict — a typo'd path must fail,
        # not masquerade as an empty span log
        if one_path != path and not os.path.exists(one_path):
            continue
        with open(one_path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if not (isinstance(rec, dict) and 'name' in rec
                        and 'trace' in rec):
                    continue
                key = (rec['trace'], rec.get('span'), rec['name'],
                       rec.get('t0'))
                if key in seen:
                    continue
                seen.add(key)
                records.append(rec)
    return records


def group_traces(records: List[dict]) -> Dict[str, dict]:
    """trace_id -> {'root': record|None, 'spans': [records]} (spans in
    file order; the root is the parentless span)."""
    traces: Dict[str, dict] = {}
    for rec in records:
        entry = traces.setdefault(rec['trace'],
                                  {'root': None, 'spans': []})
        entry['spans'].append(rec)
        if rec.get('parent') is None:
            entry['root'] = rec
    return traces


def percentile(sorted_ms: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (same convention
    as telemetry.core.Timer.snapshot)."""
    if not sorted_ms:
        return 0.0
    idx = min(len(sorted_ms) - 1, max(0, int(q * len(sorted_ms))))
    return sorted_ms[idx]


def trace_key(entry: dict) -> Tuple[str, str, str]:
    """(tier, bucket, replica) attribution for one trace: tier from the
    root attrs, bucket + replica from the pack span that dispatched it
    ('-' for traces that never reached a dispatch — shed/expired/closed
    — and '-' replica for single-engine traffic)."""
    root = entry['root'] or {}
    tier = str((root.get('attrs') or {}).get('tier', '-'))
    bucket = '-'
    replica = '-'
    for rec in entry['spans']:
        if rec['name'] == 'serving.pack':
            attrs = rec.get('attrs') or {}
            bucket = str(attrs.get('bucket', '-'))
            # the pack span also carries the EFFECTIVE tier (post-
            # degradation) and, on a mesh, the serving replica
            tier = str(attrs.get('tier', tier))
            replica = str(attrs.get('replica', '-'))
            break
    return tier, bucket, replica


def trace_scenario(entry: dict) -> str:
    """Scenario attribution for one trace: the workload label stamped
    into the root attrs at mesh admission and carried by the dispatch
    trace context (WORKLOADS.md); '-' for unlabeled traffic."""
    root = entry['root'] or {}
    scenario = (root.get('attrs') or {}).get('scenario')
    if scenario is None:
        for rec in entry['spans']:
            scenario = (rec.get('attrs') or {}).get('scenario')
            if scenario is not None:
                break
    return '-' if scenario is None else str(scenario)


def phase_rows(traces: Dict[str, dict]
               ) -> Dict[Tuple[str, str, str, str], List[float]]:
    """(phase, tier, bucket, replica) -> ascending durations (ms)."""
    rows: Dict[Tuple[str, str, str, str], List[float]] = {}
    for entry in traces.values():
        tier, bucket, replica = trace_key(entry)
        for rec in entry['spans']:
            rows.setdefault((rec['name'], tier, bucket, replica),
                            []).append(float(rec.get('dur_ms', 0.0)))
    for durs in rows.values():
        durs.sort()
    return rows


def _union_ms(spans: List[dict], name: str) -> float:
    """Total wall-clock covered by the named spans (ms): the union of
    their [t0, t1] intervals — an oversize request's chunks run their
    queue waits and device executes CONCURRENTLY, and summing the
    overlapping durations would over-count by the chunk fan-out."""
    intervals = sorted((float(r['t0']), float(r['t1']))
                       for r in spans if r['name'] == name)
    covered = 0.0
    end = None
    for t0, t1 in intervals:
        if end is None or t0 > end:
            covered += t1 - t0
            end = t1
        elif t1 > end:
            covered += t1 - end
            end = t1
    return covered * 1e3


def decomposition(traces: Dict[str, dict]) -> Dict[str, List[float]]:
    """Per delivered trace: end-to-end, queue-wait, and device-time
    (ms, ascending) — the queue-vs-device attribution."""
    out: Dict[str, List[float]] = {'end_to_end': [], 'queue_wait': [],
                                   'device': [], 'other': []}
    for entry in traces.values():
        root = entry['root']
        if root is None or root.get('status') not in (None, 'ok'):
            continue
        total = float(root.get('dur_ms', 0.0))
        queue = _union_ms(entry['spans'], 'serving.queue_wait')
        device = _union_ms(entry['spans'], 'serving.device_execute')
        out['end_to_end'].append(total)
        out['queue_wait'].append(queue)
        out['device'].append(device)
        out['other'].append(max(0.0, total - queue - device))
    for values in out.values():
        values.sort()
    return out


def replica_decomposition(traces: Dict[str, dict]
                          ) -> Dict[Tuple[str, str],
                                    Dict[str, List[float]]]:
    """(replica, tier) -> {end_to_end, queue_wait, device} (ms,
    ascending) over delivered traces — the per-replica column of the
    fleet decomposition (mesh traffic stamps the replica on the pack
    span; single-engine traffic lands under replica '-')."""
    out: Dict[Tuple[str, str], Dict[str, List[float]]] = {}
    for entry in traces.values():
        root = entry['root']
        if root is None or root.get('status') not in (None, 'ok'):
            continue
        tier, _bucket, replica = trace_key(entry)
        parts = out.setdefault((replica, tier),
                               {'end_to_end': [], 'queue_wait': [],
                                'device': []})
        parts['end_to_end'].append(float(root.get('dur_ms', 0.0)))
        parts['queue_wait'].append(
            _union_ms(entry['spans'], 'serving.queue_wait'))
        parts['device'].append(
            _union_ms(entry['spans'], 'serving.device_execute'))
    for parts in out.values():
        for values in parts.values():
            values.sort()
    return out


#: the fleet decomposition's wire residual subtracts the parent-side
#: phases that are NOT queue wait; everything left after queue + the
#: remote envelope is time on the wire (frame send, kernel buffers,
#: receiver scheduling)
_PARENT_PHASES = ('serving.admission', 'serving.tokenize')


def fleet_decomposition(traces: Dict[str, dict]
                        ) -> Dict[Tuple[str, str, str],
                                  Dict[str, List[float]]]:
    """(replica, tier, scenario) -> {end_to_end, queue_wait, wire,
    device, worker_host} (ms, ascending) over delivered traces — the
    ``--fleet`` view of STITCHED cross-process traces.  The scenario
    axis rides the spans the stitching already carries: the admission-
    time workload label lands in the root attrs and the dispatch trace
    context, so per-scenario fleet latency needs no new span names
    ('-' buckets unlabeled traffic).

    For worker-mode mesh traffic the parent only sees admission,
    tokenize, and queue wait; the grafted ``serving.remote`` envelope
    covers the worker's receipt-to-finish, ``serving.device_execute``
    nests inside it, and the residual between end-to-end and
    (parent phases + queue + remote) is true WIRE time — the
    cross-process transport cost no single-process span could show.
    Thread-mode traces land with wire 0 (there is no wire).

    Requests served from the memoization tier (a ``serving.memo_hit``
    marker span, SERVING.md "Memoization tier") are split out under
    replica ``memo``: their end-to-end IS the whole story — zero
    queue, zero wire, zero device — so the fleet table attributes the
    saved device work to the cache instead of diluting a replica's
    column with sub-ms rows."""
    out: Dict[Tuple[str, str, str], Dict[str, List[float]]] = {}
    for entry in traces.values():
        root = entry['root']
        if root is None or root.get('status') not in (None, 'ok'):
            continue
        tier, _bucket, replica = trace_key(entry)
        scenario = trace_scenario(entry)
        if any(rec['name'] == 'serving.memo_hit'
               for rec in entry['spans']):
            replica = 'memo'
        total = float(root.get('dur_ms', 0.0))
        queue = _union_ms(entry['spans'], 'serving.queue_wait')
        device = _union_ms(entry['spans'], 'serving.device_execute')
        remote = _union_ms(entry['spans'], 'serving.remote')
        if remote > 0:
            parent = sum(_union_ms(entry['spans'], name)
                         for name in _PARENT_PHASES)
            wire = max(0.0, total - queue - remote - parent)
            worker_host = max(0.0, remote - device)
        else:
            wire = 0.0
            worker_host = 0.0
        parts = out.setdefault(
            (replica, tier, scenario),
            {'end_to_end': [], 'queue_wait': [], 'wire': [],
             'device': [], 'worker_host': []})
        parts['end_to_end'].append(total)
        parts['queue_wait'].append(queue)
        parts['wire'].append(wire)
        parts['device'].append(device)
        parts['worker_host'].append(worker_host)
    for parts in out.values():
        for values in parts.values():
            values.sort()
    return out


def unstitched_traces(traces: Dict[str, dict]) -> List[str]:
    """Delivered traces with NO device-execute attribution — for
    worker-mode mesh traffic that means the worker-side spans never
    made it back over the wire (the stitching failure mode
    ``scripts/mesh_soak.py`` asserts to zero).  Thread-mode and
    single-engine traces record device_execute locally, so any
    delivered trace missing it is wire-truncated."""
    out = []
    for trace_id, entry in traces.items():
        root = entry['root']
        if root is None or root.get('status') not in (None, 'ok'):
            continue
        if root.get('name') != 'serving.request':
            continue  # engine-level singles (canary shadows) have no
            #           device leg by design
        if any(rec['name'] == 'serving.memo_hit'
               for rec in entry['spans']):
            continue  # served from the memoization tier: ZERO device
            #           work is the point, not a truncated wire
        if not any(rec['name'] == 'serving.device_execute'
                   for rec in entry['spans']):
            out.append(trace_id)
    return sorted(out)


def status_counts(traces: Dict[str, dict]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for entry in traces.values():
        root = entry['root']
        status = root.get('status', '?') if root else '?'
        counts[status] = counts.get(status, 0) + 1
    return counts


def format_tree(entry: dict) -> List[str]:
    """Indented span-tree lines for one trace (children under parents,
    by span id)."""
    spans = sorted(entry['spans'], key=lambda r: (r['t0'], r['span']))
    children: Dict[Optional[int], List[dict]] = {}
    for rec in spans:
        children.setdefault(rec.get('parent'), []).append(rec)
    lines: List[str] = []

    def walk(rec: dict, depth: int) -> None:
        attrs = rec.get('attrs') or {}
        extra = ' '.join('%s=%s' % (k, v) for k, v in sorted(
            attrs.items()) if k not in ('reason',))
        reason = attrs.get('reason') or rec.get('attrs', {}).get('reason')
        lines.append('  %s%-28s %9.2fms%s%s'
                     % ('  ' * depth, rec['name'],
                        float(rec.get('dur_ms', 0.0)),
                        ('  [' + extra + ']') if extra else '',
                        ('  reason: ' + str(reason)) if reason else ''))
        for child in children.get(rec['span'], ()):
            walk(child, depth + 1)

    for root in children.get(None, ()):
        walk(root, 0)
    return lines


def to_perfetto(traces: Dict[str, dict]) -> List[dict]:
    """Chrome trace-event ('X' complete events) conversion: one tid lane
    per trace, microsecond timestamps rebased to the earliest span."""
    t_min = min((rec['t0'] for entry in traces.values()
                 for rec in entry['spans']), default=0.0)
    events = []
    for lane, (trace_id, entry) in enumerate(sorted(traces.items()), 1):
        tier, bucket, replica = trace_key(entry)
        for rec in entry['spans']:
            attrs = dict(rec.get('attrs') or {})
            attrs['trace'] = trace_id
            if rec.get('status'):
                attrs['status'] = rec['status']
            events.append({
                'name': rec['name'],
                'cat': 'tier:%s,bucket:%s,replica:%s'
                       % (tier, bucket, replica),
                'ph': 'X',
                'ts': (rec['t0'] - t_min) * 1e6,
                'dur': max(0.0, (rec['t1'] - rec['t0']) * 1e6),
                'pid': 1,
                'tid': lane,
                'args': attrs,
            })
    return events


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='p50/p95/p99 latency attribution from a serving '
                    'span log')
    parser.add_argument('--spans', required=True,
                        help='spans.jsonl or flight_<event>.jsonl path')
    parser.add_argument('--top', type=int, default=5,
                        help='slowest span trees to print (0 = none)')
    parser.add_argument('--fleet', action='store_true',
                        help='cross-process fleet view over STITCHED '
                             'traces: queue-vs-wire-vs-device '
                             'decomposition per replica, plus the '
                             'count of delivered traces whose worker-'
                             'side spans never stitched (wire-'
                             'truncated)')
    parser.add_argument('--json', action='store_true',
                        help='emit machine-readable JSON lines instead '
                             'of the table')
    parser.add_argument('--perfetto', default=None, metavar='OUT.json',
                        help='also write a Chrome-trace/Perfetto file')
    args = parser.parse_args(argv)

    if not os.path.exists(args.spans):
        print('no span log at %s' % args.spans, file=sys.stderr)
        return 1
    records = load_spans(args.spans)
    traces = group_traces(records)
    if not traces:
        print('no traces in %s' % args.spans, file=sys.stderr)
        return 1

    rows = phase_rows(traces)
    statuses = status_counts(traces)
    decomp = decomposition(traces)
    per_replica = replica_decomposition(traces)
    # the per-replica table earns its ink only when a mesh actually
    # stamped replica ids (single-engine logs land entirely under '-')
    meshy = any(replica != '-' for replica, _tier in per_replica)

    if args.json:
        print(json.dumps({'measure': 'trace_statuses', 'value': statuses,
                          'traces': len(traces)}))
        for (phase, tier, bucket, replica), durs in sorted(rows.items()):
            print(json.dumps({
                'measure': 'phase_latency_ms', 'phase': phase,
                'tier': tier, 'bucket': bucket, 'replica': replica,
                'count': len(durs),
                'p50': round(percentile(durs, 0.50), 3),
                'p95': round(percentile(durs, 0.95), 3),
                'p99': round(percentile(durs, 0.99), 3),
            }))
        for part, values in sorted(decomp.items()):
            if not values:
                continue
            print(json.dumps({
                'measure': 'latency_decomposition_ms', 'part': part,
                'count': len(values),
                'p50': round(percentile(values, 0.50), 3),
                'p99': round(percentile(values, 0.99), 3),
            }))
        for (replica, tier), parts in sorted(per_replica.items()):
            for part, values in sorted(parts.items()):
                print(json.dumps({
                    'measure': 'replica_decomposition_ms',
                    'replica': replica, 'tier': tier, 'part': part,
                    'count': len(values),
                    'p50': round(percentile(values, 0.50), 3),
                    'p99': round(percentile(values, 0.99), 3),
                }))
        if args.fleet:
            unstitched = unstitched_traces(traces)
            print(json.dumps({'measure': 'unstitched_traces',
                              'value': len(unstitched),
                              'traces': unstitched[:32]}))
            for (replica, tier, scenario), parts in sorted(
                    fleet_decomposition(traces).items()):
                for part in ('end_to_end', 'queue_wait', 'wire',
                             'device', 'worker_host'):
                    values = parts[part]
                    print(json.dumps({
                        'measure': 'fleet_decomposition_ms',
                        'replica': replica, 'tier': tier,
                        'scenario': scenario, 'part': part,
                        'count': len(values),
                        'p50': round(percentile(values, 0.50), 3),
                        'p99': round(percentile(values, 0.99), 3),
                    }))
    else:
        print('== %d trace(s) from %s' % (len(traces), args.spans))
        print('statuses: ' + ', '.join('%s=%d' % kv
                                       for kv in sorted(statuses.items())))
        print()
        print('%-26s %-10s %-7s %-7s %6s %9s %9s %9s'
              % ('phase', 'tier', 'bucket', 'replica', 'count',
                 'p50_ms', 'p95_ms', 'p99_ms'))
        for (phase, tier, bucket, replica), durs in sorted(rows.items()):
            print('%-26s %-10s %-7s %-7s %6d %9.2f %9.2f %9.2f'
                  % (phase, tier, bucket, replica, len(durs),
                     percentile(durs, 0.50), percentile(durs, 0.95),
                     percentile(durs, 0.99)))
        if decomp['end_to_end']:
            print()
            print('fleet decomposition over %d delivered trace(s):'
                  % len(decomp['end_to_end']))
            for part in ('end_to_end', 'queue_wait', 'device', 'other'):
                values = decomp[part]
                print('  %-12s p50 %9.2fms  p99 %9.2fms'
                      % (part, percentile(values, 0.50),
                         percentile(values, 0.99)))
        if meshy:
            print()
            print('per-replica decomposition (queue-wait vs device):')
            print('  %-7s %-10s %6s %9s %9s %9s %9s %9s %9s'
                  % ('replica', 'tier', 'count', 'queue_p50',
                     'queue_p99', 'dev_p50', 'dev_p99', 'e2e_p50',
                     'e2e_p99'))
            for (replica, tier), parts in sorted(per_replica.items()):
                print('  %-7s %-10s %6d %9.2f %9.2f %9.2f %9.2f '
                      '%9.2f %9.2f'
                      % (replica, tier, len(parts['end_to_end']),
                         percentile(parts['queue_wait'], 0.50),
                         percentile(parts['queue_wait'], 0.99),
                         percentile(parts['device'], 0.50),
                         percentile(parts['device'], 0.99),
                         percentile(parts['end_to_end'], 0.50),
                         percentile(parts['end_to_end'], 0.99)))
        if args.fleet:
            unstitched = unstitched_traces(traces)
            print()
            print('fleet view (stitched cross-process traces): %d '
                  'delivered trace(s) UNSTITCHED (no device-execute '
                  'attribution — worker spans lost on the wire)'
                  % len(unstitched))
            fleet = fleet_decomposition(traces)
            if fleet:
                print('  %-7s %-10s %-16s %6s %9s %9s %9s %9s %9s'
                      % ('replica', 'tier', 'scenario', 'count',
                         'queue_p99', 'wire_p99', 'dev_p99',
                         'whost_p99', 'e2e_p99'))
                for (replica, tier, scenario), parts in sorted(
                        fleet.items()):
                    print('  %-7s %-10s %-16s %6d %9.2f %9.2f %9.2f '
                          '%9.2f %9.2f'
                          % (replica, tier, scenario,
                             len(parts['end_to_end']),
                             percentile(parts['queue_wait'], 0.99),
                             percentile(parts['wire'], 0.99),
                             percentile(parts['device'], 0.99),
                             percentile(parts['worker_host'], 0.99),
                             percentile(parts['end_to_end'], 0.99)))
        if args.top > 0:
            slowest = sorted(
                (entry for entry in traces.values()
                 if entry['root'] is not None),
                key=lambda e: float(e['root'].get('dur_ms', 0.0)),
                reverse=True)[:args.top]
            for entry in slowest:
                root = entry['root']
                print()
                print('trace %s  status=%s  %0.2fms'
                      % (root['trace'], root.get('status', '?'),
                         float(root.get('dur_ms', 0.0))))
                for line in format_tree(entry):
                    print(line)

    if args.perfetto:
        events = to_perfetto(traces)
        with open(args.perfetto, 'w') as f:
            json.dump({'traceEvents': events,
                       'displayTimeUnit': 'ms'}, f)
        print('perfetto trace (%d events) -> %s'
              % (len(events), args.perfetto),
              file=sys.stderr)
    return 0


if __name__ == '__main__':
    sys.exit(main())
