#!/usr/bin/env bash
###########################################################
# preprocess.sh — dataset build (role of the reference's preprocess.sh:41-68)
#
# Extracts path contexts from raw Java projects with the native extractor,
# builds histograms, and runs vocab-aware truncation/padding.
# The awk histogram pass of the reference is folded into the Python
# preprocessor (code2vec_tpu/data/preprocess.py), which also accepts
# pre-built histogram files via -wh/-ph/-th.

TRAIN_DIR=${TRAIN_DIR:-dataset/train}
VAL_DIR=${VAL_DIR:-dataset/val}
TEST_DIR=${TEST_DIR:-dataset/test}
DATASET_NAME=${DATASET_NAME:-java14m}
MAX_CONTEXTS=${MAX_CONTEXTS:-200}
WORD_VOCAB_SIZE=${WORD_VOCAB_SIZE:-1301136}
PATH_VOCAB_SIZE=${PATH_VOCAB_SIZE:-911417}
TARGET_VOCAB_SIZE=${TARGET_VOCAB_SIZE:-261245}
NUM_THREADS=${NUM_THREADS:-64}
EXTRACTOR=${EXTRACTOR:-extractor/build/c2v-extract}

set -e
mkdir -p data/${DATASET_NAME}

extract() {  # extract <dir> <out-file>
  echo "Extracting paths from $1 ..."
  "${EXTRACTOR}" --dir "$1" --max_path_length 8 --max_path_width 2 \
      --num_threads "${NUM_THREADS}" > "$2"
  echo "Finished extracting paths from $1"
}

TRAIN_RAW=data/${DATASET_NAME}/train.raw
VAL_RAW=data/${DATASET_NAME}/val.raw
TEST_RAW=data/${DATASET_NAME}/test.raw

extract "${VAL_DIR}" "${VAL_RAW}"
extract "${TEST_DIR}" "${TEST_RAW}"
extract "${TRAIN_DIR}" "${TRAIN_RAW}.unshuffled"
shuf "${TRAIN_RAW}.unshuffled" > "${TRAIN_RAW}"
rm -f "${TRAIN_RAW}.unshuffled"

python -m code2vec_tpu.data.preprocess \
  --train_data "${TRAIN_RAW}" --val_data "${VAL_RAW}" --test_data "${TEST_RAW}" \
  --max_contexts "${MAX_CONTEXTS}" \
  --word_vocab_size "${WORD_VOCAB_SIZE}" \
  --path_vocab_size "${PATH_VOCAB_SIZE}" \
  --target_vocab_size "${TARGET_VOCAB_SIZE}" \
  --output_name data/${DATASET_NAME}/${DATASET_NAME}

rm -f "${TRAIN_RAW}" "${VAL_RAW}" "${TEST_RAW}"
echo "Done preprocessing ${DATASET_NAME}"
