"""The one-command lint entry point: every graftlint rule over the repo.

    python scripts/lint_all.py          # exit 0 iff the repo is clean
    python scripts/lint_all.py --list   # show suppressed/baselined too

Runs the full registered rule set — the five jit-invariant rules
(recompile-hazard, host-sync, donation-safety, jit-purity,
lock-discipline), config-knob-docs, and the migrated catalog-drift
rules (metrics-schema, fault-points) — with the repo baseline and
inline suppressions applied.  Tier-1 asserts exactly this via
tests/test_graftlint.py; the full pass is AST-only (no jax import) and
runs in ~1s, far under the <20s budget (ANALYSIS.md).
"""
from __future__ import annotations

import os
import sys

SCRIPTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(SCRIPTS)
for path in (REPO, SCRIPTS):
    if path not in sys.path:
        sys.path.insert(0, path)


def main(argv=None) -> int:
    import graftlint
    return graftlint.main(list(sys.argv[1:] if argv is None else argv))


if __name__ == '__main__':
    sys.exit(main())
