"""Generate a synthetic C# corpus for accuracy-at-scale validation of the
C# extraction pipeline (the C# analog of scripts/gen_java_corpus.py —
reference pipeline: preprocess_csharp.sh over a real C# tree).

Reuses the Java generator's corpus machinery (noun pools, Zipfian draws,
body families with verb-synonym tells, combinatorial nesting) and maps
the emitted bodies to C# syntax — the families are deliberately C-like,
so the mapping is a handful of lexical rules:

- ``boolean`` -> ``bool``, ``String`` -> ``string``;
- ``Integer/Long/Double.compare(a, b)`` -> ``a.CompareTo(b)``;
- ``.equals(`` -> ``.Equals(``.

On top of the transliterated families, a fraction of classes gain
C#-NATIVE members (expression-bodied properties, switch-expression
methods, tuple-returning methods) so the corpus exercises the parser
paths that only exist in C# (csharp.h: SwitchExpression, TupleType,
ArrowExpressionClause) and the path vocabulary carries their kinds at
corpus scale, not just in golden tests.

Deterministic under --seed. Output: one .cs file per class under
<out>/{train,val,test}/, ready for `c2v-extract --dir`.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import random
import re

_HERE = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    'gen_java_corpus', os.path.join(_HERE, 'gen_java_corpus.py'))
gjc = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(gjc)


_COMPARE_RE = re.compile(
    r'\b(?:Integer|Long|Double)\.compare\(([^,]+), ([^)]+)\)')


def to_csharp(java_src: str) -> str:
    src = re.sub(r'\bboolean\b', 'bool', java_src)
    src = re.sub(r'\bString\b', 'string', src)
    src = _COMPARE_RE.sub(r'\1.CompareTo(\2)', src)
    src = src.replace('.equals(', '.Equals(')
    return src


def csharp_native_members(rng: random.Random, cls: 'gjc.ClassGen') -> list:
    """C#-only member templates over the class's fields (names stay
    camelCase like the transliterated families — the extractor's
    subtoken split produces identical labels either way)."""
    members = []
    ftype, fname = rng.choice(cls.fields)
    cap = gjc.capitalized(fname)
    if rng.random() < 0.5:
        # expression-bodied property (ArrowExpressionClause paths);
        # properties are not methods, so this also exercises the
        # member-skip path at scale
        members.append('public string %sTag => "%s" + this.%s;'
                       % (cap, fname, fname))
    num = cls.numeric_fields()
    if num and rng.random() < 0.6:
        t1, f1 = rng.choice(num)
        cap1 = gjc.capitalized(f1)
        members.append(
            'public string describe%sBand() { return this.%s switch '
            '{ 0 => "zero", 1 => "one", _ => "many" }; }'
            % (cap1, f1))
    if len(num) >= 2 and rng.random() < 0.6:
        (t1, f1), (t2, f2) = rng.sample(num, 2)
        cap1, cap2 = gjc.capitalized(f1), gjc.capitalized(f2)
        members.append(
            'public (%s, %s) pairOf%sAnd%s() { return (this.%s, this.%s); }'
            % (t1 if t1 != 'boolean' else 'bool',
               t2 if t2 != 'boolean' else 'bool', cap1, cap2, f1, f2))
    return members


def gen_csharp_class(rng: random.Random, name: str, noun_pairs,
                     methods_per_class) -> str:
    cls = gjc.ClassGen(rng, noun_pairs)
    lines = ['public class %s {' % name]
    for ftype, fname in cls.fields:
        lines.append('    private %s %s;'
                     % ({'boolean': 'bool', 'String': 'string'}.get(
                         ftype, ftype), fname))
    n_methods = rng.randint(*methods_per_class)
    seen = set()
    for _ in range(n_methods):
        m = to_csharp(cls.method())
        sig = m.split('(')[0]
        if sig in seen:
            continue
        seen.add(sig)
        lines.append('    public ' + m)
    for member in csharp_native_members(rng, cls):
        lines.append('    ' + member)
    lines.append('}')
    return '\n'.join(lines) + '\n'


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('-o', '--out', required=True)
    parser.add_argument('--classes', type=int, default=8000)
    parser.add_argument('--methods-per-class', type=int, nargs=2,
                        default=(3, 6))
    parser.add_argument('--val-frac', type=float, default=0.025)
    parser.add_argument('--test-frac', type=float, default=0.025)
    parser.add_argument('--files-per-dir', type=int, default=2000)
    parser.add_argument('--seed', type=int, default=11)
    args = parser.parse_args()

    rng = random.Random(args.seed)
    noun_pairs = ([(a, n) for a in gjc.ADJS for n in gjc.NOUNS]
                  + [(n1, n2) for n1 in gjc.NOUNS for n2 in gjc.NOUNS
                     if n1 != n2])
    rng.shuffle(noun_pairs)

    counts = {'train': 0, 'val': 0, 'test': 0}
    for split in counts:
        os.makedirs(os.path.join(args.out, split), exist_ok=True)
    methods = 0
    for i in range(args.classes):
        r = rng.random()
        split = ('val' if r < args.val_frac else
                 'test' if r < args.val_frac + args.test_frac else 'train')
        sub = 'p%03d' % (counts[split] // args.files_per_dir)
        d = os.path.join(args.out, split, sub)
        os.makedirs(d, exist_ok=True)
        name = 'C%05d' % i
        src = gen_csharp_class(rng, name, noun_pairs,
                               args.methods_per_class)
        with open(os.path.join(d, name + '.cs'), 'w') as f:
            f.write(src)
        counts[split] += 1
        # count only method-shaped members: a parameter list before any
        # `=>`. Expression-bodied properties (`public string XTag => ...`)
        # are skipped by the extractor, so they must not inflate the
        # count; the class declaration line has no parens either.
        methods += sum(
            1 for line in src.splitlines()
            if line.lstrip().startswith('public ')
            and '(' in line.split('=>')[0])
    print('classes: %s  methods: %d' % (counts, methods))


if __name__ == '__main__':
    main()
