#!/usr/bin/env bash
# build_extractor.sh — build the native path-context extractor
# (role of the reference's build_extractor.sh, which ran `mvn clean package`)
set -e
cd "$(dirname "$0")/../extractor"
make
echo "Built extractor/build/c2v-extract"
