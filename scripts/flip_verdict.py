#!/usr/bin/env python3
"""Settle queued >=2% flip verdicts mechanically from capture rounds.

Every default flip in this repo follows one decision rule (PERF.md): a
knob flips only on a >=2% measured step-time win at the java14m config
on a REAL chip; ties keep the current behavior. The TPU backend has
been wedged for every capture round since 2026-07-31 (`tpu_unavailable`
in BENCH_r02-r05), so several verdicts are queued — above all the
ragged train-kernel flip (RAGGED_TRAIN_KERNEL, ISSUE 12). This CLI
makes settling them a command instead of a judgment call: run
`capture_all.sh` at the next healthy window, then

    python scripts/flip_verdict.py --write

It reads, newest first:

- ``benchmarks/results/*.jsonl`` — capture rounds (stage-wrapped
  ``{"stage", "rc", "data": {...}}`` lines and raw measure lines, the
  same two shapes summarize_captures.py collates), including the
  durable ``tpu_unavailable`` reason records;
- repo-root ``BENCH_*.json`` / ``MULTICHIP_*.json`` — the driver's
  committed snapshots (``{"parsed": {...}, "tail": ...}``), used only
  to count wedged rounds (their headline metric carries
  ``error: tpu_unavailable`` when the probe died).

and emits one verdict row per tracked measure:

- ``flip``    — newest healthy value clears the threshold: set the knob
- ``keep``    — newest healthy value exists but does not clear it
- ``pending`` — no healthy on-chip record yet (only wedged rounds /
  smoke lines); the verdict stays queued

``--write`` appends the rows (with provenance: source file, value,
threshold, timestamp) to ``benchmarks/results/flip_verdicts.json`` so
the decision is durable — the next session reads the settled verdict
instead of re-deriving it. jax-free, stdlib-only.
"""
from __future__ import annotations

import argparse
import datetime
import glob
import json
import os
import re
import sys

# The tracked flips: measure name (as emitted by the bench harnesses)
# -> the config knob the >=2% rule gates. ``_c<N>`` capacity-suffixed
# variants ride as corroborating evidence, never as the deciding row
# (the rule keys on the java14m headline shape).
TRACKED = {
    'ragged_train_kernel_speedup': {
        'knob': 'RAGGED_TRAIN_KERNEL',
        'meaning': 'packed TRAIN step through the Pallas '
                   'forward+backward kernel pair vs the SHIPPED fused '
                   'custom-VJP twin it would replace '
                   '(ops/pallas_ragged.py)',
    },
    'ragged_fusion_train_speedup': {
        'knob': 'USE_PALLAS_RAGGED_FUSION (train; already default-ON)',
        'meaning': 'fused custom-VJP train vs unpack-then-dense — '
                   'on-chip confirmation of the flipped default; a '
                   'keep verdict here argues for reverting it',
    },
    'ragged_fusion_predict_speedup': {
        'knob': 'USE_PALLAS_RAGGED_FUSION (serving kernels; '
                'already default-ON)',
        'meaning': 'deterministic packed forward through the Pallas '
                   'kernel on TPU vs unpack-then-dense — on-chip '
                   'confirmation of the flipped default',
    },
}
# a smoke record must never settle an on-chip verdict
_SMOKE = '_SMOKE_ONLY'


def iter_jsonl_records(path):
    """Yield measure dicts from a capture .jsonl (both shapes)."""
    with open(path) as f:
        for raw in f:
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if 'tpu_unavailable' in rec:
                yield {'_wedged': True}
                continue
            data = rec.get('data') if isinstance(rec.get('data'), dict) \
                else (rec if 'stage' not in rec else None)
            if isinstance(data, dict):
                yield data


def scan_results_dir(results_dir):
    """-> (newest-first {measure: (value, source_file)}, file count,
    wedged round count)."""
    newest = {}
    wedged_rounds = 0
    files = sorted(glob.glob(os.path.join(results_dir, '*.jsonl')))
    for path in files:  # oldest..newest: later files overwrite
        saw_measure = False
        saw_wedge = False
        for data in iter_jsonl_records(path):
            if data.get('_wedged'):
                saw_wedge = True
                continue
            name = data.get('measure')
            value = data.get('value')
            if not name or name.endswith(_SMOKE) \
                    or not isinstance(value, (int, float)):
                continue
            saw_measure = True
            newest[name] = (float(value), os.path.basename(path))
        if saw_wedge and not saw_measure:
            wedged_rounds += 1
    return newest, len(files), wedged_rounds


def scan_driver_snapshots(root):
    """Count the driver's BENCH_*/MULTICHIP_* rounds that recorded a
    wedged backend — the queue the verdicts have been waiting behind."""
    wedged = 0
    total = 0
    for path in sorted(glob.glob(os.path.join(root, 'BENCH_*.json'))
                       + glob.glob(os.path.join(root,
                                                'MULTICHIP_*.json'))):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (ValueError, OSError):
            continue
        total += 1
        # scan the parsed record AND the raw tail: a wedged round can
        # surface as the structured error token, the probe-timeout
        # message, or a backend-init traceback — each mode has really
        # occurred in this repo's BENCH_r01-r05 history
        parsed = snap.get('parsed')
        text = (json.dumps(parsed) if isinstance(parsed, dict) else '') \
            + str(snap.get('tail', ''))
        if any(marker in text for marker in (
                'tpu_unavailable', 'wedged backend',
                'Unable to initialize backend')):
            wedged += 1
    return wedged, total


def decide(measures, threshold):
    """Apply the rule to every tracked measure -> verdict rows."""
    rows = []
    for base, info in TRACKED.items():
        best = measures.get(base)
        corroborating = {
            name: val for name, (val, _src) in measures.items()
            if re.fullmatch(re.escape(base) + r'_c\d+', name)}
        if best is None:
            rows.append(dict(
                measure=base, verdict='pending', value=None,
                threshold=threshold, knob=info['knob'],
                reason='no healthy on-chip record of this measure in '
                       'any capture round (smoke lines excluded)',
                corroborating=corroborating))
            continue
        value, source = best
        # strict '>' on the (already 4-decimal-rounded) recorded value:
        # the exact comparison the bench's own verdict line makes, so
        # the two decision records always agree
        verdict = 'flip' if value > threshold else 'keep'
        rows.append(dict(
            measure=base, verdict=verdict, value=value,
            threshold=threshold, knob=info['knob'], source=source,
            reason='%s %.4fx %s the %.2fx rule: %s'
                   % (base, value,
                      'clears' if verdict == 'flip' else 'misses',
                      threshold,
                      ('set %s' % info['knob']) if verdict == 'flip'
                      else 'keep current default'),
            corroborating=corroborating))
    return rows


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parser.add_argument('--dir', default=os.path.join(
        repo, 'benchmarks', 'results'),
        help='capture rounds directory (default benchmarks/results)')
    parser.add_argument('--root', default=repo,
                        help='repo root holding BENCH_*/MULTICHIP_* '
                             'driver snapshots')
    parser.add_argument('--threshold', type=float, default=1.02,
                        help='the flip rule (default 1.02: flip on a '
                             'strictly-greater-than-2%% win)')
    parser.add_argument('--measure', action='append', default=None,
                        help='restrict to specific tracked measures '
                             '(repeatable)')
    parser.add_argument('--write', action='store_true',
                        help='append the verdict rows durably to '
                             '<dir>/flip_verdicts.json')
    parser.add_argument('--json', action='store_true',
                        help='print the rows as JSON lines only')
    args = parser.parse_args(argv)

    if os.path.isdir(args.dir):
        measures, rounds, wedged_jsonl = scan_results_dir(args.dir)
    else:
        measures, rounds, wedged_jsonl = {}, 0, 0
    wedged_snaps, total_snaps = scan_driver_snapshots(args.root)

    tracked = args.measure or list(TRACKED)
    unknown = [m for m in tracked if m not in TRACKED]
    if unknown:
        print('unknown measure(s): %s (tracked: %s)'
              % (', '.join(unknown), ', '.join(TRACKED)),
              file=sys.stderr)
        return 2
    rows = [r for r in decide(measures, args.threshold)
            if r['measure'] in tracked]
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    context = dict(
        checked_at=stamp, capture_rounds_scanned=rounds,
        wedged_capture_rounds=wedged_jsonl,
        wedged_driver_snapshots='%d/%d' % (wedged_snaps, total_snaps))
    for row in rows:
        row.update(context)

    if args.json:
        for row in rows:
            print(json.dumps(row))
    else:
        for row in rows:
            print('%-36s %-8s value=%-8s knob=%s'
                  % (row['measure'], row['verdict'].upper(),
                     ('%.4f' % row['value'])
                     if row['value'] is not None else '-',
                     row['knob']))
            print('    %s' % row['reason'])
            for name, val in sorted(row['corroborating'].items()):
                print('    corroborating %s = %.4f' % (name, val))
        if all(r['verdict'] == 'pending' for r in rows):
            print('\nall verdicts PENDING: %d wedged capture round(s), '
                  '%s wedged driver snapshot(s) — run '
                  'benchmarks/capture_all.sh at the next healthy TPU '
                  'window, then re-run this CLI'
                  % (wedged_jsonl, context['wedged_driver_snapshots']))

    if args.write:
        out_path = os.path.join(args.dir, 'flip_verdicts.json')
        os.makedirs(args.dir, exist_ok=True)
        history = []
        if os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    history = json.load(f)
            except ValueError:
                history = []
        history.extend(rows)
        tmp = out_path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(history, f, indent=1)
        os.replace(tmp, out_path)
        print('wrote %d verdict row(s) -> %s' % (len(rows), out_path),
              file=sys.stderr)
    # exit code mirrors the state: 0 settled (any flip/keep), 3 all
    # pending — scripts can branch without parsing
    return 3 if rows and all(r['verdict'] == 'pending'
                             for r in rows) else 0


if __name__ == '__main__':
    sys.exit(main())
