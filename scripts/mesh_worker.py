"""Standalone mesh worker (SERVING.md "Elastic fleet").

The entry point an EXTERNAL orchestrator — a static host list, k8s, a
drill — execs to add capacity to a running socket-mode mesh without
the mesh spawning anything: the worker builds its model (its own
sub-mesh when ``--device-indices`` places it), warms its ladder, dials
the mesh listener at ``--address``, and serves the framed dispatch
wire exactly like a mesh-spawned worker (scripts/../serving/mesh.py
``_replica_worker_main`` IS the serve loop — this script only
assembles its config).

Because the rid is one the mesh never registered, the dial-in lands on
``SocketListener``'s unclaimed path and the mesh ADOPTS it: validates
wire proto / batch wire format / warm tiers, re-adopts it onto the
fleet's current params step, and gives it a puller.  Restart
supervision stays HERE (the orchestrator's job): if this process dies
the mesh retires its slot without charging the local restart budget,
and re-execing this script is the restart.

The worker dials FIRST, then cold-starts (model build + warmup), then
sends its ready frame — same order as a mesh-spawned worker — so the
mesh's adoption wait (``ServingMesh.adopt_ready_timeout_s``) covers
the cold start; a worker that wedges before ready is dropped typed
when that wait expires (the ``adopt_stall`` drill's shape).

Usage:
  python scripts/mesh_worker.py --address HOST:PORT --load PATH \\
      [--rid RID] [--device-indices 4,5,6,7] [--tiers topk,vectors] \\
      [--heartbeat-secs S] [--config-json FILE]

``--config-json`` ships a full config-overrides dict (what the mesh
would have shipped at spawn) for orchestrators that template worker
configs; the flags below override it.  config-knob-docs lint note:
these are argparse flags of a script, not package knobs — the knobs
they set (``MESH_DEVICE_INDICES`` et al) are documented in README.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def parse_address(text: str):
    host, _, port = text.rpartition(':')
    if not host or not port.isdigit():
        raise argparse.ArgumentTypeError(
            'expected HOST:PORT, got %r' % text)
    return host, int(port)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='externally-orchestrated mesh worker: dials a '
                    'socket-mode ServingMesh listener and serves the '
                    'dispatch wire until closed or killed')
    parser.add_argument('--address', required=True, type=parse_address,
                        help='the mesh listener (MESH_SOCKET_HOST:port '
                             'as logged by the mesh at build)')
    parser.add_argument('--rid', default=None,
                        help='replica id to introduce as (default '
                             'ext-<pid>); must be unique in the fleet')
    parser.add_argument('--load', default=None,
                        help='checkpointed model path (at least one '
                             'retained step); required unless '
                             '--config-json carries MODEL_LOAD_PATH')
    parser.add_argument('--config-json', default=None,
                        help='JSON file of Config field overrides (the '
                             'shape the mesh ships at spawn); flags '
                             'here override its entries')
    parser.add_argument('--device-indices', default=None,
                        help='comma-separated indices into '
                             'jax.devices() — this worker\'s placement '
                             'slice (sets MESH_DEVICE_INDICES)')
    parser.add_argument('--tiers', default=None,
                        help='warm-tier ladder (SERVING_WARM_TIERS); '
                             'must cover the mesh\'s tiers or adoption '
                             'is rejected typed')
    parser.add_argument('--heartbeat-secs', type=float, default=None,
                        help='liveness beat period (MESH_HEARTBEAT_'
                             'SECS); match the mesh\'s or its monitor '
                             'mis-reads the beat cadence')
    args = parser.parse_args(argv)

    overrides = {}
    if args.config_json:
        with open(args.config_json) as handle:
            overrides = dict(json.load(handle))
    if args.load:
        overrides['MODEL_LOAD_PATH'] = args.load
    if args.device_indices:
        overrides['MESH_DEVICE_INDICES'] = args.device_indices
    if args.tiers:
        overrides['SERVING_WARM_TIERS'] = args.tiers
    if args.heartbeat_secs is not None:
        overrides['MESH_HEARTBEAT_SECS'] = args.heartbeat_secs
    if not overrides.get('MODEL_LOAD_PATH'):
        parser.error('a worker restores params from a checkpoint '
                     'store: pass --load PATH (or MODEL_LOAD_PATH in '
                     '--config-json)')
    # the worker serves; it must never save, train, or self-roll —
    # rollover arrives over the wire from the mesh's coordinated canary
    overrides['MODEL_SAVE_PATH'] = ''
    overrides['TRAIN_DATA_PATH_PREFIX'] = ''
    overrides['SERVE_FOLLOW_CHECKPOINTS_SECS'] = 0.0
    rid = args.rid if args.rid else 'ext-%d' % os.getpid()

    from code2vec_tpu.serving import mesh as mesh_lib
    # the serve loop is the ONE worker implementation: same handshake,
    # same wire, same fault sites as a mesh-spawned replica
    mesh_lib._replica_worker_main(rid, overrides, None, args.address)
    return 0


if __name__ == '__main__':
    sys.exit(main())
