#!/usr/bin/env python
"""Reconstruct a training run's goodput story from ``intervals.jsonl``
(telemetry/goodput.py).

Reads the durable goodput ledger a telemetry-enabled fit appends —
``run_start`` / ``window`` / ``interval`` / ``anomaly`` / ``run_end``
records — and prints:

- the goodput line: productive seconds over wall, per run span;
- the badput breakdown table (compile, input_wait, checkpoint, eval,
  rewind, rewind_replay, preempt, warmup) plus an explicit
  ``unattributed`` row, so the buckets visibly sum to wall — the
  honesty check, same contract as memory_report's reconciliation;
- restart gaps: wall time between a ``run_end(reason=preempt)`` and
  the next ``run_start`` (time the job existed but trained nothing);
- the MFU timeline from the per-flush window records;
- the anomaly list (step, shape, step-time vs median, whether a
  profiler capture auto-triggered).

Multi-process runs write one ledger per process
(``intervals.proc<N>.jsonl``); pass the telemetry DIRECTORY to merge
them — per-process spans are reported separately (their wall clocks
overlap; summing would double-count).

jax-free by design (OBSERVABILITY.md "Training goodput").

    python scripts/goodput_report.py telemetry/intervals.jsonl
    python scripts/goodput_report.py telemetry/ --json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

# keep in sync with telemetry/goodput.py BADPUT_KINDS (jax-free script:
# no package import)
BADPUT_KINDS = ('compile', 'input_wait', 'checkpoint', 'eval', 'rewind',
                'rewind_replay', 'preempt', 'warmup')


def load_records(path):
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                pass  # torn tail line of a crashed run
    return records


def discover(target):
    """-> [(proc_label, path)].  A file is one ledger; a directory is
    the proc-0 ledger plus any intervals.proc<N>.jsonl siblings."""
    if os.path.isdir(target):
        paths = sorted(glob.glob(os.path.join(target,
                                              'intervals*.jsonl')))
        if not paths:
            raise FileNotFoundError(
                'no intervals*.jsonl under %s (was the run telemetry-'
                'enabled?)' % target)
    else:
        paths = [target]
    out = []
    for path in paths:
        name = os.path.basename(path)
        label = name[len('intervals'):-len('.jsonl')].lstrip('.') \
            if name.startswith('intervals') else name
        out.append((label or 'proc0', path))
    return out


def split_spans(records):
    """Records -> run spans (run_start .. run_end/EOF).  A crash loses
    the run_end line; the span is then reconstructed from its windows
    so the report still renders."""
    spans, current = [], None
    for record in records:
        kind = record.get('kind')
        if kind == 'run_start':
            if current is not None:
                spans.append(current)
            current = {'start': record, 'end': None, 'windows': [],
                       'intervals': [], 'anomalies': []}
            continue
        if current is None:
            if spans:
                # trailing flush records after a run_end (older ledgers
                # wrote the final window post-run_end) belong to the
                # just-closed span, not a phantom crash span
                current = spans.pop()
            else:
                # tolerate a truncated head: synthesize an open span
                current = {'start': None, 'end': None, 'windows': [],
                           'intervals': [], 'anomalies': []}
        if kind == 'run_end':
            current['end'] = record
            spans.append(current)
            current = None
        elif kind == 'window':
            current['windows'].append(record)
        elif kind == 'interval':
            current['intervals'].append(record)
        elif kind == 'anomaly':
            current['anomalies'].append(record)
    if current is not None:
        spans.append(current)
    return spans


def span_totals(span):
    """Totals for one run span: from its run_end record when present,
    else rebuilt from the window records (crash-safe path)."""
    end = span['end']
    if end is not None:
        return {'wall_s': end.get('wall_s', 0.0),
                'productive_s': end.get('productive_s', 0.0),
                'steps': end.get('steps', 0),
                'badput_s': dict(end.get('badput_s', {})),
                'reason': end.get('reason', 'done'),
                'reconstructed': False}
    badput = {kind: 0.0 for kind in BADPUT_KINDS}
    productive = wall = 0.0
    steps = 0
    for window in span['windows']:
        productive += window.get('productive_s', 0.0)
        wall += window.get('elapsed_s', 0.0)
        steps = max(steps, window.get('step', 0))
        for kind, secs in (window.get('badput_s') or {}).items():
            badput[kind] = badput.get(kind, 0.0) + secs
    return {'wall_s': wall, 'productive_s': productive, 'steps': steps,
            'badput_s': badput, 'reason': 'CRASH (no run_end)',
            'reconstructed': True}


def restart_gaps(spans):
    """Wall seconds between each run_end and the next run_start — job
    alive but training nothing (preemption restart, scheduler requeue)."""
    gaps = []
    for prev, nxt in zip(spans, spans[1:]):
        if prev['end'] is None or nxt['start'] is None:
            continue
        gap = nxt['start'].get('wall', 0) - prev['end'].get('wall', 0)
        if gap > 0:
            gaps.append({'after_reason': prev['end'].get('reason'),
                         'gap_s': gap})
    return gaps


def summarize(spans):
    per_span = [span_totals(span) for span in spans]
    gaps = restart_gaps(spans)
    total_wall = sum(t['wall_s'] for t in per_span) \
        + sum(g['gap_s'] for g in gaps)
    total_productive = sum(t['productive_s'] for t in per_span)
    badput = {kind: 0.0 for kind in BADPUT_KINDS}
    for totals in per_span:
        for kind, secs in totals['badput_s'].items():
            badput[kind] = badput.get(kind, 0.0) + secs
    badput['restart_gap'] = sum(g['gap_s'] for g in gaps)
    attributed = total_productive + sum(badput.values())
    badput['unattributed'] = max(0.0, total_wall - attributed)
    return {'wall_s': total_wall, 'productive_s': total_productive,
            'goodput_fraction': (total_productive / total_wall
                                 if total_wall > 0 else 0.0),
            'steps': sum(t['steps'] for t in per_span),
            'badput_s': badput, 'spans': per_span,
            'restart_gaps': gaps}


def fmt_s(seconds):
    return '%10.2fs' % seconds


def print_summary(summary, label):
    print('== %s: %d run span(s), %d step(s) =='
          % (label, len(summary['spans']), summary['steps']))
    wall = max(summary['wall_s'], 1e-9)
    print('goodput: %.1f%%  (%s productive of %s wall)'
          % (100.0 * summary['goodput_fraction'],
             fmt_s(summary['productive_s']).strip(),
             fmt_s(summary['wall_s']).strip()))
    print()
    print('%-14s %11s %7s' % ('bucket', 'seconds', 'share'))
    print('%-14s %11s %6.1f%%' % ('productive',
                                  fmt_s(summary['productive_s']).strip(),
                                  100.0 * summary['productive_s'] / wall))
    for kind, secs in sorted(summary['badput_s'].items(),
                             key=lambda kv: -kv[1]):
        if secs <= 0 and kind != 'unattributed':
            continue
        print('%-14s %11s %6.1f%%' % (kind, fmt_s(secs).strip(),
                                      100.0 * secs / wall))
    for totals in summary['spans']:
        if totals['reconstructed']:
            print('NOTE: a span had no run_end record (crash?); its '
                  'totals were rebuilt from flush windows and '
                  'understate wall by up to one flush interval.')
            break


def print_mfu_timeline(spans, width):
    rows = [(w.get('step'), w.get('mfu'), w.get('elapsed_s'))
            for span in spans for w in span['windows']
            if w.get('mfu') is not None]
    if not rows:
        return
    print()
    print('MFU timeline (per telemetry flush window):')
    peak = max(m for _s, m, _e in rows)
    for step, mfu, _elapsed in rows:
        bar = '#' * int(round(width * mfu / peak)) if peak > 0 else ''
        print('  step %-8s %7.2f%%  %s' % (step, 100.0 * mfu, bar))


def print_anomalies(spans):
    anomalies = [a for span in spans for a in span['anomalies']]
    if not anomalies:
        return
    print()
    print('step-time anomalies (%d):' % len(anomalies))
    for a in anomalies:
        print('  %s step %-7s %-12s %7.1fms vs median %7.1fms '
              '(%.1f robust sigmas)%s'
              % (time.strftime('%H:%M:%S',
                               time.localtime(a.get('wall', 0))),
                 a.get('step'), a.get('shape', '?'),
                 a.get('step_ms', 0.0), a.get('median_ms', 0.0),
                 a.get('sigma', 0.0),
                 '  [profiler capture auto-triggered]'
                 if a.get('autocapture') else ''))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Reconstruct training goodput from intervals.jsonl '
                    '(OBSERVABILITY.md "Training goodput").')
    parser.add_argument('target',
                        help='intervals.jsonl, or the telemetry '
                             'directory (merges intervals.proc<N>.jsonl '
                             'for multi-process runs)')
    parser.add_argument('--json', action='store_true',
                        help='emit one machine-readable JSON line per '
                             'process instead of tables')
    parser.add_argument('--width', type=int, default=40,
                        help='MFU timeline bar width (default 40)')
    args = parser.parse_args(argv)

    first = True
    for label, path in discover(args.target):
        spans = split_spans(load_records(path))
        summary = summarize(spans)
        if args.json:
            print(json.dumps({'proc': label, **{
                key: summary[key] for key in
                ('wall_s', 'productive_s', 'goodput_fraction', 'steps',
                 'badput_s', 'restart_gaps')}}))
            continue
        if not first:
            print()
        first = False
        print_summary(summary, label)
        print_mfu_timeline(spans, args.width)
        print_anomalies(spans)
    return 0


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `goodput_report.py ... | head` closes the pipe mid-table; die
        # quietly like any well-behaved filter
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
