#!/usr/bin/env bash
###########################################################
# train.sh — canonical training invocation
# (role of the reference's train.sh:9-18)
# Change the following values to train a new model.
# type: the name of the new model.
# dataset_name: the name of the dataset, as was preprocessed.
# data_dir: directory containing the preprocessed data.
type=${TYPE:-code2vec_tpu_model}
dataset_name=${DATASET_NAME:-java14m}
data_dir=${DATA_DIR:-data/${dataset_name}}
data=${data_dir}/${dataset_name}
test_data=${data_dir}/${dataset_name}.val.c2v
model_dir=${MODEL_DIR:-models/${type}}

set -e
mkdir -p "${model_dir}"
exec python -u -m code2vec_tpu.cli \
  --data "${data}" \
  --test "${test_data}" \
  --save "${model_dir}/saved_model" \
  "$@"
