"""Lint: every metric name emitted anywhere in the codebase must exist in
the telemetry catalog (code2vec_tpu/telemetry/catalog.py), and every
cataloged name must be documented in OBSERVABILITY.md — so metric names
cannot silently drift from the catalog/doc (ISSUE 2 satellite; runs in
tier-1 via tests/test_metrics_schema.py).

Since ISSUE 6 this is a thin CLI over the graftlint rule
``metrics-schema`` (code2vec_tpu/analysis/rules/metrics_schema.py —
ANALYSIS.md): same regex, same scan scope, same exit codes; the rule
additionally runs under ``scripts/lint_all.py`` with the shared
suppression/baseline machinery.

Grep-based by design: emission sites are method calls with a string
literal —

    registry.counter('train/steps_total')   .gauge(...)   .timer(...)
    writer.scalar('eval/top1_acc', ...)     registry.get('step/h2d_ms')

A literal only counts as a metric name if it contains '/' (the catalog's
``subsystem/metric`` shape), which keeps ordinary ``dict.get`` calls out.

Exit status: 0 clean, 1 on unknown emissions or undocumented catalog
entries.  ``--list`` prints every discovered emission with its site.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the rule owns the regex + scan; re-exported here because
# tests/test_metrics_schema.py (and muscle memory) import them from
# this module
from code2vec_tpu.analysis.rules.metrics_schema import (  # noqa: E402
    EMIT_RE)
from code2vec_tpu.analysis.rules import metrics_schema as _rule  # noqa: E402
from code2vec_tpu.analysis.walker import SourceTree  # noqa: E402


def find_emissions():
    """[(relpath, lineno, metric_name)] across the scanned tree."""
    return _rule.find_emissions(SourceTree(REPO))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from code2vec_tpu.analysis import engine
    from code2vec_tpu.telemetry.catalog import CATALOG

    tree = SourceTree(REPO)
    emissions = _rule.find_emissions(tree)
    if '--list' in argv:
        for rel, lineno, name in emissions:
            print('%s:%d: %s' % (rel, lineno, name))

    # standalone semantics: no baseline — schema drift is never OK —
    # and ONLY this rule's findings: unrelated graftlint meta-findings
    # (malformed suppressions elsewhere in the tree) belong to lint_all
    report = engine.run(root=REPO, rule_names=['metrics-schema'],
                        baseline_path='', tree=tree)
    failures = [f for f in report.findings if f.rule == 'metrics-schema']

    emitted = {name for _rel, _lineno, name in emissions}
    for name in sorted(set(CATALOG) - emitted):
        # informational only: names can be emitted dynamically or be
        # reserved ahead of an integration landing
        print('note: cataloged metric %r has no static emission site'
              % name)

    if failures:
        for finding in failures:
            print(finding.format(), file=sys.stderr)
        print('%d metric-schema violation(s).' % len(failures),
              file=sys.stderr)
        return 1
    print('metrics schema OK: %d emission sites, %d cataloged names.'
          % (len(emissions), len(CATALOG)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
