"""Lint: every metric name emitted anywhere in the codebase must exist in
the telemetry catalog (code2vec_tpu/telemetry/catalog.py), and every
cataloged name must be documented in OBSERVABILITY.md — so metric names
cannot silently drift from the catalog/doc (ISSUE 2 satellite; runs in
tier-1 via tests/test_metrics_schema.py).

Grep-based by design: emission sites are method calls with a string
literal —

    registry.counter('train/steps_total')   .gauge(...)   .timer(...)
    writer.scalar('eval/top1_acc', ...)     registry.get('step/h2d_ms')

A literal only counts as a metric name if it contains '/' (the catalog's
``subsystem/metric`` shape), which keeps ordinary ``dict.get`` calls out.

Exit status: 0 clean, 1 on unknown emissions or undocumented catalog
entries.  ``--list`` prints every discovered emission with its site.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# Directories scanned for emission sites (the whole code2vec_tpu tree —
# including subsystem packages like serving/, resilience/ and index/; a
# coverage regression on index/ is guarded by tests/test_index.py).
# tests/ is deliberately out: tests mint throwaway names to exercise the
# instruments themselves.
SCAN_DIRS = ('code2vec_tpu', 'benchmarks', 'scripts')
SCAN_FILES = ('bench.py',)

# \s* spans newlines: emission calls wrap across lines under the
# 79-column style, so matching is against whole-file content
EMIT_RE = re.compile(
    r"""\.(?:counter|gauge|timer|scalar|get)\(\s*['"]([^'"]*/[^'"]*)['"]""")


def iter_python_files():
    for rel in SCAN_DIRS:
        for dirpath, _dirnames, filenames in os.walk(os.path.join(REPO, rel)):
            if '__pycache__' in dirpath:
                continue
            for name in sorted(filenames):
                if name.endswith('.py'):
                    yield os.path.join(dirpath, name)
    for rel in SCAN_FILES:
        path = os.path.join(REPO, rel)
        if os.path.isfile(path):
            yield path


def find_emissions():
    """[(relpath, lineno, metric_name)] across the scanned tree."""
    out = []
    for path in iter_python_files():
        rel = os.path.relpath(path, REPO)
        with open(path, 'r') as f:
            content = f.read()
        for match in EMIT_RE.finditer(content):
            lineno = content.count('\n', 0, match.start()) + 1
            out.append((rel, lineno, match.group(1)))
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from code2vec_tpu.telemetry.catalog import CATALOG

    emissions = find_emissions()
    if '--list' in argv:
        for rel, lineno, name in emissions:
            print('%s:%d: %s' % (rel, lineno, name))

    failures = []
    for rel, lineno, name in emissions:
        if name not in CATALOG:
            failures.append(
                '%s:%d: metric %r is not in the catalog '
                '(code2vec_tpu/telemetry/catalog.py) — add it there and to '
                'OBSERVABILITY.md, or fix the name' % (rel, lineno, name))

    doc_path = os.path.join(REPO, 'OBSERVABILITY.md')
    if os.path.isfile(doc_path):
        with open(doc_path, 'r') as f:
            doc = f.read()
        for name in sorted(CATALOG):
            if name not in doc:
                failures.append(
                    'OBSERVABILITY.md: cataloged metric %r is undocumented'
                    % name)
    else:
        failures.append('OBSERVABILITY.md is missing (the metric catalog '
                        'must be documented)')

    emitted = {name for _rel, _lineno, name in emissions}
    for name in sorted(set(CATALOG) - emitted):
        # informational only: names can be emitted dynamically or be
        # reserved ahead of an integration landing
        print('note: cataloged metric %r has no static emission site'
              % name)

    if failures:
        print('\n'.join(failures), file=sys.stderr)
        print('%d metric-schema violation(s).' % len(failures),
              file=sys.stderr)
        return 1
    print('metrics schema OK: %d emission sites, %d cataloged names.'
          % (len(emissions), len(CATALOG)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
