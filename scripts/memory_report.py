#!/usr/bin/env python
"""Render device-memory ledger snapshots (telemetry/memory.py).

Reads the JSON the ledger dumps — ``memory_report.json``
(``--memory-report``), ``memory_step<N>.json`` (``MEM_NOW``), or
``oom_ledger.json`` (forensics) — and prints:

- the bucket table: bytes, share of attributed, watermark;
- the reconciliation line: attributed vs backend live bytes and the
  unattributed residual (the honesty check);
- the per-bucket x capacity executable-size table of the warm serving
  ladder;
- the recent allocation-event tail;
- with ``--diff OLDER.json``: per-bucket and per-entry deltas — the
  leak check between two moments of a run.

jax-free by design (OBSERVABILITY.md "Device memory ledger").

    python scripts/memory_report.py telemetry/memory_report.json
    python scripts/memory_report.py oom_ledger.json --diff baseline.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def human(nbytes) -> str:
    if nbytes is None:
        return '-'
    value = float(nbytes)
    for unit in ('B', 'KiB', 'MiB', 'GiB', 'TiB'):
        if abs(value) < 1024.0 or unit == 'TiB':
            return ('%+.1f %s' % (value, unit) if nbytes < 0
                    else '%.1f %s' % (value, unit))
        value /= 1024.0
    return str(nbytes)


def load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def print_buckets(snap: dict, entries_per_bucket: int) -> None:
    attributed = max(1, snap.get('attributed_bytes', 0))
    watermarks = snap.get('watermarks', {})
    print('%-14s %14s %7s %14s' % ('bucket', 'bytes', 'share',
                                   'watermark'))
    for bucket, record in sorted(snap['buckets'].items(),
                                 key=lambda kv: -kv[1]['bytes']):
        print('%-14s %14s %6.1f%% %14s'
              % (bucket, human(record['bytes']),
                 100.0 * record['bytes'] / attributed,
                 human(watermarks.get(bucket))))
        for entry in record['entries'][:entries_per_bucket]:
            print('    %-40s %14s  %s'
                  % (entry['key'], human(entry['bytes']),
                     entry.get('attrs', '')))
        hidden = len(record['entries']) - entries_per_bucket
        if hidden > 0:
            print('    ... %d more entries (--entries N)' % hidden)


def print_reconciliation(snap: dict) -> None:
    backend = snap.get('backend')
    attributed = snap.get('attributed_bytes', 0)
    print('attributed: %s  (executables, reported separately: %s)'
          % (human(attributed), human(snap.get('executables_bytes', 0))))
    budget = snap.get('budget_bytes', 0)
    if budget:
        print('budget:     %s  (headroom %s)'
              % (human(budget), human(budget - attributed)))
    if backend is None:
        print('backend:    (snapshot was not reconciled)')
        return
    live = backend['live_bytes']
    residual = snap.get('unattributed_bytes', live - attributed)
    print('backend:    %s live across %d arrays (%s)'
          % (human(live), backend.get('live_arrays', 0),
             backend.get('source', '?')))
    print('unattributed residual: %s (%.1f%% of live)'
          % (human(residual), 100.0 * residual / max(1, live)))
    for dev in backend.get('devices', []):
        print('  device %s: in_use %s, peak %s'
              % (dev.get('id'), human(dev.get('bytes_in_use')),
                 human(dev.get('peak_bytes_in_use'))))


def print_executables(snap: dict) -> None:
    entries = snap['buckets'].get('executables', {}).get('entries', [])
    rows = [e for e in entries if 'attrs' in e
            and 'bucket' in e['attrs']]
    if not rows:
        return
    print()
    print('warm serving ladder (per bucket x capacity executable sizes):')
    print('%-10s %7s %9s %12s %12s %12s %12s'
          % ('tier', 'bucket', 'capacity', 'code', 'temp', 'args',
             'outputs'))
    for entry in sorted(rows, key=lambda e: (
            e['attrs'].get('tier', ''), e['attrs'].get('bucket', 0),
            e['attrs'].get('capacity', 0))):
        attrs = entry['attrs']
        print('%-10s %7s %9s %12s %12s %12s %12s'
              % (attrs.get('tier', '?'), attrs.get('bucket', '?'),
                 attrs.get('capacity', '?'),
                 human(attrs.get('generated_code_bytes')),
                 human(attrs.get('temp_bytes')),
                 human(attrs.get('argument_bytes')),
                 human(attrs.get('output_bytes'))))


def print_events(snap: dict, tail: int) -> None:
    events = snap.get('events', [])[-tail:]
    if not events:
        return
    print()
    print('recent allocation events:')
    for event in events:
        print('  %s %-8s %-10s %-40s %s'
              % (time.strftime('%H:%M:%S',
                               time.localtime(event.get('t', 0))),
                 event.get('op'), event.get('bucket'),
                 event.get('key'), human(event.get('bytes'))))


def print_diff(before: dict, after: dict) -> None:
    print('diff (%s -> %s):'
          % (before.get('reason', '?'), after.get('reason', '?')))
    delta = after.get('attributed_bytes', 0) \
        - before.get('attributed_bytes', 0)
    print('attributed delta: %s' % human(delta))
    if 'backend' in before and 'backend' in after:
        print('backend live delta: %s'
              % human(after['backend']['live_bytes']
                      - before['backend']['live_bytes']))
        print('unattributed delta: %s'
              % human(after.get('unattributed_bytes', 0)
                      - before.get('unattributed_bytes', 0)))
    for bucket in sorted(after['buckets']):
        b_rec = before['buckets'].get(bucket, {'bytes': 0, 'entries': []})
        a_rec = after['buckets'][bucket]
        bucket_delta = a_rec['bytes'] - b_rec['bytes']
        b_entries = {e['key']: e['bytes'] for e in b_rec['entries']}
        a_entries = {e['key']: e['bytes'] for e in a_rec['entries']}
        changed = {key: a_entries.get(key, 0) - b_entries.get(key, 0)
                   for key in set(b_entries) | set(a_entries)
                   if a_entries.get(key, 0) != b_entries.get(key, 0)}
        if not bucket_delta and not changed:
            continue
        print('%-14s %14s' % (bucket, human(bucket_delta)))
        for key, entry_delta in sorted(changed.items(),
                                       key=lambda kv: -abs(kv[1])):
            state = ('added' if key not in b_entries else
                     'removed' if key not in a_entries else 'resized')
            print('    %-40s %14s  (%s)'
                  % (key, human(entry_delta), state))
    if delta > 0:
        print('NOTE: attributed bytes grew — if this spans a drill that '
              'should be footprint-neutral (e.g. a rollover swap), the '
              'grown entries above are the leak suspects.')


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='Render device-memory ledger snapshots '
                    '(OBSERVABILITY.md "Device memory ledger").')
    parser.add_argument('snapshot', help='ledger snapshot JSON '
                        '(memory_report.json / memory_step<N>.json / '
                        'oom_ledger.json)')
    parser.add_argument('--diff', metavar='OLDER.json', default=None,
                        help='print deltas from an older snapshot '
                             '(leak check) instead of the full render')
    parser.add_argument('--entries', type=int, default=4,
                        help='entries shown per bucket (default 4)')
    parser.add_argument('--events', type=int, default=10,
                        help='allocation events shown (default 10)')
    parser.add_argument('--json', action='store_true',
                        help='emit one machine-readable JSON line '
                             'instead of tables')
    args = parser.parse_args(argv)

    snap = load(args.snapshot)
    if args.diff:
        before = load(args.diff)
        if args.json:
            from code2vec_tpu.telemetry.memory import MemoryLedger
            print(json.dumps(MemoryLedger.diff(before, snap)))
            return 0
        print_diff(before, snap)
        return 0
    if args.json:
        print(json.dumps({
            'reason': snap.get('reason'),
            'attributed_bytes': snap.get('attributed_bytes'),
            'unattributed_bytes': snap.get('unattributed_bytes'),
            'backend_live_bytes': snap.get('backend', {}).get(
                'live_bytes'),
            'budget_bytes': snap.get('budget_bytes'),
            'buckets': {bucket: record['bytes'] for bucket, record
                        in snap['buckets'].items()},
            'watermarks': snap.get('watermarks', {}),
        }))
        return 0
    print('ledger snapshot: %s (reason: %s)'
          % (args.snapshot, snap.get('reason', '?')))
    print_reconciliation(snap)
    print()
    print_buckets(snap, args.entries)
    print_executables(snap)
    print_events(snap, args.events)
    return 0


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `memory_report.py ... | head` closes the pipe mid-table; die
        # quietly like any well-behaved filter
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
