"""Chaos soak for the self-healing serving mesh (SERVING.md
"Multi-host mesh").

A paced open-loop generator drives a worker-mode mesh while the fault
grammar periodically kills its workers: every worker incarnation is
armed with ``kill_worker`` (SIGKILL at its K-th dispatch, mid-batch)
and ``drop_heartbeat`` (goes silent after its B-th beat, the
hung-worker shape) — each supervised restart re-arms the plan in the
fresh process, so the faults fire PERIODICALLY for the whole soak.
The assertions are the self-healing contract:

- **zero lost admitted requests** — every submitted future resolves
  with results or a TYPED serving error; a hung future or an untyped
  exception fails the soak (crash-safe redispatch + supervised restart
  mean a crash costs latency, not answers);
- **zero post-warmup compiles in the parent** — healing never escapes
  the warm path on the serving side of the wire (worker cold starts
  compile in their OWN processes, off the parent's counter);
- **bounded p99** — restart latency is visible but bounded
  (``--p99-bound-ms``);
- **zero unstitched trace trees** — at ``TRACING_SAMPLE_RATE=1.0``,
  every delivered request's span tree must carry its worker-side
  device-execute spans (cross-process stitching, OBSERVABILITY.md
  "Fleet observability"); a wire-truncated tree fails the soak.

Prints one JSON line per metric (``mesh_soak_*``); exit 1 on any
violation.  ``BENCH_SMOKE=1`` shrinks shapes and duration for the
tier-1 smoke (tests/test_bench_smoke.py); the slow-marked full run and
``capture_all.sh`` (stage ``mesh_soak``) use the real durations.

Usage: python scripts/mesh_soak.py [--secs S] [--replicas N]
       [--mode process|socket] [--kill-every K] [--drop-beat-at B]
       [--interval-ms MS] [--p99-bound-ms MS]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402


def main() -> int:
    benchlib.honor_env_platforms()
    smoke = benchlib.smoke_requested()
    parser = argparse.ArgumentParser()
    parser.add_argument('--secs', type=float,
                        default=10.0 if smoke else 45.0,
                        help='paced-load duration')
    parser.add_argument('--replicas', type=int, default=2)
    parser.add_argument('--mode', default='process',
                        choices=['process', 'socket'])
    parser.add_argument('--kill-every', type=int,
                        default=6 if smoke else 25,
                        help='kill_worker fires at each incarnation\'s '
                             'K-th dispatch (mid-batch SIGKILL)')
    parser.add_argument('--drop-beat-at', type=int,
                        default=14 if smoke else 60,
                        help='drop_heartbeat window start: the '
                             'incarnation goes silent from its B-th '
                             'beat (liveness kill)')
    parser.add_argument('--interval-ms', type=float,
                        default=80.0 if smoke else 50.0,
                        help='pacing between submits')
    parser.add_argument('--p99-bound-ms', type=float, default=30000.0,
                        help='bounded-p99 assertion over delivered '
                             'requests (restart latency included)')
    parser.add_argument('--rows', type=int, default=200 if smoke else 1000)
    parser.add_argument('--contexts', type=int, default=6 if smoke else 50)
    parser.add_argument('--tokens', type=int, default=500 if smoke else 5000)
    parser.add_argument('--paths', type=int, default=500 if smoke else 8000)
    parser.add_argument('--labels', type=int, default=100 if smoke else 1000)
    args = parser.parse_args()

    from benchmarks.bench_serving import synthesize_dataset
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.serving.errors import ServingError
    from code2vec_tpu.telemetry import core as tele_core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener

    workdir = tempfile.mkdtemp(prefix='c2v_meshsoak_')
    prefix = os.path.join(workdir, 'synth')
    lines = synthesize_dataset(prefix, args.rows, args.contexts,
                               args.tokens, args.paths, args.labels)
    # every restarted worker re-arms this plan in its fresh process, so
    # the faults fire once per INCARNATION — periodic chaos by
    # construction
    fault_spec = ('kill_worker@dispatch=%d,drop_heartbeat@beat=%d..%d'
                  % (args.kill_every, args.drop_beat_at,
                     args.drop_beat_at + 9999))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=prefix,
        MODEL_SAVE_PATH=os.path.join(workdir, 'model'),
        DL_FRAMEWORK='jax', VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MAX_CONTEXTS=args.contexts, SERVING_BATCH_BUCKETS='8,32',
        SERVING_WARM_TIERS='topk', FAULT_INJECT=fault_spec,
        MESH_HEARTBEAT_SECS=0.25, MESH_HEARTBEAT_MISSES=2,
        MESH_RESTART_BACKOFF_SECS=0.1,
        MESH_RESTART_LIMIT=10_000,  # the soak must keep healing
        MESH_RESTART_WINDOW_SECS=3600.0,
        # trace EVERY request: the stitching assertion below needs the
        # full span-tree population, not a sample
        TRACING_SAMPLE_RATE=1.0)
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)

    tele_core.enable()
    install_compile_listener()
    compiles = tele_core.registry().counter('jit/compiles_total')

    def emit(record):
        if smoke:
            record['smoke'] = True
        print(json.dumps(record), flush=True)

    mesh = model.serving_mesh(replicas=args.replicas, tiers=('topk',),
                              mode=args.mode, max_delay_ms=1.0)
    violations = []
    try:
        # warm the whole serving path once, then pin the compile mark
        mesh.predict([lines[0]], tier='topk', timeout=300)
        warm = compiles.value
        rng = np.random.default_rng(11)
        futures = []
        stamps = []
        t0 = time.perf_counter()
        deadline = t0 + args.secs
        while time.perf_counter() < deadline:
            request_lines = [lines[rng.integers(len(lines))]
                             for _ in range(int(rng.integers(1, 4)))]
            try:
                futures.append(mesh.submit(request_lines, tier='topk'))
                stamps.append(time.perf_counter())
            except ServingError:
                futures.append(None)  # typed shed at admission: fine
                stamps.append(time.perf_counter())
            time.sleep(args.interval_ms / 1e3)
        # drain: every admitted future must RESOLVE — results or typed
        from concurrent.futures import TimeoutError as FutureTimeout
        ok = shed = typed = lost = untyped = 0
        latencies = []
        for t_submit, future in zip(stamps, futures):
            if future is None:
                shed += 1
                continue
            try:
                results = future.result(timeout=180)
            except ServingError:
                typed += 1  # expired/shed/replica-dead: typed, not lost
            except FutureTimeout:
                # a future that never resolved inside the generous
                # drain window is LOST — the exact hang this soak
                # exists to catch
                lost += 1
                violations.append('hung future (never resolved)')
            except Exception as exc:
                untyped += 1
                violations.append('untyped failure: %r' % exc)
            else:
                assert results
                ok += 1
                latencies.append(time.perf_counter() - t_submit)
        postwarm = compiles.value - warm
        wall = time.perf_counter() - t0
        stats = mesh.stats()
    finally:
        mesh.close()
        model.close_stores()

    lat_ms = np.asarray(sorted(latencies)) * 1e3
    p50 = float(np.percentile(lat_ms, 50)) if len(lat_ms) else None
    p99 = float(np.percentile(lat_ms, 99)) if len(lat_ms) else None
    total = len(futures)
    if ok == 0:
        violations.append('no request ever completed')
    if postwarm != 0:
        violations.append('%d post-warmup parent compiles' % postwarm)
    if p99 is not None and p99 > args.p99_bound_ms:
        violations.append('p99 %.0fms > bound %.0fms'
                          % (p99, args.p99_bound_ms))
    if stats['restarts_total'] < 1:
        violations.append('no supervised restart fired — the chaos '
                          'never bit (raise --secs or lower '
                          '--kill-every)')

    # cross-process stitching contract (OBSERVABILITY.md "Fleet
    # observability"): ZERO admitted requests may finish with a
    # wire-truncated trace tree — every delivered trace must carry its
    # worker-side device-execute spans, grafted by adopt_spans
    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    from latency_report import (group_traces, load_spans,
                                unstitched_traces)
    spans_path = os.path.join(workdir, 'telemetry', 'spans.jsonl')
    stitched_total = unstitched = None
    if os.path.exists(spans_path):
        traces = group_traces(load_spans(spans_path))
        delivered = [e for e in traces.values()
                     if e['root'] is not None
                     and e['root'].get('status') in (None, 'ok')]
        truncated = unstitched_traces(traces)
        stitched_total = len(delivered)
        unstitched = len(truncated)
        if ok and not delivered:
            violations.append('requests completed but the span log '
                              'has no delivered traces (tracing '
                              'broken?)')
        if truncated:
            violations.append(
                '%d delivered trace(s) finished UNSTITCHED (no '
                'worker device-execute spans): %s'
                % (len(truncated), truncated[:8]))
    elif ok:
        violations.append('no span log at %s (stitching assertion '
                          'could not run)' % spans_path)
    emit({'metric': 'mesh_soak_unstitched_traces', 'value': unstitched,
          'delivered_traces': stitched_total,
          'adopted_spans': stats.get('adopted_spans_total'),
          'remote_spans_dropped':
              stats.get('remote_spans_dropped_total')})

    emit({'metric': 'mesh_soak_requests', 'value': total, 'ok': ok,
          'shed_at_admission': shed, 'typed_failures': typed,
          'untyped_failures': untyped, 'lost': lost,
          'wall_s': round(wall, 2), 'mode': args.mode,
          'replicas': args.replicas, 'fault_spec': fault_spec})
    emit({'metric': 'mesh_soak_lost_requests', 'value': lost + untyped})
    emit({'metric': 'mesh_soak_p99_ms',
          'value': round(p99, 1) if p99 is not None else None,
          'p50_ms': round(p50, 1) if p50 is not None else None,
          'bound_ms': args.p99_bound_ms})
    emit({'metric': 'mesh_soak_restarts',
          'value': stats['restarts_total'],
          'redispatched': stats['redispatched_total'],
          'heartbeat_misses': stats['heartbeat_misses_total'],
          'replica_breaker_open_total':
              stats['replica_breaker_open_total']})
    emit({'metric': 'mesh_soak_postwarm_compiles', 'value': postwarm})
    if violations:
        emit({'metric': 'mesh_soak_violations', 'value': len(violations),
              'detail': violations})
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
