"""Chaos soak for the self-healing serving mesh (SERVING.md
"Multi-host mesh").

A paced open-loop generator drives a worker-mode mesh while the fault
grammar periodically kills its workers: every worker incarnation is
armed with ``kill_worker`` (SIGKILL at its K-th dispatch, mid-batch)
and ``drop_heartbeat`` (goes silent after its B-th beat, the
hung-worker shape) — each supervised restart re-arms the plan in the
fresh process, so the faults fire PERIODICALLY for the whole soak.
The assertions are the self-healing contract:

- **zero lost admitted requests** — every submitted future resolves
  with results or a TYPED serving error; a hung future or an untyped
  exception fails the soak (crash-safe redispatch + supervised restart
  mean a crash costs latency, not answers);
- **zero post-warmup compiles in the parent** — healing never escapes
  the warm path on the serving side of the wire (worker cold starts
  compile in their OWN processes, off the parent's counter);
- **bounded p99** — restart latency is visible but bounded
  (``--p99-bound-ms``);
- **zero unstitched trace trees** — at ``TRACING_SAMPLE_RATE=1.0``,
  every delivered request's span tree must carry its worker-side
  device-execute spans (cross-process stitching, OBSERVABILITY.md
  "Fleet observability"); a wire-truncated tree fails the soak
  (memo-hit traces are exempt by design — they never reach a worker);
- **zero stale memo serves** — the soak runs with the memoization tier
  ON (``--memo-bytes``) and half the load replaying one hot request;
  mid-soak fleet rollover drills (``--rollovers``) swap params to a
  freshly saved step and assert the swap atomically invalidated the
  cache: zero entries survive, the first post-swap duplicate runs
  LIVE, and the generation advanced per completed rollover;
- **elastic transitions survive the chaos** (SERVING.md "Elastic
  fleet") — mid-soak the fleet SCALES UP by one replica while the
  kill/heartbeat chaos keeps firing (the cold start, step re-adopt,
  and queue join must not lose a request), serves through it, then
  DRAINS that replica back out while a ``partition`` fault blackholes
  parent-side frames — the liveness monitor, not the drain, must break
  the stall, the retirement lands typed (``retired_reason='drain'``),
  and zero admitted requests are lost across both transitions.

Prints one JSON line per metric (``mesh_soak_*``); exit 1 on any
violation.  ``BENCH_SMOKE=1`` shrinks shapes and duration for the
tier-1 smoke (tests/test_bench_smoke.py); the slow-marked full run and
``capture_all.sh`` (stage ``mesh_soak``) use the real durations.

Usage: python scripts/mesh_soak.py [--secs S] [--replicas N]
       [--mode process|socket] [--kill-every K] [--drop-beat-at B]
       [--interval-ms MS] [--p99-bound-ms MS] [--memo-bytes B]
       [--rollovers R]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402


def main() -> int:
    benchlib.honor_env_platforms()
    smoke = benchlib.smoke_requested()
    parser = argparse.ArgumentParser()
    parser.add_argument('--secs', type=float,
                        default=10.0 if smoke else 45.0,
                        help='paced-load duration')
    parser.add_argument('--replicas', type=int, default=2)
    parser.add_argument('--mode', default='process',
                        choices=['process', 'socket'])
    parser.add_argument('--kill-every', type=int,
                        default=6 if smoke else 25,
                        help='kill_worker fires at each incarnation\'s '
                             'K-th dispatch (mid-batch SIGKILL)')
    parser.add_argument('--drop-beat-at', type=int,
                        default=14 if smoke else 60,
                        help='drop_heartbeat window start: the '
                             'incarnation goes silent from its B-th '
                             'beat (liveness kill)')
    parser.add_argument('--interval-ms', type=float,
                        default=80.0 if smoke else 50.0,
                        help='pacing between submits')
    parser.add_argument('--p99-bound-ms', type=float, default=30000.0,
                        help='bounded-p99 assertion over delivered '
                             'requests (restart latency included)')
    parser.add_argument('--memo-bytes', type=int, default=32 << 20,
                        help='memoization-tier budget for the soak '
                             '(default ON: the chaos drills must hold '
                             'with the cache in front of the fleet; '
                             '0 disables)')
    parser.add_argument('--rollovers', type=int, default=2,
                        help='mid-soak fleet rollover drills: each '
                             'must atomically invalidate the memo '
                             'cache (generation bump) with zero stale '
                             'serves after the swap')
    parser.add_argument('--elastic', type=int, default=1,
                        help='run the elastic-transition drill: scale '
                             'up one replica under the kill chaos, '
                             'serve, then drain it back out during a '
                             'partition window (0 disables)')
    parser.add_argument('--index-rollovers', type=int, default=1,
                        help='run the canaried INDEX rollover drill: '
                             'shadow-query a disagreeing candidate on '
                             'live neighbor traffic (must roll back, '
                             'memo stays warm), then an agreeing one '
                             '(must swap: memo index generation bumps, '
                             'zero stale neighbor serves, predict '
                             'entries survive) (0 disables)')
    parser.add_argument('--rows', type=int, default=200 if smoke else 1000)
    parser.add_argument('--contexts', type=int, default=6 if smoke else 50)
    parser.add_argument('--tokens', type=int, default=500 if smoke else 5000)
    parser.add_argument('--paths', type=int, default=500 if smoke else 8000)
    parser.add_argument('--labels', type=int, default=100 if smoke else 1000)
    args = parser.parse_args()

    from benchmarks.bench_serving import synthesize_dataset
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.resilience import faults
    from code2vec_tpu.serving.errors import ServingError
    from code2vec_tpu.telemetry import core as tele_core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener

    workdir = tempfile.mkdtemp(prefix='c2v_meshsoak_')
    prefix = os.path.join(workdir, 'synth')
    lines = synthesize_dataset(prefix, args.rows, args.contexts,
                               args.tokens, args.paths, args.labels)
    # every restarted worker re-arms this plan in its fresh process, so
    # the faults fire once per INCARNATION — periodic chaos by
    # construction
    fault_spec = ('kill_worker@dispatch=%d,drop_heartbeat@beat=%d..%d'
                  % (args.kill_every, args.drop_beat_at,
                     args.drop_beat_at + 9999))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=prefix,
        MODEL_SAVE_PATH=os.path.join(workdir, 'model'),
        DL_FRAMEWORK='jax', VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MAX_CONTEXTS=args.contexts, SERVING_BATCH_BUCKETS='8,32',
        SERVING_WARM_TIERS='topk', FAULT_INJECT=fault_spec,
        MESH_HEARTBEAT_SECS=0.25, MESH_HEARTBEAT_MISSES=2,
        MESH_RESTART_BACKOFF_SECS=0.1,
        MESH_RESTART_LIMIT=10_000,  # the soak must keep healing
        MESH_RESTART_WINDOW_SECS=3600.0,
        # trace EVERY request: the stitching assertion below needs the
        # full span-tree population, not a sample
        TRACING_SAMPLE_RATE=1.0)
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)

    tele_core.enable()
    install_compile_listener()
    compiles = tele_core.registry().counter('jit/compiles_total')

    def emit(record):
        if smoke:
            record['smoke'] = True
        print(json.dumps(record), flush=True)

    tiers = (('topk', 'vectors') if args.index_rollovers
             else ('topk',))  # attach_index needs the vectors tier
    mesh = model.serving_mesh(replicas=args.replicas, tiers=tiers,
                              mode=args.mode, max_delay_ms=1.0,
                              memo_cache_bytes=args.memo_bytes)
    memo_on = args.memo_bytes > 0
    violations = []
    rollovers_done = 0
    drill_retries = 0
    try:
        import jax.numpy as jnp

        # warm the whole serving path once
        mesh.predict([lines[0]], tier='topk', timeout=300)
        rng = np.random.default_rng(11)
        # the memo tier's traffic shape: half the load replays one hot
        # request, so cache hits ride THROUGH the kill/restart chaos
        hot = [lines[0], lines[1]]

        index_drill = {'rollback_ok': None, 'swap_ok': None,
                       'agreement': None, 'stale_serves': 0,
                       'predict_survived': None, 'error': None}

        def index_rollover_drill(attempt: int):
            """Canaried index rollover (ISSUE 19): shadow-query a
            DISAGREEING candidate on live neighbor traffic (must roll
            back; the neighbor memo stays warm), then an AGREEING one
            (must swap: the memo index generation bumps — zero stale
            neighbor serves — while predict entries survive, since the
            model didn't change).  Runs before the compile mark is
            pinned: index builds/searches compile their own warm
            programs, which are not the serving path's compiles."""
            from code2vec_tpu.index import store as store_lib
            from code2vec_tpu.index.quant import QuantizedIVFIndex
            index_drill.update(rollback_ok=None, swap_ok=None,
                               agreement=None, stale_serves=0,
                               predict_survived=None, error=None)
            # a prior attempt may have died with a rollover armed;
            # feed it shadow traffic until it concludes so arming a
            # fresh one doesn't refuse with 'already in flight'
            for _ in range(64):
                if mesh._index_rollover is None:
                    break
                try:
                    mesh.submit_neighbors(hot, k=5).result(timeout=300)
                except Exception:
                    time.sleep(0.2)
            dim = mesh.predict([lines[0]], tier='vectors',
                               timeout=300)[0].code_vector.shape[0]
            rng_i = np.random.default_rng(7)
            corpus = rng_i.normal(size=(512, dim)).astype(np.float32)
            store = store_lib.build(
                os.path.join(workdir, 'drill%d.vecindex' % attempt),
                [corpus], labels=['m%d' % i for i in range(512)])
            class _Counting:
                """Search-call counter: a cache-served neighbor answer
                never touches the index, while a live one always does —
                unlike .done(), which is also True when the chain
                resolves synchronously off a warm vectors-tier hit."""

                def __init__(self, inner):
                    self._inner = inner
                    self.searches = 0

                def search(self, vectors, k):
                    self.searches += 1
                    return self._inner.search(vectors, k)

                def __getattr__(self, name):
                    return getattr(self._inner, name)

            live_idx = QuantizedIVFIndex.build(store, kind='int8',
                                               seed=0)
            live_idx.warmup(5)
            live = _Counting(live_idx)
            mesh.attach_index(live)
            # warm one neighbor memo entry + confirm the duplicate is
            # served WITHOUT a live index search
            mesh.submit_neighbors(hot, k=5).result(timeout=300)
            searches = live.searches
            mesh.submit_neighbors(hot, k=5).result(timeout=300)
            if live.searches != searches:
                index_drill['error'] = 'neighbor memo never warmed'
                return
            # predict-tier entry that must SURVIVE the index swap
            mesh.predict(hot, tier='topk', timeout=300)
            if not mesh.submit(hot, tier='topk').done():
                index_drill['error'] = 'predict memo never warmed'
                return
            # --- leg 1: disagreeing candidate must ROLL BACK
            other = rng_i.normal(size=(512, dim)).astype(np.float32)
            bad_store = store_lib.build(
                os.path.join(workdir, 'drill%d_bad.vecindex' % attempt),
                [other], labels=['x%d' % i for i in range(512)])
            bad = QuantizedIVFIndex.build(bad_store, kind='int8',
                                          seed=0)
            bad.warmup(5)
            # drive the shadow with a DIFFERENT query than the `hot`
            # probe key: a driver admitted right after a conclusion
            # re-inserts its own key under the new generation, which
            # must not turn the staleness probe into a legitimate hit
            drv = [lines[2], lines[3]]
            handle = mesh.rollover_index(bad, shadow_queries=2,
                                         min_agreement=0.9)
            while not handle.done():  # memo stands down: runs live
                mesh.submit_neighbors(drv, k=5).result(timeout=300)
            report = handle.result(timeout=300)
            index_drill['rollback_ok'] = (report['swapped'] is False)
            searches = live.searches
            mesh.submit_neighbors(hot, k=5).result(timeout=300)
            if live.searches != searches:
                # rollback must leave the neighbor memo WARM
                index_drill['rollback_ok'] = False
            # --- leg 2: agreeing candidate (same sidecars) must SWAP
            cand_idx = QuantizedIVFIndex(
                store_lib.VectorStore(store.path))
            cand_idx.warmup(5)
            cand = _Counting(cand_idx)
            handle = mesh.rollover_index(cand, shadow_queries=2,
                                         min_agreement=0.9)
            while not handle.done():
                mesh.submit_neighbors(drv, k=5).result(timeout=300)
            report = handle.result(timeout=300)
            index_drill['swap_ok'] = (report['swapped'] is True)
            index_drill['agreement'] = report['agreement']
            searches = cand.searches
            post = mesh.submit_neighbors(hot, k=5)
            post.result(timeout=300)
            if cand.searches == searches:
                # answered WITHOUT touching the new index: a pre-swap
                # neighbor result was served post-swap
                index_drill['stale_serves'] += 1
            index_drill['predict_survived'] = \
                mesh.submit(hot, tier='topk').done()

        if args.index_rollovers:
            for attempt in range(5):
                try:
                    index_rollover_drill(attempt)
                    break
                except Exception as exc:  # worker died mid-drill: retry
                    index_drill['error'] = repr(exc)
                    time.sleep(1.0)

        # pin the compile mark AFTER the index drill: the soak loop
        # below must run compile-free
        warm = compiles.value

        def rollover_drill(i: int):
            """Save the current params at a fresh step, roll the fleet
            to it (restore-and-swap, no canary), then probe the memo
            stale-serving contract: the swap must atomically invalidate
            (generation bump) and the first post-swap duplicate must
            run LIVE.  Returns (ok, error)."""
            step = 100 + rollovers_done
            model.save(state=model.state._replace(
                step=jnp.asarray(step, jnp.int32)), epoch=0, wait=True)
            probe = [lines[0]]
            try:
                mesh.predict(probe, tier='topk', timeout=180)
                report = mesh.load_params(
                    step, canary_batches=0).result(timeout=180)
            except Exception as exc:  # a worker died mid-drill: retry
                return False, repr(exc)
            if not report.get('swapped'):
                return False, 'rollover did not swap: %r' % (report,)
            if memo_on:
                memo_stats = mesh.stats()['memo']
                if memo_stats['entries'] != 0 or memo_stats['bytes']:
                    violations.append(
                        'rollover %d left %d memo entries (%d bytes) '
                        'live after the swap'
                        % (i, memo_stats['entries'],
                           memo_stats['bytes']))
                post = mesh.submit(probe, tier='topk')
                if post.done():
                    violations.append(
                        'STALE: memo served a pre-rollover result '
                        'after swap %d' % i)
                try:
                    post.result(timeout=180)
                except ServingError:
                    pass  # typed shed under chaos: the stale check above
                          # already ran; nothing stale was delivered
            return True, None

        drill_state = {'scale_rid': None, 'scale_ms': None,
                       'drain_ms': None, 'drain_reason': None}

        def elastic_drill():
            """Scale-up-under-kill, then drain-during-partition
            (SERVING.md "Elastic fleet").  Runs CONCURRENTLY with the
            paced generator: the transitions happen under live load
            and live chaos, which is the whole point."""
            t = time.perf_counter()
            try:
                rid = mesh.add_replica()
            except Exception as exc:
                violations.append(
                    'scale-up-under-kill drill failed: %r' % exc)
                return
            drill_state['scale_rid'] = rid
            drill_state['scale_ms'] = (time.perf_counter() - t) * 1e3
            # let the new replica pull some of the paced load before
            # draining it back out
            time.sleep(max(1.0, args.secs * 0.15))
            # the partition window: parent-side frames (results AND
            # heartbeats, from every worker) blackhole while the drain
            # is in flight — liveness detection must break any stall
            faults.configure(fault_spec + ',partition@frame=0..19')
            t = time.perf_counter()
            try:
                mesh.retire(rid, timeout=120.0, reason='drain')
                drill_state['drain_ms'] = \
                    (time.perf_counter() - t) * 1e3
            except Exception as exc:
                violations.append(
                    'drain-during-partition drill failed: %r' % exc)
            finally:
                # restore the soak's ambient plan (the configure above
                # replaced it parent-side; worker plans are per-process
                # and unaffected)
                faults.configure(fault_spec)
            row = next((r for r in mesh.stats()['replicas']
                        if r['replica'] == rid), None)
            drill_state['drain_reason'] = (row['retired_reason']
                                           if row else None)

        elastic_thread = None
        futures = []
        stamps = []
        t0 = time.perf_counter()
        deadline = t0 + args.secs
        elastic_at = (t0 + args.secs * 0.3 if args.elastic else None)
        roll_idx = 0
        roll_times = [t0 + args.secs * (i + 1) / (args.rollovers + 1)
                      for i in range(args.rollovers)]
        while time.perf_counter() < deadline:
            if elastic_at is not None and \
                    time.perf_counter() >= elastic_at:
                elastic_at = None
                elastic_thread = threading.Thread(
                    target=elastic_drill, daemon=True,
                    name='soak-elastic-drill')
                elastic_thread.start()
            if roll_idx < len(roll_times) and \
                    time.perf_counter() >= roll_times[roll_idx]:
                ok_drill, err = rollover_drill(roll_idx)
                if ok_drill:
                    rollovers_done += 1
                    roll_idx += 1
                else:
                    drill_retries += 1
                    print('rollover drill %d retry %d: %s'
                          % (roll_idx, drill_retries, err),
                          file=sys.stderr)
                    roll_times[roll_idx] = time.perf_counter() + 1.0
                    if drill_retries > 5 * max(1, args.rollovers):
                        violations.append(
                            'rollover drill %d kept failing: %s'
                            % (roll_idx, err))
                        roll_idx += 1
            if memo_on and rng.random() < 0.5:
                request_lines = hot
            else:
                request_lines = [lines[rng.integers(len(lines))]
                                 for _ in range(int(rng.integers(1, 4)))]
            try:
                futures.append(mesh.submit(request_lines, tier='topk'))
                stamps.append(time.perf_counter())
            except ServingError:
                futures.append(None)  # typed shed at admission: fine
                stamps.append(time.perf_counter())
            time.sleep(args.interval_ms / 1e3)
        if elastic_at is not None:
            # the soak ended before the drill's start mark (a very
            # short --secs): run it now so the contract still gets
            # exercised once
            elastic_thread = threading.Thread(
                target=elastic_drill, daemon=True,
                name='soak-elastic-drill')
            elastic_thread.start()
        if elastic_thread is not None:
            elastic_thread.join(timeout=300.0)
            if elastic_thread.is_alive():
                violations.append('elastic drill wedged (scale-up or '
                                  'partitioned drain never finished)')
        # drain: every admitted future must RESOLVE — results or typed
        from concurrent.futures import TimeoutError as FutureTimeout
        ok = shed = typed = lost = untyped = 0
        latencies = []
        for t_submit, future in zip(stamps, futures):
            if future is None:
                shed += 1
                continue
            try:
                results = future.result(timeout=180)
            except ServingError:
                typed += 1  # expired/shed/replica-dead: typed, not lost
            except FutureTimeout:
                # a future that never resolved inside the generous
                # drain window is LOST — the exact hang this soak
                # exists to catch
                lost += 1
                violations.append('hung future (never resolved)')
            except Exception as exc:
                untyped += 1
                violations.append('untyped failure: %r' % exc)
            else:
                assert results
                ok += 1
                latencies.append(time.perf_counter() - t_submit)
        postwarm = compiles.value - warm
        wall = time.perf_counter() - t0
        stats = mesh.stats()
    finally:
        mesh.close()
        model.close_stores()

    lat_ms = np.asarray(sorted(latencies)) * 1e3
    p50 = float(np.percentile(lat_ms, 50)) if len(lat_ms) else None
    p99 = float(np.percentile(lat_ms, 99)) if len(lat_ms) else None
    total = len(futures)
    if ok == 0:
        violations.append('no request ever completed')
    if postwarm != 0:
        violations.append('%d post-warmup parent compiles' % postwarm)
    if p99 is not None and p99 > args.p99_bound_ms:
        violations.append('p99 %.0fms > bound %.0fms'
                          % (p99, args.p99_bound_ms))
    if stats['restarts_total'] < 1:
        violations.append('no supervised restart fired — the chaos '
                          'never bit (raise --secs or lower '
                          '--kill-every)')

    # cross-process stitching contract (OBSERVABILITY.md "Fleet
    # observability"): ZERO admitted requests may finish with a
    # wire-truncated trace tree — every delivered trace must carry its
    # worker-side device-execute spans, grafted by adopt_spans
    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    from latency_report import (group_traces, load_spans,
                                unstitched_traces)
    spans_path = os.path.join(workdir, 'telemetry', 'spans.jsonl')
    stitched_total = unstitched = None
    if os.path.exists(spans_path):
        traces = group_traces(load_spans(spans_path))
        delivered = [e for e in traces.values()
                     if e['root'] is not None
                     and e['root'].get('status') in (None, 'ok')]
        truncated = unstitched_traces(traces)
        stitched_total = len(delivered)
        unstitched = len(truncated)
        if ok and not delivered:
            violations.append('requests completed but the span log '
                              'has no delivered traces (tracing '
                              'broken?)')
        if truncated:
            violations.append(
                '%d delivered trace(s) finished UNSTITCHED (no '
                'worker device-execute spans): %s'
                % (len(truncated), truncated[:8]))
    elif ok:
        violations.append('no span log at %s (stitching assertion '
                          'could not run)' % spans_path)
    emit({'metric': 'mesh_soak_unstitched_traces', 'value': unstitched,
          'delivered_traces': stitched_total,
          'adopted_spans': stats.get('adopted_spans_total'),
          'remote_spans_dropped':
              stats.get('remote_spans_dropped_total')})

    emit({'metric': 'mesh_soak_requests', 'value': total, 'ok': ok,
          'shed_at_admission': shed, 'typed_failures': typed,
          'untyped_failures': untyped, 'lost': lost,
          'wall_s': round(wall, 2), 'mode': args.mode,
          'replicas': args.replicas, 'fault_spec': fault_spec})
    emit({'metric': 'mesh_soak_lost_requests', 'value': lost + untyped})
    emit({'metric': 'mesh_soak_p99_ms',
          'value': round(p99, 1) if p99 is not None else None,
          'p50_ms': round(p50, 1) if p50 is not None else None,
          'bound_ms': args.p99_bound_ms})
    emit({'metric': 'mesh_soak_restarts',
          'value': stats['restarts_total'],
          'redispatched': stats['redispatched_total'],
          'heartbeat_misses': stats['heartbeat_misses_total'],
          'replica_breaker_open_total':
              stats['replica_breaker_open_total']})
    emit({'metric': 'mesh_soak_postwarm_compiles', 'value': postwarm})
    if args.elastic:
        if drill_state['scale_ms'] is None:
            violations.append('scale-up-under-kill never completed')
        if drill_state['drain_ms'] is None:
            violations.append(
                'drain-during-partition never completed')
        elif drill_state['drain_reason'] != 'drain':
            violations.append(
                "drained replica retired as %r, expected 'drain'"
                % (drill_state['drain_reason'],))
        emit({'metric': 'mesh_soak_scale_up_ms',
              'value': (round(drill_state['scale_ms'], 1)
                        if drill_state['scale_ms'] is not None
                        else None),
              'rid': drill_state['scale_rid']})
        emit({'metric': 'mesh_soak_drain_partition_ms',
              'value': (round(drill_state['drain_ms'], 1)
                        if drill_state['drain_ms'] is not None
                        else None),
              'retired_reason': drill_state['drain_reason']})
    if args.index_rollovers:
        if index_drill['rollback_ok'] is not True:
            violations.append(
                'index rollover drill: disagreeing candidate did not '
                'roll back cleanly (%r)'
                % (index_drill['error'] or index_drill['rollback_ok'],))
        if index_drill['swap_ok'] is not True:
            violations.append(
                'index rollover drill: agreeing candidate did not swap '
                '(%r)' % (index_drill['error']
                          or index_drill['swap_ok'],))
        if index_drill['stale_serves']:
            violations.append(
                'STALE: memo served %d pre-swap neighbor result(s) '
                'after the index rollover'
                % index_drill['stale_serves'])
        if index_drill['swap_ok'] and not index_drill['predict_survived']:
            violations.append(
                'index rollover drill: predict memo entries did not '
                'survive the index swap (the model did not change)')
        emit({'metric': 'mesh_soak_index_rollover',
              'value': 1 if (index_drill['swap_ok']
                             and index_drill['rollback_ok']) else 0,
              'agreement': index_drill['agreement'],
              'stale_neighbor_serves': index_drill['stale_serves'],
              'predict_survived': index_drill['predict_survived'],
              'index_version': stats.get('index_version'),
              'memo_index_generation':
                  (stats['memo'].get('index_generation')
                   if memo_on else None),
              'error': index_drill['error']})
    if memo_on:
        # memoization-tier soak contract (SERVING.md "Memoization
        # tier"): the cache must actually serve under the duplicate-
        # heavy traffic, and every completed rollover must have
        # invalidated it (generation bump) — zero stale serves is
        # asserted inline by each drill's post-swap probe above.
        memo_stats = stats['memo']
        if memo_stats['hits'] == 0:
            violations.append('memo tier never served a hit under the '
                              'duplicate-heavy soak traffic')
        if args.rollovers > 0 and rollovers_done == 0:
            violations.append('no rollover drill ever completed '
                              '(%d retries)' % drill_retries)
        # >= not ==: a drill whose handle died AFTER the swap landed
        # still bumped the generation server-side; under-counting
        # rollovers must not read as a missed invalidation
        if memo_stats['generation'] < rollovers_done:
            violations.append(
                'memo generation %d < %d completed rollovers — a swap '
                'concluded without invalidating the cache'
                % (memo_stats['generation'], rollovers_done))
        emit({'metric': 'mesh_soak_memo', 'value': memo_stats['hits'],
              'hit_rate': round(memo_stats['hit_rate'], 3),
              'entries': memo_stats['entries'],
              'bytes': memo_stats['bytes'],
              'evictions': memo_stats['evictions'],
              'generation': memo_stats['generation'],
              'rollovers': rollovers_done,
              'drill_retries': drill_retries})
    if violations:
        emit({'metric': 'mesh_soak_violations', 'value': len(violations),
              'detail': violations})
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
