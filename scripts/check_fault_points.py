"""Lint: every fault point fired anywhere in the codebase must exist in
the fault catalog (code2vec_tpu/resilience/faults.py::FAULT_POINTS), and
every cataloged point must be documented in ROBUSTNESS.md — so a typo'd
point name fails tier-1 instead of silently never firing (ISSUE 3
satellite; runs in tier-1 via tests/test_fault_points_lint.py).

Since ISSUE 6 this is a thin CLI over the graftlint rule
``fault-points`` (code2vec_tpu/analysis/rules/fault_points.py —
ANALYSIS.md): same regex, same scan scope, same exit codes; the rule
additionally runs under ``scripts/lint_all.py`` with the shared
suppression/baseline machinery.

Grep-based by design: fault sites are ``maybe_fire`` calls with a string
literal —

    faults.maybe_fire('nan_loss', step=batch_num)
    if faults.maybe_fire('hang_input'):

(this file and the rule module never scan themselves: the examples
above would count as sites and mask a deleted real site).

Exit status: 0 clean, 1 on unknown sites, undocumented catalog entries,
or cataloged points with no wired site.  ``--list`` prints every
discovered site.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the rule owns the regex + scan; re-exported here because
# tests/test_fault_points_lint.py imports them from this module
from code2vec_tpu.analysis.rules.fault_points import (  # noqa: E402
    FIRE_RE)
from code2vec_tpu.analysis.rules import fault_points as _rule  # noqa: E402
from code2vec_tpu.analysis.walker import SourceTree  # noqa: E402


def find_sites():
    """[(relpath, lineno, point_name)] across the scanned tree."""
    return _rule.find_sites(SourceTree(REPO))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from code2vec_tpu.analysis import engine
    from code2vec_tpu.resilience.faults import FAULT_POINTS

    tree = SourceTree(REPO)
    sites = _rule.find_sites(tree)
    if '--list' in argv:
        for rel, lineno, name in sites:
            print('%s:%d: %s' % (rel, lineno, name))

    # standalone semantics: no baseline — catalog drift is never OK —
    # and ONLY this rule's findings: unrelated graftlint meta-findings
    # (malformed suppressions elsewhere in the tree) belong to lint_all
    report = engine.run(root=REPO, rule_names=['fault-points'],
                        baseline_path='', tree=tree)
    failures = [f for f in report.findings if f.rule == 'fault-points']
    if failures:
        for finding in failures:
            print(finding.format(), file=sys.stderr)
        print('%d fault-point violation(s).' % len(failures),
              file=sys.stderr)
        return 1
    print('fault points OK: %d sites, %d cataloged points.'
          % (len(sites), len(FAULT_POINTS)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
