"""Lint: every fault point fired anywhere in the codebase must exist in
the fault catalog (code2vec_tpu/resilience/faults.py::FAULT_POINTS), and
every cataloged point must be documented in ROBUSTNESS.md — so a typo'd
point name fails tier-1 instead of silently never firing (ISSUE 3
satellite; same pattern as scripts/check_metrics_schema.py, runs in
tier-1 via tests/test_fault_points_lint.py).

Grep-based by design: fault sites are ``maybe_fire`` calls with a string
literal —

    faults.maybe_fire('nan_loss', step=batch_num)
    if faults.maybe_fire('hang_input'):

Exit status: 0 clean, 1 on unknown sites or undocumented catalog
entries.  ``--list`` prints every discovered site.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# Directories scanned for fault sites. tests/ is deliberately out: tests
# mint throwaway names to exercise the plan machinery itself.
SCAN_DIRS = ('code2vec_tpu', 'benchmarks', 'scripts')
SCAN_FILES = ('bench.py',)

# \s* spans newlines: calls wrap across lines under the 79-column style
FIRE_RE = re.compile(r"""maybe_fire\(\s*['"]([A-Za-z0-9_]+)['"]""")


def iter_python_files():
    self_path = os.path.abspath(__file__)
    for rel in SCAN_DIRS:
        for dirpath, _dirnames, filenames in os.walk(os.path.join(REPO, rel)):
            if '__pycache__' in dirpath:
                continue
            for name in sorted(filenames):
                path = os.path.join(dirpath, name)
                # never scan this script itself: its docstring examples
                # would count as sites and mask a deleted real site
                if name.endswith('.py') and \
                        os.path.abspath(path) != self_path:
                    yield path
    for rel in SCAN_FILES:
        path = os.path.join(REPO, rel)
        if os.path.isfile(path):
            yield path


def find_sites():
    """[(relpath, lineno, point_name)] across the scanned tree."""
    out = []
    for path in iter_python_files():
        rel = os.path.relpath(path, REPO)
        with open(path, 'r') as f:
            content = f.read()
        for match in FIRE_RE.finditer(content):
            lineno = content.count('\n', 0, match.start()) + 1
            out.append((rel, lineno, match.group(1)))
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from code2vec_tpu.resilience.faults import FAULT_POINTS

    sites = find_sites()
    if '--list' in argv:
        for rel, lineno, name in sites:
            print('%s:%d: %s' % (rel, lineno, name))

    failures = []
    for rel, lineno, name in sites:
        if name not in FAULT_POINTS:
            failures.append(
                '%s:%d: fault point %r is not in the catalog '
                '(code2vec_tpu/resilience/faults.py) — add it there and to '
                'ROBUSTNESS.md, or fix the name' % (rel, lineno, name))

    doc_path = os.path.join(REPO, 'ROBUSTNESS.md')
    if os.path.isfile(doc_path):
        with open(doc_path, 'r') as f:
            doc = f.read()
        for name in sorted(FAULT_POINTS):
            if name not in doc:
                failures.append(
                    'ROBUSTNESS.md: cataloged fault point %r is '
                    'undocumented' % name)
    else:
        failures.append('ROBUSTNESS.md is missing (the fault-point catalog '
                        'must be documented)')

    fired = {name for _rel, _lineno, name in sites}
    for name in sorted(set(FAULT_POINTS) - fired):
        # a cataloged point with NO site is a real failure here (unlike
        # the metrics lint's note): a fault spec naming it would parse
        # fine and then never fire — the silent-injection trap this lint
        # exists to close
        failures.append(
            'fault point %r is cataloged but has no maybe_fire site — '
            'every point must be wired, or specs naming it silently '
            'inject nothing' % name)

    if failures:
        print('\n'.join(failures), file=sys.stderr)
        print('%d fault-point violation(s).' % len(failures),
              file=sys.stderr)
        return 1
    print('fault points OK: %d sites, %d cataloged points.'
          % (len(sites), len(FAULT_POINTS)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
