"""Generate a java-small-scale synthetic Java corpus for accuracy-at-scale
validation (VERDICT r2 missing #2).

The reference validates its learning loop implicitly every time someone
follows its README (train.sh on java-small: ~700K methods, 20 epochs, best
epoch by F1). No Java corpus exists in this environment, so this generator
produces one at comparable *statistical* scale from a template grammar:

- ~24K classes / ~110K methods, split by class into train/val/test;
- method names are verb+noun compounds whose BODIES correlate with the
  name (getters return the field, finders loop over a parameter, compare
  methods delegate to java.lang comparisons, ...) — so subtoken F1 above
  the majority baseline requires actually learning path-context -> name
  structure, not memorizing one label;
- identifiers are drawn Zipfian from a compound-noun pool large enough
  that the token/target vocabs overflow the configured sizes (real vocab
  truncation + OOV pressure, unlike the tiny overfit tests);
- bodies carry small structural variations (guards, temps, literals) so
  identical names don't collapse to identical context bags.

Ambiguity hardening (VERDICT r3 #6 — the round-3 corpus saturated val F1
by epoch 6):

- verb SYNONYMS: the same body family carries different name verbs
  (get/fetch/read, set/update/assign, validate/ensure/require, ...).
  Each synonym's body differs by a small structural tell (a temp, a
  guard, a cast), so the ambiguity is PARTIALLY resolvable — a model
  that learns the tells keeps improving instead of plateauing at the
  majority verb;
- shared-prefix near-duplicates: getX vs getXOrDefault, setX vs
  setXIfValid — close names over close bodies;
- structural diversity: loops, while-drains, ternary min/max, swaps and
  toggles add AST shapes (the round-3 corpus produced only 292 unique
  paths; real Java corpora have orders of magnitude more).

Deterministic under --seed. Output: one .java file per class under
<out>/{train,val,test}/, ready for `c2v-extract --dir`.
"""
from __future__ import annotations

import argparse
import os
import random

ADJS = ['max', 'min', 'total', 'last', 'first', 'next', 'prev', 'base',
        'raw', 'final', 'cached', 'pending', 'active', 'stale', 'local',
        'remote', 'global', 'default', 'current', 'initial', 'merged',
        'sorted', 'unique', 'valid', 'dirty', 'live', 'spare', 'extra',
        'inner', 'outer', 'upper', 'lower', 'left', 'right', 'open',
        'closed', 'free', 'used', 'busy', 'idle', 'primary', 'secondary',
        'nested', 'shared', 'private', 'public', 'visible', 'hidden',
        'stable', 'frozen', 'mutable', 'temp', 'old', 'new', 'main',
        'backup', 'partial', 'full', 'empty', 'dense']
NOUNS = ['count', 'index', 'size', 'value', 'name', 'key', 'weight',
         'offset', 'limit', 'length', 'width', 'height', 'depth', 'score',
         'rank', 'rate', 'ratio', 'total', 'sum', 'delta', 'retry',
         'timeout', 'buffer', 'queue', 'stack', 'cache', 'token', 'node',
         'edge', 'path', 'label', 'field', 'record', 'row', 'column',
         'page', 'block', 'chunk', 'frame', 'slot', 'seed', 'state',
         'flag', 'mode', 'level', 'phase', 'step', 'stage', 'epoch',
         'batch', 'shard', 'worker', 'task', 'job', 'event', 'error',
         'warning', 'message', 'header', 'footer', 'body', 'item',
         'entry', 'element', 'member', 'owner', 'user', 'group', 'role',
         'session', 'request', 'response', 'result', 'input', 'output',
         'source', 'target', 'origin', 'bound', 'range', 'window',
         'cursor', 'pointer', 'handle', 'id', 'tag', 'type', 'kind',
         'version', 'revision', 'branch', 'commit', 'digest', 'checksum',
         'price', 'cost', 'budget', 'balance', 'amount', 'quantity',
         'stock', 'order', 'invoice', 'account', 'address', 'city',
         'street', 'code', 'zone', 'region', 'distance', 'speed',
         'duration', 'interval', 'moment', 'instant', 'day', 'month',
         'year', 'week', 'hour', 'minute', 'second', 'ticket', 'seat',
         'lane', 'route', 'stop', 'station', 'port', 'host', 'domain',
         'scheme', 'query', 'fragment', 'anchor', 'margin', 'padding',
         'border', 'radius', 'angle', 'degree', 'pixel', 'glyph', 'font',
         'color', 'shade', 'tint', 'layer', 'mask', 'channel', 'sample',
         'signal', 'pulse', 'wave', 'peak', 'trough', 'floor', 'ceiling',
         'quota', 'share', 'split', 'merge', 'fold', 'segment']


def zipf_choice(rng: random.Random, pool, a: float = 1.15):
    """Zipf-ish draw: low pool indices are hot, the tail is long."""
    n = len(pool)
    # inverse-CDF for a power law over ranks 1..n
    u = rng.random()
    rank = int(n ** u) if a <= 1.0 else int((n ** (1 - a) * u + (1 - u))
                                            ** (1 / (1 - a)))
    return pool[min(max(rank - 1, 0), n - 1)]


def capitalized(name: str) -> str:
    """fieldName -> FieldName (for verbNoun method names)."""
    return name[0].upper() + name[1:]


def camel(*parts: str) -> str:
    head, *tail = [p for p in parts if p]
    return head + ''.join(p.capitalize() for p in tail)


class ClassGen:
    TYPES = ['int', 'long', 'double', 'boolean', 'String']

    def __init__(self, rng: random.Random, noun_pairs):
        self.rng = rng
        self._loop_seq = 0  # unique loop-variable counter (nested fors)
        self.fields = []
        used = set()
        for _ in range(rng.randint(3, 6)):
            adj, noun = zipf_choice(rng, noun_pairs)
            name = camel(adj, noun) if rng.random() < 0.7 else noun
            if name in used:
                continue
            used.add(name)
            ftype = rng.choices(self.TYPES, weights=[5, 2, 2, 2, 3])[0]
            self.fields.append((ftype, name))
        if not self.fields:
            self.fields.append(('int', camel(*zipf_choice(rng, noun_pairs))))

    def numeric_fields(self):
        return [f for f in self.fields if f[0] in ('int', 'long', 'double')]

    def method(self) -> str:
        rng = self.rng
        ftype, fname = rng.choice(self.fields)
        num = self.numeric_fields()
        kinds = ['getter', 'setter', 'resetter', 'predicate', 'validator',
                 'defaulted_getter',
                 # combinatorial-nesting families (path-space pressure)
                 'accumulator', 'scanner', 'normalizer', 'resolver',
                 'processor']
        if ftype in ('int', 'long', 'double'):
            kinds += ['adder', 'clamper', 'scaler', 'counter', 'drainer',
                      'guarded_setter']
        if ftype == 'boolean':
            kinds += ['toggler']
        if len(num) >= 2:
            kinds += ['computer', 'comparator', 'picker', 'swapper']
        if ftype == 'String':
            kinds += ['describer', 'checker', 'appender']
        kind = rng.choice(kinds)
        return getattr(self, '_' + kind)(ftype, fname)

    # --- method templates; each correlates body structure with the name.
    # Verb synonyms share a body FAMILY but differ by a structural tell
    # (a temp, a guard, a cast), so the name ambiguity they create is
    # partially resolvable — the learnable signal that keeps the val
    # curve climbing past the majority-verb plateau.
    def _getter(self, ftype, fname):
        cap = capitalized(fname)
        verb = self.rng.choices(['get', 'fetch', 'read'],
                                weights=[6, 2, 2])[0]
        if verb == 'get':
            return '%s get%s() { return this.%s; }' % (ftype, cap, fname)
        if verb == 'fetch':
            # tell: null/zero guard before the return
            if ftype == 'String':
                return ('%s fetch%s() { if (this.%s == null) { return ""; } '
                        'return this.%s; }' % (ftype, cap, fname, fname))
            zero = {'int': '0', 'long': '0L', 'double': '0.0',
                    'boolean': 'false'}[ftype]
            return ('%s fetch%s() { if (this.%s == %s) { return %s; } '
                    'return this.%s; }'
                    % (ftype, cap, fname, zero, zero, fname))
        # read: tell — copies through a local temp first
        return ('%s read%s() { %s snapshot = this.%s; return snapshot; }'
                % (ftype, cap, ftype, fname))

    def _defaulted_getter(self, ftype, fname):
        # shared-prefix near-duplicate of the getter: getXOrDefault
        cap = capitalized(fname)
        if ftype == 'String':
            return ('%s get%sOrDefault(%s fallback) { return this.%s == '
                    'null ? fallback : this.%s; }'
                    % (ftype, cap, ftype, fname, fname))
        if ftype == 'boolean':
            return ('%s get%sOrDefault(%s fallback) { return this.%s || '
                    'fallback; }' % (ftype, cap, ftype, fname))
        return ('%s get%sOrDefault(%s fallback) { return this.%s > 0 ? '
                'this.%s : fallback; }'
                % (ftype, cap, ftype, fname, fname))

    def _setter(self, ftype, fname):
        cap = capitalized(fname)
        verb = self.rng.choices(['set', 'update', 'assign'],
                                weights=[6, 2, 2])[0]
        if verb == 'set':
            guard = ''
            if ftype in ('int', 'long', 'double') and self.rng.random() < 0.5:
                guard = 'if (value < 0) { return; } '
            return ('void set%s(%s value) { %sthis.%s = value; }'
                    % (cap, ftype, guard, fname))
        if verb == 'update':
            # tell: keeps the previous value in a temp
            return ('void update%s(%s value) { %s previous = this.%s; '
                    'this.%s = value; }' % (cap, ftype, ftype, fname, fname))
        # assign: tell — chains through a local before the store
        return ('void assign%s(%s value) { %s next = value; this.%s = '
                'next; }' % (cap, ftype, ftype, fname))

    def _guarded_setter(self, ftype, fname):
        # shared-prefix near-duplicate of the setter: setXIfValid
        cap = capitalized(fname)
        return ('void set%sIfValid(%s value) { if (value >= 0) { this.%s '
                '= value; } }' % (cap, ftype, fname))

    def _resetter(self, ftype, fname):
        cap = capitalized(fname)
        zero = {'int': '0', 'long': '0L', 'double': '0.0',
                'boolean': 'false', 'String': '""'}[ftype]
        verb = self.rng.choices(['reset', 'clear'], weights=[6, 4])[0]
        if verb == 'reset':
            return 'void reset%s() { this.%s = %s; }' % (cap, fname, zero)
        # clear: tell — validates after zeroing
        return ('void clear%s() { this.%s = %s; if (this.%s != %s) { '
                'throw new IllegalStateException("clear %s"); } }'
                % (cap, fname, zero, fname, zero, fname))

    def _predicate(self, ftype, fname):
        cap = capitalized(fname)
        if ftype == 'boolean':
            return 'boolean is%s() { return this.%s; }' % (cap, fname)
        if ftype == 'String':
            return ('boolean has%s() { return this.%s != null; }'
                    % (cap, fname))
        return 'boolean has%s() { return this.%s > 0; }' % (cap, fname)

    def _validator(self, ftype, fname):
        cap = capitalized(fname)
        if ftype in ('int', 'long', 'double'):
            cond = 'this.%s < 0' % fname
        elif ftype == 'boolean':
            cond = '!this.%s' % fname
        else:
            cond = 'this.%s == null' % fname
        verb = self.rng.choices(['validate', 'ensure', 'require'],
                                weights=[6, 2, 2])[0]
        if verb == 'validate':
            return ('void validate%s() { if (%s) { throw new '
                    'IllegalStateException("bad %s"); } }'
                    % (cap, cond, fname))
        if verb == 'ensure':
            # tell: early-return style instead of throw-on-bad
            return ('void ensure%s() { if (!(%s)) { return; } throw new '
                    'IllegalStateException("bad %s"); }'
                    % (cap, cond, fname))
        # require: tell — returns the field after the check
        return ('%s require%s() { if (%s) { throw new '
                'IllegalArgumentException("bad %s"); } return this.%s; }'
                % (ftype, cap, cond, fname, fname))

    def _adder(self, ftype, fname):
        cap = capitalized(fname)
        verb = self.rng.choices(['addTo', 'increase', 'bump'],
                                weights=[6, 2, 2])[0]
        if verb == 'addTo':
            return ('void addTo%s(%s amount) { this.%s = this.%s + '
                    'amount; }' % (cap, ftype, fname, fname))
        if verb == 'increase':
            # tell: guards against negative deltas
            return ('void increase%s(%s amount) { if (amount > 0) { '
                    'this.%s = this.%s + amount; } }'
                    % (cap, ftype, fname, fname))
        # bump: tell — fixed increment, no parameter
        one = {'int': '1', 'long': '1L', 'double': '1.0'}[ftype]
        return ('void bump%s() { this.%s = this.%s + %s; }'
                % (cap, fname, fname, one))

    def _clamper(self, ftype, fname):
        cap = capitalized(fname)
        return ('%s clamp%s(%s low, %s high) { if (this.%s < low) { return '
                'low; } if (this.%s > high) { return high; } return '
                'this.%s; }' % (ftype, cap, ftype, ftype, fname, fname,
                                fname))

    def _scaler(self, ftype, fname):
        cap = capitalized(fname)
        return ('%s scale%s(%s factor) { return this.%s * factor; }'
                % (ftype, cap, ftype, fname))

    def _computer(self, ftype, fname):
        num = self.numeric_fields()
        (t1, f1), (t2, f2) = self.rng.sample(num, 2)
        cap1 = capitalized(f1)
        cap2 = capitalized(f2)
        op = self.rng.choice(['+', '-', '*'])
        rtype = 'double' if 'double' in (t1, t2) else (
            'long' if 'long' in (t1, t2) else 'int')
        return ('%s compute%sAnd%s() { return this.%s %s this.%s; }'
                % (rtype, cap1, cap2, f1, op, f2))

    def _comparator(self, ftype, fname):
        num = self.numeric_fields()
        t1, f1 = self.rng.choice(num)
        cap = capitalized(f1)
        box = {'int': 'Integer', 'long': 'Long', 'double': 'Double'}[t1]
        return ('int compare%s(%s other) { return %s.compare(this.%s, '
                'other); }' % (cap, t1, box, f1))

    def _describer(self, ftype, fname):
        cap = capitalized(fname)
        verb = self.rng.choices(['describe', 'format'], weights=[6, 4])[0]
        if verb == 'describe':
            return ('String describe%s() { return "%s=" + this.%s; }'
                    % (cap, fname, fname))
        # format: tell — builds through a local
        return ('String format%s() { String text = "%s=" + this.%s; '
                'return text; }' % (cap, fname, fname))

    def _checker(self, ftype, fname):
        cap = capitalized(fname)
        verb = self.rng.choices(['check', 'verify'], weights=[6, 4])[0]
        if verb == 'check':
            return ('boolean check%sEquals(String expected) { return '
                    'this.%s.equals(expected); }' % (cap, fname))
        # verify: tell — null-guards before delegating
        return ('boolean verify%sEquals(String expected) { if (this.%s == '
                'null) { return false; } return this.%s.equals(expected); }'
                % (cap, fname, fname))

    # --- structural-diversity kinds: new AST shapes (loops, ternaries,
    # swaps) that widen the path vocabulary toward real-Java variety
    def _counter(self, ftype, fname):
        cap = capitalized(fname)
        return ('int countUpTo%s(int limit) { int n = 0; for (int i = 0; '
                'i < limit; i++) { if (i < this.%s) { n = n + 1; } } '
                'return n; }' % (cap, fname))

    def _drainer(self, ftype, fname):
        cap = capitalized(fname)
        one = {'int': '1', 'long': '1L', 'double': '1.0'}[ftype]
        return ('void drain%s() { while (this.%s > 0) { this.%s = this.%s '
                '- %s; } }' % (cap, fname, fname, fname, one))

    def _toggler(self, ftype, fname):
        cap = capitalized(fname)
        return ('void toggle%s() { this.%s = !this.%s; }'
                % (cap, fname, fname))

    def _picker(self, ftype, fname):
        num = self.numeric_fields()
        (t1, f1), (t2, f2) = self.rng.sample(num, 2)
        cap1 = capitalized(f1)
        cap2 = capitalized(f2)
        rtype = 'double' if 'double' in (t1, t2) else (
            'long' if 'long' in (t1, t2) else 'int')
        which = self.rng.choice(['max', 'min'])
        op = '>' if which == 'max' else '<'
        return ('%s %sOf%sAnd%s() { return this.%s %s this.%s ? this.%s : '
                'this.%s; }' % (rtype, which, cap1, cap2, f1, op, f2, f1,
                                f2))

    def _swapper(self, ftype, fname):
        num = self.numeric_fields()
        same_type = {}
        for t, f in num:
            same_type.setdefault(t, []).append(f)
        pools = [fs for fs in same_type.values() if len(fs) >= 2]
        if not pools:
            return self._computer(ftype, fname)
        f1, f2 = self.rng.sample(self.rng.choice(pools), 2)
        t1 = next(t for t, f in num if f == f1)
        cap1 = capitalized(f1)
        cap2 = capitalized(f2)
        return ('void swap%sAnd%s() { %s held = this.%s; this.%s = this.%s; '
                'this.%s = held; }' % (cap1, cap2, t1, f1, f1, f2, f2))

    def _appender(self, ftype, fname):
        cap = capitalized(fname)
        return ('void appendTo%s(String suffix) { this.%s = this.%s + '
                'suffix; }' % (cap, fname, fname))

    # --- combinatorial-nesting kinds (VERDICT r4 #3): the template kinds
    # above produce a few hundred unique paths total because every body is
    # a fixed AST shape. These families build bodies from RANDOM expression
    # trees and statement nestings, so the corpus's path space grows
    # combinatorially (target: >50K unique paths with a singleton tail,
    # versus java14m's 911K kept paths) while each family keeps a
    # learnable verb <-> skeleton correlation and the field noun stays in
    # the context tokens.
    NUM_OPS = ['+', '-', '*', '%']
    CMP_OPS = ['<', '>', '<=', '>=', '==', '!=']

    def _num_expr(self, depth, names):
        rng = self.rng
        if depth <= 0 or rng.random() < 0.35:
            return rng.choice(names + [str(rng.randint(0, 99))])
        return '(%s %s %s)' % (self._num_expr(depth - 1, names),
                               rng.choice(self.NUM_OPS),
                               self._num_expr(depth - 1, names))

    def _cond_expr(self, depth, names):
        rng = self.rng
        if depth <= 0 or rng.random() < 0.55:
            return '%s %s %s' % (self._num_expr(1, names),
                                 rng.choice(self.CMP_OPS),
                                 self._num_expr(0, names))
        return '(%s %s %s)' % (self._cond_expr(depth - 1, names),
                               rng.choice(['&&', '||']),
                               self._cond_expr(depth - 1, names))

    def _nested_stmt(self, depth, names):
        """One statement over int-typed ``names``, recursively nested."""
        rng = self.rng
        kinds = ['assign', 'compound']
        if depth > 0:
            kinds += ['if', 'ifelse', 'for', 'while', 'ternary', 'block']
        kind = rng.choice(kinds)
        target = rng.choice(names)
        if kind == 'assign':
            return '%s = %s;' % (target, self._num_expr(2, names))
        if kind == 'compound':
            return '%s %s= %s;' % (target, rng.choice(self.NUM_OPS),
                                   self._num_expr(1, names))
        if kind == 'ternary':
            return '%s = %s ? %s : %s;' % (
                target, self._cond_expr(1, names),
                self._num_expr(1, names), self._num_expr(1, names))
        if kind == 'if':
            return 'if (%s) { %s }' % (self._cond_expr(1, names),
                                       self._nested_stmt(depth - 1, names))
        if kind == 'ifelse':
            return 'if (%s) { %s } else { %s }' % (
                self._cond_expr(1, names),
                self._nested_stmt(depth - 1, names),
                self._nested_stmt(depth - 1, names))
        if kind == 'for':
            # per-class counter: nested fors must not redeclare a loop
            # variable (the corpus stays valid compilable Java)
            self._loop_seq += 1
            loop_var = 'i%d' % self._loop_seq
            inner_names = names + [loop_var]
            return ('for (int %s = 0; %s < %s; %s++) { %s }'
                    % (loop_var, loop_var, self._num_expr(0, names),
                       loop_var, self._nested_stmt(depth - 1, inner_names)))
        if kind == 'while':
            return ('while (%s > 0) { %s %s = %s - 1; }'
                    % (target, self._nested_stmt(depth - 1, names),
                       target, target))
        # block: two siblings — widens the path fan-out at one level
        return '%s %s' % (self._nested_stmt(depth - 1, names),
                          self._nested_stmt(depth - 1, names))

    def _int_field_names(self):
        return ['this.' + f for t, f in self.fields if t == 'int']

    def _accumulator(self, ftype, fname):
        cap = capitalized(fname)
        rng = self.rng
        verb = rng.choices(['accumulate', 'tally'], weights=[6, 4])[0]
        names = ['acc', 'i'] + self._int_field_names()
        inner = self._nested_stmt(rng.randint(1, 2), names)
        # tell between the synonyms: tally post-clamps the accumulator
        tail = ('' if verb == 'accumulate'
                else ' if (acc < 0) { acc = 0; }')
        return ('int %s%s(int limit) { int acc = 0; for (int i = 0; i < '
                'limit; i++) { %s }%s return acc; }'
                % (verb, cap, inner, tail))

    def _scanner(self, ftype, fname):
        cap = capitalized(fname)
        rng = self.rng
        verb = rng.choices(['scan', 'probe'], weights=[6, 4])[0]
        names = ['i'] + self._int_field_names()
        cond = self._cond_expr(rng.randint(1, 2), names)
        if verb == 'scan':
            return ('int scan%s(int limit) { for (int i = 0; i < limit; '
                    'i++) { if (%s) { return i; } } return -1; }'
                    % (cap, cond))
        # probe: tell — tracks the last hit instead of returning early
        return ('int probe%s(int limit) { int hit = -1; for (int i = 0; '
                'i < limit; i++) { if (%s) { hit = i; } } return hit; }'
                % (cap, cond))

    def _normalizer(self, ftype, fname):
        cap = capitalized(fname)
        rng = self.rng
        verb = rng.choices(['normalize', 'adjust'], weights=[6, 4])[0]
        names = ['value'] + self._int_field_names()
        clauses = ' '.join(
            'if (%s) { value = %s; }' % (self._cond_expr(1, names),
                                         self._num_expr(1, names))
            for _ in range(rng.randint(2, 3)))
        # adjust: tell — works on a shifted copy
        if verb == 'adjust':
            return ('int adjust%s(int raw) { int value = raw + 1; %s '
                    'return value; }' % (cap, clauses))
        return ('int normalize%s(int raw) { int value = raw; %s '
                'return value; }' % (cap, clauses))

    def _resolver(self, ftype, fname):
        cap = capitalized(fname)
        rng = self.rng
        verb = rng.choices(['resolve', 'derive'], weights=[6, 4])[0]
        names = self._int_field_names() + ['seed0']
        decls = []
        locals_so_far = list(names)
        for k in range(rng.randint(2, 3)):
            var = 'step%d' % k
            decls.append('int %s = %s;'
                         % (var, self._num_expr(rng.randint(1, 2),
                                                locals_so_far)))
            locals_so_far.append(var)
        ret = locals_so_far[-1]
        # derive: tell — guards the seed first
        guard = ('if (seed0 < 0) { seed0 = 0; } ' if verb == 'derive'
                 else '')
        return ('int %s%s(int seed0) { %s%s return %s; }'
                % (verb, cap, guard, ' '.join(decls), ret))

    def _processor(self, ftype, fname):
        cap = capitalized(fname)
        rng = self.rng
        verb = rng.choices(['process', 'handle', 'apply'],
                           weights=[6, 2, 2])[0]
        names = ['work'] + self._int_field_names()
        body = ' '.join(self._nested_stmt(rng.randint(1, 3), names)
                        for _ in range(rng.randint(1, 2)))
        # tells: handle pre-guards, apply returns an expression over work
        if verb == 'handle':
            return ('int handle%s(int work) { if (work == 0) { return 0; } '
                    '%s return work; }' % (cap, body))
        if verb == 'apply':
            return ('int apply%s(int work) { %s return work + 1; }'
                    % (cap, body))
        return ('int process%s(int work) { %s return work; }'
                % (cap, body))


def gen_class(rng: random.Random, name: str, noun_pairs,
              methods_per_class) -> str:
    cls = ClassGen(rng, noun_pairs)
    lines = ['public class %s {' % name]
    for ftype, fname in cls.fields:
        lines.append('    private %s %s;' % (ftype, fname))
    n_methods = rng.randint(*methods_per_class)
    seen = set()
    for _ in range(n_methods):
        m = cls.method()
        sig = m.split('(')[0]
        if sig in seen:  # java forbids duplicate signatures often enough
            continue
        seen.add(sig)
        lines.append('    public ' + m)
    lines.append('}')
    return '\n'.join(lines) + '\n'


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('-o', '--out', required=True)
    parser.add_argument('--classes', type=int, default=24000)
    parser.add_argument('--methods-per-class', type=int, nargs=2,
                        default=(3, 6))
    parser.add_argument('--val-frac', type=float, default=0.025)
    parser.add_argument('--test-frac', type=float, default=0.025)
    parser.add_argument('--files-per-dir', type=int, default=2000)
    parser.add_argument('--seed', type=int, default=7)
    args = parser.parse_args()

    rng = random.Random(args.seed)
    # adj+noun AND noun+noun compounds: ~19K distinct identifier stems, so
    # ~110K Zipfian field draws produce a vocab that overflows a 10K-word
    # table — the truncation/OOV pressure this corpus exists to create
    noun_pairs = ([(a, n) for a in ADJS for n in NOUNS]
                  + [(n1, n2) for n1 in NOUNS for n2 in NOUNS if n1 != n2])
    rng.shuffle(noun_pairs)

    counts = {'train': 0, 'val': 0, 'test': 0}
    # Pre-create every split dir: at smoke-scale class counts a split can
    # draw zero classes, and downstream tooling (c2v-extract --dir) treats
    # a missing directory as an error while an empty one is fine.
    for split in counts:
        os.makedirs(os.path.join(args.out, split), exist_ok=True)
    methods = 0
    for i in range(args.classes):
        r = rng.random()
        split = ('val' if r < args.val_frac else
                 'test' if r < args.val_frac + args.test_frac else 'train')
        sub = 'p%03d' % (counts[split] // args.files_per_dir)
        d = os.path.join(args.out, split, sub)
        os.makedirs(d, exist_ok=True)
        name = 'C%05d' % i
        src = gen_class(rng, name, noun_pairs, args.methods_per_class)
        with open(os.path.join(d, name + '.java'), 'w') as f:
            f.write(src)
        counts[split] += 1
        methods += src.count('public ') - 1  # minus the class decl
    print('classes: %s  methods: ~%d' % (counts, methods))


if __name__ == '__main__':
    main()
