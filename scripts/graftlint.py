"""graftlint CLI: run the JAX-invariant rule engine over the repo.

    python scripts/graftlint.py                      # all rules
    python scripts/graftlint.py --rules host-sync,jit-purity
    python scripts/graftlint.py --list-rules
    python scripts/graftlint.py --list               # show suppressed/
                                                     # baselined too
    python scripts/graftlint.py --write-baseline     # regenerate (new
                                                     # entries get
                                                     # reason TODO)

Exit status: 0 clean, 1 on any unbaselined, unsuppressed finding.
Tier-1 runs the same engine in-process (tests/test_graftlint.py);
``scripts/lint_all.py`` is the one-command entry point.  ANALYSIS.md
documents the rules, the suppression/baseline workflow, and how to add
a rule.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    from code2vec_tpu.analysis import baseline as baseline_lib
    from code2vec_tpu.analysis import engine
    from code2vec_tpu.analysis import rules as _rules  # noqa: F401
    from code2vec_tpu.analysis.core import all_rules

    parser = argparse.ArgumentParser(
        prog='graftlint', description=__doc__.splitlines()[0])
    parser.add_argument('--rules', default=None, metavar='R1,R2',
                        help='comma-separated rule names (default: all)')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the registered rules and exit')
    parser.add_argument('--list', action='store_true',
                        help='also print suppressed and baselined '
                             'findings')
    parser.add_argument('--root', default=REPO, metavar='DIR',
                        help='repository root to lint (default: this '
                             'repo)')
    parser.add_argument('--baseline', default=None, metavar='FILE',
                        help='baseline file (default: '
                             '<root>/graftlint_baseline.json)')
    parser.add_argument('--no-baseline', action='store_true',
                        help='ignore the baseline (show everything)')
    parser.add_argument('--write-baseline', action='store_true',
                        help='regenerate the baseline from current '
                             'findings; NEW entries get reason TODO '
                             'and still fail until a human fills them '
                             'in')
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print('%-18s %s' % (rule.name, rule.doc))
        return 0

    rule_names = (None if args.rules is None
                  else [r.strip() for r in args.rules.split(',')
                        if r.strip()])
    baseline_path = args.baseline
    if args.no_baseline or args.write_baseline:
        baseline_path = ''  # raw findings (no stale-entry meta noise)
    report = engine.run(root=args.root, rule_names=rule_names,
                        baseline_path=baseline_path)

    if args.write_baseline:
        path = (args.baseline if args.baseline else
                os.path.join(args.root, baseline_lib.BASELINE_NAME))
        existing = baseline_lib.Baseline.load(path)
        # keep reasons of entries that still match; new entries get
        # reason TODO and keep failing until a human fills them in.
        # Entries of rules this run did NOT execute are preserved
        # verbatim — a --rules subset must not destroy the others.
        ran = set(report.rules_run)
        keep = [e for e in existing.entries if e.get('rule') not in ran]
        baseline_lib.write(path, report.findings, existing=existing,
                           preserve=keep)
        print('baseline written to %s (%d finding(s), %d preserved '
              'from un-run rules) — fill in any TODO reasons before '
              'committing' % (path, len(report.findings), len(keep)))
        return 0

    if args.list:
        for finding in report.suppressed:
            print('suppressed: %s' % finding.format())
        for finding in report.baselined:
            print('baselined:  %s' % finding.format())
    for finding in report.findings:
        print(finding.format(), file=sys.stderr)
    print(report.summary(), file=sys.stderr if report.findings
          else sys.stdout)
    return 0 if report.clean else 1


if __name__ == '__main__':
    sys.exit(main())
