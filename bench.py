"""Benchmark: training throughput at java14m scale on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology mirrors the reference's throughput trace (windowed average over
train steps, tensorflow_model.py:424-430) at the reference's headline
configuration (config.py:47-70): batch 1024, 200 contexts/example, dims
128/128/384, full java14m vocabularies (1.3M token / 911K path / 261K
target). Baseline: ~4,700 examples/sec on a Tesla V100 (README.md:69,127 —
14M examples / 50 min per epoch; BASELINE.md).

Data is synthetic (uniform random indices): this measures the device compute
path the way the reference's numbers measure theirs — the host input
pipeline is overlap-hidden behind the step in training and is benchmarked
separately (benchmarks/bench_host_pipeline.py; results in PARITY.md).

Timing methodology: batches are made device-resident up front and the timed
loop enqueues all steps, blocking once on the final loss. Each step's state
feeds the next, so device execution cannot overlap across steps — elapsed
time is the sum of true per-step device times plus ONE host round-trip.
This matters because the TPU in this environment sits behind a network
tunnel with ~70 ms host<->device round-trip latency and ~290 ms per batch
upload (benchmarks/diag_step_breakdown.py): a per-step host sync measures
the tunnel, not the chip (round-1's 2,420 ex/s number vs the true ~20,000).
The reference's per-step sess.run carried no such penalty on a local GPU.

Resilience: the TPU tunnel in this environment can be flaky in two ways —
backend init raises UNAVAILABLE, or it wedges and `jax.devices()` hangs
forever.  Neither may surface to the driver as a traceback or a hang, so
the top-level process is a small supervisor: it runs the measurement in a
child subprocess under a hard timeout, retries with backoff on failure
(~20 min of cheap probes — the driver kills this process at ~30 min, so
the normal path must finish first), and on exhaustion emits an honest
failure line: {"error": "tpu_unavailable", "value": 0.0, "vs_baseline":
0.0}, with the newest COMMITTED capture of the same metric from
benchmarks/results/ carried only under "last_known_good" — a prior
number with provenance, never promoted into the headline fields
(VERDICT r4 #8).  A SIGTERM/SIGINT
handler flushes that same fallback line if the driver kills us early.
Exit code is always 0.  Set BENCH_CHILD=1 to run the measurement directly.
"""
from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time

from code2vec_tpu import benchlib

METRIC_NAME = 'train_examples_per_sec_per_chip_java14m'

# BENCH_SMOKE=1: tiny shapes so the harness itself can be validated on CPU.
# The emitted metric is renamed so a smoke line can never be mistaken for a
# java14m benchmark number.
SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
WARMUP_STEPS, MEASURE_STEPS = benchlib.bench_steps(SMOKE)

# BENCH_RECIPE selects which knob set the headline measures now that the
# measured winners are config defaults (2026-07-31 A/B ladder):
#   'default' — the config as shipped (rbg dropout + bf16 Adam-mu)
#   'parity'  — the reference-parity knobs (threefry + fp32 mu), kept
#               refreshable so the 4.69x-vs-V100 comparison row in
#               PERF.md never goes stale while defaults move
# Unknown values fall back to 'default' (the driver must never crash on a
# stray env var); the emitted JSON carries the resolved recipe.
BENCH_RECIPE = os.environ.get('BENCH_RECIPE', 'default')
if BENCH_RECIPE not in ('default', 'default_v2', 'parity', 'ragged'):
    BENCH_RECIPE = 'default'
RECIPE_OVERRIDES = {
    'default': {},
    # the full ragged-fusion candidate (ISSUEs 10 + 12): the fusion is
    # the shipped default now, so this recipe adds the train-side
    # Pallas kernel pair (RAGGED_TRAIN_KERNEL) — the headline re-capture
    # arm once scripts/flip_verdict.py records the >=2% train win from
    # the bench_pallas_ragged A/B
    'ragged': dict(USE_PALLAS_RAGGED_FUSION=True,
                   RAGGED_TRAIN_KERNEL=True),
    # the 2026-07-31 morning default set (rbg + bf16 mu, fp32 nu/grads),
    # pinned so the headline_v2 capture stays reproducible now that the
    # shipped default moved on (bf16 nu) — a 'default' re-run would
    # silently measure the newer recipe under the older label
    'default_v2': dict(ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32'),
    'parity': dict(DROPOUT_PRNG_IMPL='threefry2x32',
                   ADAM_MU_DTYPE='float32',
                   ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32'),
}[BENCH_RECIPE]


def run_measurement() -> None:
    """Child mode: init backend, run the timed loop, print the JSON line."""
    import jax
    benchlib.honor_env_platforms()

    devices = jax.devices()
    n_devices = len(devices)
    platform = devices[0].platform.lower()
    if not SMOKE and platform not in ('tpu', 'axon'):
        # Refuse to pass off a CPU/GPU number as the java14m TPU metric.
        print(json.dumps({
            'metric': METRIC_NAME, 'value': 0.0, 'unit': 'examples/sec/chip',
            'vs_baseline': 0.0, 'error': 'tpu_unavailable',
            'detail': f'backend initialized but platform={platform}',
        }))
        return

    config = benchlib.headline_config(SHAPES, **RECIPE_OVERRIDES)
    trainer, state = benchlib.build_trainer(config, SHAPES)

    # Device-resident batches, placed with the trainer's own mesh-aware
    # staging: training overlaps uploads behind the step, so upload cost
    # must not be billed to the per-step number — through this
    # environment's device tunnel one batch upload costs ~290 ms, 6x the
    # step itself (see module docstring).
    host_batches = benchlib.random_batches(SHAPES, 4)
    if config.USE_PALLAS_RAGGED_FUSION:
        # the fused path lives behind the PACKED wire twins: plane
        # batches dispatch (by arity) to the planes program the flag
        # never touches, so the 'ragged' recipe would silently measure
        # the unfused step under the fused label — the same mislabeling
        # trap the default_v2 pin above guards against
        host_batches = benchlib.pack_batches(host_batches, trainer)
    batches = benchlib.staged(trainer, host_batches)

    for i in range(WARMUP_STEPS):
        state, loss = trainer.train_step_placed(state, batches[i % len(batches)])
        float(loss)

    # Enqueue every step, block once: steps serialize on the state
    # dependency, so this sums true device step times + one round-trip.
    start = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, loss = trainer.train_step_placed(state, batches[i % len(batches)])
    float(loss)
    elapsed = time.perf_counter() - start

    examples_per_sec = MEASURE_STEPS * SHAPES.batch_size / elapsed
    per_chip = examples_per_sec / n_devices
    # bytes/batch each wire format would put on the host->device link at
    # the realistic java14m fill (the timed loop above is device-resident
    # by design, so this is a computed property, not a timing)
    filled = benchlib.random_batches(SHAPES, 1, seed=2,
                                     fill=benchlib.JAVA14M_FILL)
    wire = {'planes': benchlib.wire_bytes(filled[0]),
            'packed': benchlib.wire_bytes(
                benchlib.pack_batches(filled, trainer)[0])}
    line = {
        'metric': ('train_examples_per_sec_SMOKE_ONLY' if SMOKE
                   else METRIC_NAME),
        'value': round(per_chip, 1),
        'unit': 'examples/sec/chip',
        'vs_baseline': (0.0 if SMOKE else round(
            per_chip / benchlib.V100_BASELINE_EXAMPLES_PER_SEC, 3)),
        'recipe': BENCH_RECIPE,
        'wire_bytes_per_batch': wire,
        # per-stage peak HBM (ISSUE 9): footprint rides the headline
        # record so the bench trajectory tracks memory next to
        # throughput (None on stats-less backends, an explicit gap)
        **benchlib.device_memory_record(),
    }
    if SMOKE:
        # echo the RESOLVED knobs so the smoke test can assert the recipe
        # actually reached the config, not just the label
        line['knobs'] = {'dropout_prng': config.DROPOUT_PRNG_IMPL,
                         'adam_mu': config.ADAM_MU_DTYPE,
                         'adam_nu': config.ADAM_NU_DTYPE,
                         'grads': config.GRADS_DTYPE}
    print(json.dumps(line))


def run_probe() -> None:
    """Probe mode: just initialize the backend and report the platform.
    Cheap enough to retry often when the tunnel is wedged (a wedged tunnel
    HANGS jax.devices() rather than raising — observed in round 1/2)."""
    import jax
    benchlib.honor_env_platforms()
    devices = jax.devices()
    print(json.dumps({'probe': devices[0].platform.lower(),
                      'n_devices': len(devices)}))


def _json_line(stdout: str, key: str) -> dict | None:
    """Last stdout line that parses as a JSON object containing ``key``."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith('{'):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and key in obj:
            return obj
    return None


# The probe/measure child currently in flight, so the supervisor's signal
# handler can kill it on the way down — an orphaned ~900s measure loop
# would keep the tunnel occupied long after the driver killed us.
_ACTIVE_CHILD: subprocess.Popen | None = None


def _run_child(mode: str, timeout: float):
    """Returns (stdout, failure_detail). stdout is None on timeout."""
    global _ACTIVE_CHILD
    env = dict(os.environ, BENCH_CHILD=mode)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    _ACTIVE_CHILD = proc
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired as e:
        proc.kill()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        # Salvage partial stdout: a child that printed its result line and
        # then wedged in backend teardown still succeeded.
        partial = e.stdout.decode(errors='replace') if isinstance(
            e.stdout, bytes) else (e.stdout or '')
        return (partial or None,
                f'{mode} child timed out after {timeout:.0f}s (wedged backend?)')
    finally:
        _ACTIVE_CHILD = None
    tail = (stderr or stdout).strip().splitlines()
    detail = ' | '.join(tail[-3:]) if tail else f'rc={proc.returncode}'
    return stdout, detail


_FILENAME_STAMP_RE = re.compile(r'(\d{4}-\d{2}-\d{2}T\d{4}Z)')


def _capture_recency(results_dir: str, name: str) -> tuple:
    """Sort key for capture files, newest first when reverse-sorted.

    Git checkouts do not preserve mtimes — after a fresh clone every
    results file shares one timestamp — so prefer the ISO stamp embedded
    in capture_<ISO>_rN filenames and fall back to mtime only for files
    that don't carry one (stamped files always outrank unstamped ones,
    since any committed stamp is more trustworthy than a clone mtime)."""
    m = _FILENAME_STAMP_RE.search(name)
    if m:
        return (1, m.group(1))
    try:
        return (0, os.path.getmtime(os.path.join(results_dir, name)))
    except OSError:
        return (0, 0.0)


def _last_known_good(results_dir: str | None = None):
    """Newest prior capture of the headline metric from
    benchmarks/results/*.jsonl, or None. Scanned newest-file-first; lines
    may be raw ({"metric": ...}) or stage-wrapped ({"data": {...}})."""
    if results_dir is None:
        results_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            'benchmarks', 'results')
    try:
        files = sorted(
            os.listdir(results_dir),
            key=lambda n: _capture_recency(results_dir, n),
            reverse=True)
    except OSError:
        return None
    # Prefer a capture of the SAME recipe as this run: a default-recipe
    # fallback must not cite a parity-recipe number (or vice versa) as
    # last-known-good.  Captures from before the recipe field existed
    # were all measured pre-flip, i.e. the parity knobs.  If no
    # same-recipe capture exists, the newest other-recipe one is still
    # returned — with its recipe carried explicitly — because a
    # provenance-labeled prior number beats none at all.
    best_same, best_other = None, None
    for name in files:
        if not name.endswith('.jsonl'):
            continue
        try:
            with open(os.path.join(results_dir, name)) as f:
                for raw in f:
                    try:
                        rec = json.loads(raw)
                    except ValueError:
                        continue
                    if not isinstance(rec, dict):
                        continue
                    rec = rec.get('data') or rec
                    if (isinstance(rec, dict)
                            and rec.get('metric') == METRIC_NAME
                            and not rec.get('error')
                            # a prior run's own stale fallback is a copy,
                            # not a capture — never re-ingest it
                            and not rec.get('stale')
                            and not rec.get('capture_error')
                            and rec.get('value')):
                        found = {'source_file':
                                 f'benchmarks/results/{name}',
                                 'value': rec['value'],
                                 'unit': rec.get('unit'),
                                 'vs_baseline': rec.get('vs_baseline'),
                                 'recipe': rec.get('recipe', 'parity')}
                        if found['recipe'] == BENCH_RECIPE:
                            best_same = found
                        else:
                            best_other = best_other or found
        except OSError:
            continue
        if best_same is not None:
            return best_same
    return best_same or best_other


def _fallback_line(last_failure: str) -> dict:
    """The result line for when no fresh measurement could be taken.

    The headline fields stay honest: value 0.0, vs_baseline 0.0, and an
    explicit `error` — a reader of the fresh-run fields can never mistake
    a tunnel outage for a measurement (VERDICT r4 weak #1 / ADVICE r3 #1).
    If a prior COMMITTED capture of the same metric exists it is carried
    ONLY under 'last_known_good' (with its unit/vs_baseline/source_file),
    never promoted into the headline."""
    line = {
        'metric': ('train_examples_per_sec_SMOKE_ONLY' if SMOKE
                   else METRIC_NAME),
        'value': 0.0, 'unit': 'examples/sec/chip',
        'vs_baseline': 0.0, 'error': 'tpu_unavailable',
        'detail': str(last_failure)[:500],
        # which recipe the FAILED run targeted — a consumer refreshing
        # the parity vs default rows must be able to tell
        'recipe': BENCH_RECIPE,
    }
    known_good = None if SMOKE else _last_known_good()
    if known_good is not None:
        line['last_known_good'] = {
            'value': known_good['value'],
            'unit': known_good.get('unit'),
            'vs_baseline': known_good.get('vs_baseline'),
            'source_file': known_good['source_file'],
            # may legitimately differ from the headline recipe (an
            # other-recipe capture beats none) — labeled so it can never
            # be mistaken for a same-recipe number
            'recipe': known_good.get('recipe'),
        }
    return line


def supervise() -> None:
    """Probe the backend cheaply, then run the measurement in a child —
    both under hard timeouts, retried with backoff within a total budget.

    Always prints exactly one JSON result line and exits 0, whatever the
    backend does (raise, hang, or die): the driver's capture must never see
    a bare traceback again (round-1 BENCH_r01.json was rc=1 with no number).

    Two constraints shape the budget (VERDICT r3 #1): the driver runs this
    process under its OWN ~30-minute kill, so (a) the default budget is
    ~20 min — the normal path must finish first — and (b) a SIGTERM/SIGINT
    handler is installed before the first attempt that flushes the
    stale-fallback line and exits 0, so even an early external kill leaves
    a parseable artifact instead of round-3's `rc: 124, parsed: null`.
    Wedge-outwaiting beyond this budget belongs to
    benchmarks/watch_supervisor.sh, which runs all round.
    """
    budget = float(os.environ.get('BENCH_TOTAL_BUDGET',
                                  '300' if SMOKE else '1200'))
    probe_timeout = float(os.environ.get('BENCH_PROBE_TIMEOUT', '90'))
    child_timeout = float(os.environ.get(
        'BENCH_CHILD_TIMEOUT', '150' if SMOKE else '900'))
    deadline = time.monotonic() + budget
    backoffs = [10.0, 20.0, 45.0, 90.0]

    state = {'last_failure': 'no attempt made', 'final_line': None}

    def _flush_and_exit(signum, frame):
        child = _ACTIVE_CHILD
        if child is not None and child.poll() is None:
            # Don't orphan a TPU-holding measure loop past our own death.
            child.kill()
        if state['final_line'] is not None:
            # A result was (or was about to be) printed: re-emit that exact
            # line. A duplicated identical line is harmless to a last-line
            # parser; a missing or superseded one is the round-3 failure.
            print(state['final_line'], flush=True)
        else:
            line = _fallback_line(
                f'killed by signal {signum} mid-supervision; '
                f'last failure: {state["last_failure"]}')
            print(json.dumps(line), flush=True)
        # os._exit: the handler may fire inside subprocess communication —
        # skip interpreter teardown that could raise and clobber the code.
        os._exit(0)

    signal.signal(signal.SIGTERM, _flush_and_exit)
    signal.signal(signal.SIGINT, _flush_and_exit)

    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining < probe_timeout:
            break
        stdout, state['last_failure'] = _run_child('probe', probe_timeout)
        probe = _json_line(stdout, 'probe') if stdout is not None else None
        if probe is not None and not SMOKE and probe['probe'] not in ('tpu',
                                                                      'axon'):
            # A measure child would only re-init the backend to refuse;
            # skip it and keep retrying for the tunnel to come back.
            state['last_failure'] = f"backend up but platform={probe['probe']}"
        elif probe is not None:
            remaining = deadline - time.monotonic()
            stdout, detail = _run_child(
                'measure', max(60.0, min(child_timeout, remaining)))
            result = _json_line(stdout, 'metric') if stdout is not None else None
            if result is not None and 'error' not in result:
                # Record the line BEFORE printing: a signal landing in the
                # window re-emits this same fresh line instead of a stale
                # fallback (or nothing).
                state['final_line'] = json.dumps(result)
                print(state['final_line'], flush=True)
                return
            state['last_failure'] = (result.get('detail', result['error'])
                                     if result is not None else detail)
        delay = backoffs[min(attempt - 1, len(backoffs) - 1)]
        if time.monotonic() + delay > deadline:
            break
        print(f'bench attempt {attempt} failed ({state["last_failure"]}); '
              f'retrying in {delay:.0f}s', file=sys.stderr)
        time.sleep(delay)

    # The tunnel stayed wedged through the whole probe budget: report an
    # honest failure (value 0.0 + error), with the most recent COMMITTED
    # capture (methodology + cross-checks: PERF.md) under last_known_good.
    state['final_line'] = json.dumps(_fallback_line(state['last_failure']))
    print(state['final_line'], flush=True)


def main() -> None:
    mode = os.environ.get('BENCH_CHILD', '')
    if mode == 'probe':
        run_probe()
    elif mode:
        run_measurement()
    else:
        supervise()


if __name__ == '__main__':
    main()
