"""Benchmark: training throughput at java14m scale on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology mirrors the reference's throughput trace (windowed average over
train steps, tensorflow_model.py:424-430) at the reference's headline
configuration (config.py:47-70): batch 1024, 200 contexts/example, dims
128/128/384, full java14m vocabularies (1.3M token / 911K path / 261K
target). Baseline: ~4,700 examples/sec on a Tesla V100 (README.md:69,127 —
14M examples / 50 min per epoch; BASELINE.md).

Data is synthetic (uniform random indices): this measures the device compute
path the way the reference's numbers measure theirs — the host input
pipeline is benchmarked separately (it is overlap-hidden behind the step in
training).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

V100_BASELINE_EXAMPLES_PER_SEC = 4700.0

TOKEN_VOCAB = 1301136
PATH_VOCAB = 911417
TARGET_VOCAB = 261245
BATCH_SIZE = 1024
MAX_CONTEXTS = 200
WARMUP_STEPS = 10
MEASURE_STEPS = 30

# BENCH_SMOKE=1: tiny shapes so the harness itself can be validated on CPU.
# The emitted metric is renamed so a smoke line can never be mistaken for a
# java14m benchmark number.
SMOKE = os.environ.get('BENCH_SMOKE', '') not in ('', '0', 'false')
if SMOKE:
    TOKEN_VOCAB, PATH_VOCAB, TARGET_VOCAB = 1000, 1000, 500
    BATCH_SIZE, MAX_CONTEXTS = 64, 16
    WARMUP_STEPS, MEASURE_STEPS = 2, 5


def main() -> None:
    import jax
    from code2vec_tpu.config import Config
    from code2vec_tpu.data.reader import Batch
    from code2vec_tpu.models.backends import create_backend
    from code2vec_tpu.parallel import mesh as mesh_lib
    from code2vec_tpu.training.trainer import Trainer

    n_devices = len(jax.devices())
    config = Config(
        TRAIN_DATA_PATH_PREFIX='bench', DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='bfloat16', VERBOSE_MODE=0, READER_USE_NATIVE=False,
        TRAIN_BATCH_SIZE=BATCH_SIZE, TEST_BATCH_SIZE=BATCH_SIZE,
        MAX_CONTEXTS=MAX_CONTEXTS,
        MAX_TOKEN_VOCAB_SIZE=TOKEN_VOCAB, MAX_PATH_VOCAB_SIZE=PATH_VOCAB,
        MAX_TARGET_VOCAB_SIZE=TARGET_VOCAB)

    from code2vec_tpu.vocab import SizeOnlyVocabs
    backend = create_backend(
        config, SizeOnlyVocabs(TOKEN_VOCAB, PATH_VOCAB, TARGET_VOCAB))
    trainer = Trainer(config, backend)
    state = trainer.init_state(seed=0)

    rng = np.random.default_rng(0)

    def make_batch():
        return Batch(
            source=rng.integers(1, TOKEN_VOCAB, (BATCH_SIZE, MAX_CONTEXTS)).astype(np.int32),
            path=rng.integers(1, PATH_VOCAB, (BATCH_SIZE, MAX_CONTEXTS)).astype(np.int32),
            target=rng.integers(1, TOKEN_VOCAB, (BATCH_SIZE, MAX_CONTEXTS)).astype(np.int32),
            mask=np.ones((BATCH_SIZE, MAX_CONTEXTS), np.float32),
            label=rng.integers(1, TARGET_VOCAB, (BATCH_SIZE,)).astype(np.int32),
            weight=np.ones((BATCH_SIZE,), np.float32))

    batches = [make_batch() for _ in range(4)]

    # Per-step hard sync: honest under async dispatch (block_until_ready on
    # the final loss under-reports through the device tunnel), and it is
    # what the reference's per-step sess.run([optimizer, loss]) did
    # (tensorflow_model.py:74-80).
    for i in range(WARMUP_STEPS):
        state, loss = trainer.train_step(state, batches[i % len(batches)])
        float(loss)

    start = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, loss = trainer.train_step(state, batches[i % len(batches)])
        float(loss)
    elapsed = time.perf_counter() - start

    examples_per_sec = MEASURE_STEPS * BATCH_SIZE / elapsed
    per_chip = examples_per_sec / n_devices
    print(json.dumps({
        'metric': ('train_examples_per_sec_SMOKE_ONLY' if SMOKE
                   else 'train_examples_per_sec_per_chip_java14m'),
        'value': round(per_chip, 1),
        'unit': 'examples/sec/chip',
        'vs_baseline': (0.0 if SMOKE else
                        round(per_chip / V100_BASELINE_EXAMPLES_PER_SEC, 3)),
    }))


if __name__ == '__main__':
    main()
