"""Typed configuration for code2vec_tpu.

Replicates every knob of the reference ``Config`` (reference config.py:46-70)
and its file-naming contract (config.py:173-230) so existing ``.c2v`` datasets
and launch scripts drop in unchanged, and adds TPU-specific knobs (mesh shape,
compute dtype, checkpointing) that have no reference counterpart.
"""
from __future__ import annotations

import dataclasses
import logging
import math
import os
import sys
from argparse import ArgumentParser
from typing import Optional, Iterator, Tuple, Any


@dataclasses.dataclass
class Config:
    # ---- training schedule (reference config.py:47-57) ----
    NUM_TRAIN_EPOCHS: int = 20
    SAVE_EVERY_EPOCHS: int = 1
    # 0 = per-epoch saves only. At java14m scale an epoch is ~14K steps
    # (~an hour of chip time); step-interval async saves bound the work a
    # preemption can destroy — the reference had no equivalent.
    SAVE_EVERY_N_STEPS: int = 0
    TRAIN_BATCH_SIZE: int = 1024
    TEST_BATCH_SIZE: int = 1024
    TOP_K_WORDS_CONSIDERED_DURING_PREDICTION: int = 10
    NUM_BATCHES_TO_LOG_PROGRESS: int = 100
    NUM_TRAIN_BATCHES_TO_EVALUATE: int = 1800
    READER_NUM_PARALLEL_BATCHES: int = 6
    SHUFFLE_BUFFER_SIZE: int = 10000
    CSV_BUFFER_SIZE: int = 100 * 1024 * 1024
    MAX_TO_KEEP: int = 10

    # ---- model hyper-params (reference config.py:60-70) ----
    MAX_CONTEXTS: int = 200
    MAX_TOKEN_VOCAB_SIZE: int = 1301136
    MAX_TARGET_VOCAB_SIZE: int = 261245
    MAX_PATH_VOCAB_SIZE: int = 911417
    DEFAULT_EMBEDDINGS_SIZE: int = 128
    TOKEN_EMBEDDINGS_SIZE: int = 128
    PATH_EMBEDDINGS_SIZE: int = 128
    CODE_VECTOR_SIZE: int = 384          # = context_vector_size by default
    TARGET_EMBEDDINGS_SIZE: int = 384    # = CODE_VECTOR_SIZE by default
    DROPOUT_KEEP_RATE: float = 0.75
    SEPARATE_OOV_AND_PAD: bool = False

    # ---- TPU-native knobs (no reference counterpart) ----
    # Compute dtype for the forward/backward pass. Params are always fp32;
    # 'bfloat16' casts activations/matmuls for the MXU and keeps the loss in
    # fp32. 'float32' matches reference numerics bit-closely for tests.
    COMPUTE_DTYPE: str = 'bfloat16'
    # PRNG implementation for the dropout mask. 'threefry2x32' is JAX's
    # default counter-based generator — portable across platforms, but the
    # (B, C, 3d) mask is ~131M draws/step at the java14m config, ~10% of
    # the measured train step (PERF.md). 'rbg' derives a per-step key for
    # the hardware RngBitGenerator instead — same keep-probability, a
    # different (still deterministic, seed-keyed) random stream. The
    # checkpointed key stays threefry either way; the rbg key is derived
    # inside the step, so checkpoints are unaffected by this knob.
    # DEFAULT 'rbg' per the ≥2% rule: the on-chip A/B measured 43.36 vs
    # 47.32 ms/step (-8.4%, capture_2026-07-31T0344Z_r5.jsonl), and the
    # full-dims learning curve under rbg matches the threefry/fp32 twin
    # (accuracy_cpu_full_bf16.json: F1 0.7487 vs 0.7470). 'threefry2x32'
    # remains the portable reference behavior.
    DROPOUT_PRNG_IMPL: str = 'rbg'
    # Mesh shape: (data, model). data axis = DP (gradient psum over ICI);
    # model axis = row-sharded embedding tables + column-sharded softmax.
    MESH_DATA_AXIS_SIZE: int = -1   # -1: all devices on the data axis
    MESH_MODEL_AXIS_SIZE: int = 1
    # Learning rate for Adam (reference uses tf.train.AdamOptimizer defaults,
    # tensorflow_model.py:232 -> lr=0.001).
    LEARNING_RATE: float = 0.001
    # Update the token/path embedding tables with lazy (sparse-row) Adam
    # (tf.contrib.opt.LazyAdamOptimizer semantics) instead of dense Adam:
    # moments decay only for rows present in the batch, and the
    # optimizer's HBM traffic scales with the batch (<=614K touched rows)
    # instead of the 2.2M-row vocabulary. The DEFAULT dense Adam is the
    # reference-parity behavior (TF1's AdamOptimizer decays moments
    # densely even for IndexedSlices gradients); the lazy variant is a
    # deliberate throughput/semantics trade-off for giant tables and stays
    # off until the on-chip A/B records a win and a quality check passes
    # (ops/lazy_adam.py, benchmarks/diag_step_breakdown.py). Dense
    # parameters (TRANSFORM/ATTENTION/target table) keep optax Adam
    # either way.
    LAZY_EMBEDDING_ADAM: bool = False
    # Storage dtype for Adam's FIRST moment (optax mu_dtype). 'bfloat16'
    # halves the first-moment HBM traffic (~1.5 GB/step read+write at
    # java14m's 384M params) in the HBM-bound update (PERF.md roofline);
    # params stay fp32 (the second moment has its own knob below).
    # DEFAULT 'bfloat16' per the
    # ≥2% rule: the on-chip A/B measured 44.89 vs 47.32 ms/step (-5.1%
    # alone; -13.4% combined with rbg dropout,
    # capture_2026-07-31T0344Z_r5.jsonl); the equivalence twins
    # (accuracy_*bf16mu*.json) pair its F1 curve against the fp32-moment
    # runs. Changing it changes the optimizer-state dtype; resuming a
    # checkpoint written under the OTHER setting adapts automatically
    # (checkpoints.py restores mu as stored, warns, and casts to the
    # configured dtype — set --adam-mu-dtype to the stored dtype to
    # resume bit-exactly).
    ADAM_MU_DTYPE: str = 'bfloat16'
    # Storage dtype for Adam's SECOND moment (training/adam_dtypes.py).
    # The nu tree is the same-size stream as mu before its flip (~1.54 GB
    # fp32 at java14m's 384M params, read+write every step of the
    # HBM-bound dense update): 'bfloat16' halves it (~1.9 ms/step
    # analytic at the measured ~819 GB/s). Moment math stays fp32 every
    # step — only HBM storage narrows (the sqrt denominator is formed
    # after an fp32 upcast). DEFAULT 'bfloat16' per the >=2% flip rule
    # (PERF.md): the on-chip A/B measured 38.24 vs 41.10 ms/step on the
    # default recipe (-7.0%, 26,777 ex/s/chip;
    # moment_dtypes_manual_2026-07-31T0716Z.jsonl) and the learning-curve
    # twin matches — best F1 0.5606 (accuracy_cpu_full_bf16nu.json) vs
    # 0.5565/0.5566 for the bf16-mu and fp32-moment twins on the
    # identical dataset. Cross-dtype checkpoint resume adapts
    # automatically, like ADAM_MU_DTYPE (checkpoints.py).
    ADAM_NU_DTYPE: str = 'bfloat16'
    # Dtype the GRADIENTS are produced and streamed in (training/
    # trainer.py): 'bfloat16' differentiates the loss wrt the pre-cast
    # bf16 params, so the two table-grad scatter-adds and the full grad
    # tree cross HBM at half width (~1.54 GB fp32 -> 0.77 GB at java14m
    # scale, plus the eliminated bf16->fp32 cast of the table
    # cotangents). Requires COMPUTE_DTYPE='bfloat16' (enforced by
    # verify()): under bf16 compute the FORWARD is unchanged — every
    # param is cast to bf16 before use either way — and master params +
    # Adam
    # moment MATH stay fp32 (training/adam_dtypes.py upcasts before any
    # arithmetic; only storage narrows). What changes numerically is one
    # rounding of each gradient to bf16 — the standard mixed-precision
    # regime (fp32 master + bf16 grads). DEFAULT 'float32' until the
    # on-chip A/B (benchmarks/bench_moment_dtypes.py) and the
    # learning-curve twin (profile cpu_full_bf16grads) clear the >=2%
    # flip rule, like every perf knob here (PERF.md).
    GRADS_DTYPE: str = 'float32'
    # Backward-pass strategy for the token/path table gradients
    # (ops/embed_grad.py): 'dense' leaves the B*C-row scatter-add to XLA;
    # 'sorted' sorts the index stream so duplicate row hits are adjacent;
    # 'dedup' additionally pre-combines duplicates with a segmented scan so
    # each table row is written at most once. Numerically equivalent up to
    # fp summation order. The on-chip A/B decided for 'dense' on both
    # uniform and zipf index streams (48.69 vs 54.45 sorted / 65.42 dedup
    # ms/step zipf, capture_2026-07-31T0344Z_r5.jsonl): XLA's native
    # scatter-add beats both pre-combine strategies, which break its
    # fusion the same way lazy Adam does (PERF.md).
    EMBED_GRAD_IMPL: str = 'dense'
    # Route the TRAINING cross-entropy through the flash-style fused Pallas
    # kernel (ops/pallas_ce.py): logsumexp + label pick computed blockwise
    # over the target table, so the (B, target_vocab) logits matrix never
    # exists in HBM in either direction (~4.3 GB/step at java14m shapes).
    # Multi-device meshes use the shard_mapped variant (table row-sharded
    # over 'model', batch over 'data', online stats merged over ICI).
    # The on-chip A/B measured it NEUTRAL at java14m shapes (47.18 vs
    # 47.23 ms/step alone; +1.4% on top of the rbg+bf16-mu winner,
    # capture_2026-07-31T0344Z_r5.jsonl) — below the ≥2% flip rule, so it
    # stays opt-in: XLA's own CE fusion already avoids most of the logits
    # round-trip. Eval/predict always materialize logits (top-k needs
    # them).
    USE_PALLAS_FUSED_CE: bool = False
    # Shard the contexts axis (the 'sequence' analog, MAX_CONTEXTS) over the
    # model mesh axis — order-free sequence parallelism for large bags: the
    # attention softmax reductions become XLA collectives (SURVEY.md §5
    # 'long-context'). Off by default (MAX_CONTEXTS=200 fits comfortably).
    SHARD_CONTEXTS: bool = False
    # Rematerialize the encode block (jax.checkpoint): the (B, C, 3d)
    # activations — gathered context embeddings, dropout output, tanh
    # input — are recomputed during the backward instead of living in HBM
    # across the loss. FLOPs-for-memory for long-context configs (large
    # MAX_CONTEXTS / big batch); pointless at C=200 where they fit easily.
    REMAT_ENCODE: bool = False
    # Layout of Adam's moment tables over the mesh. 'mirror' (default)
    # copies each parameter's own sharding: row-sharded over 'model',
    # REPLICATED along 'data' — every data shard stores the full ~3.1 GB
    # of moments at java14m scale. 'zero' (ZeRO-1-style) additionally
    # shards the three tables' moments over the data axis: per-device
    # optimizer memory drops by the data-axis size and XLA turns the
    # update into reduce-scatter/all-gather collectives it places itself.
    # Parameters stay replicated along 'data' either way (this is
    # optimizer-STATE partitioning, not ZeRO-3). Numerics are unchanged
    # (tests/test_sharding.py); requires PARAM_ROW_ALIGNMENT divisible by
    # the whole mesh size and the dense optax Adam (not LAZY_EMBEDDING_ADAM).
    OPTIMIZER_STATE_SHARDING: str = 'mirror'
    # Embedding tables are padded to a multiple of this many rows so they
    # shard evenly over any model axis that DIVIDES this value (validated at
    # Trainer construction), keeping checkpoint shapes topology-independent.
    # Padded target rows are masked out of the softmax/top-k. Changing this
    # changes checkpoint shapes — it is recorded in a checkpoint sidecar and
    # verified on restore.
    PARAM_ROW_ALIGNMENT: int = 128
    # Host input pipeline.
    READER_PREFETCH_BATCHES: int = 8
    # How many batches fit()/evaluate() stage onto the device ahead of the
    # step consuming them, so host->device transfer overlaps the previous
    # steps' compute (jax transfers are async; without staging, each
    # step's dispatch serializes behind its own upload). 0 disables.
    DEVICE_PREFETCH_BATCHES: int = 2
    # What crosses the host->device wire per batch (data/packed.py).
    # 'planes' is the v1 format: six padded arrays, 16 bytes per context
    # SLOT — at the java14m fill rate (contexts/method p50 28 of 200)
    # mostly padding. 'packed' (default) densifies each example's
    # contexts to its effective length: 12 bytes per RETAINED slot + 12
    # per example (~3-5x fewer bytes/batch at java14m shape), with a
    # jitted device-side unpack that reproduces the v1 planes
    # BIT-exactly (tests/test_packed.py), so the model and its numerics
    # are untouched. Multi-host runs fall back to 'planes'
    # (wire_format_for): per-shard capacities are data-dependent and
    # processes cannot agree on them without communication.
    BATCH_WIRE_FORMAT: str = 'packed'
    # Donate staged batch buffers to the consuming train/eval step so
    # XLA may reuse their device memory for intermediates while the
    # staging ring (DEVICE_PREFETCH_BATCHES) holds the next uploads.
    # fit()/evaluate() consume each staged batch exactly once; harnesses
    # that re-feed the same placed arrays across steps must disable this
    # (benchlib.headline_config pins it off).
    DONATE_STAGED_BATCHES: bool = True
    READER_USE_NATIVE: bool = True  # use the C++ tokenizer when available
    # Tokenize the train split once into a binary cache
    # (<data>.train.c2v.tokcache/, ~12 bytes/context on disk) and stream
    # int32 tensors for every later epoch.
    TRAIN_DATA_CACHE: bool = True
    # Use the fused Pallas encode kernel (split-TRANSFORM matmul + tanh +
    # attention scores in one VMEM pass) for the deterministic forward
    # (eval/predict). Measured on-chip at the java14m config: 0.99x vs
    # XLA (PERF.md) — the encode block is small next to the 261K-vocab
    # logits matmul + top-k — so this stays off by default; it is worth
    # re-measuring for long-context configs (MAX_CONTEXTS >> 200) where
    # the encode block dominates.
    USE_PALLAS_FUSED_ENCODE: bool = False
    # Run encode + attention straight off the packed wire
    # (ops/pallas_ragged.py): the (D, cap, 3) triples + counts feed a
    # ragged fused encoder — gather, row-split transform, tanh, score,
    # and a FuseMax-style single-pass per-example softmax + weighted sum
    # — so the (B, max_contexts) segment-scatter unpack and every dense
    # (B, C, .) intermediate disappear from the packed train/eval/
    # predict/serving programs. On a real TPU backend the deterministic
    # forward runs the Pallas kernel; training (dropout, backward) and
    # non-TPU backends run the differentiable jnp twin on the same
    # packed layout. Outputs match the unpack-then-dense path to fp32
    # rounding (tests/test_pallas_ragged.py); dropout draws its mask
    # over the packed layout (a different seed-keyed stream, the
    # DROPOUT_PRNG_IMPL precedent). ON by default: the deterministic
    # paths run the kernel only on a real TPU backend (jnp twin
    # everywhere else — never the interpreter), and the train path runs
    # the custom-VJP twin whose recompute backward saves no (B, C, .)/
    # (D, cap, .) residuals (structural wins on every backend; CPU
    # harness smoke 1.59x train / 1.91x predict, PERF.md "Ragged
    # fusion"). --no-ragged-fusion restores the unpack-then-dense
    # (bit-exact vs planes) path.
    USE_PALLAS_RAGGED_FUSION: bool = True
    # Route the packed TRAIN step's forward AND recompute-backward
    # through the Pallas kernel pair on a real TPU backend
    # (ops/pallas_ragged.py::_ragged_kernel/_bwd_kernel). This is the
    # on-chip train flip the >=2% rule still gates: OFF until
    # scripts/flip_verdict.py reads a healthy capture round
    # (benchmarks/bench_pallas_ragged.py train arms) clearing 1.02x —
    # the verdicts have been queued since the 2026-07-31 TPU wedge.
    # Inert off-TPU (the custom-VJP jnp twin runs regardless) and
    # without USE_PALLAS_RAGGED_FUSION (the train step then unpacks).
    RAGGED_TRAIN_KERNEL: bool = False
    # When set, capture a jax.profiler trace of a few training steps into
    # this directory (viewable with TensorBoard/Perfetto) — the step-level
    # profiler the reference lacked (SURVEY.md §5 'Tracing / profiling').
    PROFILE_DIR: Optional[str] = None
    PROFILE_START_STEP: int = 10
    PROFILE_NUM_STEPS: int = 5
    # ---- telemetry (code2vec_tpu/telemetry/, OBSERVABILITY.md) ----
    # Master switch for the step-phase/pipeline telemetry layer: phase
    # timers (batch-wait / h2d / dispatch / sync), throughput counters,
    # staging-ring occupancy, jit-compile tracking, and the JSONL /
    # Prometheus-textfile / console exporters. Off by default: the hot
    # loop then carries only `is None` checks (measured <1% either way,
    # benchmarks/bench_telemetry_overhead.py).
    TELEMETRY: bool = False
    # Where telemetry artifacts (metrics.jsonl, metrics.prom, traces/)
    # land; None resolves next to the model artifacts like the
    # metrics_writer 'summaries' convention (telemetry/stepwatch.py).
    TELEMETRY_DIR: Optional[str] = None
    # Exporter flush cadence, in train steps. Rates (examples/sec) are
    # computed per flush window.
    TELEMETRY_FLUSH_EVERY_STEPS: int = 50
    # Minimum seconds between telemetry console progress lines.
    TELEMETRY_CONSOLE_EVERY_SECS: float = 30.0
    # On-demand jax.profiler capture: start a TELEMETRY_TRACE_NUM_STEPS
    # trace when this global step is reached (-1: disabled; the
    # TELEMETRY_TRACE_AT_STEP env var fills in when the field is unset,
    # and `touch <telemetry_dir>/TRACE_NOW` triggers a capture from a
    # LIVE run with no restart — telemetry/trace.py).
    TELEMETRY_TRACE_AT_STEP: int = -1
    TELEMETRY_TRACE_NUM_STEPS: int = 5
    # ---- per-request serving traces (telemetry/tracing.py) ----
    # Head-based sample rate in [0, 1] for the serving engine's
    # per-request span log (OBSERVABILITY.md "Per-request serving
    # traces"). 0 disables tracing entirely (no spans, no flight
    # recorder); any shed/expired/degraded/split/closed request, and any
    # request slower than TRACING_SLOW_MS, is retained regardless of the
    # rate (tail retention). -1 = UNSET: the TRACING_SAMPLE_RATE
    # environment variable fills in (same convention as
    # TELEMETRY_TRACE_AT_STEP), else the 0.01 default.
    TRACING_SAMPLE_RATE: float = -1.0
    # Tail-retention latency threshold in milliseconds: completed
    # requests slower than this are written to the span log even when
    # head sampling skipped them. 0 disables the latency tail.
    TRACING_SLOW_MS: float = 250.0
    # Flight-recorder ring capacity: the last N completed traces
    # (sampled or not) held for the flight_<event>.jsonl dumps on
    # overload bursts, canary rollback, breaker open, and close().
    TRACING_FLIGHT_TRACES: int = 256
    # ---- device-memory ledger (telemetry/memory.py) ----
    # HBM budget in bytes for the ledger's predictive admission checks:
    # an index attach or serving rollover whose predicted footprint
    # would cross it fails typed (MemoryBudgetExceeded) BEFORE
    # allocating, with a forensic oom_ledger.json dump. -1 = UNSET: the
    # HBM_BUDGET_BYTES environment variable fills in (the
    # TELEMETRY_TRACE_AT_STEP convention), else 0 = unlimited.
    HBM_BUDGET_BYTES: int = -1
    # Write a reconciled device-memory ledger snapshot
    # (memory_report.json, rendered by scripts/memory_report.py) when
    # the run's work completes (--memory-report). Live runs can instead
    # `touch <telemetry_dir>/MEM_NOW` for a snapshot with no restart.
    MEMORY_REPORT: bool = False
    # ---- training goodput plane (telemetry/goodput.py) ----
    # Per-device peak FLOP/s used as the MFU denominator (train/mfu =
    # achieved model FLOP/s over peak x device count). -1 = UNSET: the
    # DEVICE_PEAK_FLOPS environment variable fills in (the
    # TELEMETRY_TRACE_AT_STEP convention), else the device-kind table
    # in telemetry/goodput.py (known TPU generations, a CPU floor),
    # else a conservative default. Set it explicitly for hardware the
    # table doesn't know — MFU is only as honest as this denominator.
    DEVICE_PEAK_FLOPS: float = -1.0
    # Step-time anomaly watchdog threshold, in robust standard
    # deviations (MAD-scaled) above the per-shape rolling median. A
    # sustained regression past it fires goodput/anomalies_total, dumps
    # flight_step_anomaly.jsonl, and auto-triggers a profiler capture.
    # 0 disables the watchdog.
    GOODPUT_ANOMALY_SIGMA: float = 6.0
    # Minimum seconds between anomaly-triggered profiler captures, so a
    # persistently degraded run produces one trace, not hundreds.
    GOODPUT_AUTOCAPTURE_COOLDOWN_SECS: float = 600.0
    # ---- resilience (code2vec_tpu/resilience/, ROBUSTNESS.md) ----
    # Divergence guard: check the windowed losses for NaN/Inf at each
    # log-window sync (zero extra host syncs — the losses come to host
    # there anyway); on divergence rewind to the newest checkpoint and
    # skip the offending data window. On by default: with no checkpoint
    # to rewind to it degrades to abort-with-diagnostics, which still
    # beats silently training on NaN.
    DIVERGENCE_GUARD: bool = True
    # Rewinds the guard attempts before declaring the run systematically
    # divergent and aborting with a diagnostic dump.
    MAX_DIVERGENCE_REWINDS: int = 3
    # Hang watchdog deadline in seconds for the hot loop's two blocking
    # waits (next staged batch; log-window device sync). Past it the run
    # dumps all thread stacks and hard-aborts (SIGABRT) so a wedged
    # multi-host collective fails loud. 0 disables. Size it well above
    # the slowest legitimate wait — at least first-step jit compile plus
    # a full eval interval on multi-host meshes (minutes, not seconds).
    HANG_WATCHDOG_SECS: float = 0.0
    # Install SIGTERM/SIGINT handlers for the duration of train(): the
    # fit loop then exits at the next step boundary after one final
    # snapshot save, so spot-VM preemption loses at most the current
    # step. No-op when fit runs outside the main thread.
    HANDLE_PREEMPTION_SIGNALS: bool = True
    # Deterministic fault injection spec (resilience/faults.py):
    # comma-separated <point>@<trigger>=<n>, e.g.
    # 'nan_loss@step=120,sigterm@step=50'. None = UNSET, so the
    # FAULT_INJECT environment variable fills in (runs launched by
    # scripts you can't edit, like the TELEMETRY_TRACE_AT_STEP
    # convention); '' = explicitly disabled, overriding the env var
    # (the clean control arm of a fault drill).
    FAULT_INJECT: Optional[str] = None
    # ---- serving (code2vec_tpu/serving/engine.py, SERVING.md) ----
    # Batch buckets of the serving engine's warm program ladder,
    # comma-separated ascending. Every bucket is rounded up to a multiple
    # of the mesh data axis; a request stream is coalesced into the
    # smallest covering bucket. More buckets = less padding waste per
    # dispatch but more programs to pre-compile at load.
    SERVING_BATCH_BUCKETS: str = '8,64,512,1024'
    # Micro-batcher deadline: how long the dispatcher may hold the OLDEST
    # queued request while coalescing followers into one bucket. The
    # direct latency/throughput trade — 0 dispatches every request
    # immediately (still bucketed + warm, just unbatched).
    SERVING_MAX_DELAY_MS: float = 5.0
    # Worker threads for host-side decode (device fetch, top-k word
    # lookup, attention parsing), so device dispatch never waits on
    # Python.
    SERVING_DECODE_WORKERS: int = 2
    # Output tiers warmed at engine load, comma-separated subset of
    # {topk, attention, full, vectors} (training/trainer.py
    # PREDICT_TIERS). Fewer tiers = proportionally fewer eager compiles.
    SERVING_WARM_TIERS: str = 'topk,attention,full'
    # ---- serving resilience (SERVING.md "Overload & rollover") ----
    # Default per-request SLO deadline in milliseconds (submit's
    # deadline_ms= overrides per request; 0 = no deadline). A deadlined
    # request is shed at admission when the queue's drain estimate
    # already exceeds it, and expired (typed DeadlineExceeded) if it is
    # still queued when the deadline passes — dead work is never
    # dispatched.
    SERVING_DEADLINE_MS: float = 0.0
    # Admission-controlled front-queue bound, in ROWS queued across all
    # tiers. Submissions beyond it are shed with EngineOverloaded
    # instead of queueing unboundedly. 0 = auto (8x the top batch
    # bucket: a few in-flight bucket fills); -1 = unbounded (the
    # pre-resilience behavior).
    SERVING_QUEUE_BOUND: int = 0
    # Canaried checkpoint rollover (ServingEngine.load_params): live
    # micro-batches shadow-scored against BOTH param sets before the
    # swap decision. 0 = swap immediately, no canary.
    SERVING_CANARY_BATCHES: int = 8
    # Minimum top-1 agreement (new vs serving params, over the canaried
    # rows) for the swap; below it the rollover rolls back.
    SERVING_CANARY_AGREEMENT: float = 0.9
    # An armed canary that has not concluded after this many seconds of
    # dispatches rolls back instead of wedging later rollovers — a
    # mixed-tier engine serving only vectors traffic (submit_neighbors)
    # produces no top-1 comparisons, so without a bound the rollover
    # never decides. 0 disables the timeout.
    SERVING_CANARY_TIMEOUT_SECS: float = 300.0
    # Poll the checkpoint store every this-many seconds for a newer
    # retained step and roll it over through the canary
    # (--serve-follow-checkpoints; 0 disables). On a serving mesh the
    # poller runs at the MESH (one coordinated fleet rollover), never
    # per replica.
    SERVE_FOLLOW_CHECKPOINTS_SECS: float = 0.0
    # ---- serving mesh (code2vec_tpu/serving/mesh.py, SERVING.md) ----
    # Engine replicas behind the ONE shared front queue
    # (--mesh-replicas). 1 keeps single-replica behavior behind the
    # mesh API.
    MESH_REPLICAS: int = 1
    # Shared front-queue admission bound in ROWS across all tiers and
    # replicas (--mesh-queue-bound). 0 = auto (replicas x 8 x the top
    # batch bucket — the fleet's absorbable backlog scales with its
    # size); -1 = unbounded.
    MESH_QUEUE_BOUND: int = 0
    # Per-replica in-flight window: dispatched-but-undecoded
    # micro-batches a replica may hold before its puller stops claiming
    # queue work. The mesh's dispatch weighting knob — a canarying
    # replica runs at half this, a half-open breaker probes with 1.
    MESH_MAX_INFLIGHT: int = 2
    # Replica dispatch circuit breaker: consecutive dispatch failures
    # that weight a replica OUT of queue pulling, and how long it stays
    # out before a single half-open probe batch.
    MESH_BREAKER_THRESHOLD: int = 3
    MESH_BREAKER_COOLDOWN_SECS: float = 10.0
    # Replica placement: 'thread' = in-process engine replicas sharing
    # the trainer's warm programs; 'process' = one spawned worker
    # process per replica speaking the framed dispatch wire over a
    # pipe; 'socket' = the same wire over TCP (workers dial the mesh
    # listener — replicas can live on other machines). Worker modes
    # require a checkpointed model (workers restore params from the
    # store). SERVING.md "Serving mesh" / "Multi-host mesh".
    MESH_REPLICA_MODE: str = 'thread'
    # ---- mesh self-healing (SERVING.md "Multi-host mesh") ----
    # Worker heartbeat period in seconds (liveness DISTINCT from
    # dispatch health: a hung or partitioned worker with nothing in
    # flight is invisible to the breaker; its missing beats are not).
    # 0 disables the liveness monitor. Worker modes only.
    MESH_HEARTBEAT_SECS: float = 2.0
    # Consecutive heartbeat intervals a worker may miss before the
    # mesh marks it dead typed, kills it, and redispatches its
    # in-flight batches.
    MESH_HEARTBEAT_MISSES: int = 3
    # Supervised-restart budget: how many restarts one replica may
    # spend inside MESH_RESTART_WINDOW_SECS before it retires
    # PERMANENTLY (a flapping worker must not restart-storm). 0 =
    # never restart (first death retires).
    MESH_RESTART_LIMIT: int = 3
    MESH_RESTART_WINDOW_SECS: float = 300.0
    # First-restart backoff in seconds; doubles per attempt inside the
    # window (capped at 30s).
    MESH_RESTART_BACKOFF_SECS: float = 0.5
    # Bind address of the socket-mode mesh listener. 127.0.0.1 keeps
    # spawned-local workers loopback-only; a routable address lets
    # workers on other machines dial in (scripts/mesh_worker.py dials
    # it and the mesh ADOPTS the dial-in — SERVING.md "Elastic fleet").
    MESH_SOCKET_HOST: str = '127.0.0.1'
    # ---- elastic fleet (SERVING.md "Elastic fleet") ----
    # Per-replica device placement: partition jax.devices() into
    # disjoint slices of this many devices, one slice per replica, so
    # N replicas on one host stop time-sharing the same chips. Each
    # worker builds its own sub-mesh over its slice — the warm ladder,
    # the ragged kernel's shard_map, and the memory ledger all follow
    # the slice geometry. Must be a multiple of MESH_MODEL_AXIS_SIZE.
    # 0 (default) = off: every replica sees the full device set.
    # Worker modes only ('process'/'socket'): thread replicas share
    # the trainer's programs, which are compiled over the parent mesh.
    MESH_DEVICES_PER_REPLICA: int = 0
    # Internal plumbing for placement: comma-separated indices into
    # jax.devices() this process's mesh is built over (create_mesh).
    # The ServingMesh sets it in per-worker config overrides to pin a
    # worker onto its slice; scripts/mesh_worker.py exposes it as
    # --device-indices for orchestrator-spawned workers. '' = all.
    MESH_DEVICE_INDICES: str = ''
    # ---- SLO-driven autoscaler (serving/autoscaler.py, SERVING.md) ----
    # Fleet-size bounds for the autoscaler control loop. MAX 0
    # (default) keeps the autoscaler OFF — the fleet stays the shape
    # it was built with. MAX > 0 arms the loop: scale-up spawns (or
    # requests via hook) up to MAX, scale-down drains via retire()
    # (never a kill) down to MIN.
    AUTOSCALE_MIN_REPLICAS: int = 1
    AUTOSCALE_MAX_REPLICAS: int = 0
    # Control-loop evaluation period in seconds.
    AUTOSCALE_INTERVAL_SECS: float = 5.0
    # Scale-UP trigger: the front queue's drain estimate (queued rows
    # over the fleet's observed service rate) exceeding this many
    # seconds means the current fleet cannot absorb the backlog.
    AUTOSCALE_UP_QUEUE_SECS: float = 2.0
    # Optional second scale-UP trigger: SLO error-budget burn rate
    # (serving/slo.py) above this on BOTH the fast and slow windows.
    # 0 disables the burn leg (queue-drain only).
    AUTOSCALE_UP_BURN: float = 0.0
    # Scale-DOWN trigger: the fleet must look over-provisioned for
    # this many CONSECUTIVE seconds — the drain estimate recomputed
    # with one fewer replica stays under AUTOSCALE_DOWN_UTILIZATION x
    # AUTOSCALE_UP_QUEUE_SECS and no SLO burn alert is pending.
    AUTOSCALE_DOWN_IDLE_SECS: float = 30.0
    AUTOSCALE_DOWN_UTILIZATION: float = 0.5
    # Per-direction cooldowns: seconds after a scale-up (resp. -down)
    # before the NEXT transition in either direction is considered —
    # a new replica needs its warmup before the signals mean anything.
    AUTOSCALE_UP_COOLDOWN_SECS: float = 10.0
    AUTOSCALE_DOWN_COOLDOWN_SECS: float = 60.0
    # Flap guard: more than AUTOSCALE_FLAP_LIMIT direction REVERSALS
    # inside AUTOSCALE_FLAP_WINDOW_SECS freezes the autoscaler (no
    # transitions, autoscale/flap_freezes_total increments) until the
    # window drains — oscillating demand must not thrash the fleet.
    AUTOSCALE_FLAP_WINDOW_SECS: float = 120.0
    AUTOSCALE_FLAP_LIMIT: int = 2
    # ---- fleet observability (OBSERVABILITY.md "Fleet observability") ----
    # Worker telemetry backhaul: -1 = auto (workers enable telemetry
    # iff the parent process had it enabled at spawn, so the fleet
    # export is one decision), 1 = force on, 0 = off. With it on,
    # each heartbeat ships the worker's registry snapshot + memory-
    # ledger rollup for the replica-labeled fleet merge.
    MESH_TELEMETRY_BACKHAUL: int = -1
    # ---- SLO burn-rate monitor (serving/slo.py, SERVING.md) ----
    # Availability SLO target for the serving mesh (e.g. 0.99: sheds,
    # expiries, and failures burn the 1% error budget). 0 disables the
    # availability leg.
    SERVING_SLO_AVAILABILITY: float = 0.0
    # p99 latency SLO target in ms: delivered requests slower than
    # this burn the fixed 1% latency budget. 0 disables the latency
    # leg.
    SERVING_SLO_P99_MS: float = 0.0
    # Multiwindow burn-rate alerting: an alert needs the budget burn
    # rate over BOTH windows above SERVING_SLO_BURN_THRESHOLD (burn
    # 1.0 = spending budget exactly as fast as the SLO allows). The
    # fast window sets detection latency; the slow window keeps blips
    # from paging.
    SERVING_SLO_FAST_WINDOW_SECS: float = 60.0
    SERVING_SLO_SLOW_WINDOW_SECS: float = 600.0
    SERVING_SLO_BURN_THRESHOLD: float = 10.0
    # ---- memoization tier (serving/memo.py, SERVING.md) ----
    # Exact-tier result cache budget in bytes (--memo-cache-bytes):
    # repeated requests (keyed on the canonicalized path-context bag,
    # per tier and per k) are served at mesh admission — before
    # tokenize, the front queue, and the device. 0 disables the tier.
    # Entries are generation-keyed: a fleet rollover invalidates the
    # whole cache atomically.
    MEMO_CACHE_BYTES: int = 0
    # Semantic tier epsilon: serve a single-row neighbors query from
    # the cached result of a prior query whose code vector is within
    # this cosine distance. 0 (default) keeps the tier OFF — it trades
    # exactness for hit rate and must be rolled out gated on the
    # memo/semantic_agreement metric (SERVING.md "Memoization tier").
    MEMO_SEMANTIC_EPSILON: float = 0.0
    # ---- scenario traffic plane (code2vec_tpu/workloads/, WORKLOADS.md) ----
    # Retrieval-augmented naming blend weight (--blend-neighbor-weight):
    # submit_blended scores a candidate label as
    # (1 - w) * softmax_p + w * neighbor_vote over the union of the
    # softmax head's top-k and the attached index's top-k neighbor
    # labels. 0 short-circuits to the plain softmax path (bit-identical
    # scores); 1 ranks purely on retrieval votes. Must lie in [0, 1].
    BLEND_NEIGHBOR_WEIGHT: float = 0.5
    # ---- extractor bridge hardening (serving/extractor_bridge.py) ----
    # Per-invocation extractor timeout (--extractor-timeout): a wedged
    # JVM/parser fails the call (typed ExtractorCrash, stderr attached)
    # instead of hanging the caller forever. 0 disables the bound.
    EXTRACTOR_TIMEOUT_SECS: float = 30.0
    # ExtractorPool retries per call after a crash-class failure
    # (spawn/exit/timeout — clean "no paths" content errors are never
    # retried), with exponential backoff from EXTRACTOR_BACKOFF_SECS.
    EXTRACTOR_RETRIES: int = 2
    EXTRACTOR_BACKOFF_SECS: float = 0.1
    # Persistent extractor pool worker threads (bounded subprocess
    # concurrency for raw-source serving traffic).
    EXTRACTOR_POOL_WORKERS: int = 2
    # Circuit breaker: consecutive crashed calls (each already retried)
    # that trip it open; while open, calls fail fast with
    # ExtractorUnavailable until the cooldown elapses and a half-open
    # probe succeeds.
    EXTRACTOR_BREAKER_THRESHOLD: int = 3
    EXTRACTOR_BREAKER_COOLDOWN_SECS: float = 30.0
    # ---- embedding index (code2vec_tpu/index/, INDEX.md) ----
    # Storage dtype for exported code vectors AND the index store:
    # 'float16' halves disk + device-resident (HBM) footprint; scores
    # always accumulate in float32 on device, and recall@10 is
    # parity-tested across the two (tests/test_index.py).
    VECTORS_DTYPE: str = 'float32'
    # Index tier: 'exact' is the brute-force matmul + sharded top-k
    # (bit-for-rank exact); 'ivf' adds the k-means coarse quantizer +
    # inverted lists for corpora that outgrow exact search.
    INDEX_KIND: str = 'exact'
    # Similarity metric: 'cosine' (store rows normalized at build) or
    # raw 'dot'.
    INDEX_METRIC: str = 'cosine'
    # IVF: inverted lists probed per query. The recall/latency dial —
    # nprobe/C of the corpus is scanned. 0 picks the default (ivf.py).
    INDEX_NPROBE: int = 8
    # IVF: k-means cluster count; 0 = sqrt(N) heuristic.
    INDEX_CLUSTERS: int = 0
    # Quantized IVF tier (index/quant.py): '' serves full-precision
    # rows at INDEX_KIND; 'int8' / 'pq' store compressed codes on
    # device (int8 = 1/2, PQ = ~1/8 the bytes of f16) with an exact
    # top-R re-rank from the mmap store (INDEX.md "Quantized tier").
    INDEX_QUANT: str = ''
    # Quantized tier: exact re-rank depth R — the recall-recovery dial
    # (0 serves the quantized order raw).
    INDEX_RERANK: int = 128
    # PQ subspace count per vector; 0 = dim/4 clamped to a divisor.
    INDEX_PQ_M: int = 0
    # Live inserts: append-segment page size in rows (each segment is
    # a fixed-shape sidecar probed alongside the base lists).
    INDEX_SEGMENT_ROWS: int = 4096
    # Auto-compaction threshold: fold append segments into the base
    # CSR when their count passes this; 0 = manual compaction only.
    INDEX_COMPACT_SEGMENTS: int = 8
    # Neighbors returned per query by the serving/CLI paths, and the k
    # the index warm-compiles at load.
    INDEX_NEIGHBORS_K: int = 10
    # Model backend: 'flax' (nn.Module) or 'jax' (pure-pytree functional).
    # Mirrors the reference's two swappable backends (keras/tensorflow),
    # selected at runtime (reference code2vec.py:7-13).
    DL_FRAMEWORK: str = 'flax'

    # ---- run-mode flags (filled from CLI; reference config.py:72-87) ----
    PREDICT: bool = False
    # Source file the interactive shell (re)reads each turn. The
    # reference hardcodes Input.java (interactive_predict.py:8); making
    # it a flag lets the SAME REPL serve the C# frontend — the extractor
    # dispatches by file extension, so `--input-file Input.cs` predicts
    # over Roslyn-kind paths with a C#-trained model.
    PREDICT_INPUT_PATH: str = 'Input.java'
    MODEL_SAVE_PATH: Optional[str] = None
    MODEL_LOAD_PATH: Optional[str] = None
    TRAIN_DATA_PATH_PREFIX: Optional[str] = None
    TEST_DATA_PATH: str = ''
    RELEASE: bool = False
    EXPORT_CODE_VECTORS: bool = False
    # Offline corpus embedding (serving/bulk.py): stream this .c2v file
    # through the 'vectors'-tier predict program and write one code
    # vector per kept example to <file>.vectors.
    BULK_VECTORS_PATH: Optional[str] = None
    # Index build source (index/service.py): a .c2v corpus (streamed
    # through the vectors tier, no text round-trip), a .vectors text
    # export, or a word2vec text file (--export_vocab_vectors output —
    # the index then serves nearest-method-NAME queries).
    BUILD_INDEX_FROM: Optional[str] = None
    # Where the index directory lives; None derives <source>.vecindex
    # on build and is required for --query-neighbors.
    INDEX_PATH: Optional[str] = None
    # Batch neighbor queries: stream this .c2v file through the vectors
    # tier + index lookup and write <file>.neighbors.jsonl.
    QUERY_NEIGHBORS_PATH: Optional[str] = None
    SAVE_W2V: Optional[str] = None
    SAVE_T2V: Optional[str] = None
    # One-flag parity export of BOTH vocab embedding tables in word2vec
    # text format: <prefix>.tokens.txt + <prefix>.targets.txt
    # (reference --save_w2v/--save_t2v, model_base.py:176-182).
    EXPORT_VOCAB_VECTORS: Optional[str] = None
    VERBOSE_MODE: int = 1
    LOGS_PATH: Optional[str] = None
    USE_TENSORBOARD: bool = False

    # ---- filled by the model lifecycle (reference config.py:130-132) ----
    NUM_TRAIN_EXAMPLES: int = 0
    NUM_TEST_EXAMPLES: int = 0

    _logger: Optional[logging.Logger] = dataclasses.field(
        default=None, repr=False, compare=False)

    # ------------------------------------------------------------------ CLI
    @classmethod
    def arguments_parser(cls) -> ArgumentParser:
        """CLI surface-compatible with the reference (config.py:11-44)."""
        parser = ArgumentParser(prog='code2vec_tpu')
        parser.add_argument('-d', '--data', dest='data_path', required=False,
                            help='path prefix of the preprocessed dataset')
        parser.add_argument('-te', '--test', dest='test_path', metavar='FILE',
                            required=False, default='',
                            help='path to the test/validation .c2v file')
        parser.add_argument('-s', '--save', dest='save_path', metavar='FILE',
                            required=False, help='path to save the model to')
        parser.add_argument('-w2v', '--save_word2v', dest='save_w2v',
                            metavar='FILE', required=False,
                            help='save token embeddings in word2vec format')
        parser.add_argument('-t2v', '--save_target2v', dest='save_t2v',
                            metavar='FILE', required=False,
                            help='save target embeddings in word2vec format')
        parser.add_argument('-l', '--load', dest='load_path', metavar='FILE',
                            required=False, help='path to load the model from')
        parser.add_argument('--export_code_vectors', action='store_true',
                            help='export code vectors for the given examples')
        parser.add_argument('--release', action='store_true',
                            help='strip optimizer state from a loaded model '
                                 'for a smaller artifact')
        parser.add_argument('--predict', action='store_true',
                            help='run the interactive prediction shell')
        parser.add_argument('--input-file', dest='predict_input_path',
                            default=None, metavar='PATH',
                            help='source file the prediction shell reads '
                                 '(.java or .cs; default Input.java)')
        parser.add_argument('-fw', '--framework', dest='dl_framework',
                            choices=['flax', 'jax'], default='flax',
                            help='model backend to use')
        parser.add_argument('-v', '--verbose', dest='verbose_mode', type=int,
                            default=1, help='verbosity in {0,1,2}')
        parser.add_argument('-lp', '--logs-path', dest='logs_path',
                            metavar='FILE', required=False,
                            help='file to mirror logs into')
        parser.add_argument('-tb', '--tensorboard', dest='use_tensorboard',
                            action='store_true',
                            help='write metric summaries during training')
        parser.add_argument('--dtype', dest='compute_dtype',
                            choices=['bfloat16', 'float32'], default=None,
                            help='compute dtype for the forward/backward pass')
        parser.add_argument('--mesh', dest='mesh', default=None,
                            help='mesh shape as DATAxMODEL, e.g. 4x2')
        parser.add_argument('--batch-size', dest='batch_size', type=int,
                            default=None, help='override TRAIN_BATCH_SIZE')
        parser.add_argument('--epochs', dest='epochs', type=int, default=None,
                            help='override NUM_TRAIN_EPOCHS')
        parser.add_argument('--no-data-cache', dest='no_data_cache',
                            action='store_true',
                            help='disable the binary token cache for the '
                                 'train split')
        parser.add_argument('--profile', dest='profile_dir', default=None,
                            metavar='DIR',
                            help='capture a jax.profiler trace of a few '
                                 'train steps into DIR')
        parser.add_argument('--save-every-steps', dest='save_every_steps',
                            type=int, default=None, metavar='N',
                            help='additionally checkpoint every N train '
                                 'steps (async), bounding preemption loss')
        parser.add_argument('--dropout-prng', dest='dropout_prng_impl',
                            choices=['threefry2x32', 'rbg'], default=None,
                            help='PRNG for the dropout mask; rbg uses the '
                                 'hardware generator (PERF.md)')
        parser.add_argument('--adam-mu-dtype', dest='adam_mu_dtype',
                            choices=['float32', 'bfloat16'], default=None,
                            help='storage dtype for Adam\'s first moment')
        parser.add_argument('--adam-nu-dtype', dest='adam_nu_dtype',
                            choices=['float32', 'bfloat16'], default=None,
                            help='storage dtype for Adam\'s second moment '
                                 '(training/adam_dtypes.py, PERF.md)')
        parser.add_argument('--grads-dtype', dest='grads_dtype',
                            choices=['float32', 'bfloat16'], default=None,
                            help='gradient stream dtype; bfloat16 keeps '
                                 'the table-grad scatters and grad tree '
                                 'in bf16 (fp32 master params + fp32 '
                                 'moment math, PERF.md)')
        parser.add_argument('--embed-grad', dest='embed_grad_impl',
                            choices=['dense', 'sorted', 'dedup'],
                            default=None,
                            help='token/path table gradient strategy '
                                 '(ops/embed_grad.py, PERF.md)')
        parser.add_argument('--fused-ce', dest='fused_ce',
                            action='store_true',
                            help='train-time CE via the flash-style fused '
                                 'Pallas kernel: no (B, V) logits in HBM '
                                 '(ops/pallas_ce.py, PERF.md)')
        parser.add_argument('--ragged-fusion', dest='ragged_fusion',
                            action='store_true',
                            help='fuse encode + attention straight off '
                                 'the packed wire: no device-side '
                                 'unpack, no dense (B, C, .) '
                                 'intermediates (ops/pallas_ragged.py, '
                                 'PERF.md; the default since the '
                                 'custom-VJP backward landed)')
        parser.add_argument('--no-ragged-fusion', dest='no_ragged_fusion',
                            action='store_true',
                            help='restore the unpack-then-dense packed '
                                 'path (bit-exact vs the plane wire)')
        parser.add_argument('--ragged-train-kernel',
                            dest='ragged_train_kernel',
                            action='store_true',
                            help='run the packed TRAIN step through the '
                                 'Pallas forward+backward kernel pair '
                                 'on TPU (pending the >=2% flip '
                                 'verdict, scripts/flip_verdict.py)')
        parser.add_argument('--remat-encode', dest='remat_encode',
                            action='store_true',
                            help='recompute encode activations in the '
                                 'backward (jax.checkpoint) — memory '
                                 'headroom for long-context configs')
        parser.add_argument('--wire-format', dest='wire_format',
                            choices=['planes', 'packed'], default=None,
                            help='host->device batch wire format: packed '
                                 'densifies ragged contexts (~3-5x fewer '
                                 'bytes/batch, bit-identical batches after '
                                 'the device-side unpack; data/packed.py)')
        parser.add_argument('--device-prefetch', dest='device_prefetch',
                            type=int, default=None, metavar='N',
                            help='staging-ring depth: batches placed on '
                                 'device ahead of the consuming step '
                                 '(DEVICE_PREFETCH_BATCHES; 0 disables)')
        parser.add_argument('--telemetry', dest='telemetry',
                            action='store_true',
                            help='enable the telemetry layer: step-phase '
                                 'timers, throughput counters, JSONL + '
                                 'Prometheus exporters (OBSERVABILITY.md)')
        parser.add_argument('--telemetry-dir', dest='telemetry_dir',
                            default=None, metavar='DIR',
                            help='directory for telemetry artifacts '
                                 '(default: next to the model artifacts)')
        parser.add_argument('--trace-at-step', dest='trace_at_step',
                            type=int, default=None, metavar='N',
                            help='capture an on-demand jax.profiler trace '
                                 'when global step N is reached (implies '
                                 '--telemetry; live runs can instead touch '
                                 '<telemetry_dir>/TRACE_NOW)')
        parser.add_argument('--device-peak-flops',
                            dest='device_peak_flops',
                            type=float, default=None, metavar='FLOPS',
                            help='per-device peak FLOP/s used as the '
                                 'MFU denominator (train/mfu); unset '
                                 'falls back to the DEVICE_PEAK_FLOPS '
                                 'env var, then a device-kind table '
                                 '(telemetry/goodput.py)')
        parser.add_argument('--memory-report', dest='memory_report',
                            action='store_true',
                            help='write a reconciled device-memory '
                                 'ledger snapshot (memory_report.json) '
                                 'when the run completes; render with '
                                 'scripts/memory_report.py '
                                 '(OBSERVABILITY.md)')
        parser.add_argument('--hbm-budget-bytes', dest='hbm_budget_bytes',
                            type=int, default=None, metavar='BYTES',
                            help='HBM budget for the memory ledger\'s '
                                 'predictive admission checks: index '
                                 'attaches / serving rollovers that '
                                 'would cross it fail typed before '
                                 'allocating (0 = unlimited; the '
                                 'HBM_BUDGET_BYTES env var fills in '
                                 'when unset)')
        parser.add_argument('--fault-inject', dest='fault_inject',
                            default=None, metavar='SPEC',
                            help='deterministic fault injection: '
                                 'comma-separated <point>@<trigger>=<n> '
                                 '(e.g. nan_loss@step=120); the '
                                 'FAULT_INJECT env var fills in when '
                                 'unset (ROBUSTNESS.md)')
        parser.add_argument('--watchdog-secs', dest='watchdog_secs',
                            type=float, default=None, metavar='S',
                            help='hang-watchdog deadline for the hot '
                                 "loop's blocking waits; past it the run "
                                 'dumps thread stacks and aborts '
                                 '(0 disables; ROBUSTNESS.md)')
        parser.add_argument('--max-divergence-rewinds',
                            dest='max_divergence_rewinds', type=int,
                            default=None, metavar='N',
                            help='rewind budget of the divergence guard '
                                 'before the run aborts with diagnostics')
        parser.add_argument('--no-divergence-guard',
                            dest='no_divergence_guard', action='store_true',
                            help='disable the NaN/Inf loss-window guard')
        parser.add_argument('--serving-buckets', dest='serving_buckets',
                            default=None, metavar='B1,B2,...',
                            help='batch buckets of the serving engine\'s '
                                 'warm program ladder '
                                 '(SERVING_BATCH_BUCKETS; SERVING.md)')
        parser.add_argument('--serving-max-delay-ms',
                            dest='serving_max_delay_ms', type=float,
                            default=None, metavar='MS',
                            help='micro-batcher coalescing deadline: max '
                                 'added latency while batching concurrent '
                                 'requests (0 = dispatch immediately)')
        parser.add_argument('--serving-deadline-ms',
                            dest='serving_deadline_ms', type=float,
                            default=None, metavar='MS',
                            help='default per-request SLO deadline: '
                                 'requests are shed at admission when '
                                 'the queue cannot drain in time, and '
                                 'expired instead of dispatched once '
                                 'past it (0 = none; SERVING.md)')
        parser.add_argument('--serving-queue-bound',
                            dest='serving_queue_bound', type=int,
                            default=None, metavar='ROWS',
                            help='admission-controlled front-queue '
                                 'bound in rows; excess submissions '
                                 'are shed with a typed error (0 = '
                                 'auto, -1 = unbounded; SERVING.md)')
        parser.add_argument('--mesh-replicas', dest='mesh_replicas',
                            type=int, default=None, metavar='N',
                            help='serving-engine replicas behind the '
                                 'shared mesh front queue '
                                 '(MESH_REPLICAS; SERVING.md "Serving '
                                 'mesh")')
        parser.add_argument('--mesh-queue-bound', dest='mesh_queue_bound',
                            type=int, default=None, metavar='ROWS',
                            help='shared mesh front-queue admission '
                                 'bound in rows across all replicas '
                                 '(0 = auto: replicas x 8 x top '
                                 'bucket, -1 = unbounded; SERVING.md)')
        parser.add_argument('--memo-cache-bytes', dest='memo_cache_bytes',
                            type=int, default=None, metavar='BYTES',
                            help='exact-tier memoization cache budget '
                                 'in bytes — repeated requests are '
                                 'served before the queue and the '
                                 'device (MEMO_CACHE_BYTES; 0 '
                                 'disables; SERVING.md "Memoization '
                                 'tier")')
        parser.add_argument('--blend-neighbor-weight',
                            dest='blend_neighbor_weight', type=float,
                            default=None, metavar='W',
                            help='retrieval-augmented naming blend '
                                 'weight in [0, 1] — neighbor-vote '
                                 'share in submit_blended scoring '
                                 '(BLEND_NEIGHBOR_WEIGHT; 0 = pure '
                                 'softmax; WORKLOADS.md "Retrieval-'
                                 'augmented naming")')
        parser.add_argument('--mesh-replica-mode',
                            dest='mesh_replica_mode',
                            choices=['thread', 'process', 'socket'],
                            default=None,
                            help='replica placement: in-process engine '
                                 'threads (shared warm programs), one '
                                 'worker process per replica on the '
                                 'framed dispatch wire over a pipe, or '
                                 'the same wire over TCP — workers '
                                 'dial the mesh listener, so replicas '
                                 'can live on other machines '
                                 '(SERVING.md "Multi-host mesh")')
        parser.add_argument('--serve-follow-checkpoints',
                            dest='serve_follow_checkpoints', type=float,
                            default=None, metavar='SECS',
                            help='poll the checkpoint store every SECS '
                                 'for newer steps and roll them into '
                                 'the live serving engine through the '
                                 'canary (zero-downtime rollover; '
                                 'SERVING.md)')
        parser.add_argument('--extractor-timeout',
                            dest='extractor_timeout_secs', type=float,
                            default=None, metavar='SECS',
                            help='per-invocation extractor timeout: a '
                                 'wedged extractor fails the call with '
                                 'its stderr instead of hanging the '
                                 'caller (0 disables; SERVING.md)')
        parser.add_argument('--bulk-vectors', dest='bulk_vectors',
                            default=None, metavar='FILE.c2v',
                            help='stream a whole .c2v corpus through the '
                                 'vectors-only predict program and write '
                                 'FILE.c2v.vectors (offline embedding '
                                 'export; serving/bulk.py)')
        parser.add_argument('--vectors-dtype', dest='vectors_dtype',
                            choices=['float32', 'float16'], default=None,
                            help='storage dtype for exported code vectors '
                                 'and the index store (float16 halves '
                                 'disk + HBM; INDEX.md)')
        parser.add_argument('--export_vocab_vectors',
                            dest='export_vocab_vectors', default=None,
                            metavar='PREFIX',
                            help='write BOTH vocab embedding tables in '
                                 'word2vec text format: PREFIX.tokens.txt '
                                 '+ PREFIX.targets.txt (one-flag parity '
                                 'with --save_w2v/--save_t2v)')
        parser.add_argument('--build-index', dest='build_index',
                            default=None, metavar='SOURCE',
                            help='build a k-NN index from SOURCE: a .c2v '
                                 'corpus (streamed through the vectors '
                                 'tier), a .vectors export, or a word2vec '
                                 'text file (code2vec_tpu/index/, '
                                 'INDEX.md)')
        parser.add_argument('--index-path', dest='index_path',
                            default=None, metavar='DIR',
                            help='index directory (default on build: '
                                 '<source>.vecindex; required for '
                                 '--query-neighbors)')
        parser.add_argument('--query-neighbors', dest='query_neighbors',
                            default=None, metavar='FILE.c2v',
                            help='stream a .c2v file through the vectors '
                                 'tier + index lookup and write '
                                 'FILE.neighbors.jsonl (one query per '
                                 'kept example)')
        parser.add_argument('--index-kind', dest='index_kind',
                            choices=['exact', 'ivf'], default=None,
                            help='index tier: exact brute-force or IVF '
                                 'approximate (INDEX.md)')
        parser.add_argument('--index-metric', dest='index_metric',
                            choices=['cosine', 'dot'], default=None,
                            help='similarity metric of the index store')
        parser.add_argument('--nprobe', dest='index_nprobe', type=int,
                            default=None, metavar='N',
                            help='IVF inverted lists probed per query '
                                 '(the recall/latency dial)')
        parser.add_argument('--index-clusters', dest='index_clusters',
                            type=int, default=None, metavar='C',
                            help='IVF k-means cluster count (0 = sqrt(N))')
        parser.add_argument('--neighbors-k', dest='index_neighbors_k',
                            type=int, default=None, metavar='K',
                            help='neighbors returned per query')
        parser.add_argument('--index-quant', dest='index_quant',
                            choices=['off', 'int8', 'pq'], default=None,
                            help='quantized IVF tier: int8 or product-'
                                 'quantized device codes + exact '
                                 're-rank (INDEX.md "Quantized tier")')
        parser.add_argument('--index-rerank', dest='index_rerank',
                            type=int, default=None, metavar='R',
                            help='exact re-rank depth of the quantized '
                                 'tier (0 = quantized order only)')
        parser.add_argument('--index-pq-m', dest='index_pq_m',
                            type=int, default=None, metavar='M',
                            help='PQ subspaces per vector (0 = dim/4)')
        parser.add_argument('--index-segment-rows',
                            dest='index_segment_rows', type=int,
                            default=None, metavar='N',
                            help='append-segment page size (rows) for '
                                 'live index inserts')
        parser.add_argument('--index-compact-segments',
                            dest='index_compact_segments', type=int,
                            default=None, metavar='S',
                            help='auto-compact after S append segments '
                                 '(0 = manual compaction only)')
        parser.add_argument('--opt-state-sharding',
                            dest='opt_state_sharding',
                            choices=['mirror', 'zero'], default=None,
                            help="Adam moment layout: 'mirror' copies the "
                                 "param sharding (replicated along data), "
                                 "'zero' shards moments over the whole "
                                 'mesh (ZeRO-1-style)')
        return parser

    def load_from_args(self, args=None) -> 'Config':
        parsed = self.arguments_parser().parse_args(args)
        self.PREDICT = parsed.predict
        if parsed.predict_input_path:
            self.PREDICT_INPUT_PATH = parsed.predict_input_path
        self.MODEL_SAVE_PATH = parsed.save_path
        self.MODEL_LOAD_PATH = parsed.load_path
        self.TRAIN_DATA_PATH_PREFIX = parsed.data_path
        self.TEST_DATA_PATH = parsed.test_path or ''
        self.RELEASE = parsed.release
        self.EXPORT_CODE_VECTORS = parsed.export_code_vectors
        self.SAVE_W2V = parsed.save_w2v
        self.SAVE_T2V = parsed.save_t2v
        self.VERBOSE_MODE = parsed.verbose_mode
        self.LOGS_PATH = parsed.logs_path
        self.DL_FRAMEWORK = parsed.dl_framework or 'flax'
        self.USE_TENSORBOARD = parsed.use_tensorboard
        if parsed.compute_dtype:
            self.COMPUTE_DTYPE = parsed.compute_dtype
        if parsed.mesh:
            try:
                data_sz, model_sz = parsed.mesh.lower().split('x')
                self.MESH_DATA_AXIS_SIZE = int(data_sz)
                self.MESH_MODEL_AXIS_SIZE = int(model_sz)
            except ValueError:
                raise ValueError(
                    "--mesh must look like DATAxMODEL (e.g. '4x2'), got %r"
                    % parsed.mesh)
        if parsed.batch_size:
            self.TRAIN_BATCH_SIZE = parsed.batch_size
            self.TEST_BATCH_SIZE = parsed.batch_size
        if parsed.epochs:
            self.NUM_TRAIN_EPOCHS = parsed.epochs
        if parsed.no_data_cache:
            self.TRAIN_DATA_CACHE = False
        if parsed.profile_dir:
            self.PROFILE_DIR = parsed.profile_dir
        if parsed.save_every_steps is not None:
            self.SAVE_EVERY_N_STEPS = parsed.save_every_steps
        if parsed.dropout_prng_impl:
            self.DROPOUT_PRNG_IMPL = parsed.dropout_prng_impl
        if parsed.adam_mu_dtype:
            self.ADAM_MU_DTYPE = parsed.adam_mu_dtype
        if parsed.adam_nu_dtype:
            self.ADAM_NU_DTYPE = parsed.adam_nu_dtype
        if parsed.grads_dtype:
            self.GRADS_DTYPE = parsed.grads_dtype
        if parsed.embed_grad_impl:
            self.EMBED_GRAD_IMPL = parsed.embed_grad_impl
        if parsed.fused_ce:
            self.USE_PALLAS_FUSED_CE = True
        if parsed.ragged_fusion:
            self.USE_PALLAS_RAGGED_FUSION = True
        if parsed.no_ragged_fusion:
            self.USE_PALLAS_RAGGED_FUSION = False
        if parsed.ragged_train_kernel:
            self.RAGGED_TRAIN_KERNEL = True
        if parsed.remat_encode:
            self.REMAT_ENCODE = True
        if parsed.opt_state_sharding:
            self.OPTIMIZER_STATE_SHARDING = parsed.opt_state_sharding
        if parsed.wire_format:
            self.BATCH_WIRE_FORMAT = parsed.wire_format
        if parsed.device_prefetch is not None:
            self.DEVICE_PREFETCH_BATCHES = parsed.device_prefetch
        if parsed.telemetry:
            self.TELEMETRY = True
        if parsed.telemetry_dir:
            self.TELEMETRY_DIR = parsed.telemetry_dir
        if parsed.trace_at_step is not None:
            self.TELEMETRY_TRACE_AT_STEP = parsed.trace_at_step
            self.TELEMETRY = True  # a trace request implies the layer
        elif self.TELEMETRY_TRACE_AT_STEP < 0:
            # the env var is for runs launched by scripts you can't edit
            # (OBSERVABILITY.md) — so it must imply the telemetry layer
            # exactly like the flag does, or it is silently inert
            try:
                env_step = int(os.environ.get('TELEMETRY_TRACE_AT_STEP',
                                              '-1'))
            except ValueError:
                env_step = -1
            if env_step >= 0:
                self.TELEMETRY_TRACE_AT_STEP = env_step
                self.TELEMETRY = True
        if parsed.device_peak_flops is not None:
            self.DEVICE_PEAK_FLOPS = parsed.device_peak_flops
        if parsed.memory_report:
            self.MEMORY_REPORT = True
        if parsed.hbm_budget_bytes is not None:
            self.HBM_BUDGET_BYTES = parsed.hbm_budget_bytes
        if parsed.fault_inject is not None:
            # an explicit --fault-inject '' DISABLES injection even when
            # the env var is set (the control arm of a drill)
            self.FAULT_INJECT = parsed.fault_inject
        elif self.FAULT_INJECT is None:
            # env-var fallback, same rationale as TELEMETRY_TRACE_AT_STEP:
            # fault drills on runs whose launch scripts you can't edit
            self.FAULT_INJECT = os.environ.get('FAULT_INJECT')
        if parsed.watchdog_secs is not None:
            self.HANG_WATCHDOG_SECS = parsed.watchdog_secs
        if parsed.max_divergence_rewinds is not None:
            self.MAX_DIVERGENCE_REWINDS = parsed.max_divergence_rewinds
        if parsed.no_divergence_guard:
            self.DIVERGENCE_GUARD = False
        if parsed.serving_buckets:
            self.SERVING_BATCH_BUCKETS = parsed.serving_buckets
        if parsed.serving_max_delay_ms is not None:
            self.SERVING_MAX_DELAY_MS = parsed.serving_max_delay_ms
        if parsed.serving_deadline_ms is not None:
            self.SERVING_DEADLINE_MS = parsed.serving_deadline_ms
        if parsed.serving_queue_bound is not None:
            self.SERVING_QUEUE_BOUND = parsed.serving_queue_bound
        if parsed.mesh_replicas is not None:
            self.MESH_REPLICAS = parsed.mesh_replicas
        if parsed.mesh_queue_bound is not None:
            self.MESH_QUEUE_BOUND = parsed.mesh_queue_bound
        if parsed.memo_cache_bytes is not None:
            self.MEMO_CACHE_BYTES = parsed.memo_cache_bytes
        if parsed.blend_neighbor_weight is not None:
            self.BLEND_NEIGHBOR_WEIGHT = parsed.blend_neighbor_weight
        if parsed.mesh_replica_mode:
            self.MESH_REPLICA_MODE = parsed.mesh_replica_mode
        if parsed.serve_follow_checkpoints is not None:
            self.SERVE_FOLLOW_CHECKPOINTS_SECS = \
                parsed.serve_follow_checkpoints
        if parsed.extractor_timeout_secs is not None:
            self.EXTRACTOR_TIMEOUT_SECS = parsed.extractor_timeout_secs
        if parsed.bulk_vectors:
            self.BULK_VECTORS_PATH = parsed.bulk_vectors
        if parsed.vectors_dtype:
            self.VECTORS_DTYPE = parsed.vectors_dtype
        if parsed.export_vocab_vectors:
            self.EXPORT_VOCAB_VECTORS = parsed.export_vocab_vectors
        if parsed.build_index:
            self.BUILD_INDEX_FROM = parsed.build_index
        if parsed.index_path:
            self.INDEX_PATH = parsed.index_path
        if parsed.query_neighbors:
            self.QUERY_NEIGHBORS_PATH = parsed.query_neighbors
        if parsed.index_kind:
            self.INDEX_KIND = parsed.index_kind
        if parsed.index_metric:
            self.INDEX_METRIC = parsed.index_metric
        if parsed.index_nprobe is not None:
            self.INDEX_NPROBE = parsed.index_nprobe
        if parsed.index_clusters is not None:
            self.INDEX_CLUSTERS = parsed.index_clusters
        if parsed.index_neighbors_k is not None:
            self.INDEX_NEIGHBORS_K = parsed.index_neighbors_k
        if parsed.index_quant is not None:
            self.INDEX_QUANT = ('' if parsed.index_quant == 'off'
                                else parsed.index_quant)
        if parsed.index_rerank is not None:
            self.INDEX_RERANK = parsed.index_rerank
        if parsed.index_pq_m is not None:
            self.INDEX_PQ_M = parsed.index_pq_m
        if parsed.index_segment_rows is not None:
            self.INDEX_SEGMENT_ROWS = parsed.index_segment_rows
        if parsed.index_compact_segments is not None:
            self.INDEX_COMPACT_SEGMENTS = parsed.index_compact_segments
        return self

    # ------------------------------------------------------- derived props
    @property
    def context_vector_size(self) -> int:
        """Concatenation of source-token, path and target-token embeddings
        (reference config.py:143-147)."""
        return self.PATH_EMBEDDINGS_SIZE + 2 * self.TOKEN_EMBEDDINGS_SIZE

    @property
    def is_training(self) -> bool:
        return bool(self.TRAIN_DATA_PATH_PREFIX)

    @property
    def is_loading(self) -> bool:
        return bool(self.MODEL_LOAD_PATH)

    @property
    def is_saving(self) -> bool:
        return bool(self.MODEL_SAVE_PATH)

    @property
    def is_testing(self) -> bool:
        return bool(self.TEST_DATA_PATH)

    @property
    def train_steps_per_epoch(self) -> int:
        return (math.ceil(self.NUM_TRAIN_EXAMPLES / self.TRAIN_BATCH_SIZE)
                if self.TRAIN_BATCH_SIZE else 0)

    @property
    def test_steps(self) -> int:
        return (math.ceil(self.NUM_TEST_EXAMPLES / self.TEST_BATCH_SIZE)
                if self.TEST_BATCH_SIZE else 0)

    def data_path(self, is_evaluating: bool = False) -> Optional[str]:
        return self.TEST_DATA_PATH if is_evaluating else self.train_data_path

    def batch_size(self, is_evaluating: bool = False) -> int:
        return self.TEST_BATCH_SIZE if is_evaluating else self.TRAIN_BATCH_SIZE

    @property
    def serving_batch_buckets(self) -> Tuple[int, ...]:
        """Parsed, sorted SERVING_BATCH_BUCKETS (serving/engine.py rounds
        them up to the mesh data axis at engine construction)."""
        try:
            buckets = tuple(sorted(
                int(part) for part in
                str(self.SERVING_BATCH_BUCKETS).split(',') if part.strip()))
        except ValueError:
            raise ValueError(
                'SERVING_BATCH_BUCKETS must be comma-separated ints, got '
                '%r' % self.SERVING_BATCH_BUCKETS)
        if not buckets or any(bucket < 1 for bucket in buckets):
            raise ValueError(
                'SERVING_BATCH_BUCKETS needs at least one bucket >= 1, '
                'got %r' % self.SERVING_BATCH_BUCKETS)
        return buckets

    @property
    def serving_warm_tiers(self) -> Tuple[str, ...]:
        """Parsed SERVING_WARM_TIERS (validated against PREDICT_TIERS in
        verify() and at engine construction)."""
        return tuple(part.strip()
                     for part in str(self.SERVING_WARM_TIERS).split(',')
                     if part.strip())

    @property
    def tracing_sample_rate(self) -> float:
        """Resolved head-sampling rate for per-request serving traces:
        the TRACING_SAMPLE_RATE field when set (>= 0), else the
        environment variable of the same name, else 0.01 — clamped to
        [0, 1]."""
        rate = self.TRACING_SAMPLE_RATE
        if rate < 0:
            try:
                rate = float(os.environ.get('TRACING_SAMPLE_RATE', 0.01))
            except ValueError:
                raise ValueError(
                    'TRACING_SAMPLE_RATE env var must be a float, got %r'
                    % os.environ.get('TRACING_SAMPLE_RATE'))
        return max(0.0, min(1.0, rate))

    def wire_format_for(self, process_count: int) -> str:
        """The EFFECTIVE batch wire format for a run of ``process_count``
        hosts. Multi-host runs always use 'planes': the packed format's
        per-shard capacity is data-dependent per batch, and processes
        cannot agree on one global shape without a host round-trip."""
        if process_count > 1:
            return 'planes'
        return self.BATCH_WIRE_FORMAT

    # -------------------------------------- file-naming contract (parity)
    @property
    def train_data_path(self) -> Optional[str]:
        if not self.is_training:
            return None
        return '{}.train.c2v'.format(self.TRAIN_DATA_PATH_PREFIX)

    @property
    def word_freq_dict_path(self) -> Optional[str]:
        if not self.is_training:
            return None
        return '{}.dict.c2v'.format(self.TRAIN_DATA_PATH_PREFIX)

    @classmethod
    def get_vocabularies_path_from_model_path(cls, model_file_path: str) -> str:
        """``dictionaries.bin`` sidecar next to the model
        (reference config.py:191-194)."""
        return os.path.join(os.path.dirname(model_file_path), 'dictionaries.bin')

    @classmethod
    def get_entire_model_path(cls, model_path: str) -> str:
        return model_path + '__entire-model'

    @classmethod
    def get_model_weights_path(cls, model_path: str) -> str:
        return model_path + '__only-weights'

    @classmethod
    def get_step_snapshots_path(cls, model_path: str) -> str:
        """Step-interval preemption snapshots (SAVE_EVERY_N_STEPS)."""
        return model_path + '__step-snapshots'

    @property
    def model_load_dir(self) -> str:
        return os.path.dirname(self.MODEL_LOAD_PATH)

    @property
    def entire_model_load_path(self) -> Optional[str]:
        return self.get_entire_model_path(self.MODEL_LOAD_PATH) if self.is_loading else None

    @property
    def model_weights_load_path(self) -> Optional[str]:
        return self.get_model_weights_path(self.MODEL_LOAD_PATH) if self.is_loading else None

    @property
    def entire_model_save_path(self) -> Optional[str]:
        return self.get_entire_model_path(self.MODEL_SAVE_PATH) if self.is_saving else None

    @property
    def model_weights_save_path(self) -> Optional[str]:
        return self.get_model_weights_path(self.MODEL_SAVE_PATH) if self.is_saving else None

    # ------------------------------------------------------------- verify
    def verify(self) -> None:
        """Startup sanity checks (reference config.py:232-239)."""
        if not self.is_training and not self.is_loading:
            raise ValueError('Must train or load a model.')
        if self.is_loading and not os.path.isdir(self.model_load_dir):
            raise ValueError('Model load dir `{}` does not exist.'.format(
                self.model_load_dir))
        if self.DL_FRAMEWORK not in {'flax', 'jax'}:
            raise ValueError("config.DL_FRAMEWORK must be in {'flax', 'jax'}.")
        if self.COMPUTE_DTYPE not in {'bfloat16', 'float32'}:
            raise ValueError("config.COMPUTE_DTYPE must be in "
                             "{'bfloat16', 'float32'}.")
        if self.DROPOUT_PRNG_IMPL not in {'threefry2x32', 'rbg'}:
            raise ValueError("config.DROPOUT_PRNG_IMPL must be in "
                             "{'threefry2x32', 'rbg'}.")
        if self.EMBED_GRAD_IMPL not in {'dense', 'sorted', 'dedup'}:
            raise ValueError("config.EMBED_GRAD_IMPL must be in "
                             "{'dense', 'sorted', 'dedup'}.")
        if self.ADAM_MU_DTYPE not in {'float32', 'bfloat16'}:
            raise ValueError("config.ADAM_MU_DTYPE must be in "
                             "{'float32', 'bfloat16'}.")
        if self.ADAM_NU_DTYPE not in {'float32', 'bfloat16'}:
            raise ValueError("config.ADAM_NU_DTYPE must be in "
                             "{'float32', 'bfloat16'}.")
        if self.GRADS_DTYPE not in {'float32', 'bfloat16'}:
            raise ValueError("config.GRADS_DTYPE must be in "
                             "{'float32', 'bfloat16'}.")
        if self.GRADS_DTYPE == 'bfloat16' and self.LAZY_EMBEDDING_ADAM:
            raise ValueError(
                'GRADS_DTYPE=\'bfloat16\' requires the dense optax path: '
                'LAZY_EMBEDDING_ADAM\'s sparse-row update consumes raw '
                'fp32 gradients.')
        if self.GRADS_DTYPE == 'bfloat16' \
                and self.COMPUTE_DTYPE != 'bfloat16':
            # The knob works by differentiating wrt the PRE-CAST bf16
            # params; that is only value-preserving for the forward when
            # the model would cast params to bf16 anyway. Under fp32
            # compute it would silently bf16-round every weight in the
            # training forward (and diverge from the uncast eval forward).
            raise ValueError(
                "GRADS_DTYPE='bfloat16' requires "
                "COMPUTE_DTYPE='bfloat16' (the bf16 pre-cast must round "
                "exactly where the compute cast already would).")
        # LAZY_EMBEDDING_ADAM keeps fp32 moments (the sparse-row update
        # does not implement reduced-precision mu), so ADAM_MU_DTYPE is
        # simply not consumed on that path. Now that 'bfloat16' is the
        # DEFAULT, raising here would break lazy users who never touched
        # the knob — the trainer logs the ignored-knob warning instead.
        if self.TELEMETRY_FLUSH_EVERY_STEPS < 1:
            raise ValueError(
                'config.TELEMETRY_FLUSH_EVERY_STEPS must be >= 1.')
        if self.TELEMETRY_TRACE_NUM_STEPS < 1:
            raise ValueError(
                'config.TELEMETRY_TRACE_NUM_STEPS must be >= 1.')
        if self.TRACING_SAMPLE_RATE > 1.0:
            raise ValueError('config.TRACING_SAMPLE_RATE must be in '
                             '[0, 1] (or < 0 for env/default fallback).')
        if self.TRACING_SLOW_MS < 0:
            raise ValueError('config.TRACING_SLOW_MS must be >= 0 '
                             '(0 disables latency tail retention).')
        if self.TRACING_FLIGHT_TRACES < 1:
            raise ValueError('config.TRACING_FLIGHT_TRACES must be >= 1.')
        if self.HBM_BUDGET_BYTES < -1:
            raise ValueError('config.HBM_BUDGET_BYTES must be >= -1 '
                             '(-1 = env fallback, 0 = unlimited).')
        if self.DEVICE_PEAK_FLOPS != -1.0 and self.DEVICE_PEAK_FLOPS <= 0:
            raise ValueError('config.DEVICE_PEAK_FLOPS must be > 0 '
                             '(-1 = env/device-table fallback).')
        if self.GOODPUT_ANOMALY_SIGMA < 0:
            raise ValueError('config.GOODPUT_ANOMALY_SIGMA must be >= 0 '
                             '(0 disables the anomaly watchdog).')
        if self.GOODPUT_AUTOCAPTURE_COOLDOWN_SECS < 0:
            raise ValueError(
                'config.GOODPUT_AUTOCAPTURE_COOLDOWN_SECS must be >= 0.')
        if self.BATCH_WIRE_FORMAT not in {'planes', 'packed'}:
            raise ValueError("config.BATCH_WIRE_FORMAT must be in "
                             "{'planes', 'packed'}.")
        if self.OPTIMIZER_STATE_SHARDING not in {'mirror', 'zero'}:
            raise ValueError("config.OPTIMIZER_STATE_SHARDING must be in "
                             "{'mirror', 'zero'}.")
        if self.LAZY_EMBEDDING_ADAM and \
                self.OPTIMIZER_STATE_SHARDING != 'mirror':
            raise ValueError(
                "config.OPTIMIZER_STATE_SHARDING='zero' shards the dense "
                'optax Adam moment tree; LAZY_EMBEDDING_ADAM keeps its own '
                'state layout.')
        if self.MAX_DIVERGENCE_REWINDS < 0:
            raise ValueError('config.MAX_DIVERGENCE_REWINDS must be >= 0.')
        if self.HANG_WATCHDOG_SECS < 0:
            raise ValueError('config.HANG_WATCHDOG_SECS must be >= 0 '
                             '(0 disables the watchdog).')
        self.serving_batch_buckets  # raises on malformed bucket specs
        if self.SERVING_MAX_DELAY_MS < 0:
            raise ValueError('config.SERVING_MAX_DELAY_MS must be >= 0.')
        if self.SERVING_DECODE_WORKERS < 1:
            raise ValueError('config.SERVING_DECODE_WORKERS must be >= 1.')
        if self.SERVING_DEADLINE_MS < 0:
            raise ValueError('config.SERVING_DEADLINE_MS must be >= 0 '
                             '(0 = no deadline).')
        if self.SERVING_QUEUE_BOUND < -1:
            raise ValueError('config.SERVING_QUEUE_BOUND must be >= -1 '
                             '(0 = auto, -1 = unbounded).')
        if self.MESH_REPLICAS < 1:
            raise ValueError('config.MESH_REPLICAS must be >= 1.')
        if self.MESH_QUEUE_BOUND < -1:
            raise ValueError('config.MESH_QUEUE_BOUND must be >= -1 '
                             '(0 = auto, -1 = unbounded).')
        if self.MEMO_CACHE_BYTES < 0:
            raise ValueError('config.MEMO_CACHE_BYTES must be >= 0 '
                             '(0 disables the memoization tier).')
        if not 0.0 <= self.MEMO_SEMANTIC_EPSILON <= 1.0:
            raise ValueError('config.MEMO_SEMANTIC_EPSILON must be in '
                             '[0, 1] (0 keeps the semantic tier off).')
        if not 0.0 <= self.BLEND_NEIGHBOR_WEIGHT <= 1.0:
            raise ValueError('config.BLEND_NEIGHBOR_WEIGHT must be in '
                             '[0, 1] (0 = pure softmax ranking).')
        if self.MESH_MAX_INFLIGHT < 1:
            raise ValueError('config.MESH_MAX_INFLIGHT must be >= 1.')
        if self.MESH_BREAKER_THRESHOLD < 1:
            raise ValueError('config.MESH_BREAKER_THRESHOLD must be '
                             '>= 1.')
        if self.MESH_BREAKER_COOLDOWN_SECS < 0:
            raise ValueError('config.MESH_BREAKER_COOLDOWN_SECS must '
                             'be >= 0.')
        if self.MESH_REPLICA_MODE not in ('thread', 'process', 'socket'):
            raise ValueError("config.MESH_REPLICA_MODE must be 'thread', "
                             "'process' or 'socket'.")
        if self.MESH_HEARTBEAT_SECS < 0:
            raise ValueError('config.MESH_HEARTBEAT_SECS must be >= 0 '
                             '(0 disables the liveness monitor).')
        if self.MESH_HEARTBEAT_MISSES < 1:
            raise ValueError('config.MESH_HEARTBEAT_MISSES must be '
                             '>= 1.')
        if self.MESH_RESTART_LIMIT < 0:
            raise ValueError('config.MESH_RESTART_LIMIT must be >= 0 '
                             '(0 = never restart).')
        if self.MESH_RESTART_WINDOW_SECS <= 0:
            raise ValueError('config.MESH_RESTART_WINDOW_SECS must be '
                             '> 0 (the restart budget is window-'
                             'scoped).')
        if self.MESH_RESTART_BACKOFF_SECS < 0:
            raise ValueError('config.MESH_RESTART_BACKOFF_SECS must be '
                             '>= 0.')
        if self.MESH_TELEMETRY_BACKHAUL not in (-1, 0, 1):
            raise ValueError('config.MESH_TELEMETRY_BACKHAUL must be '
                             '-1 (auto), 0 (off) or 1 (on).')
        if self.MESH_DEVICES_PER_REPLICA < 0:
            raise ValueError('config.MESH_DEVICES_PER_REPLICA must be '
                             '>= 0 (0 = replicas share the full '
                             'device set).')
        if self.MESH_DEVICES_PER_REPLICA > 0 and \
                self.MESH_DEVICES_PER_REPLICA % max(
                    1, self.MESH_MODEL_AXIS_SIZE) != 0:
            raise ValueError('config.MESH_DEVICES_PER_REPLICA must be a '
                             'multiple of MESH_MODEL_AXIS_SIZE (each '
                             'slice builds its own (data, model) '
                             'sub-mesh).')
        if self.AUTOSCALE_MIN_REPLICAS < 1:
            raise ValueError('config.AUTOSCALE_MIN_REPLICAS must be '
                             '>= 1.')
        if self.AUTOSCALE_MAX_REPLICAS < 0:
            raise ValueError('config.AUTOSCALE_MAX_REPLICAS must be >= 0 '
                             '(0 keeps the autoscaler off).')
        if self.AUTOSCALE_MAX_REPLICAS > 0 and \
                self.AUTOSCALE_MAX_REPLICAS < self.AUTOSCALE_MIN_REPLICAS:
            raise ValueError('config.AUTOSCALE_MAX_REPLICAS must be >= '
                             'AUTOSCALE_MIN_REPLICAS when armed.')
        if self.AUTOSCALE_INTERVAL_SECS <= 0:
            raise ValueError('config.AUTOSCALE_INTERVAL_SECS must be '
                             '> 0.')
        if self.AUTOSCALE_UP_QUEUE_SECS <= 0:
            raise ValueError('config.AUTOSCALE_UP_QUEUE_SECS must be '
                             '> 0.')
        if self.AUTOSCALE_UP_BURN < 0:
            raise ValueError('config.AUTOSCALE_UP_BURN must be >= 0 '
                             '(0 disables the burn leg).')
        if self.AUTOSCALE_DOWN_IDLE_SECS < 0:
            raise ValueError('config.AUTOSCALE_DOWN_IDLE_SECS must be '
                             '>= 0.')
        if not 0.0 < self.AUTOSCALE_DOWN_UTILIZATION <= 1.0:
            raise ValueError('config.AUTOSCALE_DOWN_UTILIZATION must be '
                             'in (0, 1].')
        if self.AUTOSCALE_UP_COOLDOWN_SECS < 0 or \
                self.AUTOSCALE_DOWN_COOLDOWN_SECS < 0:
            raise ValueError('config.AUTOSCALE_*_COOLDOWN_SECS must be '
                             '>= 0.')
        if self.AUTOSCALE_FLAP_WINDOW_SECS <= 0:
            raise ValueError('config.AUTOSCALE_FLAP_WINDOW_SECS must be '
                             '> 0.')
        if self.AUTOSCALE_FLAP_LIMIT < 1:
            raise ValueError('config.AUTOSCALE_FLAP_LIMIT must be >= 1.')
        if not 0.0 <= self.SERVING_SLO_AVAILABILITY < 1.0:
            raise ValueError('config.SERVING_SLO_AVAILABILITY must be '
                             'in [0, 1) (0 disables; 1.0 would leave '
                             'no error budget to burn).')
        if self.SERVING_SLO_P99_MS < 0:
            raise ValueError('config.SERVING_SLO_P99_MS must be >= 0 '
                             '(0 disables the latency leg).')
        if self.SERVING_SLO_FAST_WINDOW_SECS <= 0 or \
                self.SERVING_SLO_SLOW_WINDOW_SECS <= 0:
            raise ValueError('config.SERVING_SLO_*_WINDOW_SECS must be '
                             '> 0.')
        if self.SERVING_SLO_FAST_WINDOW_SECS > \
                self.SERVING_SLO_SLOW_WINDOW_SECS:
            raise ValueError('config.SERVING_SLO_FAST_WINDOW_SECS must '
                             'not exceed SERVING_SLO_SLOW_WINDOW_SECS '
                             '(the fast window detects, the slow one '
                             'confirms).')
        if self.SERVING_SLO_BURN_THRESHOLD <= 0:
            raise ValueError('config.SERVING_SLO_BURN_THRESHOLD must '
                             'be > 0.')
        if self.SERVING_CANARY_BATCHES < 0:
            raise ValueError('config.SERVING_CANARY_BATCHES must be >= 0 '
                             '(0 = swap without canary).')
        if not 0.0 <= self.SERVING_CANARY_AGREEMENT <= 1.0:
            raise ValueError('config.SERVING_CANARY_AGREEMENT must be in '
                             '[0, 1].')
        if self.SERVING_CANARY_TIMEOUT_SECS < 0:
            raise ValueError('config.SERVING_CANARY_TIMEOUT_SECS must be '
                             '>= 0 (0 disables the canary timeout).')
        if self.SERVE_FOLLOW_CHECKPOINTS_SECS < 0:
            raise ValueError('config.SERVE_FOLLOW_CHECKPOINTS_SECS must '
                             'be >= 0 (0 disables).')
        if self.EXTRACTOR_TIMEOUT_SECS < 0:
            raise ValueError('config.EXTRACTOR_TIMEOUT_SECS must be >= 0 '
                             '(0 disables the bound).')
        if self.EXTRACTOR_RETRIES < 0:
            raise ValueError('config.EXTRACTOR_RETRIES must be >= 0.')
        if self.EXTRACTOR_BACKOFF_SECS < 0:
            raise ValueError('config.EXTRACTOR_BACKOFF_SECS must be >= 0.')
        if self.EXTRACTOR_POOL_WORKERS < 1:
            raise ValueError('config.EXTRACTOR_POOL_WORKERS must be >= 1.')
        if self.EXTRACTOR_BREAKER_THRESHOLD < 1:
            raise ValueError('config.EXTRACTOR_BREAKER_THRESHOLD must be '
                             '>= 1.')
        if self.EXTRACTOR_BREAKER_COOLDOWN_SECS < 0:
            raise ValueError('config.EXTRACTOR_BREAKER_COOLDOWN_SECS must '
                             'be >= 0.')
        valid_tiers = {'topk', 'attention', 'full', 'vectors'}
        tiers = self.serving_warm_tiers
        if not tiers or not set(tiers) <= valid_tiers:
            raise ValueError(
                'config.SERVING_WARM_TIERS must be a non-empty '
                'comma-separated subset of %s, got %r'
                % (sorted(valid_tiers), self.SERVING_WARM_TIERS))
        if self.VECTORS_DTYPE not in {'float32', 'float16'}:
            raise ValueError("config.VECTORS_DTYPE must be in "
                             "{'float32', 'float16'}.")
        if self.INDEX_KIND not in {'exact', 'ivf'}:
            raise ValueError("config.INDEX_KIND must be in "
                             "{'exact', 'ivf'}.")
        if self.INDEX_METRIC not in {'cosine', 'dot'}:
            raise ValueError("config.INDEX_METRIC must be in "
                             "{'cosine', 'dot'}.")
        if self.INDEX_NPROBE < 0:
            raise ValueError('config.INDEX_NPROBE must be >= 0 '
                             '(0 = default).')
        if self.INDEX_CLUSTERS < 0:
            raise ValueError('config.INDEX_CLUSTERS must be >= 0 '
                             '(0 = sqrt(N)).')
        if self.INDEX_NEIGHBORS_K < 1:
            raise ValueError('config.INDEX_NEIGHBORS_K must be >= 1.')
        if self.INDEX_QUANT not in {'', 'int8', 'pq'}:
            raise ValueError("config.INDEX_QUANT must be in "
                             "{'', 'int8', 'pq'} ('' = full-precision "
                             "tier).")
        if self.INDEX_RERANK < 0:
            raise ValueError('config.INDEX_RERANK must be >= 0 '
                             '(0 disables the exact re-rank).')
        if self.INDEX_PQ_M < 0:
            raise ValueError('config.INDEX_PQ_M must be >= 0 '
                             '(0 = dim/4).')
        if self.INDEX_SEGMENT_ROWS < 1:
            raise ValueError('config.INDEX_SEGMENT_ROWS must be >= 1.')
        if self.INDEX_COMPACT_SEGMENTS < 0:
            raise ValueError('config.INDEX_COMPACT_SEGMENTS must be '
                             '>= 0 (0 = manual compaction only).')
        if self.QUERY_NEIGHBORS_PATH and not (self.INDEX_PATH
                                              or self.BUILD_INDEX_FROM):
            raise ValueError(
                '--query-neighbors needs an index: pass --index-path '
                'DIR (an existing index) or --build-index SOURCE '
                '(build one first).')
        if self.FAULT_INJECT:
            # a typo'd injection spec must fail at startup, not silently
            # inject nothing (parse_spec raises ValueError with the
            # offending entry and the known fault points)
            from code2vec_tpu.resilience.faults import parse_spec
            parse_spec(self.FAULT_INJECT)

    def __iter__(self) -> Iterator[Tuple[str, Any]]:
        for field in dataclasses.fields(self):
            if field.name.startswith('_'):
                continue
            yield field.name, getattr(self, field.name)

    # ------------------------------------------------------------ logging
    def get_logger(self) -> logging.Logger:
        if self._logger is None:
            logger = logging.getLogger('code2vec_tpu')
            logger.setLevel(logging.INFO)
            logger.handlers = []
            logger.propagate = False
            formatter = logging.Formatter('%(asctime)s %(levelname)-8s %(message)s')
            if self.VERBOSE_MODE >= 1:
                handler = logging.StreamHandler(sys.stdout)
                handler.setLevel(logging.INFO)
                handler.setFormatter(formatter)
                logger.addHandler(handler)
            if self.LOGS_PATH:
                file_handler = logging.FileHandler(self.LOGS_PATH)
                file_handler.setLevel(logging.INFO)
                file_handler.setFormatter(formatter)
                logger.addHandler(file_handler)
            self._logger = logger
        return self._logger

    def log(self, msg: str) -> None:
        self.get_logger().info(msg)
