"""Inline suppressions: ``# graftlint: disable=<rule>[,<rule>] -- reason``.

A suppression silences findings of the named rule(s) on its own line or
on the line DIRECTLY below (the usual shape: comment above the flagged
statement).  The ``-- reason`` clause is mandatory — a reason-less
suppression is itself a finding (rule ``graftlint``), so every silenced
site carries its justification in the diff where reviewers see it.

File-level form, for generated or deliberately-exempt files::

    # graftlint: disable-file=<rule>[,<rule>] -- reason

Suppressions are per-rule by design: ``disable=all`` is rejected (a
blanket gag would silently swallow rules added later).
"""
from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from code2vec_tpu.analysis.core import Finding
from code2vec_tpu.analysis.walker import SourceFile

SUPPRESS_RE = re.compile(
    r'#\s*graftlint:\s*(disable|disable-file)=([A-Za-z0-9_,-]+)'
    r'(?:\s*--\s*(.*))?')

META_RULE = 'graftlint'  # findings about the lint mechanics themselves


class Suppressions:
    """Parsed suppressions of one file.  ``used`` records which
    line-suppressions actually silenced something — a suppression left
    behind after the code under it was fixed pre-silences the NEXT
    regression at that site, so the engine flags unused ones (same
    philosophy as stale baseline entries)."""

    def __init__(self, line_rules: Dict[int, Set[str]],
                 file_rules: Set[str], problems: List[Finding]):
        self.line_rules = line_rules
        self.file_rules = file_rules
        self.problems = problems
        self.used: Set[Tuple[int, str]] = set()  # (comment line, rule)

    def covers(self, rule: str, line: int) -> bool:
        if rule in self.file_rules:
            return True
        # the comment's own line, or a comment on the line above the
        # flagged statement
        for at in (line, line - 1):
            if rule in self.line_rules.get(at, ()):
                self.used.add((at, rule))
                return True
        return False

    def stale(self, file: str, ran_rules: Set[str]) -> List[Finding]:
        """Line-suppressions for rules that RAN but silenced nothing."""
        out: List[Finding] = []
        for lineno in sorted(self.line_rules):
            for rule in sorted(self.line_rules[lineno]):
                if rule in ran_rules and (lineno, rule) not in self.used:
                    out.append(Finding(
                        META_RULE, file, lineno,
                        'stale suppression: `disable=%s` here silences '
                        'nothing — the code under it was fixed; remove '
                        'the comment so it cannot pre-silence a future '
                        'regression' % rule))
        return out


def parse_file(source: SourceFile) -> Suppressions:
    line_rules: Dict[int, Set[str]] = {}
    file_rules: Set[str] = set()
    problems: List[Finding] = []
    # real COMMENT tokens only: docstring examples (`# graftlint: ...`
    # inside a string) never parse as live suppressions
    for lineno, text in source.comments:
        match = SUPPRESS_RE.search(text)
        if match is None:
            if 'graftlint:' in text and 'disable' in text:
                problems.append(Finding(
                    META_RULE, source.rel, lineno,
                    'malformed graftlint suppression (expected '
                    '`# graftlint: disable=<rule> -- reason`)'))
            continue
        kind, rules_text, reason = match.groups()
        rules = {r.strip() for r in rules_text.split(',') if r.strip()}
        if 'all' in rules:
            problems.append(Finding(
                META_RULE, source.rel, lineno,
                'blanket `disable=all` is not allowed — name the '
                'rule(s) being suppressed'))
            rules.discard('all')
        if not (reason or '').strip():
            problems.append(Finding(
                META_RULE, source.rel, lineno,
                'suppression without a reason — append `-- <why this '
                'site is sanctioned>`'))
            continue  # an unjustified suppression does not suppress
        if kind == 'disable-file':
            file_rules.update(rules)
        else:
            line_rules.setdefault(lineno, set()).update(rules)
    return Suppressions(line_rules, file_rules, problems)


def apply(findings: List[Finding], by_file: Dict[str, Suppressions]
          ) -> Tuple[List[Finding], List[Finding]]:
    """Partition findings into (kept, suppressed)."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for finding in findings:
        sup = by_file.get(finding.file)
        if sup is not None and sup.covers(finding.rule, finding.line):
            suppressed.append(finding)
        else:
            kept.append(finding)
    return kept, suppressed
