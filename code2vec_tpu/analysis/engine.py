"""graftlint engine: walk once, run rules, apply suppressions and the
baseline, report.

The pipeline::

    SourceTree (one parse)  ->  rule.run(tree) per rule
        ->  inline suppressions (suppress.py; reasons mandatory)
        ->  baseline (baseline.py; reasons mandatory, stale = finding)
        ->  Report{findings, suppressed, baselined}

``Report.findings`` non-empty = exit 1 for the CLIs and a failed tier-1
test (tests/test_graftlint.py) — the repo must be clean of unbaselined,
unsuppressed findings at all times.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

from code2vec_tpu.analysis import baseline as baseline_lib
from code2vec_tpu.analysis import suppress
from code2vec_tpu.analysis.core import Finding, get_rules
from code2vec_tpu.analysis.walker import SourceTree


class Report:
    def __init__(self, findings: List[Finding],
                 suppressed: List[Finding],
                 baselined: List[Finding],
                 rules_run: List[str],
                 files_scanned: int,
                 elapsed_s: float):
        self.findings = findings
        self.suppressed = suppressed
        self.baselined = baselined
        self.rules_run = rules_run
        self.files_scanned = files_scanned
        self.elapsed_s = elapsed_s

    @property
    def clean(self) -> bool:
        return not self.findings

    def summary(self) -> str:
        return ('graftlint: %d finding(s), %d suppressed, %d baselined '
                '(%d rules over %d files in %.1fs)'
                % (len(self.findings), len(self.suppressed),
                   len(self.baselined), len(self.rules_run),
                   self.files_scanned, self.elapsed_s))


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run(root: Optional[str] = None,
        rule_names: Optional[Sequence[str]] = None,
        baseline_path: Optional[str] = None,
        tree: Optional[SourceTree] = None) -> Report:
    """Run the named rules (None = all registered) over ``root``.

    ``baseline_path`` default: ``<root>/graftlint_baseline.json`` when it
    exists; pass '' to force no baseline (the per-rule unit tests).
    """
    from code2vec_tpu.analysis import rules as _rules  # noqa: F401
    t0 = time.perf_counter()
    root = root if root is not None else repo_root()
    if tree is None:
        tree = SourceTree(root)
    rules = get_rules(rule_names)

    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.run(tree))
    # parse failures surface through whichever rule set runs
    for source in tree.files('all'):
        if source.parse_error is not None:
            raw.append(Finding(
                suppress.META_RULE, source.rel,
                source.parse_error.lineno or 0,
                'file does not parse: %s' % source.parse_error.msg))

    # inline suppressions (and their own problems)
    sup_by_file: Dict[str, suppress.Suppressions] = {}
    for source in tree.files('all'):
        parsed = suppress.parse_file(source)
        sup_by_file[source.rel] = parsed
        raw.extend(parsed.problems)
    kept, suppressed = suppress.apply(raw, sup_by_file)
    # a suppression that silenced nothing is stale (restricted to the
    # rules that RAN — a --rules subset must not flag the others')
    ran_rules = {rule.name for rule in rules}
    for rel, sup in sorted(sup_by_file.items()):
        kept.extend(sup.stale(rel, ran_rules))

    # baseline
    if baseline_path is None:
        baseline_path = os.path.join(root, baseline_lib.BASELINE_NAME)
    baselined: List[Finding] = []
    if baseline_path:
        base = baseline_lib.Baseline.load(baseline_path)
        # a rule-subset run only sees that subset's entries: entries of
        # un-run rules are neither matchable nor stale
        base = base.restricted_to({rule.name for rule in rules}
                                  | {suppress.META_RULE})
        kept, baselined, stale = base.apply(kept)
        kept.extend(stale)
        kept.extend(base.problems())

    kept.sort(key=lambda f: (f.file, f.line, f.rule))
    return Report(kept, suppressed, baselined,
                  [rule.name for rule in rules],
                  len(tree.files('all')), time.perf_counter() - t0)
