"""graftlint core: findings and the rule registry.

A rule is an object with:

- ``name``      — kebab-case id (``'host-sync'``), the key suppressions
                  and baselines reference;
- ``doc``       — one-line description (``--list-rules``);
- ``scope``     — 'package' or 'all' (which files it walks);
- ``run(tree)`` — ``SourceTree -> list[Finding]``.

Rules register at import time via ``@register`` (``rules/__init__.py``
imports every rule module); the engine resolves names through
``all_rules()``/``get_rules()``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from code2vec_tpu.analysis.walker import SourceTree


class Finding:
    """One rule violation.

    ``message`` is deliberately line-number-free: the baseline matches
    on ``(rule, file, message)`` so entries survive unrelated edits that
    shift lines.  ``line`` localizes the finding for humans and for
    inline suppressions.
    """

    __slots__ = ('rule', 'file', 'line', 'message')

    def __init__(self, rule: str, file: str, line: int, message: str):
        self.rule = rule
        self.file = file
        self.line = int(line)
        self.message = message

    def key(self):
        """The baseline identity (line-insensitive)."""
        return (self.rule, self.file, self.message)

    def __repr__(self) -> str:
        return 'Finding(%r, %r:%d, %r)' % (self.rule, self.file,
                                           self.line, self.message)

    def format(self) -> str:
        return '%s:%d: [%s] %s' % (self.file, self.line, self.rule,
                                   self.message)


class Rule:
    """Base class for rules; subclasses set ``name``/``doc``/``scope``
    and implement ``run``."""

    name = ''
    doc = ''
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        raise NotImplementedError

    def finding(self, file: str, line: int, message: str) -> Finding:
        return Finding(self.name, file, line, message)


_RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a rule by its name."""
    rule = cls()
    if not rule.name:
        raise ValueError('rule %r has no name' % cls)
    if rule.name in _RULES:
        raise ValueError('duplicate rule name %r' % rule.name)
    _RULES[rule.name] = rule
    return cls


def all_rules() -> List[Rule]:
    """Every registered rule, name order (rules/__init__.py must have
    been imported)."""
    return [_RULES[name] for name in sorted(_RULES)]


def get_rules(names: Optional[Sequence[str]]) -> List[Rule]:
    """Resolve rule names to instances; None = all."""
    if names is None:
        return all_rules()
    out = []
    for name in names:
        if name not in _RULES:
            raise KeyError('unknown rule %r (known: %s)'
                           % (name, ', '.join(sorted(_RULES))))
        out.append(_RULES[name])
    return out
