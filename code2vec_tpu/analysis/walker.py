"""Shared AST walker: one parse per file, reused by every rule.

``SourceTree`` loads and parses the scanned files once; rules receive
the tree and iterate ``tree.files(scope)``.  Each ``SourceFile`` carries
the raw text (for the grep-shaped rules and the suppression comments),
the parsed AST, and a per-file function index (qualified names +
line->enclosing-function map) so rules never re-derive structure.

Dependency-free and jax-free by design: the lint pass must run in a
bare interpreter in well under the tier-1 budget (ANALYSIS.md targets
<20s for the full pass; measured ~1s).
"""
from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import Dict, Iterator, List, Optional, Tuple

# Default scan scopes, relative to the repo root.  'package' is the
# runtime tree the invariant rules guard; 'all' adds the measurement
# harness + scripts for the catalog-drift rules (metrics/fault points),
# matching the pre-migration scripts' coverage.  tests/ stays out
# everywhere: tests mint throwaway names and seed deliberate violations
# to exercise the rules themselves.
PACKAGE_DIRS = ('code2vec_tpu',)
ALL_DIRS = ('code2vec_tpu', 'benchmarks', 'scripts')
ALL_FILES = ('bench.py',)


class FunctionInfo:
    """One function (or method) definition: qualified name, the AST
    node, and its line span."""

    __slots__ = ('qualname', 'node', 'lineno', 'end_lineno')

    def __init__(self, qualname: str, node: ast.AST):
        self.qualname = qualname
        self.node = node
        self.lineno = node.lineno
        self.end_lineno = getattr(node, 'end_lineno', node.lineno)


class SourceFile:
    """One parsed source file. ``rel`` is the repo-relative path every
    finding/catalog entry keys on."""

    def __init__(self, root: str, rel: str):
        self.rel = rel
        self.path = os.path.join(root, rel)
        with open(self.path, 'r') as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(self.text)
        except SyntaxError as exc:  # surfaced as an engine finding
            self.tree = None
            self.parse_error = exc
        self._functions: Optional[List[FunctionInfo]] = None
        self._comments: Optional[List[Tuple[int, str]]] = None

    @property
    def comments(self) -> List[Tuple[int, str]]:
        """(lineno, text) of every REAL comment token — docstrings and
        string literals that merely look like annotations never count
        (suppress.py and the lock-discipline annotations key off this)."""
        if self._comments is None:
            self._comments = []
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.text).readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments.append((tok.start[0], tok.string))
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass  # unparsable files already surface via parse_error
        return self._comments

    # ------------------------------------------------------- structure
    @property
    def functions(self) -> List[FunctionInfo]:
        """Every def/async-def in the file (nested included), with
        ``Class.method`` / ``outer.<locals>.inner`` qualified names."""
        if self._functions is None:
            self._functions = []
            if self.tree is not None:
                self._collect(self.tree, '', self._functions)
        return self._functions

    def _collect(self, node: ast.AST, prefix: str,
                 out: List[FunctionInfo]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (prefix + '.' if prefix else '') + child.name
                out.append(FunctionInfo(qual, child))
                self._collect(child, qual + '.<locals>', out)
            elif isinstance(child, ast.ClassDef):
                qual = (prefix + '.' if prefix else '') + child.name
                self._collect(child, qual, out)
            else:
                self._collect(child, prefix, out)

    def enclosing_function(self, lineno: int) -> Optional[str]:
        """Qualified name of the innermost function containing a line
        (None at module level)."""
        best: Optional[FunctionInfo] = None
        for info in self.functions:
            if info.lineno <= lineno <= info.end_lineno:
                if best is None or info.lineno >= best.lineno:
                    best = info
        return best.qualname if best is not None else None

    def classes(self) -> Iterator[ast.ClassDef]:
        if self.tree is None:
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                yield node


class SourceTree:
    """All scanned files of one repository, parsed once.

    ``scan_dirs``/``scan_files`` default to the repo layout; tests point
    them at a tmp tree of synthetic snippets.
    """

    def __init__(self, root: str,
                 scan_dirs: Tuple[str, ...] = ALL_DIRS,
                 scan_files: Tuple[str, ...] = ALL_FILES,
                 package_dirs: Tuple[str, ...] = PACKAGE_DIRS):
        self.root = os.path.abspath(root)
        self.package_dirs = package_dirs
        self._files: Dict[str, SourceFile] = {}
        for rel in self._iter_relpaths(scan_dirs, scan_files):
            self._files[rel] = SourceFile(self.root, rel)

    def _iter_relpaths(self, scan_dirs, scan_files) -> Iterator[str]:
        for rel_dir in scan_dirs:
            top = os.path.join(self.root, rel_dir)
            if not os.path.isdir(top):
                continue
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = [d for d in dirnames if d != '__pycache__']
                for name in sorted(filenames):
                    if name.endswith('.py'):
                        yield os.path.relpath(
                            os.path.join(dirpath, name), self.root)
        for rel in scan_files:
            if os.path.isfile(os.path.join(self.root, rel)):
                yield rel

    def files(self, scope: str = 'all') -> List[SourceFile]:
        """'package' = the runtime tree only; 'all' = everything
        scanned."""
        if scope == 'package':
            prefixes = tuple(d + os.sep for d in self.package_dirs)
            return [f for f in self._files.values()
                    if f.rel.startswith(prefixes)]
        return list(self._files.values())

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._files.get(rel)

    def doc_text(self, *names: str) -> str:
        """Concatenated text of the named repo-root docs that exist
        (doc-coverage rules)."""
        parts = []
        for name in names:
            path = os.path.join(self.root, name)
            if os.path.isfile(path):
                with open(path, 'r') as f:
                    parts.append(f.read())
        return '\n'.join(parts)

    def root_docs(self) -> List[str]:
        """Every *.md at the repo root (the documentation surface the
        config-knob rule accepts)."""
        return sorted(name for name in os.listdir(self.root)
                      if name.endswith('.md'))


# --------------------------------------------------------------- helpers
def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.device_get' / 'self._program' for a Name/Attribute chain;
    None for anything not a plain dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last attribute/name of a call target: ``self.a.b`` -> 'b',
    ``f`` -> 'f'.  The match key for method-style catalogs."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def assigned_names(target: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(name, target_node) pairs bound by one assignment target —
    handles Name, tuple/list destructuring, starred; attribute targets
    report their terminal name."""
    out: List[Tuple[str, ast.AST]] = []

    def walk(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.append((t.id, t))
        elif isinstance(t, ast.Attribute):
            out.append((t.attr, t))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                walk(elt)
        elif isinstance(t, ast.Starred):
            walk(t.value)
    walk(target)
    return out
