"""graftlint catalogs: the reviewed invariants the rules check against.

Like ``telemetry/catalog.py`` and ``resilience/faults.py::FAULT_POINTS``,
these are the single source of truth their rules lint the tree against —
adding a host sync or a donation edge means adding a catalog entry (with
its justification) in the same diff, where reviewers see it.

ANALYSIS.md documents every catalog and the workflow around it.
"""
from __future__ import annotations

# --------------------------------------------------------- host syncs
# Sanctioned host-synchronization sites (rule ``host-sync``).  Keyed by
# (file, enclosing function qualname, kind); ``count`` pins the number
# of sites inside that function, so a NEW sync slipped into an already-
# sanctioned function still fails.  Kinds:
#   device_get        — jax.device_get(...)
#   block_until_ready — jax.block_until_ready(...) / x.block_until_ready()
#   item              — x.item()
#   fetch             — np.asarray/float/int over a value traced to a
#                       jitted program's output in the same function
#
# Every entry carries the WHY — the performance contract that makes the
# sync acceptable at that site.
SANCTIONED_SYNCS = (
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer._fit_loop', 'kind': 'device_get', 'count': 4,
     'reason': 'the per-log-window sync (telemetry + plain paths), the '
               'eval-interval partial-window check, and the epoch-end '
               'drain — the divergence guard piggybacks on all of them '
               'at zero extra round-trips (ROBUSTNESS.md pillar 1)'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer._fit_loop', 'kind': 'block_until_ready', 'count': 1,
     'reason': 'profiler window close: the trace must contain completed '
               'device work before stop_trace'},
    {'file': 'code2vec_tpu/telemetry/trace.py',
     'func': 'TraceController.maybe_update', 'kind': 'block_until_ready',
     'count': 1,
     'reason': 'on-demand capture close: same contract as the fixed '
               'profiler window'},
    {'file': 'code2vec_tpu/serving/engine.py',
     'func': 'ServingEngine.warmup', 'kind': 'block_until_ready',
     'count': 1,
     'reason': 'eager ladder compile at load time — blocking here is '
               'the point (steady-state submit never compiles)'},
    {'file': 'code2vec_tpu/index/exact.py',
     'func': 'ExactIndex.warmup', 'kind': 'block_until_ready', 'count': 1,
     'reason': 'eager query-bucket compile at load time (same warm-'
               'ladder contract as serving warmup)'},
    {'file': 'code2vec_tpu/index/exact.py',
     'func': 'ExactIndex.search', 'kind': 'fetch', 'count': 2,
     'reason': 'search returns host numpy (scores, indices) by '
               'contract; one round-trip per query batch'},
    {'file': 'code2vec_tpu/index/exact.py',
     'func': 'search_streamed', 'kind': 'fetch', 'count': 2,
     'reason': 'per-shard candidate fetch feeding the exact host-side '
               'merge (merge_topk_host) — the streamed tier is host-'
               'merge by design'},
    {'file': 'code2vec_tpu/index/ivf.py',
     'func': 'kmeans', 'kind': 'fetch', 'count': 2,
     'reason': 'build-path result fetch after the Lloyd iterations '
               '(once per index build, not per query)'},
    {'file': 'code2vec_tpu/index/ivf.py',
     'func': 'IVFIndex.search', 'kind': 'fetch', 'count': 2,
     'reason': 'search returns host numpy (scores, ids) by contract — '
               'the probe-map back through list_ids is host-side'},
    {'file': 'code2vec_tpu/index/quant.py',
     'func': '_assign_chunks', 'kind': 'fetch', 'count': 1,
     'reason': 'build/insert-path codeword fetch per fixed-shape '
               'encode chunk (codes land in a host CSR; queries never '
               'touch this path)'},
    {'file': 'code2vec_tpu/index/quant.py',
     'func': 'train_pq', 'kind': 'fetch', 'count': 1,
     'reason': 'build-path codebook fetch after each Lloyd iteration '
               '(once per PQ training pass, not per query)'},
    {'file': 'code2vec_tpu/index/quant.py',
     'func': 'QuantizedIVFIndex.search', 'kind': 'fetch', 'count': 2,
     'reason': 'search returns host numpy (scores, ids) by contract — '
               'the LUT-gather top-k positions map back through '
               'list_ids / segment row ids host-side, and the optional '
               'exact re-rank reads the mmap store'},
    {'file': 'code2vec_tpu/model_api.py',
     'func': 'Code2VecModel.predict', 'kind': 'fetch', 'count': 1,
     'reason': 'REPL path: one blocking fetch per interactive request; '
               'throughput traffic goes through the serving engine '
               'whose decode pool owns the blocking np.asarray'},
)

# ----------------------------------------------------- jitted callables
# Names whose call RESULT is a device value (taint sources for the
# host-sync 'fetch' kind) and whose call SITES the recompile-hazard rule
# audits.  The per-file prepass additionally discovers `x = jax.jit(...)`
# bindings and @jax.jit-decorated defs; this catalog adds the dispatcher
# entry points whose jit lives behind a method boundary.
JIT_ENTRY_POINTS = frozenset((
    'train_step', 'train_step_placed', 'eval_step', 'eval_step_placed',
    'predict_step', 'predict_step_placed',
    '_train_step', '_train_step_packed', '_eval_step', '_eval_step_packed',
    '_streamed_shard_topk', '_pq_assign_chunk', '_pq_update',
))

# Methods returning a jitted program (calling the returned value
# dispatches a compiled step): `p = self._program(...); p(args)`.
JIT_RETURNING = frozenset(('_program',))

# ----------------------------------------------------- warm shape sources
# Calls that launder a raw size into a warm-ladder shape (recompile-
# hazard rule): values returned here are sanctioned shape sources.
WARM_SHAPE_SOURCES = frozenset((
    'pick_bucket', '_pick_bucket', 'capacity_ladder', 'batch_ladder',
    'bucketed_capacity', 'pad_batch_to',
))

# ------------------------------------------------------------- donation
# Callables that donate caller buffers (rule ``donation-safety``):
# {terminal call name: positions in the CALL argument list donated when
# DONATE_STAGED_BATCHES is on}.  Positions are of the call site (bound
# methods: 'self' not counted).  Reading a variable after passing it at
# a donated position is a use-after-free on the donating backends.
DONATING_CALLS = {
    '_train_step': (0, 1),          # (state, arrays)
    '_train_step_packed': (0, 1),
    'train_step_placed': (0, 1),
    '_eval_step': (1,),             # (params, arrays) — params re-fed
    '_eval_step_packed': (1,),
    'eval_step_placed': (1,),
}
