"""Rule ``lock-discipline``: declared guarded fields stay guarded.

The dispatcher thread, the decode pool, the watchdog monitor, and the
telemetry registry all share mutable state across threads.  Each owning
module DECLARES its locking contract in a module-level annotation::

    # graftlint: guard ServingEngine._queues,_pending_rows by _lock|_cond

meaning: every ``self.<field>`` access on the listed fields, in any
method of that class, must sit inside a ``with self.<lock>:`` block for
one of the listed lock aliases (a Condition wrapping a Lock lists
both).  Exemptions, matching how thread-safe classes are actually
written:

- ``__init__`` — construction happens-before any thread can observe
  the object (the thread/pool starts are the publication points);
- methods named ``*_locked`` — the documented called-with-lock-held
  convention (the caller owns the ``with``).

The rule also flags a declared field that never appears in the class
(stale annotation) and an annotation naming an unknown class — the
contract file cannot drift from the code it governs.  This is a
lightweight static race detector: it catches the common regression
(a new method touching shared state barehanded), not every interleaving.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree, dotted_name

GUARD_RE = re.compile(
    r'#\s*graftlint:\s*guard\s+(\w+)\.([\w,]+)\s+by\s+([\w|]+)')


def parse_annotations(source) -> List[Tuple[str, Set[str], Set[str]]]:
    """[(class, fields, lock aliases)] from one file's annotation
    comments (real COMMENT tokens only — docstring examples never parse
    as live annotations).  Groups stay SEPARATE: a class may guard
    different fields with different locks, and holding the wrong one
    must not count."""
    out: List[Tuple[str, Set[str], Set[str]]] = []
    for _lineno, text in source.comments:
        match = GUARD_RE.search(text)
        if match is None:
            continue
        cls, fields_text, locks_text = match.groups()
        out.append((cls,
                    {f for f in fields_text.split(',') if f},
                    {l for l in locks_text.split('|') if l}))
    return out


@register
class LockDisciplineRule(Rule):
    name = 'lock-discipline'
    doc = ('fields declared `# graftlint: guard Cls.f by lock` are only '
           'touched under `with self.lock:` (cross-thread state)')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for source in tree.files(self.scope):
            if source.tree is None:
                continue
            annotations = parse_annotations(source)
            if not annotations:
                continue
            classes = {node.name: node for node in source.classes()}
            for cls_name, fields, locks in annotations:
                cls = classes.get(cls_name)
                if cls is None:
                    findings.append(self.finding(
                        source.rel, 0,
                        'guard annotation names unknown class `%s`'
                        % cls_name))
                    continue
                findings.extend(self._check_class(
                    source, cls, fields, locks))
        return findings

    def _check_class(self, source, cls: ast.ClassDef,
                     fields: Set[str], locks: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        seen_fields: Set[str] = set()
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            method = node
            exempt = (method.name == '__init__'
                      or method.name.endswith('_locked'))
            held_spans = self._lock_spans(method, locks)
            for sub in ast.walk(method):
                if not (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == 'self'
                        and sub.attr in fields):
                    continue
                seen_fields.add(sub.attr)
                if exempt:
                    continue
                if not any(a <= sub.lineno <= b for a, b in held_spans):
                    findings.append(self.finding(
                        source.rel, sub.lineno,
                        'unguarded access to `%s.%s` in `%s` — '
                        'declared guarded by %s; wrap in `with '
                        'self.%s:` (or suppress with the why if the '
                        'race is benign)'
                        % (cls.name, sub.attr, method.name,
                           '/'.join(sorted(locks)),
                           sorted(locks)[0])))
        for field in sorted(fields - seen_fields):
            findings.append(self.finding(
                source.rel, cls.lineno,
                'stale guard annotation: `%s.%s` is declared guarded '
                'but never accessed in the class' % (cls.name, field)))
        return findings

    @staticmethod
    def _lock_spans(method: ast.AST,
                    locks: Set[str]) -> List[Tuple[int, int]]:
        """Line spans of `with self.<lock>:` bodies in the method."""
        spans: List[Tuple[int, int]] = []
        for node in ast.walk(method):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                ctx = item.context_expr
                # accept `self._lock` and `self._lock.acquire_timeout()`-
                # style wrappers whose base is the declared lock
                name = dotted_name(ctx) if not isinstance(ctx, ast.Call) \
                    else dotted_name(ctx.func)
                if name is None:
                    continue
                parts = name.split('.')
                if len(parts) >= 2 and parts[0] == 'self' and \
                        parts[1] in locks:
                    spans.append((node.lineno,
                                  getattr(node, 'end_lineno',
                                          node.lineno)))
                    break
        return spans
