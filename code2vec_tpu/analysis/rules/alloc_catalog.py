"""Rule ``alloc-catalog``: device allocations in the owner modules are
ledger-accounted.

The memory analogue of ``host-sync``: the device-memory ledger
(``telemetry/memory.py``, OBSERVABILITY.md "Device memory ledger") only
stays honest if every allocation owner actually registers what it
allocates — so every device-allocation site in the cataloged owner
modules (``ALLOC_OWNER_FILES``) must sit inside a function cataloged in
``ALLOC_CATALOG`` (each entry records WHY its accounting treatment is
right) or carry an inline ``# graftlint: disable=alloc-catalog -- why``
suppression.  Counts are pinned per function, so a NEW ``device_put``
slipped into an already-cataloged owner still fails; an entry whose
function no longer allocates is stale and fails too.

Allocation sites (AST-matched, so comments/docstrings never count):

- ``device_put``                        — direct device placement;
- ``shard_batch`` / ``shard_params``    — mesh placement of batches
                                          (the donated staging wire)
                                          and parameter trees;
- ``jnp.zeros`` / ``jnp.empty`` / ``jnp.full`` / ``jnp.asarray``
                                        — host-initiated device
                                          buffers.
"""
from __future__ import annotations

import ast
import collections
from typing import Dict, List, Tuple

from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import (SourceTree, dotted_name,
                                          terminal_name)

CATALOG_FILE = 'code2vec_tpu/telemetry/memory.py'

_TERMINAL_ALLOCS = frozenset(('device_put', 'shard_batch',
                              'shard_params'))
_DOTTED_ALLOCS = frozenset(('jnp.zeros', 'jnp.empty', 'jnp.full',
                            'jnp.asarray'))


def find_sites(tree: SourceTree, owner_files) -> List[Tuple[str, str,
                                                            int, str]]:
    """[(relpath, enclosing_function, lineno, site_name)] across the
    cataloged owner modules present in the tree."""
    out = []
    for rel in owner_files:
        source = tree.get(rel)
        if source is None or source.tree is None:
            continue
        for node in ast.walk(source.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            terminal = terminal_name(node.func)
            if terminal in _TERMINAL_ALLOCS or dotted in _DOTTED_ALLOCS:
                func = source.enclosing_function(node.lineno) or ''
                out.append((rel, func, node.lineno,
                            dotted or terminal or '?'))
    return out


@register
class AllocCatalogRule(Rule):
    name = 'alloc-catalog'
    doc = ('every device-allocation site in the cataloged owner modules '
           '(telemetry/memory.py ALLOC_CATALOG) is ledger-accounted; '
           'counts are pinned and stale entries fail')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        try:
            from code2vec_tpu.telemetry.memory import (ALLOC_CATALOG,
                                                       ALLOC_OWNER_FILES)
        except ImportError:
            return [self.finding(
                CATALOG_FILE, 0, 'alloc catalog is not importable')]
        findings: List[Finding] = []
        catalog: Dict[Tuple[str, str], dict] = {}
        for entry in ALLOC_CATALOG:
            key = (entry['file'], entry['func'])
            if key in catalog:
                findings.append(self.finding(
                    CATALOG_FILE, 0,
                    'duplicate alloc-catalog entry for %s::%s'
                    % key))
            if not entry.get('reason'):
                findings.append(self.finding(
                    CATALOG_FILE, 0,
                    'alloc-catalog entry %s::%s has no reason — the '
                    'accounting treatment must be justified where '
                    'reviewers see it' % key))
            if entry['file'] not in ALLOC_OWNER_FILES:
                findings.append(self.finding(
                    CATALOG_FILE, 0,
                    'alloc-catalog entry %s::%s names a file outside '
                    'ALLOC_OWNER_FILES — the rule never scans it, so '
                    'the entry is unverifiable' % key))
            catalog[key] = entry

        sites = find_sites(tree, ALLOC_OWNER_FILES)
        by_func: Dict[Tuple[str, str], List[Tuple[int, str]]] = \
            collections.defaultdict(list)
        for rel, func, lineno, site in sites:
            by_func[(rel, func)].append((lineno, site))

        for key, found in sorted(by_func.items()):
            rel, func = key
            entry = catalog.get(key)
            if entry is None:
                for lineno, site in found:
                    findings.append(self.finding(
                        rel, lineno,
                        'allocation site %s in %s is not in the alloc '
                        'catalog (telemetry/memory.py ALLOC_CATALOG) — '
                        'register the allocation with the memory '
                        'ledger and catalog the owner, or suppress '
                        'with a reason' % (site, func or '<module>')))
            elif entry['count'] != len(found):
                findings.append(self.finding(
                    rel, found[0][0],
                    'alloc catalog pins %d allocation site(s) in %s '
                    'but found %d — a new (or removed) allocation must '
                    'update the catalog entry and its ledger '
                    'accounting' % (entry['count'], func, len(found))))

        scanned = {rel for rel in ALLOC_OWNER_FILES
                   if tree.get(rel) is not None}
        for key, entry in sorted(catalog.items()):
            if key[0] in scanned and key not in by_func:
                findings.append(self.finding(
                    CATALOG_FILE, 0,
                    'alloc-catalog entry %s::%s is stale — the '
                    'function no longer contains an allocation site'
                    % key))
        return findings
