"""Rule ``donation-safety``: never read a buffer after donating it.

The staging ring donates consumed batch buffers into the train/eval
steps (``DONATE_STAGED_BATCHES``, data/packed.py + trainer) so the
ring's device footprint stays ~depth batches.  XLA is then free to
alias the donated input's memory for outputs — reading the Python
reference afterwards observes whatever the program scribbled there (or
raises on deleted-buffer backends).  The failure is silent corruption
on exactly the configs the donation optimizes.

The rule knows which call positions donate (``catalog.DONATING_CALLS``)
and flags a load of a donated plain-Name argument after the dispatch
and before any rebinding, using the ordered event stream of the shared
taint pass.  Lexical, single-pass: loop-carried reads are covered by
the surrounding iteration's rebinding discipline (the ring yields a
fresh placement each iteration), and attribute/subscript donations are
out of scope — keep donated buffers in locals.
"""
from __future__ import annotations

import ast
from typing import List

from code2vec_tpu.analysis import catalog, taint
from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree, terminal_name


@register
class DonationSafetyRule(Rule):
    name = 'donation-safety'
    doc = ('no reads of a local after it is passed through a donated '
           'argnum (DONATE_STAGED_BATCHES aliasing)')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for source in tree.files(self.scope):
            if source.tree is None:
                continue
            for info, analysis in taint.analyze_file(source):
                branches = _BranchMap(info.node)
                for dispatch in analysis.dispatches:
                    term = terminal_name(dispatch.node.func)
                    donated = catalog.DONATING_CALLS.get(term)
                    if donated is None:
                        continue
                    if branches.inside_return(dispatch.node):
                        continue  # the donating call exits the function
                    for pos in donated:
                        if pos >= len(dispatch.node.args):
                            continue
                        arg = dispatch.node.args[pos]
                        if not isinstance(arg, ast.Name):
                            continue
                        read = self._read_after(analysis, arg.id,
                                                dispatch.seq,
                                                dispatch.node, branches)
                        if read is not None:
                            findings.append(self.finding(
                                source.rel, read,
                                'read of `%s` in `%s` after it was '
                                'donated to `%s` (arg %d) — the step '
                                'may alias/overwrite its buffer; '
                                'rebind or copy before the dispatch'
                                % (arg.id, info.qualname, term, pos)))
        return findings

    @staticmethod
    def _read_after(analysis: taint.FunctionTaint, name: str,
                    donate_seq: int, dispatch_node: ast.AST,
                    branches: '_BranchMap'):
        """Line of the first load of ``name`` after ``donate_seq``,
        before its next rebind, on a path reachable from the dispatch
        (sibling if/else arms and except-handlers are not), else None."""
        for seq, kind, lineno, node in analysis.events.get(name, ()):
            if seq <= donate_seq:
                continue
            if node is not None and \
                    branches.siblings(dispatch_node, node):
                continue  # the lexical walk crossed into the other arm
            if kind == 'bind':
                return None
            return lineno
        return None


class _BranchMap:
    """Which if/else arm (or try/except handler) each node sits in, so
    the lexical event stream can skip pairs that never execute on the
    same path."""

    def __init__(self, func: ast.AST):
        self._arm_sets = []  # [(set(ids of arm A), set(ids of arm B))]
        self._return_ids = set()
        for node in ast.walk(func):
            if isinstance(node, ast.If):
                self._add_arms(node.body, node.orelse)
            elif isinstance(node, ast.Try):
                for handler in node.handlers:
                    self._add_arms(node.body, handler.body)
            elif isinstance(node, ast.Return):
                self._return_ids.update(
                    id(sub) for sub in ast.walk(node))

    def _add_arms(self, body_a, body_b) -> None:
        if not body_a or not body_b:
            return
        ids_a = {id(sub) for stmt in body_a for sub in ast.walk(stmt)}
        ids_b = {id(sub) for stmt in body_b for sub in ast.walk(stmt)}
        self._arm_sets.append((ids_a, ids_b))

    def siblings(self, a: ast.AST, b: ast.AST) -> bool:
        for ids_a, ids_b in self._arm_sets:
            if (id(a) in ids_a and id(b) in ids_b) or \
                    (id(a) in ids_b and id(b) in ids_a):
                return True
        return False

    def inside_return(self, node: ast.AST) -> bool:
        return id(node) in self._return_ids
