"""Rule ``config-knob-docs``: every knob the code reads is documented.

A knob nobody can find is a knob nobody can turn — and one that will be
"re-added" under a second name.  Two knob surfaces are collected from
``code2vec_tpu/``:

- **environment variables** — ``os.environ.get('X')`` / ``os.environ['X']``
  string keys;
- **CLI flags** — the option strings of every ``add_argument`` call
  (the longest ``--flag`` spelling).

Each collected name must appear verbatim in at least one repo-root
``*.md`` doc (README.md or the owning subsystem doc — SERVING.md,
OBSERVABILITY.md, ROBUSTNESS.md, INDEX.md, PERF.md, ...).  Names read
from a variable (dynamic keys) are invisible to this rule by
construction; keep knob names literal.
"""
from __future__ import annotations

import ast
from typing import List, Set, Tuple

from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree, dotted_name

# process/meta files that are NOT user-facing documentation: a knob
# named only in the changelog (which names every flag a PR adds) or the
# issue text would otherwise count as documented, making the rule
# structurally vacuous
_NON_DOC_ROOTS = frozenset((
    'CHANGES.md', 'ISSUE.md', 'ADVICE.md', 'VERDICT.md', 'SURVEY.md',
    'SNIPPETS.md', 'PAPER.md', 'PAPERS.md', 'BASELINE.md', 'ROADMAP.md',
))


@register
class ConfigKnobDocsRule(Rule):
    name = 'config-knob-docs'
    doc = ('every os.environ read and CLI flag in code2vec_tpu/ appears '
           'in a repo-root *.md doc')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        knobs: List[Tuple[str, str, int, str]] = []  # (name, file, line, kind)
        for source in tree.files(self.scope):
            if source.tree is None:
                continue
            for node in ast.walk(source.tree):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    if name == 'os.environ.get' and node.args and \
                            isinstance(node.args[0], ast.Constant) and \
                            isinstance(node.args[0].value, str):
                        knobs.append((node.args[0].value, source.rel,
                                      node.lineno, 'env var'))
                    elif name is not None and \
                            name.endswith('add_argument'):
                        flag = self._longest_flag(node)
                        if flag is not None:
                            knobs.append((flag, source.rel, node.lineno,
                                          'CLI flag'))
                elif isinstance(node, ast.Subscript) and \
                        dotted_name(node.value) == 'os.environ' and \
                        isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, str):
                    knobs.append((node.slice.value, source.rel,
                                  node.lineno, 'env var'))

        docs = [d for d in tree.root_docs() if d not in _NON_DOC_ROOTS]
        doc_text = tree.doc_text(*docs)
        findings: List[Finding] = []
        reported: Set[str] = set()
        for name, rel, lineno, kind in knobs:
            if name in doc_text or name in reported:
                continue
            reported.add(name)
            findings.append(self.finding(
                rel, lineno,
                'undocumented %s `%s` — document it in README.md or '
                'the owning subsystem doc (searched: %s)'
                % (kind, name, ', '.join(docs) if docs else '<no '
                   'repo-root *.md docs found>')))
        return findings

    @staticmethod
    def _longest_flag(node: ast.Call):
        flags = [arg.value for arg in node.args
                 if isinstance(arg, ast.Constant)
                 and isinstance(arg.value, str)
                 and arg.value.startswith('-')]
        if not flags:
            return None
        return max(flags, key=len)
