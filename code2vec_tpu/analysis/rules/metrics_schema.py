"""Rule ``metrics-schema``: metric names cannot drift from catalog/doc.

The graftlint port of ``scripts/check_metrics_schema.py`` (which stays
as the CLI wrapper over this rule): every metric name emitted anywhere
must exist in the telemetry catalog, and every cataloged name must be
documented in OBSERVABILITY.md.  Grep-shaped on purpose — emission
sites are method calls with a string literal, and only literals
containing '/' (the catalog's ``subsystem/metric`` shape) count.
"""
from __future__ import annotations

import re
from typing import List, Tuple

from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree

# \s* spans newlines: emission calls wrap across lines under the
# 79-column style, so matching is against whole-file content
EMIT_RE = re.compile(
    r"""\.(?:counter|gauge|timer|scalar|get)\(\s*['"]([^'"]*/[^'"]*)['"]""")

DOC_NAME = 'OBSERVABILITY.md'


def find_emissions(tree: SourceTree) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, metric_name)] across the scanned tree."""
    out = []
    for source in tree.files('all'):
        for match in EMIT_RE.finditer(source.text):
            lineno = source.text.count('\n', 0, match.start()) + 1
            out.append((source.rel, lineno, match.group(1)))
    return out


@register
class MetricsSchemaRule(Rule):
    name = 'metrics-schema'
    doc = ('every emitted metric name is in telemetry/catalog.py and '
           'documented in OBSERVABILITY.md')
    scope = 'all'

    def run(self, tree: SourceTree) -> List[Finding]:
        try:
            from code2vec_tpu.telemetry.catalog import CATALOG
        except ImportError:
            # synthetic test trees have no package on path — emissions
            # are then unverifiable, which must be loud, not silent
            return [self.finding(
                'code2vec_tpu/telemetry/catalog.py', 0,
                'telemetry catalog is not importable')]
        from code2vec_tpu.telemetry.catalog import base_name
        findings: List[Finding] = []
        for rel, lineno, name in find_emissions(tree):
            # an instance-labeled literal ('m{replica=r0}') validates
            # against its label-free catalog family, same resolution as
            # the Prometheus exporter (catalog.base_name)
            if base_name(name) not in CATALOG:
                findings.append(self.finding(
                    rel, lineno,
                    'metric %r is not in the catalog '
                    '(code2vec_tpu/telemetry/catalog.py) — add it there '
                    'and to OBSERVABILITY.md, or fix the name' % name))
        doc = tree.doc_text(DOC_NAME)
        if doc:
            for name in sorted(CATALOG):
                if name not in doc:
                    findings.append(self.finding(
                        DOC_NAME, 0,
                        'cataloged metric %r is undocumented' % name))
        else:
            findings.append(self.finding(
                DOC_NAME, 0,
                'OBSERVABILITY.md is missing (the metric catalog must '
                'be documented)'))
        return findings
