"""Rule ``recompile-hazard``: jitted programs may only see warm shapes.

The serving/index/trainer hot paths guarantee ZERO steady-state
compiles (compile-counter-guarded in tests/test_serving_bench.py and
tests/test_index_bench.py) because every dispatch lands on a shape from
a warm ladder — ``capacity_ladder``, the serving batch buckets, the
index query buckets.  A call site whose shape derives from a raw
Python size (``len(...)``, ``.shape``) silently re-specializes the
whole program on every new size: correct output, 100-1000x the latency,
invisible until a p99 graph melts.  Three checks:

1. **unbucketed dispatch** — a call into a jitted callable where an
   argument's shape taints back to ``len``/``.shape`` without passing a
   warm-ladder source (``catalog.WARM_SHAPE_SOURCES``);
2. **inline jit** — ``jax.jit(...)(args)`` built and invoked in one
   expression inside a function: the fresh function identity defeats
   jit's cache, so every call recompiles;
3. **nested-def jit** — ``@jax.jit`` on a def nested inside another
   function: a fresh program identity per outer call (fine on a
   build/restore path — baseline it with the why — fatal on a hot one).
"""
from __future__ import annotations

import ast
from typing import List

from code2vec_tpu.analysis import taint
from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree


@register
class RecompileHazardRule(Rule):
    name = 'recompile-hazard'
    doc = ('jit dispatches must use warm-ladder shapes; no inline or '
           'per-call jax.jit program identities')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for source in tree.files(self.scope):
            if source.tree is None:
                continue
            nested_jitted = self._nested_jit_defs(source)
            for qual, node in nested_jitted:
                findings.append(self.finding(
                    source.rel, node.lineno,
                    'jax.jit on nested def `%s`: a fresh program '
                    'identity per enclosing call — every call of the '
                    'outer function recompiles' % qual))
            for info, analysis in taint.analyze_file(source):
                for dispatch in analysis.dispatches:
                    if dispatch.inline_jit:
                        findings.append(self.finding(
                            source.rel, dispatch.node.lineno,
                            'inline jax.jit(...)(...) in `%s`: the '
                            'fresh function identity defeats the jit '
                            'cache — every call compiles'
                            % info.qualname))
                    for arg in dispatch.tainted_args:
                        findings.append(self.finding(
                            source.rel, dispatch.node.lineno,
                            'jit dispatch `%s(...)` in `%s`: argument '
                            '`%s` has a shape derived from a raw '
                            'len()/.shape size — route it through a '
                            'warm-ladder source (%s)'
                            % (dispatch.callee, info.qualname, arg,
                               'pick_bucket/capacity_ladder/'
                               'bucketed_capacity')))
        return findings

    def _nested_jit_defs(self, source):
        """(qualname, node) of jit-decorated defs nested in functions."""
        out = []
        for info in source.functions:
            if '.<locals>.' not in info.qualname:
                continue
            for deco in info.node.decorator_list:
                if taint._is_jit_decorator(deco):
                    out.append((info.qualname, info.node))
        return out
