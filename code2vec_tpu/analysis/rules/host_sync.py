"""Rule ``host-sync``: device synchronization only at cataloged sites.

A host sync (``jax.device_get``, ``block_until_ready``, ``.item()``, or
a ``np.asarray``/``float``/``int`` over a jitted program's output)
stalls the dispatch pipeline: the host blocks until the device drains.
The trainer budgets exactly ONE sync per log window (the divergence
guard deliberately piggybacks on it — ROBUSTNESS.md pillar 1), serving
confines blocking fetches to the decode pool, and the index tiers sync
once per query batch.  Every such site is cataloged with its
justification in ``analysis/catalog.py::SANCTIONED_SYNCS``; this rule
fails on:

- a sync at an uncataloged site (new stall slipped into a hot path);
- MORE syncs than the entry's pinned ``count`` inside a sanctioned
  function (a second sync hiding behind a sanctioned first);
- a stale catalog entry matching nothing (the catalog must not rot).

The 'fetch' kind rides the function-local taint pass (analysis/
taint.py): only values traced to a jit dispatch in the SAME function
are flagged, so host-numpy ``np.asarray`` staging code stays silent.
"""
from __future__ import annotations

import collections
from typing import Dict, List, Tuple

from code2vec_tpu.analysis import catalog, taint
from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree

_KIND_HINTS = {
    'device_get': 'jax.device_get blocks on the device queue',
    'block_until_ready': 'block_until_ready drains the device queue',
    'item': '.item() forces a device round-trip per scalar',
    'fetch': 'np.asarray/float/int over a jitted output blocks on it',
}


@register
class HostSyncRule(Rule):
    name = 'host-sync'
    doc = ('host synchronization (device_get/block_until_ready/.item()/'
           'jit-output fetch) only at cataloged sanctioned sites')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        # (file, func, kind) -> observed count
        observed: Dict[Tuple[str, str, str], int] = \
            collections.Counter()
        sanctioned = {(e['file'], e['func'], e['kind']): e
                      for e in catalog.SANCTIONED_SYNCS}
        for source in tree.files(self.scope):
            if source.tree is None:
                continue
            for info, analysis in taint.analyze_file(source):
                for sync in analysis.syncs:
                    key = (source.rel, info.qualname, sync.kind)
                    # nested defs: credit the innermost enclosing
                    # function actually containing the node
                    inner = source.enclosing_function(sync.node.lineno)
                    if inner != info.qualname:
                        continue  # counted when walking `inner` itself
                    observed[key] += 1
                    entry = sanctioned.get(key)
                    if entry is None:
                        findings.append(self.finding(
                            source.rel, sync.node.lineno,
                            'uncataloged host sync (%s) in `%s` — %s; '
                            'move it off the hot path or add a '
                            'SANCTIONED_SYNCS entry with its '
                            'justification (analysis/catalog.py)'
                            % (sync.kind, info.qualname,
                               _KIND_HINTS[sync.kind])))
        # count pins + stale entries (skipped when the entry's file is
        # outside the scanned tree, e.g. the synthetic unit-test trees)
        for key, entry in sanctioned.items():
            if tree.get(entry['file']) is None:
                continue
            seen = observed.get(key, 0)
            if seen == 0:
                findings.append(self.finding(
                    entry['file'], 0,
                    'stale SANCTIONED_SYNCS entry: no %s sync found in '
                    '`%s` — the sanctioned site moved or was removed; '
                    'update the catalog' % (entry['kind'], entry['func'])))
            elif seen > entry['count']:
                findings.append(self.finding(
                    entry['file'], 0,
                    '`%s` has %d %s sync(s) but the catalog sanctions '
                    '%d — a new sync is hiding behind a sanctioned '
                    'site; justify it by raising the count'
                    % (entry['func'], seen, entry['kind'],
                       entry['count'])))
            elif seen < entry['count']:
                findings.append(self.finding(
                    entry['file'], 0,
                    '`%s` has %d %s sync(s) but the catalog sanctions '
                    '%d — a site was removed; lower the count so the '
                    'headroom cannot mask a future addition'
                    % (entry['func'], seen, entry['kind'],
                       entry['count'])))
        return findings
