"""graftlint rules: importing this package registers every rule.

One module per rule; each registers itself via ``@core.register`` at
import time.  Adding a rule = adding a module here + importing it below
+ a seeded-violation unit test in tests/test_graftlint.py + a catalog
row in ANALYSIS.md (the test file asserts the doc row exists).
"""
from code2vec_tpu.analysis.rules import (  # noqa: F401
    alloc_catalog,
    config_knobs,
    donation,
    fault_points,
    host_sync,
    jit_purity,
    locks,
    metrics_schema,
    recompile_hazard,
    span_catalog,
)
