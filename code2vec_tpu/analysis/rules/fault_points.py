"""Rule ``fault-points``: fault-point names cannot drift from catalog.

The graftlint port of ``scripts/check_fault_points.py`` (which stays as
the CLI wrapper over this rule): every ``maybe_fire('<point>')`` site
must exist in ``resilience/faults.py::FAULT_POINTS``, every cataloged
point must be documented in ROBUSTNESS.md, and — stricter than the
metrics rule — every cataloged point must be WIRED somewhere: a fault
spec naming an unwired point would parse fine and silently inject
nothing, the exact trap this lint exists to close.
"""
from __future__ import annotations

import os
import re
from typing import List, Tuple

from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree

# \s* spans newlines: calls wrap across lines under the 79-column style
FIRE_RE = re.compile(r"""maybe_fire\(\s*['"]([A-Za-z0-9_]+)['"]""")

DOC_NAME = 'ROBUSTNESS.md'

# never scan the lint's own files: their docstring examples would count
# as sites and mask a deleted real site
_SELF_FILES = (
    os.path.join('scripts', 'check_fault_points.py'),
    os.path.join('code2vec_tpu', 'analysis', 'rules', 'fault_points.py'),
)


def find_sites(tree: SourceTree) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, point_name)] across the scanned tree."""
    out = []
    for source in tree.files('all'):
        if source.rel in _SELF_FILES:
            continue
        for match in FIRE_RE.finditer(source.text):
            lineno = source.text.count('\n', 0, match.start()) + 1
            out.append((source.rel, lineno, match.group(1)))
    return out


@register
class FaultPointsRule(Rule):
    name = 'fault-points'
    doc = ('every maybe_fire site is in resilience/faults.py, every '
           'cataloged point is wired and documented in ROBUSTNESS.md')
    scope = 'all'

    def run(self, tree: SourceTree) -> List[Finding]:
        try:
            from code2vec_tpu.resilience.faults import FAULT_POINTS
        except ImportError:
            return [self.finding(
                'code2vec_tpu/resilience/faults.py', 0,
                'fault-point catalog is not importable')]
        sites = find_sites(tree)
        findings: List[Finding] = []
        for rel, lineno, name in sites:
            if name not in FAULT_POINTS:
                findings.append(self.finding(
                    rel, lineno,
                    'fault point %r is not in the catalog '
                    '(code2vec_tpu/resilience/faults.py) — add it there '
                    'and to ROBUSTNESS.md, or fix the name' % name))
        doc = tree.doc_text(DOC_NAME)
        if doc:
            for name in sorted(FAULT_POINTS):
                if name not in doc:
                    findings.append(self.finding(
                        DOC_NAME, 0,
                        'cataloged fault point %r is undocumented'
                        % name))
        else:
            findings.append(self.finding(
                DOC_NAME, 0,
                'ROBUSTNESS.md is missing (the fault-point catalog '
                'must be documented)'))
        fired = {name for _rel, _lineno, name in sites}
        for name in sorted(set(FAULT_POINTS) - fired):
            findings.append(self.finding(
                'code2vec_tpu/resilience/faults.py', 0,
                'fault point %r is cataloged but has no maybe_fire '
                'site — every point must be wired, or specs naming it '
                'silently inject nothing' % name))
        return findings
