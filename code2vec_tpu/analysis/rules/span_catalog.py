"""Rule ``span-catalog``: span names cannot drift from the catalog.

The tracing analogue of ``metrics-schema`` / ``fault-points``: every
span emission site (``.begin('x.y')`` / ``.span('x.y')`` /
``.span_at('x.y')`` / ``.event('x.y')`` / ``.single('x.y')``) must name
a span cataloged in ``telemetry/tracing.py::SPAN_CATALOG``, every
cataloged span must be documented in OBSERVABILITY.md, and — like the
fault-point rule — every cataloged span must be WIRED at some call
site: a stale catalog entry would document a phase the span log can
never contain, the drift this lint exists to close.

Remote-origin spans (ISSUE 15): a span recorded in a WORKER process
and grafted into the parent trace by ``Trace.adopt_spans`` has no
local emission site by construction.  ``tracing.REMOTE_ORIGIN_SPANS``
declares those names; the rule treats a declared name as wired through
the adoption path, while still requiring it to be cataloged and
documented — and a declared name that is NOT in the catalog is itself
a finding (an adopted span the log can contain but the catalog
denies).
"""
from __future__ import annotations

import os
import re
from typing import List, Tuple

from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree

# literal dotted first argument only ('serving.pack'): internal generic
# forwarding calls (trace._add(name, ...)) are invisible by design, and
# the dot requirement keeps unrelated .begin()/.event() calls out
SPAN_RE = re.compile(
    r"""\.(?:begin|span|span_at|event|single)\(\s*"""
    r"""['"]([a-z0-9_]+\.[a-z0-9_.]+)['"]""")

DOC_NAME = 'OBSERVABILITY.md'

CATALOG_FILE = os.path.join('code2vec_tpu', 'telemetry', 'tracing.py')

# never scan the catalog's own module or this rule: their docstring
# examples would count as sites and mask a deleted real site
_SELF_FILES = (
    CATALOG_FILE,
    os.path.join('code2vec_tpu', 'analysis', 'rules', 'span_catalog.py'),
)


def find_sites(tree: SourceTree) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, span_name)] across the scanned tree."""
    out = []
    for source in tree.files('all'):
        if source.rel in _SELF_FILES:
            continue
        for match in SPAN_RE.finditer(source.text):
            lineno = source.text.count('\n', 0, match.start()) + 1
            out.append((source.rel, lineno, match.group(1)))
    return out


@register
class SpanCatalogRule(Rule):
    name = 'span-catalog'
    doc = ('every traced span site names a SPAN_CATALOG entry '
           '(telemetry/tracing.py); every cataloged span is wired and '
           'documented in OBSERVABILITY.md')
    scope = 'all'

    def run(self, tree: SourceTree) -> List[Finding]:
        try:
            from code2vec_tpu.telemetry.tracing import (
                REMOTE_ORIGIN_SPANS, SPAN_CATALOG)
        except ImportError:
            return [self.finding(
                CATALOG_FILE, 0, 'span catalog is not importable')]
        sites = find_sites(tree)
        findings: List[Finding] = []
        for rel, lineno, name in sites:
            if name not in SPAN_CATALOG:
                findings.append(self.finding(
                    rel, lineno,
                    'span %r is not in the catalog '
                    '(code2vec_tpu/telemetry/tracing.py SPAN_CATALOG) — '
                    'add it there and to OBSERVABILITY.md, or fix the '
                    'name' % name))
        doc = tree.doc_text(DOC_NAME)
        if doc:
            for name in sorted(SPAN_CATALOG):
                if name not in doc:
                    findings.append(self.finding(
                        DOC_NAME, 0,
                        'cataloged span %r is undocumented' % name))
        else:
            findings.append(self.finding(
                DOC_NAME, 0,
                'OBSERVABILITY.md is missing (the span catalog must be '
                'documented)'))
        for name in sorted(REMOTE_ORIGIN_SPANS - set(SPAN_CATALOG)):
            findings.append(self.finding(
                CATALOG_FILE, 0,
                'remote-origin span %r (REMOTE_ORIGIN_SPANS) is not in '
                'SPAN_CATALOG — adopt_spans can graft it into the span '
                'log, so the catalog must admit it' % name))
        # remote-origin spans are wired through the adoption path: a
        # worker records them and the mesh receiver grafts them, so no
        # local literal site is required
        wired = {name for _rel, _lineno, name in sites}
        wired |= REMOTE_ORIGIN_SPANS
        for name in sorted(set(SPAN_CATALOG) - wired):
            findings.append(self.finding(
                CATALOG_FILE, 0,
                'span %r is cataloged but has no emission site — stale '
                'catalog entries document phases the span log can never '
                'contain' % name))
        return findings
