"""Rule ``jit-purity``: no host side effects inside jitted bodies.

A jitted function's body runs ONCE, at trace time.  ``time.*`` reads,
``np.random`` draws, logging, prints, ``os.environ`` reads, telemetry
registry writes, and fault-point probes inside a jitted body are all
bugs of the same shape: they execute during tracing, bake one stale
value into the compiled program, and never run again — a timer that
measures the first call forever, a "random" draw that repeats every
step, a fault point that can never fire after warmup.  (In-program
randomness is ``jax.random`` with explicit keys; measurement belongs
outside the dispatch, on the host.)

Jitted bodies are found three ways: ``@jax.jit``-style decorations
(including ``functools.partial(jax.jit, ...)``), defs passed by name to
``jax.jit(...)`` anywhere in the same file, and defs NESTED inside
either (a ``loss_fn`` inside a jitted ``train_step`` traces with it).
"""
from __future__ import annotations

import ast
from typing import List, Set

from code2vec_tpu.analysis import taint
from code2vec_tpu.analysis.core import Finding, Rule, register
from code2vec_tpu.analysis.walker import SourceTree, dotted_name

# dotted-prefix ban list; matched against the full resolved chain so
# `jax.random.*` (fine) never collides with `np.random.*` (not fine)
_BANNED_PREFIXES = (
    ('time.', 'host clock read traces once and freezes'),
    ('np.random.', 'host RNG draws once at trace time — use jax.random '
                   'with an explicit key'),
    ('numpy.random.', 'host RNG draws once at trace time — use '
                      'jax.random with an explicit key'),
    ('random.', 'host RNG draws once at trace time — use jax.random '
                'with an explicit key'),
    ('os.environ', 'environment read bakes one value in at trace time'),
    ('logging.', 'logging executes at trace time only'),
    ('logger.', 'logging executes at trace time only'),
    ('tele_core.', 'telemetry registry access traces once — instrument '
                   'the dispatch site, not the program body'),
    ('telemetry.', 'telemetry registry access traces once — instrument '
                   'the dispatch site, not the program body'),
    ('faults.maybe_fire', 'fault probes trace once and never fire '
                          'again — probe at the dispatch site'),
)
_BANNED_BARE_CALLS = {
    'print': 'print executes at trace time only (use jax.debug.print)',
    'open': 'file I/O inside a traced body runs once, at trace time',
}


@register
class JitPurityRule(Rule):
    name = 'jit-purity'
    doc = ('no time/np.random/logging/os.environ/telemetry/fault-probe '
           'side effects inside jitted function bodies')
    scope = 'package'

    def run(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for source in tree.files(self.scope):
            if source.tree is None:
                continue
            jitted_roots = self._jitted_defs(source)
            for qual, node in jitted_roots:
                for finding in self._check_body(source, qual, node):
                    findings.append(finding)
        return findings

    # ------------------------------------------------------- discovery
    def _jitted_defs(self, source):
        """(qualname, def node) for every jitted root def in the file:
        decorated, or referenced by name in a jax.jit(...) call."""
        by_name = {}
        for info in source.functions:
            by_name.setdefault(info.node.name, []).append(info)
        roots = {}
        for info in source.functions:
            if any(taint._is_jit_decorator(d)
                   for d in info.node.decorator_list):
                roots[info.qualname] = info.node
        if source.tree is not None:
            for node in ast.walk(source.tree):
                # taint._is_jit_call covers every jit spelling the taint
                # pass knows (jax.jit / pjit / jax.experimental.pjit.pjit
                # / functools.partial(jax.jit, ...)(f)) — the two modules
                # must not disagree on what counts as jitted
                if isinstance(node, ast.Call) and \
                        taint._is_jit_call(node) and node.args:
                    ref = node.args[0]
                    if isinstance(ref, ast.Name):
                        for info in by_name.get(ref.id, ()):
                            roots[info.qualname] = info.node
        return sorted(roots.items())

    # ------------------------------------------------------------ check
    def _check_body(self, source, qual: str, func: ast.AST):
        # walk the WHOLE body including nested defs (they trace with
        # the root); decorators/defaults are evaluated eagerly at def
        # time, so they are exempt
        out: List[Finding] = []
        call_lines: Set[int] = set()
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            hit = self._banned(name)
            if hit is None and isinstance(node.func, ast.Name):
                why = _BANNED_BARE_CALLS.get(node.func.id)
                if why is not None:
                    hit = (node.func.id, why)
            if hit is not None:
                call_lines.add(node.lineno)
                out.append(self.finding(
                    source.rel, node.lineno,
                    'impure call `%s(...)` inside jitted `%s`: %s'
                    % (hit[0], qual, hit[1])))
        for node in ast.walk(func):
            # bare os.environ[...] reads with no call around them
            if isinstance(node, ast.Attribute) and \
                    dotted_name(node) == 'os.environ' and \
                    node.lineno not in call_lines:
                call_lines.add(node.lineno)
                out.append(self.finding(
                    source.rel, node.lineno,
                    'os.environ access inside jitted `%s`: environment '
                    'read bakes one value in at trace time' % qual))
        return out

    @staticmethod
    def _banned(name):
        if name is None:
            return None
        for prefix, why in _BANNED_PREFIXES:
            if name == prefix.rstrip('.') or name.startswith(prefix):
                return (name, why)
        return None
