"""graftlint: AST-based static enforcement of the repo's JAX invariants.

The performance and robustness wins in this tree rest on invariants
nothing used to enforce: warm-ladder shapes (zero steady-state
compiles), cataloged host syncs, donated staging buffers never read
back, pure jitted bodies, and lock-guarded cross-thread state.  This
package is the rule engine that makes those invariants fail tier-1
instead of regressing silently — ANALYSIS.md has the rule catalog, the
suppression/baseline workflow, and the guide to adding a rule.

Entry points: ``scripts/graftlint.py`` / ``scripts/lint_all.py`` (CLI),
``analysis.engine.run`` (in-process), ``tests/test_graftlint.py``
(tier-1 guard).  Dependency-free: the lint pass never imports jax.
"""
from code2vec_tpu.analysis.core import (Finding, Rule, all_rules,  # noqa: F401
                                        get_rules, register)
from code2vec_tpu.analysis.engine import Report, run  # noqa: F401
