"""Function-local taint analysis shared by the jit-invariant rules.

One ordered walk over a function body computes, per local name:

- **shape state** — UNTAINTED / TAINTED / WARM.  ``len(...)`` and
  ``.shape`` reads taint; passing through a warm-ladder source
  (``catalog.WARM_SHAPE_SOURCES``) launders to WARM.  Arithmetic
  combining a WARM value stays WARM (the ``bucket - n`` pad-to-bucket
  idiom); concatenating a WARM pad launders the result (the
  ``np.concatenate([x, zeros((bucket - n, d))])`` idiom).
- **device taint** — True when the value traces to a jitted program's
  output (``catalog.JIT_ENTRY_POINTS`` + per-file ``jax.jit`` bindings).
  ``np.asarray``/``float``/``int`` over a device value is a host sync.
- **program binding** — names holding a jitted program (assigned from a
  ``catalog.JIT_RETURNING`` method or a ``jax.jit(...)`` expression);
  calling one is a jit dispatch.

The walk is LEXICAL: statements are visited once, in source order, with
no branch joins or loop fixpoints.  That misses loop-carried flows and
cross-function flows by design — the rules trade soundness for zero
false-positive noise on idiomatic code, and ANALYSIS.md states the
blind spots.  Events (binds/loads/jit dispatches/syncs) are recorded
with a monotone sequence number so rules can reason about order
(read-after-donate).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from code2vec_tpu.analysis import catalog
from code2vec_tpu.analysis.walker import (assigned_names, dotted_name,
                                          terminal_name)

UNTAINTED, TAINTED, WARM = 0, 1, 2

# numpy/jnp constructors whose result SHAPE is their first argument
_ARRAY_CTORS = ('zeros', 'empty', 'ones', 'full', 'arange')
# combinators whose result shape merges the parts'
_ARRAY_JOINS = ('concatenate', 'stack', 'vstack', 'hstack')
# value-preserving methods: x.astype(...) etc. keep x's taint
_PASSTHROUGH_METHODS = ('astype', 'reshape', 'copy', 'items', 'values',
                        'keys', 'sum', 'max', 'min', 'mean')


class Value:
    __slots__ = ('shape', 'device', 'program')

    def __init__(self, shape: int = UNTAINTED, device: bool = False,
                 program: bool = False):
        self.shape = shape
        self.device = device
        self.program = program


def _merge(values) -> Value:
    out = Value()
    for v in values:
        out.shape = max(out.shape, v.shape)
        out.device = out.device or v.device
    return out


def _join_shapes(values) -> int:
    states = [v.shape for v in values]
    if WARM in states:
        return WARM  # a warm pad pins the joined result to the ladder
    if TAINTED in states:
        return TAINTED
    return UNTAINTED


class JitDispatch:
    """One call into a jitted program."""

    __slots__ = ('node', 'seq', 'callee', 'tainted_args', 'inline_jit')

    def __init__(self, node: ast.Call, seq: int, callee: str,
                 tainted_args: List[str], inline_jit: bool):
        self.node = node
        self.seq = seq
        self.callee = callee
        self.tainted_args = tainted_args  # descriptions of TAINTED args
        self.inline_jit = inline_jit      # jax.jit(...)(...) at call time


class SyncSite:
    """One host synchronization (host-sync rule)."""

    __slots__ = ('node', 'kind')

    def __init__(self, node: ast.Call, kind: str):
        self.node = node
        self.kind = kind


class FunctionTaint(ast.NodeVisitor):
    """Ordered walk of ONE function body (nested defs are skipped —
    they get their own analysis)."""

    def __init__(self, func: ast.AST, extra_jitted: Set[str]):
        self.env: Dict[str, Value] = {}
        self.seq = 0
        self.jitted_names = (set(catalog.JIT_ENTRY_POINTS)
                             | set(extra_jitted))
        self.dispatches: List[JitDispatch] = []
        self.syncs: List[SyncSite] = []
        # name -> ordered [(seq, 'bind'|'load', lineno, node)]
        self.events: Dict[str, List[Tuple[int, str, int, ast.AST]]] = {}
        self._root = func
        for stmt in func.body:
            self._stmt(stmt)

    # ------------------------------------------------------------ events
    def _tick(self) -> int:
        self.seq += 1
        return self.seq

    def _event(self, name: str, kind: str, lineno: int,
               node: Optional[ast.AST] = None) -> None:
        self.events.setdefault(name, []).append(
            (self._tick(), kind, lineno, node))

    def _bind(self, target: ast.AST, value: Value) -> None:
        for name, node in assigned_names(target):
            if isinstance(node, ast.Name):
                self.env[name] = Value(value.shape, value.device,
                                       value.program)
                self._event(name, 'bind', node.lineno, node)

    # -------------------------------------------------------- statements
    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes analyzed separately
        if isinstance(stmt, ast.Assign):
            value = self._expr(stmt.value)
            for target in stmt.targets:
                self._bind(target, value)
        elif isinstance(stmt, ast.AnnAssign):
            value = (self._expr(stmt.value) if stmt.value is not None
                     else Value())
            self._bind(stmt.target, value)
        elif isinstance(stmt, ast.AugAssign):
            value = self._expr(stmt.value)
            prior = (self.env.get(stmt.target.id, Value())
                     if isinstance(stmt.target, ast.Name) else Value())
            self._bind(stmt.target, _merge((value, prior)))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            value = self._expr(stmt.iter)
            self._bind(stmt.target, value)  # element ~ iterable taint
            for child in stmt.body + stmt.orelse:
                self._stmt(child)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, Value())
            for child in stmt.body:
                self._stmt(child)
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test)
            for child in stmt.body + stmt.orelse:
                self._stmt(child)
        elif isinstance(stmt, (ast.While,)):
            self._expr(stmt.test)
            for child in stmt.body + stmt.orelse:
                self._stmt(child)
        elif isinstance(stmt, ast.Try):
            for child in (stmt.body + stmt.orelse + stmt.finalbody):
                self._stmt(child)
            for handler in stmt.handlers:
                for child in handler.body:
                    self._stmt(child)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self._expr(stmt.value)
        elif isinstance(stmt, (ast.Assert, ast.Raise, ast.Delete)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child)
        # pass/break/continue/import/global/nonlocal: nothing to track

    # ------------------------------------------------------- expressions
    def _expr(self, node: ast.expr) -> Value:
        if isinstance(node, ast.Name):
            value = self.env.get(node.id, Value())
            if isinstance(node.ctx, ast.Load):
                self._event(node.id, 'load', node.lineno, node)
            return value
        if isinstance(node, ast.Attribute):
            base = self._expr(node.value)
            if node.attr == 'shape':
                return Value(TAINTED, False)
            return Value(base.shape, base.device)
        if isinstance(node, ast.Subscript):
            base = self._expr(node.value)
            self._expr(node.slice)
            return Value(base.shape, base.device)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            left, right = self._expr(node.left), self._expr(node.right)
            return Value(_join_shapes((left, right)))
        if isinstance(node, ast.UnaryOp):
            return Value(self._expr(node.operand).shape)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return _merge([self._expr(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            return _merge([self._expr(v) for v in node.values
                           if v is not None])
        if isinstance(node, ast.IfExp):
            self._expr(node.test)
            return _merge([self._expr(node.body), self._expr(node.orelse)])
        if isinstance(node, ast.BoolOp):
            return _merge([self._expr(v) for v in node.values])
        if isinstance(node, ast.Compare):
            self._expr(node.left)
            for comp in node.comparators:
                self._expr(comp)
            return Value()
        if isinstance(node, ast.Starred):
            return self._expr(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._comprehension(node)
        if isinstance(node, ast.Lambda):
            return Value()
        if isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    self._expr(part.value)
            return Value()
        if isinstance(node, ast.FormattedValue):
            self._expr(node.value)
            return Value()
        return Value()  # constants and the rest

    def _comprehension(self, node) -> Value:
        for gen in node.generators:
            self._bind(gen.target, self._expr(gen.iter))
            for cond in gen.ifs:
                self._expr(cond)
        if isinstance(node, ast.DictComp):
            self._expr(node.key)
            return self._expr(node.value)
        return self._expr(node.elt)

    # -------------------------------------------------------------- calls
    def _describe_arg(self, arg: ast.expr) -> str:
        name = dotted_name(arg)
        if name is not None:
            return name
        return '<%s at line %d>' % (type(arg).__name__, arg.lineno)

    def _call(self, node: ast.Call) -> Value:
        func = node.func
        dotted = dotted_name(func)
        term = terminal_name(func)

        # --- host syncs by name -------------------------------------
        if dotted in ('jax.device_get', 'device_get'):
            for arg in node.args:
                self._expr(arg)
            self.syncs.append(SyncSite(node, 'device_get'))
            return Value()  # host value
        if dotted in ('jax.block_until_ready',) or \
                term == 'block_until_ready':
            base = _merge([self._expr(arg) for arg in node.args])
            if isinstance(func, ast.Attribute) and \
                    term == 'block_until_ready':
                base = _merge((base, self._expr(func.value)))
            self.syncs.append(SyncSite(node, 'block_until_ready'))
            return base  # returns its (still-device) argument
        if term == 'item' and isinstance(func, ast.Attribute) and \
                not node.args:
            self._expr(func.value)
            self.syncs.append(SyncSite(node, 'item'))
            return Value()

        # keep the value-expression nodes parallel to their states so
        # keyword arguments participate in the dispatch taint check —
        # `program(x=pad)` is the same hazard as `program(pad)`
        arg_nodes = list(node.args) + [kw.value for kw in node.keywords]
        args = [self._expr(n) for n in arg_nodes]

        # --- device fetches (sync iff the value is a jit output) ----
        if dotted in ('np.asarray', 'numpy.asarray', 'np.array',
                      'numpy.array') or \
                (func_is_builtin(func, 'float') or
                 func_is_builtin(func, 'int')):
            if args and args[0].device:
                self.syncs.append(SyncSite(node, 'fetch'))
            return Value(args[0].shape if args else UNTAINTED, False)

        # --- shape sources ------------------------------------------
        if func_is_builtin(func, 'len'):
            return Value(TAINTED)
        if term in catalog.WARM_SHAPE_SOURCES:
            return Value(WARM)
        if term in _ARRAY_CTORS:
            return Value(args[0].shape if args else UNTAINTED)
        if term in _ARRAY_JOINS:
            return Value(_join_shapes(args) if args else UNTAINTED)

        # --- jit program construction / dispatch --------------------
        if dotted in ('jax.jit', 'pjit', 'jax.experimental.pjit.pjit'):
            return Value(program=True)
        if term in catalog.JIT_RETURNING:
            return Value(program=True)
        inline_jit = False
        is_dispatch = False
        if isinstance(func, ast.Call):
            inner = self._expr(func)  # evaluates the program-maker call
            if inner.program:
                is_dispatch = True
                inner_dotted = dotted_name(func.func)
                inline_jit = inner_dotted in (
                    'jax.jit', 'pjit', 'jax.experimental.pjit.pjit')
        elif isinstance(func, ast.Name) and \
                self.env.get(func.id, Value()).program:
            is_dispatch = True
            self._event(func.id, 'load', func.lineno, func)
        elif term in self.jitted_names:
            is_dispatch = True
        if is_dispatch:
            tainted = [self._describe_arg(arg)
                       for arg, value in zip(arg_nodes, args)
                       if value.shape == TAINTED]
            self.dispatches.append(JitDispatch(
                node, self._tick(),
                dotted or term or '<call>', tainted, inline_jit))
            return Value(device=True)

        # --- passthrough methods ------------------------------------
        if isinstance(func, ast.Attribute) and \
                term in _PASSTHROUGH_METHODS:
            base = self._expr(func.value)
            return Value(base.shape, base.device)
        if isinstance(func, ast.Attribute):
            self._expr(func.value)
        return Value()


def analyze_file(source):
    """[(FunctionInfo, FunctionTaint)] for every function in a file,
    computed once and cached on the SourceFile — three rules consume
    the taint pass, and the walker's one-parse contract extends to it."""
    cache = getattr(source, '_taint_analysis', None)
    if cache is None:
        extra = (file_jitted_bindings(source.tree)
                 if source.tree is not None else set())
        cache = [(info, FunctionTaint(info.node, extra))
                 for info in source.functions]
        source._taint_analysis = cache
    return cache


def func_is_builtin(func: ast.expr, name: str) -> bool:
    return isinstance(func, ast.Name) and func.id == name


def file_jitted_bindings(tree: ast.Module) -> Set[str]:
    """Terminal names bound to ``jax.jit(...)`` / ``pjit(...)`` results
    anywhere in a file (``self._train_step = jax.jit(...)``,
    ``program = jax.jit(run)``, ``_streamed_program = jax.jit(...)``),
    plus defs decorated with jit."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            if _is_jit_call(node.value):
                for target in node.targets:
                    for name, _t in assigned_names(target):
                        out.add(name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _is_jit_decorator(deco):
                    out.add(node.name)
    return out


def _is_jit_call(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = dotted_name(node.func)
    if dotted in ('jax.jit', 'pjit', 'jax.experimental.pjit.pjit'):
        return True
    # functools.partial(jax.jit, ...)(f) shape
    if isinstance(node.func, ast.Call):
        return _is_jit_decorator(node.func)
    return False


def _is_jit_decorator(deco: ast.expr) -> bool:
    dotted = dotted_name(deco)
    if dotted in ('jax.jit', 'pjit', 'jax.experimental.pjit.pjit'):
        return True
    if isinstance(deco, ast.Call):
        deco_name = dotted_name(deco.func)
        if deco_name in ('jax.jit', 'pjit', 'jax.experimental.pjit.pjit'):
            return True
        if deco_name in ('functools.partial', 'partial') and deco.args:
            return dotted_name(deco.args[0]) in (
                'jax.jit', 'pjit', 'jax.experimental.pjit.pjit')
    return False
