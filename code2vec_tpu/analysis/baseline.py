"""Reviewed baseline: findings accepted as-is, with a reason each.

``graftlint_baseline.json`` at the repo root::

    {"entries": [
      {"rule": "recompile-hazard", "file": "code2vec_tpu/checkpoints.py",
       "message": "...exact finding message...",
       "reason": "restore-path one-shot: compiles once per restore"}
    ]}

Matching is on ``(rule, file, message)`` — deliberately line-free, so
entries survive unrelated edits that shift line numbers.  Two
meta-findings keep the file honest:

- a **bare** entry (missing/empty ``reason``) is a finding — the
  baseline documents judgment calls, it is not a mute button;
- a **stale** entry (matching no current finding) is a finding — fixed
  code must shed its baseline line in the same PR, or the baseline rots
  into a list of ghosts that mask regressions at the same site.

``--write-baseline`` emits entries with ``reason: "TODO"`` which then
fail the bare-entry check: regenerating the file cannot silently launder
new findings past review.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from code2vec_tpu.analysis.core import Finding
from code2vec_tpu.analysis.suppress import META_RULE

BASELINE_NAME = 'graftlint_baseline.json'


class Baseline:
    def __init__(self, entries: List[dict], path: str = BASELINE_NAME):
        self.entries = entries
        self.path = path

    @classmethod
    def load(cls, path: str) -> 'Baseline':
        rel = os.path.basename(path)
        if not os.path.isfile(path):
            return cls([], rel)
        with open(path, 'r') as f:
            data = json.load(f)
        return cls(list(data.get('entries', [])), rel)

    def restricted_to(self, rule_names) -> 'Baseline':
        """The baseline as seen by a run of only ``rule_names``: entries
        for rules that did not run are neither matchable nor stale (a
        ``--rules host-sync`` run must not report another rule's
        entries as stale)."""
        names = set(rule_names)
        return Baseline([e for e in self.entries
                         if e.get('rule') in names], self.path)

    def problems(self) -> List[Finding]:
        """Structural issues: bare entries, duplicate keys."""
        out: List[Finding] = []
        seen: Dict[Tuple[str, str, str], int] = {}
        for i, entry in enumerate(self.entries):
            key = (entry.get('rule', ''), entry.get('file', ''),
                   entry.get('message', ''))
            if not all(key):
                out.append(Finding(
                    META_RULE, self.path, 0,
                    'baseline entry %d is missing rule/file/message' % i))
                continue
            if not str(entry.get('reason', '')).strip() \
                    or entry.get('reason') == 'TODO':
                out.append(Finding(
                    META_RULE, self.path, 0,
                    'bare baseline entry (no reason) for [%s] %s: %s'
                    % (key[0], key[1], key[2])))
            if key in seen:
                out.append(Finding(
                    META_RULE, self.path, 0,
                    'duplicate baseline entry for [%s] %s: %s'
                    % (key[0], key[1], key[2])))
            seen[key] = i
        return out

    def apply(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[Finding]]:
        """(kept, baselined, stale-entry findings)."""
        keys = {}
        for entry in self.entries:
            key = (entry.get('rule', ''), entry.get('file', ''),
                   entry.get('message', ''))
            if all(key):
                keys[key] = False
        kept: List[Finding] = []
        baselined: List[Finding] = []
        for finding in findings:
            if finding.key() in keys:
                keys[finding.key()] = True
                baselined.append(finding)
            else:
                kept.append(finding)
        stale = [Finding(META_RULE, self.path, 0,
                         'stale baseline entry (no longer found) for '
                         '[%s] %s: %s' % key)
                 for key, matched in keys.items() if not matched]
        return kept, baselined, stale


def write(path: str, findings: List[Finding],
          existing: Optional[Baseline] = None,
          preserve: Sequence[dict] = ()) -> None:
    """Regenerate the baseline from current findings, keeping reasons of
    entries that still match; new entries get reason 'TODO' (which fails
    the bare-entry check until a human fills it in).  ``preserve``
    carries entries to keep verbatim — the entries of rules a
    ``--rules``-subset run did NOT run, whose reviewed reasons must
    survive the rewrite."""
    reasons = {}
    if existing is not None:
        for entry in existing.entries:
            key = (entry.get('rule', ''), entry.get('file', ''),
                   entry.get('message', ''))
            reasons[key] = entry.get('reason', 'TODO')
    entries = []
    seen = set()
    for entry in preserve:
        key = (entry.get('rule', ''), entry.get('file', ''),
               entry.get('message', ''))
        if all(key) and key not in seen:
            seen.add(key)
            entries.append(dict(entry))
    for finding in sorted(findings, key=lambda f: (f.rule, f.file,
                                                   f.line)):
        key = finding.key()
        if key in seen:
            continue
        seen.add(key)
        entries.append({'rule': finding.rule, 'file': finding.file,
                        'message': finding.message,
                        'reason': reasons.get(key, 'TODO')})
    with open(path, 'w') as f:
        json.dump({'entries': entries}, f, indent=2, sort_keys=False)
        f.write('\n')
