"""Compact wire format for path-context batches ("packed", format v2).

The plane format ships six padded arrays per batch — source/path/target
``(B, C)`` int32, mask ``(B, C)`` float32, label/weight ``(B,)`` — 16
bytes for every context SLOT whether or not it holds a context. At the
java14m corpus shape most of the 200 slots per example are padding
(contexts/method p50 is 28, benchmarks/results/corpus_stats_r4.json), so
on a transfer-bound link (PERF.md: 246 ms to upload one 3.3 MB batch vs
a 49 ms device step through this environment's tunnel) the wire is
mostly zeros.

The packed format densifies each example's leading ``length`` context
slots — ``length`` = index of the LAST valid context + 1 — into a
contiguous stream of ``(source, path, target)`` int32 triples:

  ctx     (data_shards, capacity, 3) int32 — per-shard dense triples,
          tail-padded with (token_pad, path_pad, token_pad)
  count   (B,) int32   — per-example effective lengths
  label   (B,) int32
  weight  (B,) float32

12 bytes per RETAINED slot + 12 bytes per example. Keeping everything up
to the last valid slot (not only the mask-valid slots) is what makes the
round trip BIT-exact: an interior all-PAD hole (e.g. a ``,,`` context in
the source file) stays in the stream at its position, and every slot
past ``length`` is provably the PAD triple, so scattering the stream
back and filling the tail with PAD reproduces the v1 planes — and the
mask, recomputed from them with the same parity-critical predicate
(reader.context_valid_mask) — exactly.

Sharding-awareness: with ``data_shards > 1`` each data-parallel shard's
examples are packed into its own ``capacity`` rows, so the staged
``ctx`` array shards over the mesh data axis on its leading dim and each
device receives exactly its shard's bytes (parallel/mesh.py
shard_batch). All shards share one bucketed capacity so the array stays
rectangular.

``capacity`` is bucketed (``bucketed_capacity``) so the jitted unpack +
step program specializes on a handful of capacities per run instead of
one per batch.

Host-side code here is pure numpy; the device unpack imports jax lazily
so the data layer stays importable without it.
"""
from __future__ import annotations

import time as _time
from typing import NamedTuple, Optional, Tuple

import numpy as np

WIRE_FORMATS = ('planes', 'packed')

# Floor for the bucketed capacity. Small enough that tiny (test/smoke)
# batches still see a byte win; large batches are governed by the
# total/8 bucket below.
MIN_CAPACITY = 64


class PackedBatch(NamedTuple):
    """One device-ready batch in the packed wire format. Mirrors
    ``reader.Batch``'s host-only string ride-alongs (eval/predict)."""
    ctx: np.ndarray                  # (D, cap, 3) int32 — see module doc
    count: np.ndarray                # (B,) int32 — effective lengths
    label: np.ndarray                # (B,) int32 — target-name index
    weight: np.ndarray               # (B,) float32 — example validity
    label_strings: Optional[np.ndarray] = None     # (B,) object
    source_strings: Optional[np.ndarray] = None    # (B, C) object
    path_strings: Optional[np.ndarray] = None      # (B, C) object
    target_strings: Optional[np.ndarray] = None    # (B, C) object

    @property
    def num_valid_examples(self) -> int:
        return int(self.weight.sum())

    def device_arrays(self):
        """The arrays the jitted packed step functions consume, in a
        fixed order (the host-only strings never ship)."""
        return (self.ctx, self.count, self.label, self.weight)


def wire_bytes(batch) -> int:
    """Bytes this batch puts on the host->device wire (either format)."""
    return int(sum(np.asarray(a).nbytes for a in batch.device_arrays()))


def bucketed_capacity(total: int, minimum: int = MIN_CAPACITY) -> int:
    """Round a context total up to a bucket of ~total/8 (power of two),
    bounding both the padding waste (<12.5%) and the number of distinct
    jit specializations per run (a handful: totals cluster per corpus)."""
    cap = max(int(total), minimum)
    bucket = max(minimum, 1 << max(cap.bit_length() - 3, 0))
    return -(-cap // bucket) * bucket


def capacity_ladder(max_total: int, minimum: int = MIN_CAPACITY,
                    growth: int = 4) -> Tuple[int, ...]:
    """Fixed geometric ladder of packed capacities covering ``max_total``.

    The serving engine (serving/engine.py) pre-compiles one step program
    per rung at load, so steady-state packing always lands on a warm
    capacity — the eager-compile counterpart of ``StickyPacker``'s
    grow-on-demand bucketing (which trades a few mid-run recompiles for
    tighter fill during training). ``growth=4`` bounds the rung count to
    ~log4(max_total/minimum)+1 programs per batch bucket while keeping
    worst-case padding waste under the previous rung's 4x.

    Every rung is exact under ``pack_ragged(..., capacity_minimum=rung)``
    for totals <= rung (``bucketed_capacity`` returns its minimum
    unchanged), so picking the first rung >= the shard total yields a
    wire shape that is always one of the pre-compiled ladder shapes."""
    if max_total < 1:
        raise ValueError('max_total must be >= 1, got %d' % max_total)
    if growth < 2:
        raise ValueError('growth must be >= 2, got %d' % growth)
    rungs = []
    cap = minimum
    while cap < max_total:
        rungs.append(cap)
        cap *= growth
    rungs.append(max(max_total, minimum))
    return tuple(rungs)


def shard_totals(count: np.ndarray, data_shards: int) -> np.ndarray:
    """(data_shards,) int64 of retained-context totals per data-parallel
    shard — the quantity the packed capacity must cover (pack_ragged's
    internal reshape, exposed for callers that pick a capacity BEFORE
    packing, e.g. the serving engine's ladder lookup)."""
    n = count.shape[0]
    if n % data_shards:
        raise ValueError('batch size %d not divisible by data_shards %d'
                         % (n, data_shards))
    return count.reshape(data_shards, n // data_shards).sum(
        axis=1, dtype=np.int64)


def effective_lengths(mask: np.ndarray) -> np.ndarray:
    """(B,) int32 of per-example effective lengths: index of the last
    mask-valid slot + 1, or 0 for all-padding rows."""
    valid = mask > 0
    any_valid = valid.any(axis=1)
    last = mask.shape[1] - np.argmax(valid[:, ::-1], axis=1)
    return np.where(any_valid, last, 0).astype(np.int32)


def ragged_gather_indices(lengths: np.ndarray, stride: int) -> np.ndarray:
    """Flat indices selecting slots [0, lengths[r]) of each row r from a
    row-major (B, stride) array."""
    total = int(lengths.sum())
    starts = np.cumsum(lengths) - lengths
    intra = np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)
    return np.repeat(np.arange(lengths.shape[0], dtype=np.int64) * stride,
                     lengths) + intra


def pack_ragged(ctx_rows: np.ndarray, count: np.ndarray, token_pad: int,
                path_pad: int, data_shards: int = 1,
                capacity_minimum: int = MIN_CAPACITY) -> np.ndarray:
    """(total, 3) ragged triple stream + per-example counts -> the
    rectangular (data_shards, capacity, 3) wire array."""
    totals = shard_totals(count, data_shards)
    cap = bucketed_capacity(int(totals.max(initial=0)), capacity_minimum)
    ctx = np.empty((data_shards, cap, 3), np.int32)
    ctx[..., 0] = token_pad
    ctx[..., 1] = path_pad
    ctx[..., 2] = token_pad
    bounds = np.concatenate([[0], np.cumsum(totals)])
    for d in range(data_shards):
        ctx[d, :totals[d]] = ctx_rows[bounds[d]:bounds[d + 1]]
    return ctx


def ragged_from_planes(source: np.ndarray, path: np.ndarray,
                       target: np.ndarray, mask: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Plane arrays -> ((total, 3) int32 triple stream, (B,) effective
    lengths) — the single definition of the wire/cache triple layout."""
    lengths = effective_lengths(mask)
    flat = ragged_gather_indices(lengths, source.shape[1])
    return np.stack([source.ravel()[flat], path.ravel()[flat],
                     target.ravel()[flat]],
                    axis=1).astype(np.int32, copy=False), lengths


def pack_batch(batch, token_pad: int, path_pad: int, data_shards: int = 1,
               capacity_minimum: int = MIN_CAPACITY) -> PackedBatch:
    """reader.Batch (plane format) -> PackedBatch. Host-only string
    fields ride along untouched."""
    ctx_rows, lengths = ragged_from_planes(batch.source, batch.path,
                                           batch.target, batch.mask)
    ctx = pack_ragged(ctx_rows, lengths, token_pad, path_pad, data_shards,
                      capacity_minimum)
    return PackedBatch(ctx=ctx, count=lengths,
                       label=np.ascontiguousarray(batch.label),
                       weight=np.ascontiguousarray(batch.weight),
                       label_strings=batch.label_strings,
                       source_strings=batch.source_strings,
                       path_strings=batch.path_strings,
                       target_strings=batch.target_strings)


class StickyPacker:
    """Packs a stream of batches under a monotonically GROWING capacity:
    totals that straddle a bucket boundary reuse the larger jitted
    program instead of ping-ponging specializations. One instance per
    data source (reader / cache), living across epochs.

    Instrumented (telemetry enabled only — one bool read otherwise):
    pack time (``step/pack_ms``, recorded from whichever reader/prefetch
    thread packs) and the packed fill rate (retained slots / wire
    capacity — the padding waste the capacity buckets trade for fewer
    jit specializations)."""

    def __init__(self, token_pad: int, path_pad: int, data_shards: int = 1,
                 minimum: int = MIN_CAPACITY):
        self.token_pad = token_pad
        self.path_pad = path_pad
        self.data_shards = data_shards
        self.capacity = minimum

    @staticmethod
    def _record(seconds: float, ctx: np.ndarray, retained: int) -> None:
        from code2vec_tpu.telemetry import core
        reg = core.registry()
        reg.timer('step/pack_ms').record(seconds)
        slots = int(ctx.shape[0]) * int(ctx.shape[1])
        reg.gauge('input/packed_fill_rate').set(retained / max(slots, 1))

    def pack_batch(self, batch) -> PackedBatch:
        from code2vec_tpu.telemetry import core
        t0 = _time.perf_counter() if core.enabled() else 0.0
        packed = pack_batch(batch, self.token_pad, self.path_pad,
                            data_shards=self.data_shards,
                            capacity_minimum=self.capacity)
        self.capacity = max(self.capacity, packed.ctx.shape[1])
        if core.enabled():
            self._record(_time.perf_counter() - t0, packed.ctx,
                         int(packed.count.sum()))
        return packed

    def pack_ragged(self, ctx_rows: np.ndarray,
                    count: np.ndarray) -> np.ndarray:
        from code2vec_tpu.telemetry import core
        t0 = _time.perf_counter() if core.enabled() else 0.0
        ctx = pack_ragged(ctx_rows, count, self.token_pad, self.path_pad,
                          self.data_shards, capacity_minimum=self.capacity)
        self.capacity = max(self.capacity, ctx.shape[1])
        if core.enabled():
            self._record(_time.perf_counter() - t0, ctx,
                         int(count.sum()))
        return ctx


def unpack_ragged_np(ctx_rows: np.ndarray, count: np.ndarray,
                     max_contexts: int, token_pad: int, path_pad: int
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(total, 3) triple stream + counts -> PAD-filled (B, C) planes."""
    n = count.shape[0]
    flat = ragged_gather_indices(count.astype(np.int64), max_contexts)
    planes = []
    for column, fill in ((0, token_pad), (1, path_pad), (2, token_pad)):
        plane = np.full((n * max_contexts,), fill, np.int32)
        plane[flat] = ctx_rows[:, column]
        planes.append(plane.reshape(n, max_contexts))
    return planes[0], planes[1], planes[2]


def unpack_batch_host(packed: PackedBatch, max_contexts: int,
                      token_pad: int, path_pad: int):
    """Numpy reference inverse of ``pack_batch`` — the ground truth the
    device unpack is property-tested against, and the planes-emission
    path for v2 token caches read under the planes wire format."""
    from code2vec_tpu.data.reader import Batch, context_valid_mask
    shards, cap, _ = packed.ctx.shape
    count2 = packed.count.reshape(shards, -1)
    keep = ragged_gather_indices(
        count2.sum(axis=1, dtype=np.int64).astype(np.int64), cap)
    ctx_rows = packed.ctx.reshape(shards * cap, 3)[keep]
    source, path, target = unpack_ragged_np(
        ctx_rows, packed.count, max_contexts, token_pad, path_pad)
    mask = context_valid_mask(source, path, target, token_pad, path_pad)
    return Batch(source=source, path=path, target=target, mask=mask,
                 label=packed.label, weight=packed.weight,
                 label_strings=packed.label_strings,
                 source_strings=packed.source_strings,
                 path_strings=packed.path_strings,
                 target_strings=packed.target_strings)


def segment_structure(count2, cap: int):
    """Segment structure of the packed stream, per shard — THE single
    definition of the parity-critical slot->example arithmetic, shared
    by the device unpack below and the ragged fused encoder
    (ops/pallas_ragged.py).

    ``count2`` is the ``(data_shards, per_shard)`` per-example lengths
    (a device array inside jit); returns ``(seg, pos, in_range)``, each
    ``(data_shards, cap)``:

    - ``seg``: segment ids — +1 at each example's start offset,
      cumsummed; repeated starts (zero-length examples) accumulate, and
      slots past the shard's retained total all map to the LAST example
      (the unpack scatters them onto its PAD tail; the fused encoder
      masks them via ``in_range``). The inc row index must be shaped
      like ``starts[:, 1:]`` — (D, Bs-1), NOT a slice of the (D, cap)
      grid: per-shard batch can exceed capacity.
    - ``pos``: the slot's position within its example — its plane
      column (past-the-count for capacity padding).
    - ``in_range``: slot < the shard's retained total (capacity padding
      is not).
    """
    import jax.numpy as jnp

    shards, per_shard = count2.shape
    starts = jnp.cumsum(count2, axis=1) - count2            # (D, Bs)
    inc = jnp.zeros((shards, cap), jnp.int32)
    if per_shard > 1:
        row_idx = jnp.broadcast_to(
            jnp.arange(shards, dtype=jnp.int32)[:, None],
            (shards, per_shard - 1))
        inc = inc.at[row_idx, starts[:, 1:]].add(1, mode='drop')
    seg = jnp.cumsum(inc, axis=1)                           # (D, cap)
    pos = (jnp.arange(cap, dtype=jnp.int32)[None, :]
           - jnp.take_along_axis(starts, seg, axis=1))      # (D, cap)
    in_range = (jnp.arange(cap, dtype=jnp.int32)[None, :]
                < count2.sum(axis=1)[:, None])              # (D, cap)
    return seg, pos, in_range


def unpack_device(ctx, count, max_contexts: int, token_pad: int,
                  path_pad: int):
    """Jitted device-side inverse of ``pack_batch``: segment-scatter the
    dense triples back to the exact (B, C) planes + mask the model
    consumes.

    Shard-structured: every op batches along the leading ``data_shards``
    dim that the mesh data axis shards, so GSPMD partitions the unpack
    per shard. Capacity-padding rows hold the PAD triple and land either
    on out-of-range slots (dropped) or on tail slots whose expected
    value IS the PAD fill — bit-exactness is unconditional (property-
    tested against ``unpack_batch_host`` in tests/test_packed.py).

    The mask predicate mirrors reader.context_valid_mask — the
    parity-critical single definition for the host side; keep in sync.
    """
    import jax.numpy as jnp

    shards, cap, _ = ctx.shape
    batch = count.shape[0]
    per_shard = batch // shards
    count2 = count.reshape(shards, per_shard)
    seg, pos, _in_range = segment_structure(count2, cap)
    shard_idx = jnp.broadcast_to(
        jnp.arange(shards, dtype=jnp.int32)[:, None], (shards, cap))

    def scatter(vals, fill):
        out = jnp.full((shards, per_shard, max_contexts), fill, jnp.int32)
        out = out.at[shard_idx, seg, pos].set(vals, mode='drop')
        return out.reshape(batch, max_contexts)

    source = scatter(ctx[..., 0], token_pad)
    path = scatter(ctx[..., 1], path_pad)
    target = scatter(ctx[..., 2], token_pad)
    mask = ((source != token_pad) | (target != token_pad)
            | (path != path_pad)).astype(jnp.float32)
    return source, path, target, mask
