"""Offline dataset production: raw extractor output → ``.c2v`` + ``.dict.c2v``.

Replaces both the reference's awk histogram pass (preprocess.sh:55-58) and its
``preprocess.py`` sampling/padding pass (:23-74) with one Python module (the
histogram pass is plain counting; the native extractor can also emit
histograms directly).

Semantics preserved exactly:

- per-split context truncation to ``max_contexts`` with vocab-aware sampling:
  prefer contexts whose three parts are all in-vocab ('full found'), then
  those with any part in-vocab ('partial found'), random-sampling within a
  tier (reference preprocess.py:41-56);
- rows with zero contexts are dropped (:58-60);
- rows are padded with trailing spaces to exactly ``max_contexts`` fields
  (:64-65) so files are byte-layout compatible with reference readers;
- ``.dict.c2v`` = sequential pickles of word/path/target→count dicts +
  train example count (:12-20).
"""
from __future__ import annotations

import pickle
import random
from argparse import ArgumentParser
from collections import Counter
from typing import Dict, Iterable, Optional, Tuple

from code2vec_tpu import common


def build_histograms(raw_path: str) -> Tuple[Counter, Counter, Counter]:
    """Count target names (field 1), origin tokens (ctx fields 1 and 3) and
    paths (ctx field 2) over a raw extractor output file — the reference did
    this with three awk one-liners (preprocess.sh:55-58)."""
    target_count: Counter = Counter()
    token_count: Counter = Counter()
    path_count: Counter = Counter()
    with open(raw_path, 'r') as file:
        for line in file:
            parts = line.rstrip('\n').split(' ')
            if not parts or not parts[0]:
                continue
            target_count[parts[0]] += 1
            for ctx in parts[1:]:
                if not ctx:
                    continue
                pieces = ctx.split(',')
                if len(pieces) != 3:
                    continue
                token_count[pieces[0]] += 1
                path_count[pieces[1]] += 1
                token_count[pieces[2]] += 1
    return token_count, path_count, target_count


def save_histogram(counter: Counter, path: str) -> None:
    """``word count`` lines, most-common first (awk output is unsorted, but
    readers don't depend on order — common.load_histogram re-sorts by count)."""
    with open(path, 'w') as f:
        for word, count in counter.most_common():
            f.write('{} {}\n'.format(word, count))


truncate_to_max_size = common.truncate_histogram_to_max_size


def _context_full_found(parts, word_to_count, path_to_count) -> bool:
    return (parts[0] in word_to_count and parts[1] in path_to_count
            and parts[2] in word_to_count)


def _context_partial_found(parts, word_to_count, path_to_count) -> bool:
    return (parts[0] in word_to_count or parts[1] in path_to_count
            or parts[2] in word_to_count)


def process_file(file_path: str, data_file_role: str, dataset_name: str,
                 word_to_count: Dict[str, int], path_to_count: Dict[str, int],
                 max_contexts: int, rng: Optional[random.Random] = None) -> int:
    """Vocab-aware truncation + space padding for one split
    (reference preprocess.py:23-74). Returns the number of kept examples."""
    rng = rng or random
    sum_total = sum_sampled = total = empty = max_unfiltered = 0
    output_path = '{}.{}.c2v'.format(dataset_name, data_file_role)
    with open(output_path, 'w') as outfile, open(file_path, 'r') as file:
        for line in file:
            parts = line.rstrip('\n').split(' ')
            target_name = parts[0]
            contexts = parts[1:]
            max_unfiltered = max(max_unfiltered, len(contexts))
            sum_total += len(contexts)
            if len(contexts) > max_contexts:
                context_parts = [c.split(',') for c in contexts]
                full = [c for i, c in enumerate(contexts)
                        if _context_full_found(context_parts[i],
                                               word_to_count, path_to_count)]
                partial = [c for i, c in enumerate(contexts)
                           if _context_partial_found(context_parts[i],
                                                     word_to_count, path_to_count)
                           and not _context_full_found(context_parts[i],
                                                       word_to_count,
                                                       path_to_count)]
                if len(full) > max_contexts:
                    contexts = rng.sample(full, max_contexts)
                elif len(full) + len(partial) > max_contexts:
                    contexts = full + rng.sample(partial,
                                                 max_contexts - len(full))
                else:
                    contexts = full + partial
            if len(contexts) == 0:
                empty += 1
                continue
            sum_sampled += len(contexts)
            csv_padding = ' ' * (max_contexts - len(contexts))
            outfile.write(target_name + ' ' + ' '.join(contexts)
                          + csv_padding + '\n')
            total += 1
    print('File: ' + file_path)
    if total:
        print('Average total contexts: ' + str(float(sum_total) / total))
        print('Average final (after sampling) contexts: '
              + str(float(sum_sampled) / total))
    print('Total examples: ' + str(total))
    print('Empty examples: ' + str(empty))
    print('Max number of contexts per word: ' + str(max_unfiltered))
    return total


def save_dictionaries(dataset_name: str, word_to_count: Dict[str, int],
                      path_to_count: Dict[str, int],
                      target_to_count: Dict[str, int],
                      num_training_examples: int) -> None:
    """Sequential-pickle layout of ``.dict.c2v``
    (reference preprocess.py:12-20)."""
    save_path = '{}.dict.c2v'.format(dataset_name)
    with open(save_path, 'wb') as file:
        pickle.dump(word_to_count, file)
        pickle.dump(path_to_count, file)
        pickle.dump(target_to_count, file)
        pickle.dump(num_training_examples, file)
    print('Dictionaries saved to: {}'.format(save_path))


def preprocess_dataset(train_raw: str, val_raw: str, test_raw: str,
                       output_name: str, max_contexts: int = 200,
                       word_vocab_size: int = 1301136,
                       path_vocab_size: int = 911417,
                       target_vocab_size: int = 261245,
                       word_histogram: Optional[str] = None,
                       path_histogram: Optional[str] = None,
                       target_histogram: Optional[str] = None,
                       seed: Optional[int] = None) -> None:
    """End-to-end offline preprocessing. If histogram files aren't supplied,
    they are built from the raw train split directly (replacing the awk
    pass)."""
    rng = random.Random(seed) if seed is not None else None
    if word_histogram and path_histogram and target_histogram:
        word_to_count = common.load_histogram(word_histogram,
                                              max_size=word_vocab_size)
        path_to_count = common.load_histogram(path_histogram,
                                              max_size=path_vocab_size)
        target_to_count = common.load_histogram(target_histogram,
                                                max_size=target_vocab_size)
    else:
        token_count, path_count, target_count = build_histograms(train_raw)
        word_to_count = truncate_to_max_size(token_count, word_vocab_size)
        path_to_count = truncate_to_max_size(path_count, path_vocab_size)
        target_to_count = truncate_to_max_size(target_count, target_vocab_size)

    num_training_examples = 0
    for raw_path, role in zip([test_raw, val_raw, train_raw],
                              ['test', 'val', 'train']):
        num_examples = process_file(
            file_path=raw_path, data_file_role=role, dataset_name=output_name,
            word_to_count=word_to_count, path_to_count=path_to_count,
            max_contexts=max_contexts, rng=rng)
        if role == 'train':
            num_training_examples = num_examples
    save_dictionaries(output_name, word_to_count, path_to_count,
                      target_to_count, num_training_examples)


def main(argv=None) -> None:
    parser = ArgumentParser(prog='code2vec_tpu.data.preprocess')
    parser.add_argument('-trd', '--train_data', dest='train_data_path',
                        required=True)
    parser.add_argument('-ted', '--test_data', dest='test_data_path',
                        required=True)
    parser.add_argument('-vd', '--val_data', dest='val_data_path',
                        required=True)
    parser.add_argument('-mc', '--max_contexts', dest='max_contexts',
                        type=int, default=200)
    parser.add_argument('-wvs', '--word_vocab_size', dest='word_vocab_size',
                        type=int, default=1301136)
    parser.add_argument('-pvs', '--path_vocab_size', dest='path_vocab_size',
                        type=int, default=911417)
    parser.add_argument('-tvs', '--target_vocab_size', dest='target_vocab_size',
                        type=int, default=261245)
    parser.add_argument('-wh', '--word_histogram', dest='word_histogram',
                        default=None)
    parser.add_argument('-ph', '--path_histogram', dest='path_histogram',
                        default=None)
    parser.add_argument('-th', '--target_histogram', dest='target_histogram',
                        default=None)
    parser.add_argument('-o', '--output_name', dest='output_name',
                        required=True)
    parser.add_argument('--seed', type=int, default=None)
    args = parser.parse_args(argv)
    preprocess_dataset(
        train_raw=args.train_data_path, val_raw=args.val_data_path,
        test_raw=args.test_data_path, output_name=args.output_name,
        max_contexts=args.max_contexts,
        word_vocab_size=args.word_vocab_size,
        path_vocab_size=args.path_vocab_size,
        target_vocab_size=args.target_vocab_size,
        word_histogram=args.word_histogram,
        path_histogram=args.path_histogram,
        target_histogram=args.target_histogram, seed=args.seed)


if __name__ == '__main__':
    main()
