"""Offline dataset production: raw extractor output → ``.c2v`` + ``.dict.c2v``.

Replaces both the reference's awk histogram pass (preprocess.sh:55-58) and its
``preprocess.py`` sampling/padding pass (:23-74) with one Python module (the
histogram pass is plain counting; the native extractor can also emit
histograms directly).

Semantics preserved exactly:

- per-split context truncation to ``max_contexts`` with vocab-aware sampling:
  prefer contexts whose three parts are all in-vocab ('full found'), then
  those with any part in-vocab ('partial found'), random-sampling within a
  tier (reference preprocess.py:41-56);
- rows with zero contexts are dropped (:58-60);
- rows are padded with trailing spaces to exactly ``max_contexts`` fields
  (:64-65) so files are byte-layout compatible with reference readers;
- ``.dict.c2v`` = sequential pickles of word/path/target→count dicts +
  train example count (:12-20).
"""
from __future__ import annotations

import pickle
import random
from argparse import ArgumentParser
from collections import Counter
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from code2vec_tpu import common


def build_histograms(raw_path: str) -> Tuple[Counter, Counter, Counter]:
    """Count target names (field 1), origin tokens (ctx fields 1 and 3) and
    paths (ctx field 2) over a raw extractor output file — the reference did
    this with three awk one-liners (preprocess.sh:55-58)."""
    target_count: Counter = Counter()
    token_count: Counter = Counter()
    path_count: Counter = Counter()
    with open(raw_path, 'r') as file:
        for line in file:
            parts = line.rstrip('\n').split(' ')
            if not parts or not parts[0]:
                continue
            target_count[parts[0]] += 1
            for ctx in parts[1:]:
                if not ctx:
                    continue
                pieces = ctx.split(',')
                if len(pieces) != 3:
                    continue
                token_count[pieces[0]] += 1
                path_count[pieces[1]] += 1
                token_count[pieces[2]] += 1
    return token_count, path_count, target_count


def save_histogram(counter: Counter, path: str) -> None:
    """``word count`` lines, most-common first (awk output is unsorted, but
    readers don't depend on order — common.load_histogram re-sorts by count)."""
    with open(path, 'w') as f:
        for word, count in counter.most_common():
            f.write('{} {}\n'.format(word, count))


truncate_to_max_size = common.truncate_histogram_to_max_size


# Sampling tiers (reference preprocess.py:41-56 semantics): when a row has
# more contexts than fit, contexts whose three parts are all in-vocab win
# over those with any in-vocab part, which win over fully-OOV ones.
_TIER_ALL_IN_VOCAB = 2
_TIER_SOME_IN_VOCAB = 1
_TIER_NONE_IN_VOCAB = 0


def _vocab_tier(context: str, token_vocab: Dict[str, int],
                path_vocab: Dict[str, int]) -> int:
    pieces = context.split(',')
    hits = (pieces[0] in token_vocab, pieces[1] in path_vocab,
            pieces[2] in token_vocab)
    if all(hits):
        return _TIER_ALL_IN_VOCAB
    return _TIER_SOME_IN_VOCAB if any(hits) else _TIER_NONE_IN_VOCAB


def sample_contexts(contexts: list, limit: int,
                    token_vocab: Dict[str, int], path_vocab: Dict[str, int],
                    rng) -> list:
    """Tiered downsampling of one row's contexts to at most ``limit``.

    Rows already within the limit pass through untouched.  Oversized rows
    are partitioned by vocabulary tier; the fully-OOV tier is discarded,
    and random sampling breaks ties within the first tier that overflows
    the remaining budget.  The result can therefore be *shorter* than
    ``limit`` — or empty, which callers treat as a dropped row — exactly
    the reference's behavior (preprocess.py:41-60).
    """
    if len(contexts) <= limit:
        return contexts
    tiers: Dict[int, list] = {_TIER_ALL_IN_VOCAB: [], _TIER_SOME_IN_VOCAB: [],
                              _TIER_NONE_IN_VOCAB: []}
    for context in contexts:
        tiers[_vocab_tier(context, token_vocab, path_vocab)].append(context)
    keep = tiers[_TIER_ALL_IN_VOCAB]
    if len(keep) >= limit:
        return rng.sample(keep, limit)
    runners_up = tiers[_TIER_SOME_IN_VOCAB]
    budget = limit - len(keep)
    if len(runners_up) > budget:
        runners_up = rng.sample(runners_up, budget)
    return keep + runners_up


@dataclass
class SplitStats:
    """Per-split accounting, reported once the split is written."""
    rows_kept: int = 0
    rows_dropped_empty: int = 0
    contexts_seen: int = 0
    contexts_written: int = 0
    widest_raw_row: int = 0

    def observe_raw(self, n_contexts: int) -> None:
        self.contexts_seen += n_contexts
        self.widest_raw_row = max(self.widest_raw_row, n_contexts)

    def report(self, source_path: str) -> None:
        print(f'{source_path}: kept {self.rows_kept} rows, dropped '
              f'{self.rows_dropped_empty} empty', flush=True)
        if self.rows_kept:
            print(f'  contexts/row: {self.contexts_seen / self.rows_kept:.2f}'
                  f' raw -> {self.contexts_written / self.rows_kept:.2f}'
                  f' after sampling; widest raw row: {self.widest_raw_row}')


def process_file(file_path: str, data_file_role: str, dataset_name: str,
                 word_to_count: Dict[str, int], path_to_count: Dict[str, int],
                 max_contexts: int, rng: Optional[random.Random] = None) -> int:
    """Stream one raw split through tiered sampling into
    ``<dataset>.<role>.c2v``, space-padding every row to exactly
    ``max_contexts`` context fields (byte-layout compatible with reference
    readers, preprocess.py:64-65).  Returns the number of rows kept.
    """
    rng = rng or random
    stats = SplitStats()
    output_path = f'{dataset_name}.{data_file_role}.c2v'
    with open(file_path, 'r') as source, open(output_path, 'w') as sink:
        for line in source:
            label, *contexts = line.rstrip('\n').split(' ')
            stats.observe_raw(len(contexts))
            kept = sample_contexts(contexts, max_contexts,
                                   word_to_count, path_to_count, rng)
            if not kept:
                stats.rows_dropped_empty += 1
                continue
            stats.contexts_written += len(kept)
            stats.rows_kept += 1
            padding = ' ' * (max_contexts - len(kept))
            sink.write(f"{label} {' '.join(kept)}{padding}\n")
    stats.report(file_path)
    return stats.rows_kept


def save_dictionaries(dataset_name: str, word_to_count: Dict[str, int],
                      path_to_count: Dict[str, int],
                      target_to_count: Dict[str, int],
                      num_training_examples: int) -> None:
    """Sequential-pickle layout of ``.dict.c2v``
    (reference preprocess.py:12-20)."""
    save_path = '{}.dict.c2v'.format(dataset_name)
    with open(save_path, 'wb') as file:
        pickle.dump(word_to_count, file)
        pickle.dump(path_to_count, file)
        pickle.dump(target_to_count, file)
        pickle.dump(num_training_examples, file)
    print('Dictionaries saved to: {}'.format(save_path))


def preprocess_dataset(train_raw: str, val_raw: str, test_raw: str,
                       output_name: str, max_contexts: int = 200,
                       word_vocab_size: int = 1301136,
                       path_vocab_size: int = 911417,
                       target_vocab_size: int = 261245,
                       word_histogram: Optional[str] = None,
                       path_histogram: Optional[str] = None,
                       target_histogram: Optional[str] = None,
                       seed: Optional[int] = None) -> None:
    """End-to-end offline preprocessing. If histogram files aren't supplied,
    they are built from the raw train split directly (replacing the awk
    pass)."""
    rng = random.Random(seed) if seed is not None else None
    if word_histogram and path_histogram and target_histogram:
        word_to_count = common.load_histogram(word_histogram,
                                              max_size=word_vocab_size)
        path_to_count = common.load_histogram(path_histogram,
                                              max_size=path_vocab_size)
        target_to_count = common.load_histogram(target_histogram,
                                                max_size=target_vocab_size)
    else:
        token_count, path_count, target_count = build_histograms(train_raw)
        word_to_count = truncate_to_max_size(token_count, word_vocab_size)
        path_to_count = truncate_to_max_size(path_count, path_vocab_size)
        target_to_count = truncate_to_max_size(target_count, target_vocab_size)

    num_training_examples = 0
    for raw_path, role in zip([test_raw, val_raw, train_raw],
                              ['test', 'val', 'train']):
        num_examples = process_file(
            file_path=raw_path, data_file_role=role, dataset_name=output_name,
            word_to_count=word_to_count, path_to_count=path_to_count,
            max_contexts=max_contexts, rng=rng)
        if role == 'train':
            num_training_examples = num_examples
    save_dictionaries(output_name, word_to_count, path_to_count,
                      target_to_count, num_training_examples)


def main(argv=None) -> None:
    parser = ArgumentParser(prog='code2vec_tpu.data.preprocess')
    parser.add_argument('-trd', '--train_data', dest='train_data_path',
                        required=True)
    parser.add_argument('-ted', '--test_data', dest='test_data_path',
                        required=True)
    parser.add_argument('-vd', '--val_data', dest='val_data_path',
                        required=True)
    parser.add_argument('-mc', '--max_contexts', dest='max_contexts',
                        type=int, default=200)
    parser.add_argument('-wvs', '--word_vocab_size', dest='word_vocab_size',
                        type=int, default=1301136)
    parser.add_argument('-pvs', '--path_vocab_size', dest='path_vocab_size',
                        type=int, default=911417)
    parser.add_argument('-tvs', '--target_vocab_size', dest='target_vocab_size',
                        type=int, default=261245)
    parser.add_argument('-wh', '--word_histogram', dest='word_histogram',
                        default=None)
    parser.add_argument('-ph', '--path_histogram', dest='path_histogram',
                        default=None)
    parser.add_argument('-th', '--target_histogram', dest='target_histogram',
                        default=None)
    parser.add_argument('-o', '--output_name', dest='output_name',
                        required=True)
    parser.add_argument('--seed', type=int, default=None)
    args = parser.parse_args(argv)
    preprocess_dataset(
        train_raw=args.train_data_path, val_raw=args.val_data_path,
        test_raw=args.test_data_path, output_name=args.output_name,
        max_contexts=args.max_contexts,
        word_vocab_size=args.word_vocab_size,
        path_vocab_size=args.path_vocab_size,
        target_vocab_size=args.target_vocab_size,
        word_histogram=args.word_histogram,
        path_histogram=args.path_histogram,
        target_histogram=args.target_histogram, seed=args.seed)


if __name__ == '__main__':
    main()
