"""Host-side input pipeline: ``.c2v`` text → fixed-shape int32/float32 batches.

TPU-first redesign of the reference's in-graph tf.data pipeline
(reference path_context_reader.py:119-228):

- **Strings never touch the device.** Vocabulary lookup happens here, on the
  host, with plain dicts (the reference used in-graph
  ``tf.lookup.StaticHashTable``, vocabularies.py:108-139 — impossible and
  undesirable under XLA).
- **Static shapes.** Every batch is exactly ``(batch_size, max_contexts)``;
  row filtering happens host-side before batching, and a short final batch is
  padded with zero-``weight`` rows instead of shrinking (the reference emitted
  ragged final batches, path_context_reader.py:148).
- **Same row semantics.** A context part that is missing or out-of-vocab maps
  to PAD/OOV exactly as the reference's CSV-default + hashtable-default
  pipeline did (path_context_reader.py:82-83, 184-214), including the joined
  PAD==OOV policy subtlety: a context whose three parts all hash to index 0 is
  masked out.
- **Same filter semantics.** Train rows must have an in-vocab target and at
  least one valid context; eval rows only the latter
  (path_context_reader.py:153-177). Predict rows are never filtered (:100).

A background thread parses and tokenizes ahead of the consumer
(``READER_PREFETCH_BATCHES`` deep), mirroring the reference's
``num_parallel_calls`` + ``prefetch`` (:141-150). When the native C++
tokenizer is available (``code2vec_tpu.data.native``) it replaces the Python
inner loop.
"""
from __future__ import annotations

import queue
import random
import threading
from enum import Enum
from typing import Iterable, Iterator, List, NamedTuple, Optional, Sequence

import numpy as np

from code2vec_tpu.config import Config
from code2vec_tpu.vocab import Code2VecVocabs


class EstimatorAction(Enum):
    Train = 'train'
    Evaluate = 'evaluate'
    Predict = 'predict'

    @property
    def is_train(self) -> bool:
        return self is EstimatorAction.Train

    @property
    def is_evaluate(self) -> bool:
        return self is EstimatorAction.Evaluate

    @property
    def is_predict(self) -> bool:
        return self is EstimatorAction.Predict

    @property
    def is_evaluate_or_predict(self) -> bool:
        return self.is_evaluate or self.is_predict


def context_valid_mask(source: np.ndarray, path: np.ndarray,
                       target: np.ndarray, token_pad: int,
                       path_pad: int) -> np.ndarray:
    """A context is valid iff any of its three parts is non-PAD
    (reference path_context_reader.py:209-214, including the joined
    PAD==OOV subtlety). Single definition — parity-critical."""
    return ((source != token_pad) | (target != token_pad)
            | (path != path_pad)).astype(np.float32)


def _counted_batches(batches):
    """Pass-through that counts emitted batches into the telemetry
    pipeline counter (one bool read per batch when telemetry is off).
    Also hosts the ``hang_input`` fault point (resilience/faults.py):
    firing blocks this stream — from whichever thread drives it, usually
    the prefetch producer — exactly like a wedged filesystem would, so
    the hang watchdog's input-wait arm is exercised end to end."""
    import time as _time

    from code2vec_tpu.resilience import faults
    from code2vec_tpu.telemetry import core
    for batch in batches:
        if faults.maybe_fire('hang_input'):
            _time.sleep(faults.HANG_SECONDS)
        if core.enabled():
            core.registry().counter('input/batches_total').inc()
        yield batch


def prefetch_iterator(make_iterator, depth: int):
    """Run ``make_iterator()`` in a background thread with a bounded queue
    (the reference's ``prefetch``, path_context_reader.py:150). Safe to
    abandon mid-iteration: closing the generator cancels the producer."""
    out: 'queue.Queue' = queue.Queue(depth)
    sentinel = object()
    cancelled = threading.Event()
    error: List[BaseException] = []

    def produce():
        try:
            for item in make_iterator():
                while not cancelled.is_set():
                    try:
                        out.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancelled.is_set():
                    return
        except BaseException as exc:  # propagate to consumer
            error.append(exc)
        finally:
            # must not drop the sentinel on a full queue, or the consumer
            # blocks forever after draining it
            while not cancelled.is_set():
                try:
                    out.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    thread = threading.Thread(target=produce, daemon=True)
    thread.start()
    try:
        while True:
            item = out.get()
            if item is sentinel:
                break
            yield item
    finally:
        cancelled.set()
        thread.join()
    if error:
        raise error[0]


class Batch(NamedTuple):
    """One device-ready batch. All arrays have static leading dimension
    ``batch_size``; short final batches are padded with ``weight == 0`` rows."""
    source: np.ndarray               # (B, C) int32 — source-token indices
    path: np.ndarray                 # (B, C) int32 — path indices
    target: np.ndarray               # (B, C) int32 — target-token indices
    mask: np.ndarray                 # (B, C) float32 — context validity
    label: np.ndarray                # (B,)  int32 — target-name index
    weight: np.ndarray               # (B,)  float32 — example validity
    # Host-only string fields (eval/predict; device code never sees these).
    label_strings: Optional[np.ndarray] = None     # (B,) object
    source_strings: Optional[np.ndarray] = None    # (B, C) object
    path_strings: Optional[np.ndarray] = None      # (B, C) object
    target_strings: Optional[np.ndarray] = None    # (B, C) object

    @property
    def num_valid_examples(self) -> int:
        return int(self.weight.sum())

    def device_arrays(self):
        """The arrays the jitted step functions consume, in a fixed order."""
        return (self.source, self.path, self.target, self.mask,
                self.label, self.weight)


class ParsedRow(NamedTuple):
    label_str: str
    source_strs: List[str]
    path_strs: List[str]
    target_strs: List[str]


def parse_c2v_line(line: str, max_contexts: int) -> ParsedRow:
    """Split one ``label ctx1 ctx2 …`` line; a ctx is ``src,path,tgt``.

    Missing/short/empty contexts are padded with empty strings, which
    tokenize to PAD — the host equivalent of the reference's CSV record
    defaults (path_context_reader.py:82-83, 190-196).
    """
    parts = line.rstrip('\r\n').split(' ')  # matches the native tokenizer
    label = parts[0]
    source_strs = [''] * max_contexts
    path_strs = [''] * max_contexts
    target_strs = [''] * max_contexts
    n = min(len(parts) - 1, max_contexts)
    for i in range(n):
        ctx = parts[i + 1]
        if not ctx:
            continue
        pieces = ctx.split(',')
        if len(pieces) >= 1:
            source_strs[i] = pieces[0]
        if len(pieces) >= 2:
            path_strs[i] = pieces[1]
        if len(pieces) >= 3:
            target_strs[i] = pieces[2]
    return ParsedRow(label, source_strs, path_strs, target_strs)


def canonicalize_contexts(lines: Iterable[str],
                          max_contexts: Optional[int] = None) -> List[str]:
    """Canonical form of raw ``label ctx1 ctx2 …`` predict lines — THE
    definition of request identity (SERVING.md "Memoization tier").
    Every prediction surface funnels through it: ``process_input_rows``
    applies it (so ``model.predict``, ``serving/bulk.py``, and both
    submit paths tokenize identical canonical input), and
    ``ServingEngine.submit`` / ``ServingMesh.submit`` call it up front
    so the memoization key (``serving/memo.py``) and the tokenizer can
    never disagree on what "the same request" is.

    Tokenize-faithful by construction: each line is split exactly as
    ``parse_c2v_line`` splits it (single-space separators — an empty
    slot from a doubled space still OCCUPIES a context slot), then
    truncated to ``max_contexts`` in ORIGINAL extraction order, and
    only then are the surviving empty slots dropped and the survivors
    sorted lexicographically — a canonical MULTISET of the exact
    path-contexts the tokenizer would keep.  Truncating before the
    sort is load-bearing: sorting first would let a different context
    subset survive ``MAX_CONTEXTS`` than the evaluate-path reader
    (which never canonicalizes) keeps, silently changing predictions.
    For the same reason every serving entry point passes its
    ``config.MAX_CONTEXTS`` here — the FIRST canonicalization must be
    the one that truncates.  Dropping empty slots after truncation is
    tokenize-invariant (they map to PAD and are masked), and sorting
    makes every path reduce the attention sum in the same float
    order.  Duplicate ``src,path,tgt`` triples are KEPT: a repeated
    context contributes its attention weight twice in the reference
    model, so the duplicate count is part of request identity.  Line
    order across the request is preserved: results are per-line,
    positional.
    Idempotent at fixed ``max_contexts``:
    ``canonicalize_contexts(canonicalize_contexts(x, m), m)`` equals
    ``canonicalize_contexts(x, m)`` (a canonical line has no empty
    slots and at most ``m`` contexts, so the re-truncation is a
    no-op).
    """
    out = []
    for line in lines:
        parts = str(line).rstrip('\r\n').split(' ')  # parse_c2v_line split
        contexts = parts[1:]
        if max_contexts is not None:
            # extraction-order truncation, empty slots counted — the
            # slots parse_c2v_line would fill (and mask) for this line
            contexts = contexts[:max_contexts]
        out.append(' '.join([parts[0]] + sorted(c for c in contexts if c)))
    return out


class PathContextReader:
    def __init__(self, vocabs: Code2VecVocabs, config: Config,
                 estimator_action: EstimatorAction,
                 data_path: Optional[str] = None,
                 keep_strings: Optional[bool] = None,
                 process_index: int = 0, process_count: int = 1,
                 data_shards: int = 1):
        self.vocabs = vocabs
        self.config = config
        self.estimator_action = estimator_action
        self.data_path = data_path if data_path is not None else \
            config.data_path(is_evaluating=estimator_action.is_evaluate)
        # multi-host: each process reads a disjoint line stride and emits
        # its 1/process_count share of the GLOBAL batch
        self.process_index = process_index
        self.process_count = max(1, process_count)
        # mesh data-axis size: packed-wire batches are packed PER data
        # shard so each device's slice transfers directly to it
        # (data/packed.py; parallel/mesh.py shard_batch)
        self.data_shards = max(1, data_shards)
        # sticky packed-capacity state (packed.StickyPacker), created on
        # first packed emission and kept across epochs
        self._packer = None
        # Eval keeps only the label strings (host-side metric decode);
        # predict additionally keeps per-context strings (attention
        # display) — reference kept string tensors in the graph,
        # path_context_reader.py:225-227. Splitting the two lets the
        # native tokenizer cover the evaluate path (index arrays in C++,
        # labels sliced in Python): previously every evaluate run paid the
        # per-context Python loop (VERDICT r1 weak #3).
        if keep_strings is None:
            self.keep_context_strings = estimator_action.is_predict
            self.keep_label_strings = estimator_action.is_evaluate_or_predict
        else:
            self.keep_context_strings = keep_strings
            self.keep_label_strings = keep_strings
        self._native = None
        if config.READER_USE_NATIVE and not self.keep_context_strings:
            try:
                from code2vec_tpu.data import native
                if native.is_available():
                    self._native = native.get_tokenizer(vocabs, config)
            except (ImportError, RuntimeError):
                self._native = None

    # ------------------------------------------------------------ tokenize
    def tokenize_rows(self, rows: Sequence[ParsedRow]) -> Batch:
        """Vocab-lookup a list of parsed rows into one dense batch of
        exactly ``len(rows)`` examples (callers pad to batch size)."""
        n = len(rows)
        max_contexts = self.config.MAX_CONTEXTS
        token_get = self.vocabs.token_vocab.word_to_index.get
        path_get = self.vocabs.path_vocab.word_to_index.get
        target_get = self.vocabs.target_vocab.word_to_index.get
        token_oov = self.vocabs.token_vocab.oov_index
        token_pad = self.vocabs.token_vocab.pad_index
        path_oov = self.vocabs.path_vocab.oov_index
        path_pad = self.vocabs.path_vocab.pad_index
        target_oov = self.vocabs.target_vocab.oov_index
        # Empty strings must map to PAD, not OOV: the reference's CSV default
        # substitutes the PAD word *before* the hashtable lookup.
        source = np.empty((n, max_contexts), dtype=np.int32)
        path = np.empty((n, max_contexts), dtype=np.int32)
        target = np.empty((n, max_contexts), dtype=np.int32)
        label = np.empty((n,), dtype=np.int32)
        for r, row in enumerate(rows):
            label[r] = target_get(row.label_str, target_oov)
            src_row, path_row, tgt_row = source[r], path[r], target[r]
            for c in range(max_contexts):
                s = row.source_strs[c]
                src_row[c] = token_get(s, token_oov) if s else token_pad
                p = row.path_strs[c]
                path_row[c] = path_get(p, path_oov) if p else path_pad
                t = row.target_strs[c]
                tgt_row[c] = token_get(t, token_oov) if t else token_pad
        mask = self._context_valid_mask(source, path, target)
        weight = np.ones((n,), dtype=np.float32)
        batch = Batch(source=source, path=path, target=target, mask=mask,
                      label=label, weight=weight)
        if self.keep_label_strings:
            batch = batch._replace(
                label_strings=np.array([row.label_str for row in rows],
                                       dtype=object))
        if self.keep_context_strings:
            batch = batch._replace(
                source_strings=np.array([row.source_strs for row in rows], dtype=object),
                path_strings=np.array([row.path_strs for row in rows], dtype=object),
                target_strings=np.array([row.target_strs for row in rows], dtype=object))
        return batch

    def _context_valid_mask(self, source: np.ndarray, path: np.ndarray,
                            target: np.ndarray) -> np.ndarray:
        return context_valid_mask(source, path, target,
                                  self.vocabs.token_vocab.pad_index,
                                  self.vocabs.path_vocab.pad_index)

    # ------------------------------------------------------------- batching
    def _lines_from_file(self) -> Iterator[str]:
        with open(self.data_path, 'r', buffering=self.config.CSV_BUFFER_SIZE) as f:
            for line_number, line in enumerate(f):
                if self.process_count > 1 and \
                        line_number % self.process_count != self.process_index:
                    continue
                if line.strip():
                    yield line

    def _shuffled(self, lines: Iterable[str], rng: random.Random) -> Iterator[str]:
        """Streaming shuffle buffer (reference used
        ``dataset.shuffle(SHUFFLE_BUFFER_SIZE)``, path_context_reader.py:139)."""
        buffer: List[str] = []
        size = self.config.SHUFFLE_BUFFER_SIZE
        for line in lines:
            if len(buffer) < size:
                buffer.append(line)
                continue
            idx = rng.randrange(size)
            yield buffer[idx]
            buffer[idx] = line
        rng.shuffle(buffer)
        yield from buffer

    def tokenize_lines(self, lines: Sequence[str]) -> Batch:
        """Parse + tokenize a chunk of raw lines into one dense batch.

        This is the hot host loop; the native C++ tokenizer substitutes for
        it when available (including evaluate — only the label string is
        retained, a single split per line, not the per-context loop)."""
        if self._native is not None:
            batch = self._native.tokenize_lines(lines)
            if self.keep_label_strings:
                batch = batch._replace(label_strings=np.array(
                    [line.rstrip('\r\n').split(' ', 1)[0] for line in lines],
                    dtype=object))
            return batch
        rows = [parse_c2v_line(line, self.config.MAX_CONTEXTS)
                for line in lines]
        return self.tokenize_rows(rows)

    def _keep_mask(self, batch: Batch) -> np.ndarray:
        """Vectorized row filter (reference path_context_reader.py:153-177):
        train keeps rows with an in-vocab target AND ≥1 valid context; eval
        keeps rows with ≥1 valid context."""
        any_valid = batch.mask.any(axis=1)
        if self.estimator_action.is_train:
            return any_valid & (batch.label > self.vocabs.target_vocab.oov_index)
        return any_valid

    @staticmethod
    def _take_rows(batch: Batch, keep: np.ndarray) -> Batch:
        return Batch(*[None if field is None else field[keep]
                       for field in batch])

    @staticmethod
    def _concat(parts: List[Batch]) -> Batch:
        if len(parts) == 1:
            return parts[0]
        return Batch(*[None if parts[0][i] is None
                       else np.concatenate([p[i] for p in parts])
                       for i in range(len(parts[0]))])

    def _filtered_batches(self, lines: Iterable[str],
                          batch_size: int) -> Iterator[Batch]:
        """Parse, tokenize, filter, and emit fixed-shape batches."""
        pending: List[Batch] = []
        pending_rows = 0
        chunk: List[str] = []
        chunk_size = max(batch_size, 256)

        def flush_chunk():
            nonlocal pending, pending_rows
            batch = self.tokenize_lines(chunk)
            kept = self._take_rows(batch, self._keep_mask(batch))
            if kept.label.shape[0]:
                pending.append(kept)
                pending_rows += kept.label.shape[0]
            while pending_rows >= batch_size:
                merged = self._concat(pending)
                # slice, not fancy-index: views, no copies in the hot loop
                yield self._take_rows(merged, slice(None, batch_size))
                rest = self._take_rows(merged, slice(batch_size, None))
                pending = [rest] if rest.label.shape[0] else []
                pending_rows = merged.label.shape[0] - batch_size

        for line in lines:
            chunk.append(line)
            if len(chunk) >= chunk_size:
                yield from flush_chunk()
                chunk = []
        if chunk:
            yield from flush_chunk()
        if pending_rows:
            yield self._pad_batch(self._concat(pending), batch_size)

    def empty_batch(self, batch_size: int) -> Batch:
        """All-padding batch (every row weight 0): multi-host evaluation
        emits these so every process runs the same number of jitted steps
        even when data shards are uneven — the padded rows drop out of the
        metrics and the loss.  Delegates to ``_pad_batch`` so the pad-row
        fill policy has a single definition."""
        contexts = self.config.MAX_CONTEXTS
        zero_rows = Batch(
            source=np.zeros((0, contexts), np.int32),
            path=np.zeros((0, contexts), np.int32),
            target=np.zeros((0, contexts), np.int32),
            mask=np.zeros((0, contexts), np.float32),
            label=np.zeros((0,), np.int32),
            weight=np.zeros((0,), np.float32))
        if self.keep_label_strings:
            zero_rows = zero_rows._replace(
                label_strings=np.zeros((0,), dtype=object))
        if self.keep_context_strings:
            zero_rows = zero_rows._replace(
                source_strings=np.zeros((0, contexts), dtype=object),
                path_strings=np.zeros((0, contexts), dtype=object),
                target_strings=np.zeros((0, contexts), dtype=object))
        return self._pad_batch(zero_rows, batch_size)

    def pad_batch_to(self, batch: Batch, batch_size: int) -> Batch:
        """Pad a batch up to ``batch_size`` rows with zero-weight rows
        (replaces the reference's ragged final batch; also used to make
        predict batches divisible by the mesh data axis)."""
        return self._pad_batch(batch, batch_size)

    def _pad_batch(self, batch: Batch, batch_size: int) -> Batch:
        n = batch.label.shape[0]
        if n == batch_size:
            return batch
        pad = batch_size - n

        def pad2(arr, fill):
            return np.concatenate(
                [arr, np.full((pad,) + arr.shape[1:], fill, dtype=arr.dtype)])

        padded = Batch(
            source=pad2(batch.source, self.vocabs.token_vocab.pad_index),
            path=pad2(batch.path, self.vocabs.path_vocab.pad_index),
            target=pad2(batch.target, self.vocabs.token_vocab.pad_index),
            mask=pad2(batch.mask, 0.0),
            label=pad2(batch.label, 0),
            weight=np.concatenate([batch.weight,
                                   np.zeros((pad,), dtype=np.float32)]))
        if batch.label_strings is not None:
            padded = padded._replace(label_strings=np.concatenate(
                [batch.label_strings, np.full((pad,), '', dtype=object)]))
        if batch.source_strings is not None:
            empty_ctx = np.full((pad, self.config.MAX_CONTEXTS), '', dtype=object)
            padded = padded._replace(
                source_strings=np.concatenate([batch.source_strings, empty_ctx]),
                path_strings=np.concatenate([batch.path_strings, empty_ctx]),
                target_strings=np.concatenate([batch.target_strings, empty_ctx]))
        return padded

    # ----------------------------------------------------------- public API
    def wire_format(self) -> str:
        """The wire format this reader emits from ``iter_epoch`` (the
        multi-host fallback lives in Config.wire_format_for)."""
        return self.config.wire_format_for(self.process_count)

    def iter_epoch(self, shuffle: Optional[bool] = None,
                   seed: Optional[int] = None,
                   wire_format: Optional[str] = None) -> Iterator[Batch]:
        """One pass over the data file as fixed-shape batches.

        The trainer drives epochs explicitly (the reference baked
        ``repeat(NUM_TRAIN_EPOCHS)`` into the dataset and trained until
        ``OutOfRangeError``, tensorflow_model.py:74-102 — with JAX's explicit
        stepping we keep the loop in charge).

        ``wire_format`` selects the emitted batch type: 'planes' (the
        default, and what every introspection/test contract reads) or
        'packed' (``data/packed.py::PackedBatch`` — the compact wire
        format whose device-side unpack reproduces the plane batches
        bit-exactly). Training/eval pass ``self.wire_format()`` so the
        config default governs the product path.
        """
        if shuffle is None:
            shuffle = self.estimator_action.is_train
        lines: Iterable[str] = self._lines_from_file()
        if shuffle:
            lines = self._shuffled(lines, random.Random(seed))
        # per-process LOCAL batch: process-local shards assemble into the
        # global batch on device (parallel/mesh.py shard_batch)
        global_batch = self.config.batch_size(
            is_evaluating=self.estimator_action.is_evaluate)
        if global_batch % self.process_count:
            raise ValueError(
                'batch size %d must be divisible by the process count (%d) '
                'so process-local shards assemble into the global batch.'
                % (global_batch, self.process_count))
        batch_size = global_batch // self.process_count
        batches = self._filtered_batches(lines, batch_size)
        if wire_format == 'packed':
            from code2vec_tpu.data import packed as packed_lib
            if self._packer is None:
                self._packer = packed_lib.StickyPacker(
                    self.vocabs.token_vocab.pad_index,
                    self.vocabs.path_vocab.pad_index,
                    data_shards=self.data_shards)
            batches = (self._packer.pack_batch(batch) for batch in batches)
        yield from _counted_batches(batches)

    def iter_epoch_prefetched(self, shuffle: Optional[bool] = None,
                              seed: Optional[int] = None,
                              wire_format: Optional[str] = None
                              ) -> Iterator[Batch]:
        """``iter_epoch`` behind a background prefetch thread."""
        yield from prefetch_iterator(
            lambda: self.iter_epoch(shuffle=shuffle, seed=seed,
                                    wire_format=wire_format),
            self.config.READER_PREFETCH_BATCHES)

    def process_input_rows(self, input_lines: Iterable[str]) -> Batch:
        """Tokenize raw extractor output lines for prediction — never
        filtered (reference path_context_reader.py:96-107).  Lines are
        canonicalized first (``canonicalize_contexts``), so every
        predict surface — direct, bulk, engine, mesh — tokenizes the
        SAME canonical context bag and the memo key (serving/memo.py)
        addresses exactly what was computed."""
        rows = [parse_c2v_line(line, self.config.MAX_CONTEXTS)
                for line in canonicalize_contexts(
                    input_lines, self.config.MAX_CONTEXTS)]
        return self.tokenize_rows(rows)
