from code2vec_tpu.data.packed import PackedBatch
from code2vec_tpu.data.reader import (
    Batch, EstimatorAction, PathContextReader, parse_c2v_line)

__all__ = ['Batch', 'EstimatorAction', 'PackedBatch', 'PathContextReader',
           'parse_c2v_line']
