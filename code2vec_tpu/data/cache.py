"""Binary token cache: tokenize the training split once, stream int32
tensors from disk for every later epoch.

The reference re-ran its CSV parse + hashtable lookups for all 20 epochs
(tf.data re-executes the pipeline per repeat, path_context_reader.py:119-151).
Here the first epoch's host tokenization is persisted as raw little-endian
arrays next to the dataset; subsequent epochs are sequential disk reads with
chunk-level shuffling (permute chunk order, permute rows within a chunk) —
both faster and a better shuffle than a 10K-row reservoir.

Format v2 (current) stores the PACKED wire layout (data/packed.py): each
example's contexts densified to its effective length, so the cache on
disk shrinks with the corpus fill rate exactly like the wire does (~12
bytes per retained context + 8 per example, vs v1's 12 bytes for every
one of the C slots). Layout of ``<data>.train.c2v.tokcache/``:

  ctx.bin    int32 (num_contexts, 3) — (source, path, target) triples
  count.bin  int32 (N,) — per-example effective lengths
  label.bin  int32 (N,)
  meta.json  version, row/context counts, max_contexts, vocab fingerprint

Format v1 (``source.bin``/``path.bin``/``target.bin`` padded planes) is
still READ transparently — a fresh v1 cache is used as-is, never
rebuilt; delete the directory to re-materialize it as v2 (MIGRATION.md).
``iter_epoch`` emits either wire format from either on-disk version.

The mask is never stored — recomputed from indices (valid iff any part
!= PAD). Only the train split is cached (eval/predict keep strings for
host-side metrics).
"""
from __future__ import annotations

import contextlib
import fcntl
import json
import os
from typing import Iterator, Optional

import numpy as np

from code2vec_tpu.config import Config
from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.data.reader import (Batch, PathContextReader,
                                      context_valid_mask)
from code2vec_tpu.vocab import Code2VecVocabs

CACHE_FORMAT_VERSION = 2


@contextlib.contextmanager
def _build_lock(lock_path: str):
    """flock-based inter-process exclusion for cache builds: concurrent
    trainers sharing a dataset directory must not race the
    check → build → publish sequence."""
    with open(lock_path, 'w') as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)

_FILES_V2 = ('ctx.bin', 'count.bin', 'label.bin')


def _fingerprint(config: Config, vocabs: Code2VecVocabs,
                 data_path: str) -> dict:
    stat = os.stat(data_path)
    # vocab content hash, not just sizes: sizes are commonly pinned at the
    # MAX_*_VOCAB_SIZE caps, so loading a different model's dictionaries
    # over the same data file keeps every size equal while silently
    # remapping word→index — a stale cache would then feed wrong indices.
    return {
        'data_size': stat.st_size,
        'data_mtime': stat.st_mtime,
        'max_contexts': config.MAX_CONTEXTS,
        'token_vocab': vocabs.token_vocab.size,
        'path_vocab': vocabs.path_vocab.size,
        'target_vocab': vocabs.target_vocab.size,
        'vocab_content_hash': vocabs.content_hash(),
    }


class TokenCache:
    def __init__(self, cache_dir: str, config: Config,
                 vocabs: Code2VecVocabs):
        self.cache_dir = cache_dir
        self.config = config
        self.vocabs = vocabs
        meta_path = os.path.join(cache_dir, 'meta.json')
        with open(meta_path, 'r') as f:
            self.meta = json.load(f)
        self.num_rows = self.meta['num_rows']
        # pre-v2 metas carry no version key — that IS the v1 marker
        self.version = int(self.meta.get('version', 1))
        max_contexts = self.meta['max_contexts']
        if self.version >= 2:
            self.num_contexts = self.meta['num_contexts']
            # size validation BEFORE mapping (ISSUE 3 satellite): a
            # truncated shard (disk-full or killed build) would otherwise
            # surface as an opaque mmap error — or worse, feed mis-aligned
            # epochs if the meta undercounts
            self._check_shard_size('ctx.bin', self.num_contexts * 3 * 4)
            self._check_shard_size('count.bin', self.num_rows * 4)
            self.ctx = np.memmap(os.path.join(cache_dir, 'ctx.bin'),
                                 dtype=np.int32, mode='r',
                                 shape=(self.num_contexts, 3))
            self.count = np.memmap(os.path.join(cache_dir, 'count.bin'),
                                   dtype=np.int32, mode='r',
                                   shape=(self.num_rows,))
            # and the counts must RECONCILE with the context shard: the
            # per-example lengths are the offsets every epoch iteration
            # slices ctx.bin by — a mismatch mis-aligns every batch
            total = int(np.asarray(self.count).sum(dtype=np.int64))
            if total != self.num_contexts:
                raise ValueError(
                    'Token cache at `%s` is corrupt: count.bin totals %d '
                    'contexts but meta.json/ctx.bin hold %d — delete the '
                    'cache directory to rebuild it.'
                    % (cache_dir, total, self.num_contexts))
        else:
            shape2 = (self.num_rows, max_contexts)
            plane_bytes = self.num_rows * max_contexts * 4
            for name in ('source.bin', 'path.bin', 'target.bin'):
                self._check_shard_size(name, plane_bytes)
            self.source = np.memmap(os.path.join(cache_dir, 'source.bin'),
                                    dtype=np.int32, mode='r', shape=shape2)
            self.path = np.memmap(os.path.join(cache_dir, 'path.bin'),
                                  dtype=np.int32, mode='r', shape=shape2)
            self.target = np.memmap(os.path.join(cache_dir, 'target.bin'),
                                    dtype=np.int32, mode='r', shape=shape2)
        self._check_shard_size('label.bin', self.num_rows * 4)
        self.label = np.memmap(os.path.join(cache_dir, 'label.bin'),
                               dtype=np.int32, mode='r',
                               shape=(self.num_rows,))
        # sticky packed-capacity state (packed.StickyPacker): grows
        # monotonically across batches AND epochs so the jitted packed
        # step specializes a handful of times per run, not per batch
        self._packer = None

    def _check_shard_size(self, name: str, expected_bytes: int) -> None:
        """A shard whose on-disk size disagrees with meta.json means a
        truncated or torn cache build: fail with instructions, never
        serve mis-aligned epochs."""
        path = os.path.join(self.cache_dir, name)
        actual = os.path.getsize(path) if os.path.isfile(path) else -1
        if actual != expected_bytes:
            raise ValueError(
                'Token cache at `%s` is truncated or corrupt: %s is %d '
                'bytes but meta.json implies %d (disk-full or killed '
                'build?) — delete the cache directory to rebuild it.'
                % (self.cache_dir, name, actual, expected_bytes))

    def _packer_for(self, data_shards: int) -> packed_lib.StickyPacker:
        if self._packer is None or self._packer.data_shards != data_shards:
            self._packer = packed_lib.StickyPacker(
                self.vocabs.token_vocab.pad_index,
                self.vocabs.path_vocab.pad_index, data_shards=data_shards)
        return self._packer

    # ------------------------------------------------------------ building
    @classmethod
    def build_or_load(cls, config: Config, vocabs: Code2VecVocabs,
                      reader: PathContextReader,
                      data_path: Optional[str] = None) -> 'TokenCache':
        """Multi-host: the reader strides the data file per process, so each
        process builds/loads a cache of ITS OWN stride in a per-process
        directory (``.tokcache.p<i>of<n>``) — processes sharing storage
        never collide, and every epoch after the first is sequential disk
        reads instead of a full re-tokenization per process."""
        data_path = data_path or config.train_data_path
        suffix = ('.tokcache' if reader.process_count <= 1 else
                  '.tokcache.p%dof%d' % (reader.process_index,
                                         reader.process_count))
        cache_dir = data_path + suffix
        expected = _fingerprint(config, vocabs, data_path)
        if reader.process_count > 1:
            # single-process caches skip these keys so pre-existing caches
            # stay fresh; the stride is also encoded in the directory name
            expected['process_index'] = reader.process_index
            expected['process_count'] = reader.process_count
        meta_path = os.path.join(cache_dir, 'meta.json')

        def is_fresh() -> bool:
            # the format version is deliberately NOT part of the
            # freshness check: a fresh v1 cache keeps serving (read
            # compatibility), it is only ever REPLACED when the data or
            # vocab fingerprint changes
            if not os.path.isfile(meta_path):
                return False
            with open(meta_path, 'r') as f:
                meta = json.load(f)
            return all(meta.get(k) == v for k, v in expected.items())

        from code2vec_tpu.telemetry import core as tele_core
        if is_fresh():
            if tele_core.enabled():
                tele_core.registry().counter('input/cache_hit_total').inc()
            return cls(cache_dir, config, vocabs)
        with _build_lock(cache_dir + '.lock'):
            # another process may have built it while we waited
            if not is_fresh():
                if tele_core.enabled():
                    tele_core.registry().counter(
                        'input/cache_miss_total').inc()
                cls._build(config, reader, cache_dir, expected)
            elif tele_core.enabled():
                # a concurrent trainer built it while we held the lock
                tele_core.registry().counter('input/cache_hit_total').inc()
            return cls(cache_dir, config, vocabs)

    @classmethod
    def _build(cls, config: Config, reader: PathContextReader,
               cache_dir: str, fingerprint: dict) -> None:
        tmp_dir = cache_dir + '.building.%d' % os.getpid()
        os.makedirs(tmp_dir, exist_ok=True)
        config.log('Building token cache at `%s` (format v%d) ...'
                   % (cache_dir, CACHE_FORMAT_VERSION))
        num_rows = 0
        num_contexts = 0
        handles = {name: open(os.path.join(tmp_dir, name), 'wb')
                   for name in _FILES_V2}
        try:
            # one filtered, UNSHUFFLED pass; batches here are fixed-shape
            # with a zero-weight padded tail we must drop
            for batch in reader.iter_epoch(shuffle=False,
                                           wire_format='planes'):
                valid = batch.weight > 0
                triples, lengths = packed_lib.ragged_from_planes(
                    np.ascontiguousarray(batch.source[valid]),
                    np.ascontiguousarray(batch.path[valid]),
                    np.ascontiguousarray(batch.target[valid]),
                    batch.mask[valid])
                handles['ctx.bin'].write(
                    np.ascontiguousarray(triples).tobytes())
                handles['count.bin'].write(lengths.tobytes())
                handles['label.bin'].write(
                    np.ascontiguousarray(batch.label[valid]).tobytes())
                num_rows += int(valid.sum())
                num_contexts += int(lengths.sum())
        finally:
            for handle in handles.values():
                handle.close()
        if num_rows == 0:
            import shutil
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise ValueError(
                'No training examples survived filtering in `%s` — every '
                'row has an out-of-vocab target or no valid contexts.'
                % reader.data_path)
        meta = dict(fingerprint)
        meta['num_rows'] = num_rows
        meta['num_contexts'] = num_contexts
        meta['version'] = CACHE_FORMAT_VERSION
        with open(os.path.join(tmp_dir, 'meta.json'), 'w') as f:
            json.dump(meta, f)
        # atomic publish
        if os.path.isdir(cache_dir):
            import shutil
            shutil.rmtree(cache_dir)
        os.replace(tmp_dir, cache_dir)
        config.log('Token cache built: %d rows, %d contexts (%.1f avg).'
                   % (num_rows, num_contexts, num_contexts / num_rows))

    # ----------------------------------------------------------- iteration
    def iter_epoch(self, batch_size: int, shuffle: bool = True,
                   seed: Optional[int] = None,
                   chunk_rows: int = 1 << 16,
                   wire_format: Optional[str] = None,
                   data_shards: int = 1) -> Iterator[Batch]:
        """Fixed-shape batches from the cache. Shuffle = permuted chunk
        order + in-chunk row permutation (sequential disk reads).

        ``wire_format`` ('planes' default / 'packed') selects the emitted
        batch type independently of the ON-DISK version — a v1 cache can
        feed the packed wire and vice versa."""
        from code2vec_tpu.data.reader import _counted_batches
        wire_format = wire_format or 'planes'
        if self.version >= 2:
            yield from _counted_batches(
                self._iter_epoch_v2(batch_size, shuffle, seed, chunk_rows,
                                    wire_format, data_shards))
            return
        batches = self._iter_epoch_v1(batch_size, shuffle, seed, chunk_rows)
        if wire_format == 'packed':
            packer = self._packer_for(data_shards)
            batches = (packer.pack_batch(batch) for batch in batches)
        yield from _counted_batches(batches)

    # ------------------------------------------------------------ v2 path
    def _emit_v2(self, ctx_rows: np.ndarray, count: np.ndarray,
                 label: np.ndarray, weight: Optional[np.ndarray],
                 wire_format: str, data_shards: int):
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        if weight is None:
            weight = np.ones((count.shape[0],), np.float32)
        if wire_format == 'packed':
            ctx = self._packer_for(data_shards).pack_ragged(ctx_rows, count)
            return packed_lib.PackedBatch(ctx=ctx, count=count, label=label,
                                          weight=weight)
        source, path, target = packed_lib.unpack_ragged_np(
            ctx_rows, count, self.meta['max_contexts'], token_pad, path_pad)
        mask = context_valid_mask(source, path, target, token_pad, path_pad)
        return Batch(source=source, path=path, target=target, mask=mask,
                     label=label, weight=weight)

    def _iter_epoch_v2(self, batch_size: int, shuffle: bool,
                       seed: Optional[int], chunk_rows: int,
                       wire_format: str, data_shards: int):
        rng = np.random.default_rng(seed)
        num_chunks = max(1, -(-self.num_rows // chunk_rows))
        # context-row offset of each chunk boundary: one cheap pass over
        # the count memmap instead of materializing all N example offsets
        chunk_ctx_bounds = np.zeros(num_chunks + 1, np.int64)
        for i in range(num_chunks):
            begin = i * chunk_rows
            end = min(self.num_rows, begin + chunk_rows)
            chunk_ctx_bounds[i + 1] = chunk_ctx_bounds[i] + \
                np.asarray(self.count[begin:end]).sum(dtype=np.int64)
        chunk_order = np.arange(num_chunks)
        if shuffle:
            rng.shuffle(chunk_order)

        pend_ctx = np.zeros((0, 3), np.int32)
        pend_count = np.zeros((0,), np.int32)
        pend_label = np.zeros((0,), np.int32)

        for chunk_idx in chunk_order:
            begin = int(chunk_idx) * chunk_rows
            end = min(self.num_rows, begin + chunk_rows)
            count = np.asarray(self.count[begin:end])
            label = np.asarray(self.label[begin:end])
            ctx_rows = np.asarray(
                self.ctx[chunk_ctx_bounds[chunk_idx]:
                         chunk_ctx_bounds[chunk_idx + 1]])
            if shuffle:
                perm = rng.permutation(end - begin)
                starts = np.cumsum(count) - count
                sel = np.repeat(starts[perm], count[perm]) + \
                    (np.arange(count[perm].sum(), dtype=np.int64)
                     - np.repeat(np.cumsum(count[perm]) - count[perm],
                                 count[perm]))
                ctx_rows = ctx_rows[sel]
                count, label = count[perm], label[perm]
            if pend_count.shape[0]:
                ctx_rows = np.concatenate([pend_ctx, ctx_rows])
                count = np.concatenate([pend_count, count])
                label = np.concatenate([pend_label, label])
            bounds = np.concatenate([[0], np.cumsum(count, dtype=np.int64)])
            n_full = (count.shape[0] // batch_size) * batch_size
            for start in range(0, n_full, batch_size):
                stop = start + batch_size
                yield self._emit_v2(
                    ctx_rows[bounds[start]:bounds[stop]],
                    count[start:stop], label[start:stop], None,
                    wire_format, data_shards)
            pend_ctx = ctx_rows[bounds[n_full]:]
            pend_count = count[n_full:]
            pend_label = label[n_full:]

        if pend_count.shape[0]:
            pad = batch_size - pend_count.shape[0]
            yield self._emit_v2(
                pend_ctx,
                np.concatenate([pend_count, np.zeros((pad,), np.int32)]),
                np.concatenate([pend_label, np.zeros((pad,), np.int32)]),
                np.concatenate([np.ones((pend_count.shape[0],), np.float32),
                                np.zeros((pad,), np.float32)]),
                wire_format, data_shards)

    # ------------------------------------------------------------ v1 path
    def _iter_epoch_v1(self, batch_size: int, shuffle: bool,
                       seed: Optional[int],
                       chunk_rows: int) -> Iterator[Batch]:
        rng = np.random.default_rng(seed)
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        num_chunks = max(1, -(-self.num_rows // chunk_rows))
        chunk_order = np.arange(num_chunks)
        if shuffle:
            rng.shuffle(chunk_order)

        pending = []  # leftover rows smaller than batch_size, as arrays
        pending_rows = 0

        def emit(source, path, target, label,
                 weight: Optional[np.ndarray] = None) -> Batch:
            mask = context_valid_mask(source, path, target, token_pad,
                                      path_pad)
            if weight is None:
                weight = np.ones((source.shape[0],), np.float32)
            return Batch(source=source, path=path, target=target, mask=mask,
                         label=label, weight=weight)

        for chunk_idx in chunk_order:
            begin = int(chunk_idx) * chunk_rows
            end = min(self.num_rows, begin + chunk_rows)
            source = np.asarray(self.source[begin:end])
            path = np.asarray(self.path[begin:end])
            target = np.asarray(self.target[begin:end])
            label = np.asarray(self.label[begin:end])
            if shuffle:
                perm = rng.permutation(end - begin)
                source, path, target, label = (source[perm], path[perm],
                                               target[perm], label[perm])
            if pending:
                source = np.concatenate([pending[0], source])
                path = np.concatenate([pending[1], path])
                target = np.concatenate([pending[2], target])
                label = np.concatenate([pending[3], label])
                pending = []
            n_full = (source.shape[0] // batch_size) * batch_size
            for start in range(0, n_full, batch_size):
                stop = start + batch_size
                yield emit(source[start:stop], path[start:stop],
                           target[start:stop], label[start:stop])
            if n_full < source.shape[0]:
                pending = [source[n_full:], path[n_full:], target[n_full:],
                           label[n_full:]]
                pending_rows = source.shape[0] - n_full

        if pending and pending_rows:
            pad = batch_size - pending_rows
            yield emit(
                np.concatenate([pending[0], np.full(
                    (pad, pending[0].shape[1]), token_pad, np.int32)]),
                np.concatenate([pending[1], np.full(
                    (pad, pending[1].shape[1]), path_pad, np.int32)]),
                np.concatenate([pending[2], np.full(
                    (pad, pending[2].shape[1]), token_pad, np.int32)]),
                np.concatenate([pending[3], np.zeros((pad,), np.int32)]),
                weight=np.concatenate([
                    np.ones((pending_rows,), np.float32),
                    np.zeros((pad,), np.float32)]))
