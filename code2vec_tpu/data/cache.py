"""Binary token cache: tokenize the training split once, stream int32
tensors from disk for every later epoch.

The reference re-ran its CSV parse + hashtable lookups for all 20 epochs
(tf.data re-executes the pipeline per repeat, path_context_reader.py:119-151).
Here the first epoch's host tokenization is persisted as raw little-endian
arrays next to the dataset; subsequent epochs are sequential disk reads with
chunk-level shuffling (permute chunk order, permute rows within a chunk) —
both faster and a better shuffle than a 10K-row reservoir.

Layout of ``<data>.train.c2v.tokcache/``:
  source.bin path.bin target.bin  int32 (N, C) row-major
  label.bin                       int32 (N,)
  meta.json                       row count, max_contexts, vocab fingerprint

The mask is recomputed from indices (valid iff any part != PAD) instead of
stored — a third of the cache size for one vectorized compare. Only the
train split is cached (eval/predict keep strings for host-side metrics).
"""
from __future__ import annotations

import contextlib
import fcntl
import json
import os
from typing import Iterator, Optional

import numpy as np

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import (Batch, PathContextReader,
                                      context_valid_mask)
from code2vec_tpu.vocab import Code2VecVocabs


@contextlib.contextmanager
def _build_lock(lock_path: str):
    """flock-based inter-process exclusion for cache builds: concurrent
    trainers sharing a dataset directory must not race the
    check → build → publish sequence."""
    with open(lock_path, 'w') as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)

_FILES = ('source.bin', 'path.bin', 'target.bin', 'label.bin')


def _fingerprint(config: Config, vocabs: Code2VecVocabs,
                 data_path: str) -> dict:
    stat = os.stat(data_path)
    # vocab content hash, not just sizes: sizes are commonly pinned at the
    # MAX_*_VOCAB_SIZE caps, so loading a different model's dictionaries
    # over the same data file keeps every size equal while silently
    # remapping word→index — a stale cache would then feed wrong indices.
    return {
        'data_size': stat.st_size,
        'data_mtime': stat.st_mtime,
        'max_contexts': config.MAX_CONTEXTS,
        'token_vocab': vocabs.token_vocab.size,
        'path_vocab': vocabs.path_vocab.size,
        'target_vocab': vocabs.target_vocab.size,
        'vocab_content_hash': vocabs.content_hash(),
    }


class TokenCache:
    def __init__(self, cache_dir: str, config: Config,
                 vocabs: Code2VecVocabs):
        self.cache_dir = cache_dir
        self.config = config
        self.vocabs = vocabs
        meta_path = os.path.join(cache_dir, 'meta.json')
        with open(meta_path, 'r') as f:
            self.meta = json.load(f)
        self.num_rows = self.meta['num_rows']
        max_contexts = self.meta['max_contexts']
        shape2 = (self.num_rows, max_contexts)
        self.source = np.memmap(os.path.join(cache_dir, 'source.bin'),
                                dtype=np.int32, mode='r', shape=shape2)
        self.path = np.memmap(os.path.join(cache_dir, 'path.bin'),
                              dtype=np.int32, mode='r', shape=shape2)
        self.target = np.memmap(os.path.join(cache_dir, 'target.bin'),
                                dtype=np.int32, mode='r', shape=shape2)
        self.label = np.memmap(os.path.join(cache_dir, 'label.bin'),
                               dtype=np.int32, mode='r',
                               shape=(self.num_rows,))

    # ------------------------------------------------------------ building
    @classmethod
    def build_or_load(cls, config: Config, vocabs: Code2VecVocabs,
                      reader: PathContextReader,
                      data_path: Optional[str] = None) -> 'TokenCache':
        """Multi-host: the reader strides the data file per process, so each
        process builds/loads a cache of ITS OWN stride in a per-process
        directory (``.tokcache.p<i>of<n>``) — processes sharing storage
        never collide, and every epoch after the first is sequential disk
        reads instead of a full re-tokenization per process."""
        data_path = data_path or config.train_data_path
        suffix = ('.tokcache' if reader.process_count <= 1 else
                  '.tokcache.p%dof%d' % (reader.process_index,
                                         reader.process_count))
        cache_dir = data_path + suffix
        expected = _fingerprint(config, vocabs, data_path)
        if reader.process_count > 1:
            # single-process caches skip these keys so pre-existing caches
            # stay fresh; the stride is also encoded in the directory name
            expected['process_index'] = reader.process_index
            expected['process_count'] = reader.process_count
        meta_path = os.path.join(cache_dir, 'meta.json')

        def is_fresh() -> bool:
            if not os.path.isfile(meta_path):
                return False
            with open(meta_path, 'r') as f:
                meta = json.load(f)
            return all(meta.get(k) == v for k, v in expected.items())

        if is_fresh():
            return cls(cache_dir, config, vocabs)
        with _build_lock(cache_dir + '.lock'):
            # another process may have built it while we waited
            if not is_fresh():
                cls._build(config, reader, cache_dir, expected)
            return cls(cache_dir, config, vocabs)

    @classmethod
    def _build(cls, config: Config, reader: PathContextReader,
               cache_dir: str, fingerprint: dict) -> None:
        tmp_dir = cache_dir + '.building.%d' % os.getpid()
        os.makedirs(tmp_dir, exist_ok=True)
        config.log('Building token cache at `%s` ...' % cache_dir)
        num_rows = 0
        handles = {name: open(os.path.join(tmp_dir, name), 'wb')
                   for name in _FILES}
        try:
            # one filtered, UNSHUFFLED pass; batches here are fixed-shape
            # with a zero-weight padded tail we must drop
            for batch in reader.iter_epoch(shuffle=False):
                valid = batch.weight > 0
                handles['source.bin'].write(
                    np.ascontiguousarray(batch.source[valid]).tobytes())
                handles['path.bin'].write(
                    np.ascontiguousarray(batch.path[valid]).tobytes())
                handles['target.bin'].write(
                    np.ascontiguousarray(batch.target[valid]).tobytes())
                handles['label.bin'].write(
                    np.ascontiguousarray(batch.label[valid]).tobytes())
                num_rows += int(valid.sum())
        finally:
            for handle in handles.values():
                handle.close()
        if num_rows == 0:
            import shutil
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise ValueError(
                'No training examples survived filtering in `%s` — every '
                'row has an out-of-vocab target or no valid contexts.'
                % reader.data_path)
        meta = dict(fingerprint)
        meta['num_rows'] = num_rows
        with open(os.path.join(tmp_dir, 'meta.json'), 'w') as f:
            json.dump(meta, f)
        # atomic publish
        if os.path.isdir(cache_dir):
            import shutil
            shutil.rmtree(cache_dir)
        os.replace(tmp_dir, cache_dir)
        config.log('Token cache built: %d rows.' % num_rows)

    # ----------------------------------------------------------- iteration
    def iter_epoch(self, batch_size: int, shuffle: bool = True,
                   seed: Optional[int] = None,
                   chunk_rows: int = 1 << 16) -> Iterator[Batch]:
        """Fixed-shape batches from the cache. Shuffle = permuted chunk
        order + in-chunk row permutation (sequential disk reads)."""
        rng = np.random.default_rng(seed)
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        num_chunks = max(1, -(-self.num_rows // chunk_rows))
        chunk_order = np.arange(num_chunks)
        if shuffle:
            rng.shuffle(chunk_order)

        pending = []  # leftover rows smaller than batch_size, as arrays
        pending_rows = 0

        def emit(source, path, target, label,
                 weight: Optional[np.ndarray] = None) -> Batch:
            mask = context_valid_mask(source, path, target, token_pad,
                                      path_pad)
            if weight is None:
                weight = np.ones((source.shape[0],), np.float32)
            return Batch(source=source, path=path, target=target, mask=mask,
                         label=label, weight=weight)

        for chunk_idx in chunk_order:
            begin = int(chunk_idx) * chunk_rows
            end = min(self.num_rows, begin + chunk_rows)
            source = np.asarray(self.source[begin:end])
            path = np.asarray(self.path[begin:end])
            target = np.asarray(self.target[begin:end])
            label = np.asarray(self.label[begin:end])
            if shuffle:
                perm = rng.permutation(end - begin)
                source, path, target, label = (source[perm], path[perm],
                                               target[perm], label[perm])
            if pending:
                source = np.concatenate([pending[0], source])
                path = np.concatenate([pending[1], path])
                target = np.concatenate([pending[2], target])
                label = np.concatenate([pending[3], label])
                pending = []
            n_full = (source.shape[0] // batch_size) * batch_size
            for start in range(0, n_full, batch_size):
                stop = start + batch_size
                yield emit(source[start:stop], path[start:stop],
                           target[start:stop], label[start:stop])
            if n_full < source.shape[0]:
                pending = [source[n_full:], path[n_full:], target[n_full:],
                           label[n_full:]]
                pending_rows = source.shape[0] - n_full

        if pending and pending_rows:
            pad = batch_size - pending_rows
            yield emit(
                np.concatenate([pending[0], np.full(
                    (pad, pending[0].shape[1]), token_pad, np.int32)]),
                np.concatenate([pending[1], np.full(
                    (pad, pending[1].shape[1]), path_pad, np.int32)]),
                np.concatenate([pending[2], np.full(
                    (pad, pending[2].shape[1]), token_pad, np.int32)]),
                np.concatenate([pending[3], np.zeros((pad,), np.int32)]),
                weight=np.concatenate([
                    np.ones((pending_rows,), np.float32),
                    np.zeros((pad,), np.float32)]))
