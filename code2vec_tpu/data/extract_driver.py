"""Fault-tolerant driver for large-scale path-context extraction.

Role of the reference's ``JavaExtractor/extract.py`` / ``CSharpExtractor/
extract.py`` (SURVEY.md §5 'Failure detection'): fan extraction out over
project subdirectories in a worker pool, put a kill-timer on every
extractor subprocess, and on failure/timeout DROP the partial output and
recurse into the failing directory's children to isolate poison files
(reference extract.py:26-41, 49-57). A file that fails on its own is
skipped with a log line instead of sinking its whole project.

Usage:
    python -m code2vec_tpu.data.extract_driver --dir projects/ \
        --output raw.txt [--lang csharp] [--workers 8] [--timeout 600]
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
from argparse import ArgumentParser
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from code2vec_tpu.serving.extractor_bridge import find_default_extractor

_SOURCE_EXTENSIONS = {'java': '.java', 'csharp': '.cs'}


class ExtractionDriver:
    def __init__(self, extractor_command: List[str], lang: str = 'java',
                 max_path_length: int = 8, max_path_width: int = 2,
                 num_threads: int = 32, timeout_seconds: float = 600.0,
                 log=print):
        self.extractor_command = extractor_command
        self.lang = lang
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        self.num_threads = num_threads
        self.timeout_seconds = timeout_seconds
        self.log = log
        self._write_lock = threading.Lock()
        self.nr_failed_files = 0
        self.nr_extracted_dirs = 0

    def _command(self, *target) -> List[str]:
        return self.extractor_command + [
            '--lang', self.lang,
            '--max_path_length', str(self.max_path_length),
            '--max_path_width', str(self.max_path_width),
            '--num_threads', str(self.num_threads), *target]

    def _run(self, *target) -> Optional[str]:
        """One extractor subprocess under a kill-timer; None = failed."""
        try:
            proc = subprocess.run(self._command(*target),
                                  capture_output=True, text=True,
                                  timeout=self.timeout_seconds)
        except subprocess.TimeoutExpired:
            return None
        except OSError as e:  # bad/missing extractor binary
            self.log('Cannot run extractor %r: %s'
                     % (self.extractor_command, e))
            return None
        if proc.returncode != 0:
            return None
        return proc.stdout

    def _count_failed_file(self) -> None:
        with self._write_lock:
            self.nr_failed_files += 1

    def _count_extracted_dir(self) -> None:
        with self._write_lock:
            self.nr_extracted_dirs += 1

    def _extract_dir(self, directory: str, out_file) -> None:
        """Extract one directory; on failure, isolate by recursing
        (reference extract.py:26-41)."""
        output = self._run('--dir', directory)
        if output is not None:
            with self._write_lock:
                out_file.write(output)
            self._count_extracted_dir()
            return
        self.log('Extraction failed/timed out for `%s`; recursing to '
                 'isolate.' % directory)
        extension = _SOURCE_EXTENSIONS[self.lang]
        try:
            entries = sorted(os.scandir(directory), key=lambda e: e.path)
        except OSError as e:
            self.log('Cannot list `%s`: %s' % (directory, e))
            return
        for entry in entries:
            if entry.is_dir(follow_symlinks=False):
                self._extract_dir(entry.path, out_file)
            elif entry.is_file() and entry.name.endswith(extension):
                self._extract_loose_file(entry.path, out_file)

    def extract(self, root_dir: str, out_file, workers: int = 4) -> None:
        """Fan out over top-level subdirectories (the reference pooled over
        project dirs, extract.py:49-57); loose files at the root are one
        extra unit."""
        subdirs = [entry.path for entry in sorted(
            os.scandir(root_dir), key=lambda e: e.path)
            if entry.is_dir(follow_symlinks=False)]
        extension = _SOURCE_EXTENSIONS[self.lang]
        loose_files = [entry.path for entry in os.scandir(root_dir)
                       if entry.is_file()
                       and entry.name.endswith(extension)]
        with ThreadPoolExecutor(max_workers=max(1, workers)) as pool:
            futures = [pool.submit(self._extract_dir, d, out_file)
                       for d in subdirs]
            for path in loose_files:
                futures.append(pool.submit(self._extract_loose_file, path,
                                           out_file))
            for future in futures:
                future.result()
        self.log('Done: %d dirs extracted, %d poison files skipped.'
                 % (self.nr_extracted_dirs, self.nr_failed_files))

    def _extract_loose_file(self, path: str, out_file) -> None:
        output = self._run('--file', path)
        if output is None:
            self._count_failed_file()
            self.log('Skipping poison file `%s`.' % path)
        else:
            with self._write_lock:
                out_file.write(output)


def main(argv=None) -> None:
    parser = ArgumentParser(prog='code2vec_tpu.data.extract_driver')
    parser.add_argument('--dir', dest='root_dir', required=True)
    parser.add_argument('--output', dest='output', default='-',
                        help='output file ("-" = stdout)')
    parser.add_argument('--lang', choices=['java', 'csharp'],
                        default='java')
    parser.add_argument('--max_path_length', type=int, default=8)
    parser.add_argument('--max_path_width', type=int, default=2)
    parser.add_argument('--num_threads', type=int, default=32,
                        help='threads per extractor subprocess')
    parser.add_argument('--workers', type=int, default=4,
                        help='concurrent extractor subprocesses')
    parser.add_argument('--timeout', type=float, default=600.0,
                        help='kill-timer per subprocess, seconds')
    parser.add_argument('--extractor', default=None,
                        help='path to the c2v-extract binary')
    args = parser.parse_args(argv)

    command = [args.extractor] if args.extractor \
        else find_default_extractor()
    if command is None:
        sys.exit('No extractor binary found; build extractor/ first or '
                 'pass --extractor.')
    driver = ExtractionDriver(
        command, lang=args.lang, max_path_length=args.max_path_length,
        max_path_width=args.max_path_width, num_threads=args.num_threads,
        timeout_seconds=args.timeout,
        log=lambda msg: print(msg, file=sys.stderr))
    if args.output == '-':
        driver.extract(args.root_dir, sys.stdout, workers=args.workers)
    else:
        with open(args.output, 'w') as out_file:
            driver.extract(args.root_dir, out_file, workers=args.workers)


if __name__ == '__main__':
    main()
