"""ctypes bindings for the native C++ tokenizer (native/tokenizer.cpp).

Builds the shared library on first use (g++ only; no pybind11 in this
environment). Falls back cleanly when the toolchain is unavailable — the
Python tokenizer in ``reader.py`` has identical semantics.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, 'native', 'tokenizer.cpp')
_LIB = os.path.join(_REPO_ROOT, 'native', 'build', 'libc2vtok.so')

_TOKEN, _PATH, _TARGET = 0, 1, 2

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


def _build_library() -> None:
    os.makedirs(os.path.dirname(_LIB), exist_ok=True)
    # build to a temp path + atomic rename: a killed or concurrent build
    # must never leave a corrupt .so at the final path
    tmp = '%s.%d.tmp' % (_LIB, os.getpid())
    cmd = ['g++', '-O3', '-std=c++17', '-shared', '-fPIC', '-pthread',
           _SRC, '-o', tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError('native tokenizer build failed: '
                           + proc.stderr.strip())
    os.replace(tmp, _LIB)


def _load() -> ctypes.CDLL:
    global _lib, _lib_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_error is not None:
            raise RuntimeError(_lib_error)
        try:
            if not os.path.isfile(_LIB) or (
                    os.path.isfile(_SRC)
                    and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
                _build_library()
            lib = ctypes.CDLL(_LIB)
        except (OSError, RuntimeError) as e:
            _lib_error = str(e)
            raise RuntimeError(_lib_error)
        lib.c2v_tok_create.restype = ctypes.c_void_p
        lib.c2v_tok_destroy.argtypes = [ctypes.c_void_p]
        lib.c2v_tok_add_words.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.c2v_tok_set_special.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
        lib.c2v_tok_tokenize.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32)]
        _lib = lib
        return _lib


def is_available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


def get_tokenizer(vocabs, config) -> 'NativeTokenizer':
    """Cached per vocab-triple: building one uploads every vocab word into
    the C++ hash maps (tens of MB at java14m scale) — do it once, not per
    reader. The cache lives ON the vocabs object so it can never outlive or
    be confused with another vocab set, and dies with it."""
    cache = getattr(vocabs, '_native_tokenizer_cache', None)
    if cache is None:
        cache = {}
        vocabs._native_tokenizer_cache = cache
    tokenizer = cache.get(config.MAX_CONTEXTS)
    if tokenizer is None:
        tokenizer = NativeTokenizer(vocabs, config)
        cache[config.MAX_CONTEXTS] = tokenizer
    return tokenizer


def _i32_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeTokenizer:
    """Vocab tables live in C++; ``tokenize_lines`` produces the same Batch
    arrays as the Python path."""

    def __init__(self, vocabs, config):
        from code2vec_tpu.data.reader import Batch  # avoid import cycle
        self._Batch = Batch
        self.config = config
        self.lib = _load()
        self.handle = ctypes.c_void_p(self.lib.c2v_tok_create())
        self.num_threads = max(1, config.READER_NUM_PARALLEL_BATCHES)
        for vocab_id, vocab in ((_TOKEN, vocabs.token_vocab),
                                (_PATH, vocabs.path_vocab),
                                (_TARGET, vocabs.target_vocab)):
            self._add_vocab(vocab_id, vocab)
            pad = getattr(vocab.special_words, 'PAD', None)
            pad_index = vocab.word_to_index[pad] if pad is not None \
                else vocab.oov_index
            self.lib.c2v_tok_set_special(self.handle, vocab_id,
                                         vocab.oov_index, pad_index)

    def _add_vocab(self, vocab_id: int, vocab) -> None:
        words = list(vocab.word_to_index.keys())
        # keys() and values() iterate in the same order
        indices = np.fromiter(vocab.word_to_index.values(),
                              dtype=np.int32, count=len(words))
        blob = '\n'.join(words).encode('utf-8')
        self.lib.c2v_tok_add_words(self.handle, vocab_id, blob,
                                   len(blob), _i32_ptr(indices), len(words))

    def __del__(self):
        try:
            if getattr(self, 'handle', None):
                self.lib.c2v_tok_destroy(self.handle)
        except Exception:
            pass

    def tokenize_lines(self, lines: Sequence[str]):
        n = len(lines)
        max_contexts = self.config.MAX_CONTEXTS
        encoded = [line.encode('utf-8') for line in lines]
        blob = b'\n'.join(encoded)
        # offsets[i] = byte start of line i; the slice [off[i], off[i+1])
        # includes the '\n' separator, which the C++ side strips
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) + 1 for e in encoded], out=offsets[1:])
        offsets[n] = len(blob)

        source = np.empty((n, max_contexts), dtype=np.int32)
        path = np.empty((n, max_contexts), dtype=np.int32)
        target = np.empty((n, max_contexts), dtype=np.int32)
        mask = np.empty((n, max_contexts), dtype=np.float32)
        label = np.empty((n,), dtype=np.int32)
        self.lib.c2v_tok_tokenize(
            self.handle, blob, offsets.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)),
            n, max_contexts, self.num_threads,
            _i32_ptr(source), _i32_ptr(path), _i32_ptr(target),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            _i32_ptr(label))
        return self._Batch(source=source, path=path, target=target,
                           mask=mask, label=label,
                           weight=np.ones((n,), dtype=np.float32))
