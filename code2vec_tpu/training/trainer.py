"""Backend-agnostic training/eval/predict engine.

Replaces the reference's session-based hot loop (tensorflow_model.py:40-112)
and the Keras fit wrapper (keras_model.py:166-193) with three jitted pure
step functions over a device mesh:

- ``train_step``  — loss + grads + Adam update, params donated;
- ``eval_step``   — deterministic forward + device-side top-k;
- ``predict_step``— eval plus attention weights and softmax-normalized
  top-k scores (reference ``normalize_scores=True``,
  tensorflow_model.py:305-306), built in OUTPUT TIERS (``PREDICT_TIERS``)
  so serving pays only for the outputs a caller asked for.

Everything under jit is traced once and reused for every batch; the mesh
placement of params/batches drives XLA's partitioner (DP gradient psum,
sharded-table gathers, sharded softmax) with no collective written by hand.
"""
from __future__ import annotations

import collections
import contextlib
import logging
import os
import signal as signal_lib
import time
from typing import Any, Callable, Iterable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from code2vec_tpu.config import Config
from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.data.reader import Batch
from code2vec_tpu.models import functional
from code2vec_tpu.ops.topk import sharded_top_k
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.resilience import faults
from code2vec_tpu.telemetry import goodput as goodput_lib

# package logger: 'code2vec_tpu.training.trainer' — propagates to the
# 'code2vec_tpu' root logger Config.get_logger configures
logger = logging.getLogger(__name__)

# Output tiers of the predict step — each is a SEPARATE jitted program
# (serving/engine.py pre-compiles them per batch bucket):
#   'topk'      — softmaxed top-k scores + indices only (the cheap
#                 steady-state serving path; no attention/vector D2H)
#   'attention' — topk + per-context attention weights (the REPL contract)
#   'full'      — topk + attention + code vectors (the v1 predict_step)
#   'vectors'   — code vectors ONLY: the (B, V) logits matmul and top-k
#                 are dead-code-eliminated, for bulk embedding export
PREDICT_TIERS = ('topk', 'attention', 'full', 'vectors')


class TrainerState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array     # () int32
    rng: jax.Array      # dropout PRNG root


class Trainer:
    def __init__(self, config: Config, backend,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.config = config
        self.backend = backend
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh(config)
        data_size = self.mesh.shape[mesh_lib.DATA_AXIS]
        model_size = self.mesh.shape[mesh_lib.MODEL_AXIS]
        for attr in ('TRAIN_BATCH_SIZE', 'TEST_BATCH_SIZE'):
            if getattr(config, attr) % data_size:
                raise ValueError(
                    '%s=%d must be divisible by the mesh data axis (%d).'
                    % (attr, getattr(config, attr), data_size))
        if config.SHARD_CONTEXTS and config.MAX_CONTEXTS % model_size:
            raise ValueError(
                'SHARD_CONTEXTS requires MAX_CONTEXTS=%d divisible by the '
                'mesh model axis (%d).' % (config.MAX_CONTEXTS, model_size))
        if config.PARAM_ROW_ALIGNMENT % model_size:
            raise ValueError(
                'PARAM_ROW_ALIGNMENT=%d must be divisible by the mesh model '
                'axis (%d) for even table sharding.'
                % (config.PARAM_ROW_ALIGNMENT, model_size))
        self._zero_opt = config.OPTIMIZER_STATE_SHARDING == 'zero'
        if self._zero_opt and config.PARAM_ROW_ALIGNMENT % self.mesh.size:
            raise ValueError(
                "OPTIMIZER_STATE_SHARDING='zero' shards moment-table rows "
                'over the WHOLE mesh: PARAM_ROW_ALIGNMENT=%d must be '
                'divisible by data*model = %d.'
                % (config.PARAM_ROW_ALIGNMENT, self.mesh.size))
        # USE_PALLAS_FUSED_CE on a multi-device mesh routes through the
        # shard_mapped kernel (ops/pallas_ce.py::sharded_fused_weighted_
        # ce_sums): GSPMD cannot partition the opaque pallas_call itself,
        # so the plain kernel would be replicated (full batch + full
        # table on every device) exactly where sharding matters. The
        # PARAM_ROW_ALIGNMENT check above already guarantees the sharded
        # variant's V % model_axis == 0 requirement.
        # Reference uses tf.train.AdamOptimizer() defaults
        # (tensorflow_model.py:232): lr=1e-3, b1=0.9, b2=0.999, eps=1e-8.
        # LAZY_EMBEDDING_ADAM swaps in LazyAdam-style sparse-row updates
        # for the token/path tables (a throughput trade-off, NOT the
        # reference's semantics — see ops/lazy_adam.py); dense params keep
        # optax Adam either way.
        if config.LAZY_EMBEDDING_ADAM:
            if (config.ADAM_MU_DTYPE != 'float32'
                    or config.ADAM_NU_DTYPE != 'float32'):
                # bf16 mu is the config DEFAULT; lazy Adam's sparse-row
                # update keeps fp32 moments and does not consume either
                # dtype knob, so this must warn, not raise.
                logger.warning(
                    'ADAM_MU_DTYPE=%r / ADAM_NU_DTYPE=%r are ignored: '
                    'they apply to the dense optax Adam only; '
                    'LAZY_EMBEDDING_ADAM keeps fp32 moments.',
                    config.ADAM_MU_DTYPE, config.ADAM_NU_DTYPE)
            logger.warning(
                'LAZY_EMBEDDING_ADAM is measured SLOWER on v5e-class chips '
                '(0.54x the dense step at java14m shapes, PERF.md): the '
                'scatter update serializes against the fused dense update. '
                'It remains available for semantics experiments only.')
            from code2vec_tpu.ops.lazy_adam import LazyEmbeddingAdam
            self.optimizer = LazyEmbeddingAdam(config.LEARNING_RATE, backend)
        else:
            # ADAM_MU_DTYPE / ADAM_NU_DTYPE = 'bfloat16' store the
            # moments in bf16 — HBM-traffic knobs for the HBM-bound dense
            # update (config comments + PERF.md); None keeps optax's
            # param-dtype default.
            mu_dtype = (jnp.bfloat16
                        if config.ADAM_MU_DTYPE == 'bfloat16' else None)
            if (config.ADAM_NU_DTYPE == 'bfloat16'
                    or config.GRADS_DTYPE == 'bfloat16'):
                # optax.adam has no nu_dtype; the local transform keeps
                # optax's ScaleByAdamState field names so checkpoints
                # stay field-compatible (training/adam_dtypes.py). It is
                # also mandatory under bf16 grads: its moment math is
                # EXPLICIT fp32, where optax's dtype-promotion rules
                # would let a bf16 grad meet a bf16-stored mu and
                # accumulate the EMA in bf16.
                from code2vec_tpu.training import adam_dtypes
                nu_dtype = (jnp.bfloat16
                            if config.ADAM_NU_DTYPE == 'bfloat16' else None)
                self.optimizer = adam_dtypes.adam(
                    config.LEARNING_RATE, mu_dtype=mu_dtype,
                    nu_dtype=nu_dtype)
            else:
                self.optimizer = optax.adam(config.LEARNING_RATE,
                                            mu_dtype=mu_dtype)
        # Telemetry (OBSERVABILITY.md): None when disabled — every
        # instrumented site below is then a single `is None` check.
        self._telemetry = None
        # dispatch shapes whose AOT step cost (FLOPs/bytes for train/mfu)
        # has been captured — first sight only, telemetry path only
        self._cost_keys = set()
        if getattr(config, 'TELEMETRY', False):
            from code2vec_tpu.telemetry import StepTelemetry
            self._telemetry = StepTelemetry(
                config, log=config.log,
                process_index=jax.process_index())
        # Device-memory ledger (telemetry/memory.py, OBSERVABILITY.md):
        # this trainer's state registers under a per-instance key, so
        # restores replace (never double-count) and a garbage-collected
        # trainer auto-releases its entries.
        self._mem_key = 'trainer:%x' % id(self)
        # Resilience (ROBUSTNESS.md): arm the process-global fault plan
        # from config. None = unset -> the env var fills in (launches
        # whose scripts you can't edit); '' = explicitly disabled, so an
        # exported FAULT_INJECT cannot leak into a declared control run.
        # Re-arming per Trainer resets fired state, so each run's
        # injections are deterministic even under process reuse (tests).
        faults.configure(config.FAULT_INJECT
                         if config.FAULT_INJECT is not None
                         else os.environ.get('FAULT_INJECT', ''))
        self._build_steps()

    # ----------------------------------------------------------- jit steps
    def _build_steps(self) -> None:
        backend = self.backend
        optimizer = self.optimizer
        top_k = self.config.TOP_K_WORDS_CONSIDERED_DURING_PREDICTION

        lazy = self.config.LAZY_EMBEDDING_ADAM
        # the mesh only matters to the loss when the fused CE must be
        # shard_mapped; None keeps single-device tracing mesh-free
        loss_mesh = self.mesh if self.mesh.size > 1 else None
        # GRADS_DTYPE='bfloat16': differentiate wrt the PRE-CAST bf16
        # params so the cotangents — above all the two table-grad
        # scatter-adds and the (B, V) logits backward — are produced and
        # streamed through HBM in bf16 instead of fp32 (config comment +
        # PERF.md). Config.verify() pins COMPUTE_DTYPE='bfloat16' with
        # it, which makes the forward bit-identical either way: the
        # model casts every param to bf16 before use, so casting first
        # changes only the dtype the gradients come back in. Master
        # params stay fp32; adam_dtypes upcasts the bf16 grads to fp32
        # before any moment math.
        grads_bf16 = self.config.GRADS_DTYPE == 'bfloat16'

        def cast_for_grads(params):
            return jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)

        # Ragged fusion (USE_PALLAS_RAGGED_FUSION, ops/pallas_ragged.py):
        # the packed twins below consume the (D, cap, 3) wire directly —
        # fused gather + encode + single-pass attention softmax, no
        # device-side unpack, no (B, C, .) planes — and the TRAIN step's
        # custom-VJP backward recomputes off the same segments instead
        # of storing per-slot residuals. Lazy Adam now runs fused too:
        # its sparse-row update reads the touched rows straight off the
        # packed index stream (rows_of below), which covers exactly the
        # rows the plane wire would touch — every slot up to each
        # example's effective length plus the PAD row.
        ragged = (self.config.USE_PALLAS_RAGGED_FUSION
                  and hasattr(backend, 'forward_packed'))
        ragged_train = ragged

        def plane_rows(arrays):
            return arrays[0], arrays[1], arrays[2]

        def make_train_step(loss_call, rows_of=plane_rows):
            def train_step(state: TrainerState, arrays
                           ) -> Tuple[TrainerState, jax.Array]:
                dropout_rng = jax.random.fold_in(state.rng, state.step)

                def loss_fn(params):
                    loss, _aux = loss_call(params, arrays, dropout_rng)
                    return loss

                diff_params = (cast_for_grads(state.params) if grads_bf16
                               else state.params)
                loss, grads = jax.value_and_grad(loss_fn)(diff_params)
                if lazy:
                    source, path, target = rows_of(arrays)
                    new_params, new_opt_state = optimizer.update_sparse(
                        state.params, grads, state.opt_state, state.step,
                        source, path, target)
                else:
                    updates, new_opt_state = optimizer.update(
                        grads, state.opt_state, state.params)
                    new_params = optax.apply_updates(state.params, updates)
                new_state = TrainerState(params=new_params,
                                         opt_state=new_opt_state,
                                         step=state.step + 1, rng=state.rng)
                return new_state, loss
            return train_step

        train_step = make_train_step(
            lambda params, arrays, rng:
            backend.loss_fn(params, arrays, rng, mesh=loss_mesh))

        mesh = self.mesh
        # the forward's mesh only matters where the ragged Pallas kernel
        # must be shard_mapped (GSPMD cannot partition a pallas_call);
        # None keeps single-device tracing mesh-free, like loss_mesh
        fwd_mesh = self.mesh if self.mesh.size > 1 else None

        def take_top_k(logits):
            # cross-shard merge on model-parallel meshes, plain lax.top_k
            # otherwise — the dispatch lives in sharded_top_k
            return sharded_top_k(logits, top_k, mesh)

        export_vectors = self.config.EXPORT_CODE_VECTORS

        def make_eval_step(forward_call, labels_of):
            def eval_step(params, arrays):
                code_vectors, attention, logits = forward_call(params,
                                                               arrays)
                topk_scores, topk_indices = take_top_k(logits)
                # weighted CE sums (not the mean): exact streaming
                # aggregation across batches and hosts — the reference's
                # Keras backend reports eval loss (keras_model.py:
                # 179-193); padded rows have weight 0 and drop out
                label, weight = labels_of(arrays)
                loss_sum, weight_sum = functional.weighted_ce_sums(
                    logits, label, weight)
                out = {'topk_indices': topk_indices,
                       'topk_scores': topk_scores,
                       'loss_sum': loss_sum,
                       'weight_sum': weight_sum}
                if export_vectors:
                    # only ship (B, D) code vectors to host when
                    # exporting — per-batch device->host traffic
                    # otherwise wasted
                    out['code_vectors'] = code_vectors
                return out
            return eval_step

        eval_step = make_eval_step(backend.forward,
                                   lambda arrays: (arrays[4], arrays[5]))

        # Predict programs come in OUTPUT TIERS (PREDICT_TIERS), each its
        # own jitted program, so the cheap path stops paying for the
        # expensive one: 'topk' ships only the (B, k) indices/scores,
        # 'attention' adds the (B, C) weights, 'full' adds the (B, D)
        # code vectors, and 'vectors' drops the logits matmul + top-k
        # entirely (XLA dead-code-eliminates the whole (B, V) product —
        # the dominant FLOPs at java14m's 261K-target vocab) for bulk
        # embedding export. The serving engine pre-compiles these per
        # batch/capacity bucket (serving/engine.py, SERVING.md).
        def make_predict_step(tier, forward_call):
            with_topk = tier != 'vectors'
            with_attention = tier in ('attention', 'full')
            with_vectors = tier in ('vectors', 'full')

            def predict_step(params, arrays):
                code_vectors, attention, logits = forward_call(params,
                                                               arrays)
                out = {}
                if with_topk:
                    topk_scores, topk_indices = take_top_k(logits)
                    out['topk_indices'] = topk_indices
                    # reference normalize_scores=True
                    # (tensorflow_model.py:305-306)
                    out['topk_scores'] = jax.nn.softmax(topk_scores,
                                                        axis=-1)
                if with_attention:
                    out['attention'] = attention
                if with_vectors:
                    out['code_vectors'] = code_vectors
                return out
            return predict_step

        # Explicit output shardings for the donated state: inference alone
        # re-layouts the zero-partitioned moments back toward the grads'
        # (model-only) sharding after the first update, silently undoing
        # OPTIMIZER_STATE_SHARDING='zero'. _init_opt_state reuses the
        # opt_state field so the initialized and stepped layouts cannot
        # diverge.
        abstract_params = backend.param_shapes()
        abstract_opt = jax.eval_shape(optimizer.init, abstract_params)
        replicated = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        self._state_shardings = TrainerState(
            params=mesh_lib.sharding_for_tree(abstract_params, mesh),
            opt_state=mesh_lib.sharding_for_tree(
                abstract_opt, mesh, zero_partition=self._zero_opt),
            step=replicated, rng=replicated)

        # Packed-wire twins. Default: the same step functions behind the
        # jitted device-side unpack (data/packed.py) — the unpack
        # scatters the dense context stream back to the exact (B, C)
        # planes + mask INSIDE the compiled program, so the model sees
        # bit-identical batches and the wire carries 3-5x fewer bytes.
        # With USE_PALLAS_RAGGED_FUSION the twins skip the unpack
        # entirely: the ragged fused encoder (ops/pallas_ragged.py)
        # walks the packed segments directly, matching the
        # unpack-then-dense outputs to fp32 rounding
        # (tests/test_pallas_ragged.py). PAD indices must match the
        # reader's pack-time fill (models/backends.py).
        token_pad = getattr(backend, 'token_pad_index', 0)
        path_pad = getattr(backend, 'path_pad_index', 0)
        max_contexts = self.config.MAX_CONTEXTS

        def unpack(packed_arrays):
            ctx, count, label, weight = packed_arrays
            source, path, target, mask = packed_lib.unpack_device(
                ctx, count, max_contexts, token_pad, path_pad)
            return (source, path, target, mask, label, weight)

        def packed_rows(arrays):
            # lazy Adam's touched-row sets off the packed wire: the ctx
            # stream holds every slot up to each example's effective
            # length (capacity padding carries the PAD triple). The PAD
            # rows are appended explicitly so the x_pad-path gradient of
            # count==0 rows is covered even when a batch packs with zero
            # capacity padding — O(1), and duplicates are idempotent
            # (ops/lazy_adam.py module doc).
            ctx = arrays[0]
            source = jnp.concatenate([
                ctx[..., 0].reshape(-1),
                jnp.full((1,), token_pad, jnp.int32)])
            path = jnp.concatenate([
                ctx[..., 1].reshape(-1),
                jnp.full((1,), path_pad, jnp.int32)])
            return source, path, ctx[..., 2].reshape(-1)

        if ragged_train:
            train_step_packed = make_train_step(
                lambda params, arrays, rng:
                backend.loss_fn_packed(params, arrays, rng,
                                       mesh=loss_mesh),
                rows_of=packed_rows)
        else:
            def train_step_packed(state, packed_arrays):
                return train_step(state, unpack(packed_arrays))

        if ragged:
            forward_packed = (lambda params, arrays:
                              backend.forward_packed(params, arrays,
                                                     mesh=fwd_mesh))
            eval_step_packed = make_eval_step(
                forward_packed, lambda arrays: (arrays[2], arrays[3]))
        else:
            def eval_step_packed(params, packed_arrays):
                return eval_step(params, unpack(packed_arrays))

        # donate the consumed staging buffers alongside the state: the
        # ring (stage_batches) keeps DEVICE_PREFETCH_BATCHES uploads in
        # flight, so freeing each batch's memory into the step bounds
        # the staging footprint. Harnesses that re-feed placed arrays
        # must disable it (config comment; benchlib pins it off).
        # Backends that cannot alias a given buffer (CPU; int inputs
        # with no matching output) emit jax's "donated buffers were not
        # usable" notice once per compile — expected, deliberately NOT
        # filtered (a global warnings filter would also hide genuinely
        # broken donations in the embedding program).
        donate_train = ((0, 1) if self.config.DONATE_STAGED_BATCHES
                        else (0,))
        donate_eval = (1,) if self.config.DONATE_STAGED_BATCHES else ()
        self._train_step = jax.jit(train_step, donate_argnums=donate_train,
                                   out_shardings=(self._state_shardings,
                                                  replicated))
        self._train_step_packed = jax.jit(
            train_step_packed, donate_argnums=donate_train,
            out_shardings=(self._state_shardings, replicated))
        self._eval_step = jax.jit(eval_step, donate_argnums=donate_eval)
        self._eval_step_packed = jax.jit(eval_step_packed,
                                         donate_argnums=donate_eval)
        # one jitted program per (tier, wire) — never donated: serving
        # re-feeds warm placed buffers and predict batches are tiny
        self._predict_steps = {}
        for tier in PREDICT_TIERS:
            step_fn = make_predict_step(tier, backend.forward)
            self._predict_steps[(tier, 'planes')] = jax.jit(step_fn)
            if ragged:
                # XLA dead-code-eliminates the attention plane scatter
                # for the tiers that never ship attention, exactly as it
                # DCEs the logits matmul for 'vectors'
                packed_fn = make_predict_step(tier, forward_packed)
            else:
                packed_fn = (lambda params, packed_arrays, _fn=step_fn:
                             _fn(params, unpack(packed_arrays)))
            self._predict_steps[(tier, 'packed')] = jax.jit(packed_fn)
        self._predict_step = self._predict_steps[('full', 'planes')]
        self._predict_step_packed = self._predict_steps[('full', 'packed')]
        self._token_pad = token_pad
        self._path_pad = path_pad

    # --------------------------------------------------------------- state
    def register_state_memory(self, params, opt_state=None) -> None:
        """Attribute this trainer's state to the device-memory ledger
        (telemetry/memory.py): called by every allocation owner of the
        training state — fresh init, params load, checkpoint restore
        (model_api) — under ONE per-trainer key, so a restore replaces
        the previous registration instead of double-counting.  Bytes
        are shape-constant across steps, so this is one-time
        bookkeeping, never hot-path work."""
        from code2vec_tpu.telemetry import memory as memory_lib
        led = memory_lib.ledger()
        led.register('params', self._mem_key, params, owner=self)
        if opt_state is not None:
            led.register('opt_state', self._mem_key, opt_state,
                         owner=self)

    def init_state(self, seed: int = 42) -> TrainerState:
        init_rng, train_rng = jax.random.split(jax.random.PRNGKey(seed))
        params = self.backend.init(init_rng)
        params = mesh_lib.shard_params(params, self.mesh)
        opt_state = self._init_opt_state(params)
        self.register_state_memory(params, opt_state)
        return TrainerState(params=params, opt_state=opt_state,
                            step=jnp.zeros((), jnp.int32), rng=train_rng)

    def _init_opt_state(self, params):
        # explicit out_shardings: Adam moments must follow the configured
        # moment layout — jit alone does not propagate input shardings to
        # the opt-state outputs. Single source of truth with the train
        # step's donated-output layout (_build_steps).
        # graftlint: disable=recompile-hazard -- cold path: runs once per init/restore, never per step; the throwaway program is the point
        return jax.jit(self.optimizer.init,
                       out_shardings=self._state_shardings.opt_state)(
                           params)

    def abstract_state(self) -> Tuple[Any, Any]:
        """(abstract_canonical_params, abstract_opt_state) with
        *current-mesh* shardings attached, for checkpoint restore targets —
        nothing is materialized on device (no throwaway init at
        384M-param-scale).

        Params use the CANONICAL checkpoint layout (flat {name: array}
        dict) so checkpoints are loadable under either backend; optimizer
        state keeps the backend-native tree (training resume requires the
        same backend — enforced with a clear error in CheckpointStore)."""
        abstract_params = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
            self.backend.param_shapes())
        abstract_opt = jax.eval_shape(self.optimizer.init, abstract_params)
        canonical = self.backend.named_params(abstract_params)._asdict()
        return (mesh_lib.attach_shardings(canonical, self.mesh),
                mesh_lib.attach_shardings(abstract_opt, self.mesh,
                                          zero_partition=self._zero_opt))

    def state_from_params(self, params, step: int = 0,
                          seed: int = 42) -> TrainerState:
        params = mesh_lib.shard_params(params, self.mesh)
        opt_state = self._init_opt_state(params)
        self.register_state_memory(params, opt_state)
        return TrainerState(params=params, opt_state=opt_state,
                            step=jnp.asarray(step, jnp.int32),
                            rng=jax.random.PRNGKey(seed))

    # --------------------------------------------------------------- steps
    def _check_packed(self, arrays) -> None:
        data_axis = self.mesh.shape[mesh_lib.DATA_AXIS]
        if arrays[0].shape[0] != data_axis:
            raise ValueError(
                'packed batch was built for %d data shard(s) but the mesh '
                'data axis is %d — pack with data_shards=%d '
                '(data/packed.py).'
                % (arrays[0].shape[0], data_axis, data_axis))

    def train_step(self, state: TrainerState, batch: Batch
                   ) -> Tuple[TrainerState, jax.Array]:
        host_arrays = batch.device_arrays()
        if len(host_arrays) == 4:
            self._check_packed(host_arrays)  # clear error BEFORE placement
        arrays = mesh_lib.shard_batch(host_arrays, self.mesh,
                                      self.config.SHARD_CONTEXTS)
        return self.train_step_placed(state, arrays)

    def stage_batches(self, batches: Iterable[Batch]):
        """The device staging ring: place batches ahead of the step
        consuming them, yielding ``(placed_arrays, batch)`` (the host
        batch rides along for consumers that need its strings/weights,
        e.g. eval decode). Accepts either wire format — a batch is placed
        via its own ``device_arrays()``.

        jax transfers are async, so staging the next batch while the
        current step computes overlaps the host->device copy with device
        work instead of serializing upload -> step -> upload (through this
        environment's device tunnel one batch upload costs ~290 ms against
        a ~51 ms step — see benchmarks/diag_step_breakdown.py).
        ``DEVICE_PREFETCH_BATCHES`` bounds the ring depth (device memory
        held by staged batches; 0 degenerates to place-then-consume), and
        placement is per-device direct (shard_batch ``direct=True``): each
        data shard's slice transfers straight to its device instead of
        replicate-then-slice. The consuming step donates the buffers back
        (DONATE_STAGED_BATCHES), so the ring's footprint stays ~depth
        batches."""
        depth = max(0, self.config.DEVICE_PREFETCH_BATCHES)
        if self.mesh.devices.flat[0].platform.lower() == 'cpu':
            # XLA:CPU's in-process collectives can deadlock their 40s
            # rendezvous when extra async placements are in flight next to
            # a sharded program on starved hosts (observed as SIGABRT on a
            # 1-core 8-virtual-device mesh). Host==device memory on CPU, so
            # lookahead buys nothing there anyway.
            depth = 0
        shard_contexts = self.config.SHARD_CONTEXTS
        staged = collections.deque()
        tele = self._telemetry
        # staging-bucket ledger accounting (telemetry/memory.py) rides
        # the telemetry gate: register on placement, release at pop —
        # metadata-only (.nbytes), zero host syncs; the plain path
        # carries nothing
        led = None
        mem_keys: collections.deque = collections.deque()
        mem_seq = 0
        if tele is not None:
            from code2vec_tpu.telemetry import memory as memory_lib
            led = memory_lib.ledger()
            tele.registry.gauge('staging/ring_depth').set(depth)
        try:
            for batch in batches:
                if tele is not None:
                    # the DISPATCH cost of the async per-device placement —
                    # jax transfers complete in the background, so a spike
                    # here means host-side slicing/copy, not wire time
                    with jax.profiler.TraceAnnotation('host/h2d_place'), \
                            tele.h2d.time():
                        placed = mesh_lib.shard_batch(batch.device_arrays(),
                                                      self.mesh,
                                                      shard_contexts,
                                                      direct=True)
                    tele.ring_occupancy.set(len(staged) + 1)
                    key = '%s/%d' % (self._mem_key, mem_seq)
                    mem_seq += 1
                    led.register('staging', key,
                                 sum(int(a.nbytes) for a in placed))
                    mem_keys.append(key)
                else:
                    placed = mesh_lib.shard_batch(batch.device_arrays(),
                                                  self.mesh, shard_contexts,
                                                  direct=True)
                staged.append((placed, batch))
                if len(staged) > depth:
                    if led is not None:
                        led.release('staging', mem_keys.popleft())
                    yield staged.popleft()
            while staged:
                if tele is not None:
                    tele.ring_occupancy.set(len(staged) - 1)
                if led is not None:
                    led.release('staging', mem_keys.popleft())
                yield staged.popleft()
        finally:
            # an abandoned generator (early break, exception) must not
            # leave phantom staging entries in the ledger
            if led is not None:
                while mem_keys:
                    led.release('staging', mem_keys.popleft())

    def train_step_placed(self, state: TrainerState, arrays
                          ) -> Tuple[TrainerState, jax.Array]:
        """train_step over arrays already placed by ``stage_batches`` —
        either wire format, dispatched on the tuple's arity (packed = 4
        arrays, planes = 6)."""
        if len(arrays) == 4:
            self._check_packed(arrays)
            return self._train_step_packed(state, arrays)
        return self._train_step(state, arrays)

    def eval_step_placed(self, params, arrays) -> dict:
        """eval_step over arrays already placed by ``stage_batches``."""
        if len(arrays) == 4:
            self._check_packed(arrays)
            return self._eval_step_packed(params, arrays)
        return self._eval_step(params, arrays)

    def eval_step(self, params, batch: Batch) -> dict:
        arrays = mesh_lib.shard_batch(batch.device_arrays(), self.mesh,
                                      self.config.SHARD_CONTEXTS)
        return self.eval_step_placed(params, arrays)

    def predict_step_placed(self, params, arrays, tier: str = 'full'
                            ) -> dict:
        """Tiered predict over arrays already placed on the mesh — either
        wire format, dispatched on the tuple's arity like the other
        ``*_placed`` entry points. ``tier`` selects the output tier's
        pre-built jitted program (PREDICT_TIERS)."""
        if tier not in PREDICT_TIERS:
            raise ValueError('tier must be one of %s, got %r'
                             % (PREDICT_TIERS, tier))
        if len(arrays) == 4:
            self._check_packed(arrays)
            return self._predict_steps[(tier, 'packed')](params, arrays)
        return self._predict_steps[(tier, 'planes')](params, arrays)

    def predict_program_memory(self, params, arrays, tier: str = 'full'
                               ) -> Optional[dict]:
        """AOT memory analysis of ONE warm predict program (the shapes
        of ``arrays``): generated-code/temp/argument/output bytes, for
        the ledger's executables bucket (telemetry/memory.py).  Costs
        one extra XLA compile, so the serving engine only calls it at
        warmup with telemetry enabled; returns None where the backend
        has no memory analysis."""
        wire = 'packed' if len(arrays) == 4 else 'planes'
        return self._program_memory(self._predict_steps[(tier, wire)],
                                    params, arrays)

    @staticmethod
    def _program_memory(fn, *args) -> Optional[dict]:
        """One jitted program's AOT memory record — the single
        definition of the record shape shared by the serving ledger
        (predict) and the bench A/B (train)."""
        try:
            analysis = fn.lower(*args).compile().memory_analysis()
            return {
                'generated_code_bytes':
                    int(analysis.generated_code_size_in_bytes),
                'temp_bytes': int(analysis.temp_size_in_bytes),
                'argument_bytes': int(analysis.argument_size_in_bytes),
                'output_bytes': int(analysis.output_size_in_bytes),
            }
        except Exception:
            return None

    @staticmethod
    def _program_cost(fn, *args) -> Optional[dict]:
        """One jitted program's AOT cost record: logical FLOPs + bytes
        accessed from ``Lowered.cost_analysis()`` — analysis of the
        lowered (pre-partitioning) module, so it costs one trace +
        lowering but NO extra backend compile (a telemetry run keeps
        zero post-warmup compiles).  None where the version/backend has
        no cost analysis."""
        try:
            cost = fn.lower(*args).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            flops = float(cost.get('flops', 0.0))
            if flops <= 0:
                return None
            return {'flops': flops,
                    'bytes_accessed': float(cost.get('bytes accessed', 0.0))}
        except Exception:
            return None

    def train_program_cost(self, state: TrainerState, arrays
                           ) -> Optional[dict]:
        """AOT FLOPs/bytes of the train-step program for the shapes of
        ``arrays`` (either wire) — the MFU/roofline numerator
        (telemetry/goodput.py, OBSERVABILITY.md "Training goodput")."""
        fn = (self._train_step_packed if len(arrays) == 4
              else self._train_step)
        return self._program_cost(fn, state, arrays)

    def _maybe_record_step_cost(self, shape_key: str, state, arrays) -> None:
        """First sight of a dispatch shape: capture its AOT step cost
        into the goodput ledger (telemetry path; rides the same
        first-sight cadence as the capacity tracker)."""
        if shape_key in self._cost_keys:
            return
        self._cost_keys.add(shape_key)
        cost = self.train_program_cost(state, arrays)
        if cost is not None:
            self._telemetry.goodput.set_step_cost(
                shape_key, cost['flops'], cost['bytes_accessed'])

    def train_program_memory(self, state: TrainerState, arrays
                             ) -> Optional[dict]:
        """AOT memory analysis of the train-step program for the shapes
        of ``arrays`` (either wire) — same record shape as
        ``predict_program_memory``. ``temp_bytes`` is the axis the
        ragged custom-VJP backward moves: the recompute schedule holds
        no (D, cap, .) residuals across the loss tail, so the fused
        train executable's temporary allocation drops against the
        autodiff twin's (benchmarks/bench_pallas_ragged.py records the
        per-arm value). Costs one extra XLA compile — bench/offline use
        only, never the hot path."""
        fn = (self._train_step_packed if len(arrays) == 4
              else self._train_step)
        return self._program_memory(fn, state, arrays)

    def predict_step(self, params, batch: Batch, tier: str = 'full'
                     ) -> dict:
        """Predict over a host batch. Plane batches follow the configured
        wire format: under 'packed' the batch is packed here (the REPL
        keeps its plane/strings view) so prediction exercises the same
        wire + device-unpack path as training."""
        if isinstance(batch, Batch) and \
                self.config.wire_format_for(jax.process_count()) == 'packed':
            batch = packed_lib.pack_batch(
                batch, self._token_pad, self._path_pad,
                data_shards=self.mesh.shape[mesh_lib.DATA_AXIS])
        arrays = mesh_lib.shard_batch(batch.device_arrays(), self.mesh,
                                      self.config.SHARD_CONTEXTS)
        return self.predict_step_placed(params, arrays, tier=tier)

    # ----------------------------------------------------------- main loop
    def fit(self, state: TrainerState,
            epoch_batches: Callable[[int], Iterable[Batch]],
            start_epoch: int = 0,
            on_epoch_end: Optional[Callable[[int, TrainerState, int],
                                            None]] = None,
            on_log: Optional[Callable[[int, float, float], None]] = None,
            on_eval_interval: Optional[Callable[[int, TrainerState],
                                                None]] = None,
            on_save_interval: Optional[Callable[[int, int, TrainerState],
                                                None]] = None,
            on_epoch_time: Optional[Callable[[int, int, float],
                                             None]] = None,
            preemption=None,
            on_preempt: Optional[Callable[[int, int, TrainerState],
                                          None]] = None,
            on_divergence: Optional[Callable[[int],
                                             Optional[TrainerState]]] = None
            ) -> TrainerState:
        """Epoch-driven loop with the reference's windowed throughput trace
        (tensorflow_model.py:74-101, 424-430).

        ``on_epoch_time(epoch, batch_num, seconds)`` receives each epoch's
        training wall time (the loop over its batches, including interval
        evals; excluding ``on_epoch_end``'s eval/save) — model_api routes
        it into the metrics writer.

        Resilience hooks (ROBUSTNESS.md): ``preemption`` is a
        ``PreemptionHandler`` polled at step boundaries — when it has a
        pending signal the loop runs ``on_preempt(epoch, batch_num,
        state)`` (the final snapshot save) and returns cleanly.
        ``on_divergence(last_good_step)`` restores the newest checkpoint
        at or before that step for the divergence guard, returning a
        ``TrainerState`` or None."""
        config = self.config
        log_every = config.NUM_BATCHES_TO_LOG_PROGRESS
        # resumed runs continue the step axis instead of restarting at 0
        # (metric streams are append-mode)
        batch_num = start_epoch * config.train_steps_per_epoch
        window_losses = []  # device arrays: no per-step host sync, the
        window_examples = 0  # host only blocks once per log window
        window_start = time.time()
        guard = None
        watchdog = None
        if config.DIVERGENCE_GUARD:
            from code2vec_tpu.resilience.guard import DivergenceGuard
            from code2vec_tpu.telemetry.stepwatch import telemetry_dir
            guard = DivergenceGuard(
                config.MAX_DIVERGENCE_REWINDS, restore=on_divergence,
                dump_dir=telemetry_dir(config), log=config.log,
                telemetry=self._telemetry)
        if config.HANG_WATCHDOG_SECS > 0:
            from code2vec_tpu.resilience.watchdog import HangWatchdog
            from code2vec_tpu.telemetry.stepwatch import telemetry_dir
            tele = self._telemetry
            watchdog = HangWatchdog(
                config.HANG_WATCHDOG_SECS,
                dump_dir=telemetry_dir(config), log=config.log,
                # metrics.jsonl must record the run's last healthy state
                # before the abort
                on_expire=((lambda: tele.flush_now(
                    getattr(self, '_last_batch_num', 0)))
                    if tele is not None else None))
        try:
            state = self._fit_loop(
                state, epoch_batches, start_epoch, on_epoch_end, on_log,
                on_eval_interval, on_save_interval, batch_num, window_losses,
                window_examples, window_start, log_every, on_epoch_time,
                guard=guard, watchdog=watchdog, preemption=preemption,
                on_preempt=on_preempt)
        except Exception as exc:
            # OOM forensics (telemetry/memory.py): a RESOURCE_EXHAUSTED
            # surfacing anywhere in the hot loop — dispatch or the
            # blocking window sync — dumps the attribution ledger
            # before the run dies with an otherwise bare XLA error
            from code2vec_tpu.telemetry import memory as memory_lib
            memory_lib.ledger().note_oom(exc, 'trainer.fit')
            raise
        finally:
            if watchdog is not None:
                watchdog.shutdown()
            if getattr(self, '_profiling', False):
                jax.profiler.stop_trace()
                self._profiling = False
            if self._telemetry is not None:
                # final flush + stop any live on-demand capture, so a
                # crashing run still leaves metrics.jsonl current
                self._telemetry.shutdown(getattr(self, '_last_batch_num', 0))
        return state

    @staticmethod
    def _num_valid_contexts(host_batch) -> int:
        """Contexts a batch feeds the step: retained slots for the packed
        wire (count), mask-valid slots for planes. Telemetry-path only.
        NB: on plane batches ``.count`` resolves to the tuple METHOD, so
        probe by array-ness, not truthiness."""
        count = getattr(host_batch, 'count', None)
        if isinstance(count, np.ndarray):
            return int(count.sum())
        return int(host_batch.mask.sum())

    def _fit_loop(self, state, epoch_batches, start_epoch, on_epoch_end,
                  on_log, on_eval_interval, on_save_interval, batch_num,
                  window_losses, window_examples, window_start, log_every,
                  on_epoch_time=None, guard=None, watchdog=None,
                  preemption=None, on_preempt=None):
        config = self.config
        tele = self._telemetry
        if watchdog is None:
            # the shared nullcontext is stateless and reusable; taking
            # (and discarding) the label args keeps the disabled path
            # free of any per-batch string formatting
            null_ctx = contextlib.nullcontext()

            def watched(label_fmt, step):
                return null_ctx
        else:
            def watched(label_fmt, step):
                return watchdog.watch(label_fmt % step)
        host_batch = None

        def rewind(losses_host):
            """Divergence-guard rewind over the current window — reads
            the loop's batch_num/host_batch/state at call time; raises
            DivergenceError when the guard is out of options.  step_now
            keys the rewind ceiling in state.step units (after an
            earlier rewind they lag batch_num, and checkpoints are
            keyed by state.step)."""
            step_before = int(state.step)
            with goodput_lib.interval(goodput_lib.KIND_REWIND):
                new_state = guard.handle(batch_num,
                                         [float(x) for x in losses_host],
                                         host_batch,
                                         step_now=step_before)
            if tele is not None:
                # the steps from the restored checkpoint back to the
                # rewind point re-train lost progress: badput, not
                # productive (goodput ledger bills them as they run)
                tele.goodput.mark_replay(step_before
                                         - int(new_state.step))
            return new_state
        if tele is not None:
            tele.resume()  # shutdown() in fit's finally disables globally
        self._profiling = False
        profile_done = False
        # profile window is relative to THIS run's first batch so resumed
        # runs (batch_num starts past 0) still capture a trace
        first_batch = batch_num
        profile_start = first_batch + config.PROFILE_START_STEP
        profile_stop_step = profile_start + config.PROFILE_NUM_STEPS
        for epoch in range(start_epoch, config.NUM_TRAIN_EPOCHS):
            epoch_start = time.time()
            staged = iter(self.stage_batches(epoch_batches(epoch)))
            while True:
                # batch-wait: host time blocked on the input pipeline for
                # the next staged batch (the starvation signal). The
                # generator's h2d placement runs INSIDE this next() and is
                # timed separately (stage_batches) — subtract it so wait
                # measures pipeline starvation, not placement.
                if tele is not None:
                    h2d_before = tele.h2d.total
                    iter_t0 = time.perf_counter()
                    with jax.profiler.TraceAnnotation('host/batch_wait'), \
                            watched('next staged batch (batch %d)',
                                    batch_num):
                        item = next(staged, None)
                    wait_s = max(
                        0.0, (time.perf_counter() - iter_t0)
                        - (tele.h2d.total - h2d_before))
                    tele.batch_wait.record(wait_s)
                    # iteration-start mark for the goodput ledger; wait
                    # beyond the pipeline's steady poll cost is badput
                    tele.goodput.note_input_wait(wait_s)
                else:
                    with watched('next staged batch (batch %d)', batch_num):
                        item = next(staged, None)
                if item is None:
                    break
                # preemption (ROBUSTNESS.md pillar 2): the signal handler
                # only sets a flag; the exit happens HERE, at a step
                # boundary, so the saved state is a completed step and
                # resume loses at most the batch just pulled
                if preemption is not None and preemption.requested:
                    config.log(
                        'Preemption (%s): leaving the fit loop at step '
                        'boundary %d for a final snapshot save.'
                        % (preemption.signal_name, batch_num))
                    if on_preempt is not None:
                        with goodput_lib.interval(goodput_lib.KIND_PREEMPT):
                            on_preempt(epoch, batch_num, state)
                    if tele is not None:
                        tele.goodput.run_end(batch_num, reason='preempt')
                    return state
                arrays, host_batch = item
                # step-interval checkpointing fires at the TOP of the next
                # iteration (state reflects batch_num completed steps): an
                # interval landing on an epoch's final step must not
                # pre-empt on_epoch_end's save, which records the completed
                # epoch for resume. Async, so it costs one device->host
                # copy, not a persistence stall.
                if on_save_interval is not None and batch_num > 0 and \
                        config.SAVE_EVERY_N_STEPS > 0 and \
                        batch_num % config.SAVE_EVERY_N_STEPS == 0:
                    with goodput_lib.interval(goodput_lib.KIND_CHECKPOINT):
                        on_save_interval(epoch, batch_num, state)
                if config.PROFILE_DIR and not profile_done:
                    # jax.profiler cannot nest: the fixed window must also
                    # yield to a live on-demand capture (the controller
                    # already yields to _profiling — both directions)
                    on_demand_active = (tele is not None
                                        and tele.trace.active)
                    if batch_num >= profile_start and not self._profiling \
                            and not on_demand_active:
                        jax.profiler.start_trace(config.PROFILE_DIR)
                        self._profiling = True
                    elif batch_num >= profile_stop_step and self._profiling:
                        jax.block_until_ready(state.params)
                        jax.profiler.stop_trace()
                        self._profiling = False
                        profile_done = True
                        config.log('Profiler trace written to `%s`.'
                                   % config.PROFILE_DIR)
                if tele is not None:
                    if not self._profiling:
                        # on-demand capture (TELEMETRY_TRACE_AT_STEP /
                        # touch file); inert while PROFILE_DIR's fixed
                        # window holds the profiler
                        tele.trace.maybe_update(batch_num,
                                                sync_tree=state.params)
                    if len(arrays) == 4:
                        # each NEW packed capacity = one more jit
                        # specialization of the whole step program
                        shape_key = 'packed:%d' % int(arrays[0].shape[1])
                        tele.capacity.observe(int(arrays[0].shape[1]),
                                              batch_num)
                    else:
                        shape_key = 'planes:%d' % int(arrays[0].shape[0])
                    # first sight of a dispatch shape: AOT step FLOPs/
                    # bytes for the MFU gauges (lowering only — no
                    # extra backend compile)
                    self._maybe_record_step_cost(shape_key, state, arrays)
                    with jax.profiler.StepTraceAnnotation(
                            'train', step_num=batch_num), \
                            tele.dispatch.time():
                        state, loss = self.train_step_placed(state, arrays)
                else:
                    state, loss = self.train_step_placed(state, arrays)
                if faults.maybe_fire('slow_step', step=batch_num):
                    # a sustained per-step stall shaped like a degraded
                    # input stage or a throttled device — the step-time
                    # anomaly watchdog's drill (OBSERVABILITY.md)
                    time.sleep(faults.SLOW_STEP_SECONDS)
                if faults.maybe_fire('nan_loss', step=batch_num):
                    # poison on device: keeps the real loss's dtype and
                    # sharding, so the window sync path is exercised
                    # exactly as a genuine divergence would
                    loss = loss + float('nan')
                batch_num += 1
                if faults.maybe_fire('sigterm', step=batch_num):
                    os.kill(os.getpid(), signal_lib.SIGTERM)
                window_losses.append(loss)
                n_valid = host_batch.num_valid_examples
                window_examples += n_valid
                if tele is not None:
                    tele.count_batch(n_valid,
                                     self._num_valid_contexts(host_batch))
                if batch_num % log_every == 0:
                    # device_get, not eager jnp ops: stacking mesh-sharded
                    # scalars eagerly aborts in jaxlib on CPU meshes
                    if tele is not None:
                        sync_t0 = time.perf_counter()
                        with jax.profiler.TraceAnnotation('host/sync'), \
                                watched('log-window device sync (batch %d)',
                                        batch_num):
                            losses = jax.device_get(window_losses)
                        tele.sync.record(time.perf_counter() - sync_t0)
                    else:
                        with watched('log-window device sync (batch %d)',
                                     batch_num):
                            losses = jax.device_get(window_losses)
                    sum_loss = float(np.sum(losses))
                    # divergence guard (ROBUSTNESS.md pillar 1): the sum
                    # is non-finite iff any loss in the window is, so the
                    # check piggybacks on this sync at zero extra host
                    # round-trips
                    if guard is not None and not np.isfinite(sum_loss):
                        state = rewind(losses)
                        window_losses = []
                        window_examples = 0
                        window_start = time.time()
                        continue
                    elapsed = time.time() - window_start
                    throughput = window_examples / max(elapsed, 1e-9)
                    config.log(
                        'Average loss at batch %d: %f, \tthroughput: %d '
                        'samples/sec' % (batch_num,
                                         sum_loss / len(window_losses),
                                         throughput))
                    if on_log is not None:
                        on_log(batch_num, sum_loss / len(window_losses),
                               throughput)
                    window_losses = []
                    window_examples = 0
                    window_start = time.time()
                # mid-epoch evaluation (the reference Keras backend's
                # ModelEvaluationCallback every NUM_TRAIN_BATCHES_TO_EVALUATE
                # batches, keras_model.py:326-345, config.py:53)
                if on_eval_interval is not None and \
                        config.NUM_TRAIN_BATCHES_TO_EVALUATE > 0 and \
                        batch_num % config.NUM_TRAIN_BATCHES_TO_EVALUATE == 0:
                    # the reset below DISCARDS the partial window, so the
                    # guard must check it first or a NaN between log
                    # boundaries slips through unexamined (and the eval
                    # would run — and log — on a possibly-diverged state)
                    if guard is not None and window_losses:
                        with watched('eval-interval window sync (batch %d)',
                                     batch_num):
                            losses = jax.device_get(window_losses)
                        if not np.isfinite(float(np.sum(losses))):
                            state = rewind(losses)
                            window_losses = []
                            window_examples = 0
                            window_start = time.time()
                            continue
                    with goodput_lib.interval(goodput_lib.KIND_EVAL):
                        on_eval_interval(batch_num, state)
                    # restart the throughput window completely: a partial
                    # window timed from post-eval would overstate samples/sec
                    window_losses = []
                    window_examples = 0
                    window_start = time.time()
                if tele is not None:
                    iter_secs = time.perf_counter() - iter_t0
                    tele.step_total.record(iter_secs)
                    # goodput: clean step seconds = iteration minus the
                    # badput accrued inside it; compile-free samples feed
                    # the step-time anomaly watchdog
                    clean_s, had_compile = tele.goodput.step_done(
                        batch_num, iter_secs, shape_key)
                    if not had_compile:
                        tele.anomaly.observe(shape_key, clean_s, batch_num)
                    tele.after_step(batch_num)
                    self._last_batch_num = batch_num
            if (tele is not None or guard is not None) and window_losses:
                # short epochs (steps/epoch < log_every) may never hit a
                # log window: sync the partial window here so step/sync_ms
                # is recorded at least once per epoch AND the divergence
                # guard examines every epoch's losses — without this a
                # NaN in a short run is never detected (the losses stay
                # in the window; this sync does not consume them). With
                # telemetry off the guard pays this one extra device_get
                # per EPOCH, not per step.
                sync_t0 = time.perf_counter()
                with watched('epoch-end window sync (batch %d)', batch_num):
                    losses = jax.device_get(window_losses)
                if tele is not None:
                    sync_s = time.perf_counter() - sync_t0
                    tele.sync.record(sync_s)
                    # this sync drains dispatched device work — real
                    # training progress outside any iteration's seconds
                    tele.goodput.note_productive(sync_s)
                if guard is not None and \
                        not np.isfinite(float(np.sum(losses))):
                    state = rewind(losses)
                    window_losses = []
                    window_examples = 0
            epoch_wall = time.time() - epoch_start
            if tele is not None:
                tele.registry.gauge('train/epoch_wall_time_s').set(
                    epoch_wall)
            if on_epoch_time is not None:
                on_epoch_time(epoch, batch_num, epoch_wall)
            if on_epoch_end is not None:
                # pass the ACTUAL global batch number: estimates from the
                # unfiltered line count would put eval metrics on a
                # different (non-monotonic) step axis than interval evals
                on_epoch_end(epoch, state, batch_num)
                window_start = time.time()  # don't bill eval/save time
        return state


def as_numpy(tree):
    """Fetch a pytree of device arrays to host numpy."""
    return jax.tree_util.tree_map(np.asarray, tree)
