from code2vec_tpu.training.trainer import Trainer, TrainerState

__all__ = ['Trainer', 'TrainerState']
