"""Adam with independently reduced-precision moment STORAGE.

``optax.adam`` exposes ``mu_dtype`` (first moment) but stores the second
moment in the parameter dtype unconditionally. At java14m scale the nu
tree is another 1.54 GB of fp32 optimizer state streamed read+write every
step of the HBM-bound dense update (PERF.md roofline: ~1.9 ms/step at the
measured ~819 GB/s), the same stream the measured ``ADAM_MU_DTYPE`` flip
already halved for mu. This transform generalizes the trick: moments are
COMPUTED in fp32 every step (both are upcast before use, and the
``sqrt(nu)`` denominator is formed in fp32), only their HBM *storage*
dtype drops to bf16 — identical discipline to optax's own mu_dtype
handling (optax promotes grads+mu before the update and casts at the end).

State is ``optax.ScaleByAdamState`` — same ``count/mu/nu`` field names and
tree structure as ``optax.adam`` — so checkpoints remain field-compatible
and `checkpoints.py`'s moment-dtype adaptation covers cross-dtype resumes
in both directions.

Reference anchor: the reference trains with a default
``tf.compat.v1.train.AdamOptimizer`` (fp32 moments) —
/root/reference/tensorflow_model.py:232. Storage dtype is a TPU-side
memory-bandwidth knob with an A/B + learning-curve gate, not a semantic
departure.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax


def _cast_tree(tree: Any, dtype) -> Any:
    if dtype is None:
        return tree
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), tree)


def scale_by_adam_dtypes(b1: float = 0.9, b2: float = 0.999,
                         eps: float = 1e-8,
                         mu_dtype: Optional[Any] = None,
                         nu_dtype: Optional[Any] = None
                         ) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` plus a ``nu_dtype`` storage knob.

    ``mu_dtype=None`` / ``nu_dtype=None`` keep the parameter dtype, like
    optax. With both ``None`` the update is numerically identical to
    ``optax.scale_by_adam`` (asserted by tests/test_adam_dtypes.py).
    """
    mu_dtype = jnp.dtype(mu_dtype) if mu_dtype is not None else None
    nu_dtype = jnp.dtype(nu_dtype) if nu_dtype is not None else None

    def init_fn(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params)
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params=None):
        del params
        count = optax.safe_int32_increment(state.count)

        # Moment math runs in EXPLICIT fp32, whatever the storage dtypes
        # of the incoming grads and stored moments: an EMA accumulated in
        # bf16 silently drops sub-epsilon increments ((1-b2)*g^2 is ~1e-3
        # of nu), which is precisely the failure mode the storage-only
        # narrowing must not introduce. fp32 inputs pass through
        # unchanged, so the None/None path stays a drop-in for
        # optax.adam; bf16 inputs (GRADS_DTYPE='bfloat16' or narrowed
        # storage) are upcast before any arithmetic.
        def f32(x):
            return x.astype(jnp.float32) if jnp.issubdtype(
                x.dtype, jnp.floating) else x

        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * f32(m) + (1.0 - b1) * f32(g),
            updates, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, v: b2 * f32(v) + (1.0 - b2) * jnp.square(f32(g)),
            updates, state.nu)
        b1c = 1.0 - b1 ** count.astype(jnp.float32)
        b2c = 1.0 - b2 ** count.astype(jnp.float32)
        new_updates = jax.tree_util.tree_map(
            lambda m, v: (m / b1c) / (jnp.sqrt(v / b2c) + eps), mu, nu)
        return new_updates, optax.ScaleByAdamState(
            count=count,
            mu=_cast_tree(mu, mu_dtype),
            nu=_cast_tree(nu, nu_dtype))

    return optax.GradientTransformation(init_fn, update_fn)


def adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-8, mu_dtype: Optional[Any] = None,
         nu_dtype: Optional[Any] = None) -> optax.GradientTransformation:
    """``optax.adam`` with the extra ``nu_dtype`` storage knob."""
    return optax.chain(
        scale_by_adam_dtypes(b1=b1, b2=b2, eps=eps,
                             mu_dtype=mu_dtype, nu_dtype=nu_dtype),
        optax.scale(-learning_rate))
