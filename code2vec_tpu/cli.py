"""CLI entry point (reference code2vec.py:16-38 dispatch).

    python -m code2vec_tpu.cli --data ds --test ds.val.c2v --save models/m/s
    python -m code2vec_tpu.cli --load models/m/s --test ds.test.c2v
    python -m code2vec_tpu.cli --load models/m/s --predict
    python -m code2vec_tpu.cli --load models/m/s --release
    python -m code2vec_tpu.cli --load models/m/s --save_word2v tokens.txt
    python -m code2vec_tpu.cli --load models/m/s --bulk-vectors corpus.c2v
    python -m code2vec_tpu.cli --load models/m/s --build-index corpus.c2v
    python -m code2vec_tpu.cli --load models/m/s \
        --index-path corpus.c2v.vecindex --query-neighbors queries.c2v

The backend ('flax' | 'jax') is selected at runtime with ``--framework``
(the reference selected 'tensorflow' | 'keras' the same way,
code2vec.py:7-13).
"""
from __future__ import annotations

from code2vec_tpu.config import Config
from code2vec_tpu.vocab import VocabType


def main(args=None) -> None:
    config = Config().load_from_args(args)
    config.verify()

    # honor the caller's JAX_PLATFORMS even when a sitecustomize preimport
    # pinned a different platform list before this process's env was read
    import os

    import jax
    env_platforms = os.environ.get('JAX_PLATFORMS')
    if env_platforms and jax.config.jax_platforms != env_platforms:
        try:
            jax.config.update('jax_platforms', env_platforms)
        except RuntimeError:
            pass  # backends already initialized

    # multi-host: join the jax.distributed runtime when pod/env config is
    # present (no-op single host)
    from code2vec_tpu.parallel.distributed import \
        maybe_initialize_distributed
    maybe_initialize_distributed(log=config.log)

    from code2vec_tpu.model_api import Code2VecModel
    model = Code2VecModel(config)
    config.log('Done creating code2vec model')

    if config.is_training:
        model.train()
    if config.SAVE_W2V is not None:
        model.save_word2vec_format(config.SAVE_W2V, VocabType.Token)
        config.log('Origin word vectors saved in word2vec text format in: %s'
                   % config.SAVE_W2V)
    if config.SAVE_T2V is not None:
        model.save_word2vec_format(config.SAVE_T2V, VocabType.Target)
        config.log('Target word vectors saved in word2vec text format in: %s'
                   % config.SAVE_T2V)
    # one-flag parity export of BOTH vocab tables (reference
    # --save_w2v/--save_t2v): the word2vec text files double as index
    # build sources for nearest-method-NAME queries (INDEX.md)
    if config.EXPORT_VOCAB_VECTORS:
        prefix = config.EXPORT_VOCAB_VECTORS
        model.save_word2vec_format(prefix + '.tokens.txt', VocabType.Token)
        model.save_word2vec_format(prefix + '.targets.txt',
                                   VocabType.Target)
        config.log('Vocab embedding tables saved in word2vec text format '
                   'in: %s.{tokens,targets}.txt' % prefix)
    # offline corpus embedding: the vectors-only predict program streamed
    # over eval-sized sharded batches (serving/bulk.py, SERVING.md)
    if config.BULK_VECTORS_PATH:
        from code2vec_tpu.serving.bulk import export_code_vectors
        export_code_vectors(model, config.BULK_VECTORS_PATH)
    # embedding index: build + batch neighbor queries (index/, INDEX.md)
    index = None
    if config.BUILD_INDEX_FROM:
        from code2vec_tpu.index.service import build_index
        index = build_index(model, config)
    if config.QUERY_NEIGHBORS_PATH:
        from code2vec_tpu.index.service import query_neighbors_file
        query_neighbors_file(model, config, index=index)
    # evaluate standalone only: training already evaluates per epoch
    # (reference code2vec.py:28-33)
    if config.is_testing and not config.is_training:
        eval_results = model.evaluate()
        if eval_results is not None:
            config.log(str(eval_results).replace('topk', 'top%d' % (
                config.TOP_K_WORDS_CONSIDERED_DURING_PREDICTION)))
    if config.PREDICT:
        from code2vec_tpu.serving.predict import InteractivePredictor
        predictor = InteractivePredictor(config, model)
        predictor.predict()
    if config.RELEASE and config.is_loading:
        model.release_model()
    # --memory-report: a reconciled device-memory ledger snapshot of
    # whatever this invocation ran — train, eval, serve, index
    # (telemetry/memory.py; render with scripts/memory_report.py)
    if config.MEMORY_REPORT:
        from code2vec_tpu.telemetry import memory as memory_lib
        memory_lib.write_report(config)


if __name__ == '__main__':
    main()
