"""Model lifecycle: the user-facing ``Code2VecModel``.

TPU-native equivalent of the reference's ``Code2VecModelBase`` lifecycle
(model_base.py:37-182) fused with the per-backend train/evaluate/predict
logic (tensorflow_model.py:40-195, 311-368; keras_model.py:166-228): one
class, because the backends here share the trainer — only parameter
containers differ (models/backends.py).

Lifecycle on construction (reference model_base.py:38-50): verify config →
count examples (with ``.num_examples`` sidecar cache) → build vocabs →
load-or-create params.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu import common, metrics_writer
from code2vec_tpu.checkpoints import CheckpointStore
from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
from code2vec_tpu.metrics import (SubtokensEvaluationMetric,
                                  TopKAccuracyEvaluationMetric,
                                  decode_topk_batch)
from code2vec_tpu.models.backends import create_backend
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.telemetry import goodput as goodput_lib
from code2vec_tpu.training.trainer import Trainer, TrainerState
from code2vec_tpu.vocab import Code2VecVocabs, VocabType


def fixed_step_iterator(make_local_batches, steps_per_epoch: int,
                        process_index: int, log):
    """Exactly ``steps_per_epoch`` local batches for one multi-host epoch.

    Every process MUST run the same number of jitted steps per epoch or
    the mesh collectives pair mismatched steps and hang, so the step count
    is fixed globally and a process whose shard runs short cycles its own
    data to fill it. Line-striding keeps the imbalance to <=1 batch — that
    routine top-up is silent; cycling by MORE than one batch means this
    shard filtered down far smaller than its peers' and the epoch silently
    re-weights its examples, so it logs a warning (VERDICT r2 weak #4)."""
    import itertools

    def cycled():
        passes = 0
        while True:
            produced = 0
            for batch in make_local_batches():
                produced += 1
                yield batch
            if not produced:
                raise ValueError(
                    'Process %d has no training batches in its shard.'
                    % process_index)
            passes += 1
            if passes == 1 and produced < steps_per_epoch - 1:
                log('WARNING: process %d exhausted its shard after %d of '
                    '%d fixed steps and is cycling its local data to keep '
                    'the mesh in step; a skewed data split over-weights '
                    'this shard\'s examples.'
                    % (process_index, produced, steps_per_epoch))
    return itertools.islice(cycled(), steps_per_epoch)


class ModelEvaluationResults(NamedTuple):
    """(reference model_base.py:11-26)"""
    topk_acc: np.ndarray
    subtoken_precision: float
    subtoken_recall: float
    subtoken_f1: float
    loss: Optional[float] = None

    def __str__(self) -> str:
        res = 'topk_acc: {}, precision: {}, recall: {}, F1: {}'.format(
            self.topk_acc, self.subtoken_precision, self.subtoken_recall,
            self.subtoken_f1)
        if self.loss is not None:
            res = 'loss: {}, '.format(self.loss) + res
        return res


class ModelPredictionResults(NamedTuple):
    """(reference model_base.py:29-34)"""
    original_name: str
    topk_predicted_words: List[str]
    topk_predicted_words_scores: np.ndarray
    attention_per_context: Dict[Tuple[str, str, str], float]
    code_vector: Optional[np.ndarray] = None


class Code2VecModel:
    def __init__(self, config: Config):
        self.config = config
        config.verify()
        self.log = config.log
        self.log('Creating code2vec TPU model (backend=%s, dtype=%s)'
                 % (config.DL_FRAMEWORK, config.COMPUTE_DTYPE))
        if not config.RELEASE:
            self._init_num_of_examples()
        self.vocabs = Code2VecVocabs(config)
        self.backend = create_backend(config, self.vocabs)
        # decode table padded to the (sharding-aligned) table size: padded
        # indices can only surface when vocab_size < top_k, decode as OOV
        true_decode = self.vocabs.target_vocab.index_to_word_array()
        padded_size = self.backend.sizes['target_vocab_size']
        self._target_index_to_word = np.full(
            padded_size, self.vocabs.target_vocab.special_words.OOV,
            dtype=object)
        self._target_index_to_word[:true_decode.shape[0]] = true_decode
        self.mesh = mesh_lib.create_mesh(config)
        # device-memory ledger (telemetry/memory.py, OBSERVABILITY.md):
        # pin the HBM budget from config (env var otherwise) and land
        # forensic dumps (oom_ledger.json) with the run's other
        # artifacts instead of the CWD
        from code2vec_tpu.telemetry import memory as memory_lib
        from code2vec_tpu.telemetry.stepwatch import telemetry_dir
        memory_lib.configure(
            budget_bytes=(config.HBM_BUDGET_BYTES
                          if config.HBM_BUDGET_BYTES >= 0 else None),
            dump_dir=telemetry_dir(config))
        self.trainer = Trainer(config, self.backend, mesh=self.mesh)
        self.state: Optional[TrainerState] = None
        self.params: Optional[Any] = None
        self.eval_history: list = []
        self._stores: Dict[str, CheckpointStore] = {}
        self._load_or_create()

    # ----------------------------------------------------------- lifecycle
    def _init_num_of_examples(self) -> None:
        """(reference model_base.py:77-96)"""
        if self.config.is_training:
            self.config.NUM_TRAIN_EXAMPLES = self._count_examples(
                self.config.train_data_path)
            self.log('Number of train examples: %d'
                     % self.config.NUM_TRAIN_EXAMPLES)
        if self.config.is_testing:
            self.config.NUM_TEST_EXAMPLES = self._count_examples(
                self.config.TEST_DATA_PATH)
            self.log('Number of test examples: %d'
                     % self.config.NUM_TEST_EXAMPLES)

    @staticmethod
    def _count_examples(dataset_path: str) -> int:
        sidecar = dataset_path + '.num_examples'
        # unlike the reference (model_base.py:86-96), a sidecar older than
        # the data file is stale and recounted
        if os.path.isfile(sidecar) and \
                os.path.getmtime(sidecar) >= os.path.getmtime(dataset_path):
            with open(sidecar, 'r') as f:
                return int(f.readline())
        num = common.count_lines_in_file(dataset_path)
        try:
            with open(sidecar, 'w') as f:
                f.write(str(num))
        except OSError:
            pass  # read-only dataset dir: the fresh count is still valid
        return num

    def _store_for(self, path: str) -> CheckpointStore:
        """Stores are cached per path and stay open so per-epoch saves run
        asynchronously (closing an orbax manager drains pending saves);
        ``close_stores`` flushes everything."""
        store = self._stores.get(path)
        if store is None:
            store = CheckpointStore(
                path, max_to_keep=self.config.MAX_TO_KEEP,
                metadata={
                    'param_row_alignment': self.config.PARAM_ROW_ALIGNMENT,
                    # the ACTUAL padded target-table rows: the allocation
                    # additionally folds in the fused-CE vocab tile and
                    # mesh model axis (backends.target_row_alignment), so
                    # a resume that flips USE_PALLAS_FUSED_CE or reshapes
                    # the mesh would otherwise hit an opaque orbax shape
                    # mismatch; recording the row count (not the
                    # alignment) accepts resumes whose padding happens to
                    # coincide
                    'target_vocab_rows':
                        self.backend.sizes['target_vocab_size'],
                    'token_dim': self.config.TOKEN_EMBEDDINGS_SIZE,
                    'path_dim': self.config.PATH_EMBEDDINGS_SIZE,
                    'code_dim': self.config.CODE_VECTOR_SIZE,
                    # informational (non-strict): params load across
                    # frameworks, only training resume needs a match
                    'framework': self.config.DL_FRAMEWORK})
            self._stores[path] = store
        return store

    def close_stores(self) -> None:
        """Drain in-flight async checkpoint saves."""
        for store in self._stores.values():
            store.close()
        self._stores.clear()

    def _load_or_create(self) -> None:
        if self.config.is_loading:
            store = self._store_for(self.config.MODEL_LOAD_PATH)
            # abstract targets carry *current-mesh* shardings so orbax
            # re-shards onto this topology instead of trusting the (possibly
            # different) topology recorded in the checkpoint
            abstract_params, abstract_opt = self.trainer.abstract_state()
            if self.config.is_training:
                restored = store.restore_training(abstract_params,
                                                  abstract_opt)
                if restored is None:
                    raise ValueError('No checkpoint found under `%s`.'
                                     % self.config.MODEL_LOAD_PATH)
                self.state = TrainerState(
                    params=self.backend.from_canonical(restored.params),
                    opt_state=restored.opt_state,
                    step=jnp.asarray(restored.step, jnp.int32),
                    rng=jax.random.PRNGKey(42))
                self.params = self.state.params
                # checkpoint restore is an allocation owner: attribute
                # the restored state (telemetry/memory.py)
                self.trainer.register_state_memory(self.state.params,
                                                   self.state.opt_state)
                self._start_epoch = restored.epoch + 1
                self.log('Resumed from `%s` at epoch %d (step %d)' % (
                    self.config.MODEL_LOAD_PATH, restored.epoch,
                    restored.step))
                # preemption marker (resilience/preempt.py): advisory
                # breadcrumb from a run that exited on SIGTERM/SIGINT —
                # consumed here so a later unclean crash isn't misread
                # as a preemption
                marker = os.path.join(store.snapshot_dir, 'PREEMPTED.json')
                if os.path.isfile(marker):
                    self.log('Previous run exited on a preemption signal '
                             '(marker `%s`); continuing from its final '
                             'snapshot.' % marker)
                    try:
                        os.remove(marker)
                    except OSError:
                        pass
            else:
                params = store.restore_params(abstract_params)
                if params is None:
                    raise ValueError('No checkpoint found under `%s`.'
                                     % self.config.MODEL_LOAD_PATH)
                self.params = self.backend.from_canonical(params)
                self.trainer.register_state_memory(self.params)
                self._start_epoch = 0
        else:
            self.state = self.trainer.init_state()
            self.params = self.state.params
            self._start_epoch = 0

    # --------------------------------------------------------------- train
    def train(self) -> None:
        config = self.config
        assert config.is_training
        process_count = jax.process_count()
        # packed wire: batches are packed per data-parallel shard so each
        # device's slice uploads directly to it; multi-host falls back to
        # planes (Config.wire_format_for, via reader.wire_format())
        data_shards = (self.mesh.shape[mesh_lib.DATA_AXIS]
                       if process_count == 1 else 1)
        reader = PathContextReader(self.vocabs, config, EstimatorAction.Train,
                                   process_index=jax.process_index(),
                                   process_count=process_count,
                                   data_shards=data_shards)
        wire_format = reader.wire_format()
        save_store = (self._store_for(config.MODEL_SAVE_PATH)
                      if config.is_saving else None)
        writer = metrics_writer.maybe_create(config)
        use_cache = config.TRAIN_DATA_CACHE
        if process_count > 1 and config.TRAIN_BATCH_SIZE % process_count:
            raise ValueError(
                'TRAIN_BATCH_SIZE=%d must be divisible by the process '
                'count (%d).' % (config.TRAIN_BATCH_SIZE, process_count))
        run_evals = config.is_testing
        self.log('Starting training (%d epochs, batch %d, steps/epoch ~%d)'
                 % (config.NUM_TRAIN_EPOCHS, config.TRAIN_BATCH_SIZE,
                    config.train_steps_per_epoch))

        # multi-host: every process MUST run the same number of jitted
        # steps per epoch or the mesh collectives pair mismatched steps
        # and hang. Fix the step count globally (floor of the unfiltered
        # example count) and cycle each host's local batches to fill it.
        steps_per_epoch = max(
            1, config.NUM_TRAIN_EXAMPLES // config.TRAIN_BATCH_SIZE)

        def fixed_step_epoch(make_local_batches):
            return fixed_step_iterator(make_local_batches, steps_per_epoch,
                                       jax.process_index(), self.log)

        if use_cache:
            from code2vec_tpu.data.cache import TokenCache
            from code2vec_tpu.data.reader import prefetch_iterator
            # multi-host: per-process cache of this process's stride —
            # without it the streaming path re-reads and re-tokenizes the
            # full file every epoch on every process (round-1 weak #7)
            cache = TokenCache.build_or_load(config, self.vocabs, reader)
            local_batch_size = config.TRAIN_BATCH_SIZE // process_count

            def epoch_batches(epoch: int):
                # prefetch thread keeps chunk reads/shuffles off the
                # training thread, like the streaming path
                def local_batches():
                    return cache.iter_epoch(local_batch_size, shuffle=True,
                                            seed=epoch,
                                            wire_format=wire_format,
                                            data_shards=data_shards)
                if process_count == 1:
                    return prefetch_iterator(local_batches,
                                             config.READER_PREFETCH_BATCHES)
                return prefetch_iterator(
                    lambda: fixed_step_epoch(local_batches),
                    config.READER_PREFETCH_BATCHES)
        elif process_count > 1:
            def epoch_batches(epoch: int):
                return fixed_step_epoch(
                    lambda: reader.iter_epoch(shuffle=True, seed=epoch))
        else:
            def epoch_batches(epoch: int):
                return reader.iter_epoch_prefetched(shuffle=True, seed=epoch,
                                                    wire_format=wire_format)

        def on_log(step: int, avg_loss: float, throughput: float) -> None:
            if writer is not None:
                writer.scalar('train/loss', avg_loss, step)
                writer.scalar('train/examples_per_sec', throughput, step)

        def on_epoch_time(epoch: int, batch_num: int, seconds: float
                          ) -> None:
            # epoch wall time on the same (global batch) step axis as
            # every other scalar stream
            if writer is not None:
                writer.scalar('train/epoch_wall_time_s', seconds, batch_num)

        # one eval+log helper for both callbacks; the metric step axis is
        # ALWAYS the global batch number (mixing epoch and batch steps on
        # one tag corrupts the stream)
        last_eval_batch = [-1]
        # in-training eval results, in order — callers (and the multi-host
        # exactness tests) read the merged numbers the training loop saw
        self.eval_history = []

        def _evaluate_and_log(label: str, step: int, params) -> None:
            eval_t0 = time.time()
            # typed badput mark for the goodput ledger (no-op when
            # telemetry is off; absorbed when the trainer's eval-callback
            # wrap already opened an eval interval)
            with goodput_lib.interval(goodput_lib.KIND_EVAL):
                results = self.evaluate(params=params)
            eval_wall = time.time() - eval_t0
            self.eval_history.append({
                'label': label, 'step': step,
                'topk_acc': [float(x) for x in results.topk_acc],
                'precision': results.subtoken_precision,
                'recall': results.subtoken_recall,
                'f1': results.subtoken_f1, 'loss': results.loss})
            self.log('After %s: %s' % (label, results))
            if writer is not None:
                writer.scalar('eval/top1_acc', float(results.topk_acc[0]),
                              step)
                writer.scalar('eval/subtoken_f1', results.subtoken_f1, step)
                writer.scalar('eval/subtoken_precision',
                              results.subtoken_precision, step)
                writer.scalar('eval/subtoken_recall',
                              results.subtoken_recall, step)
                writer.scalar('eval/wall_time_s', eval_wall, step)
                # eval scalars arrive at most once per eval interval:
                # make them durable now rather than at the next buffer
                # fill (writes are buffered, metrics_writer.py)
                writer.flush()

        # both save cadences funnel through one guard: an epoch boundary
        # save must not be duplicated by the interval firing at the top of
        # the next epoch's first iteration (same step, same state). A
        # resumed run starts with its restored step already "saved".
        last_saved_step = [int(self.state.step)]

        def _save_at(state: TrainerState, last_complete_epoch: int,
                     snapshot: bool = False) -> None:
            step = int(state.step)
            if step == last_saved_step[0]:
                return
            last_saved_step[0] = step
            # async: the write finalizes in the background while training
            # continues; train()'s finally drains it. The goodput mark
            # covers the dispatch cost the loop pays (device->host copy),
            # not the background write.
            with goodput_lib.interval(goodput_lib.KIND_CHECKPOINT):
                self.save(state=state, epoch=last_complete_epoch, wait=False,
                          snapshot=snapshot)

        def on_save_interval(epoch: int, batch_num: int,
                             state: TrainerState) -> None:
            # fires at the top of an iteration of `epoch`: the state is
            # either mid-`epoch` or exactly at the previous epoch's
            # boundary — in both cases the last fully completed epoch is
            # epoch-1, and resume restarts the interrupted epoch
            # (at-least-once semantics over the epoch's data)
            _save_at(state, epoch - 1, snapshot=True)

        def on_epoch_end(epoch: int, state: TrainerState,
                         batch_num: int) -> None:
            if save_store is not None and \
                    (epoch + 1) % config.SAVE_EVERY_EPOCHS == 0:
                _save_at(state, epoch)
            if run_evals:
                if last_eval_batch[0] == batch_num:
                    return  # the interval eval just ran on this batch
                last_eval_batch[0] = batch_num
                _evaluate_and_log('epoch %d' % (epoch + 1), batch_num,
                                  state.params)

        def on_eval_interval(batch_num: int, state: TrainerState) -> None:
            last_eval_batch[0] = batch_num
            _evaluate_and_log('batch %d' % batch_num, batch_num,
                              state.params)

        # ---- resilience wiring (ROBUSTNESS.md) ----
        from code2vec_tpu.resilience.preempt import PreemptionHandler
        from code2vec_tpu.telemetry import core as tele_core
        preemption = (PreemptionHandler(log=self.log)
                      if config.HANDLE_PREEMPTION_SIGNALS else None)

        def on_preempt(epoch: int, batch_num: int,
                       state: TrainerState) -> None:
            if save_store is None:
                # no --save path: there is nowhere to snapshot — still
                # exit cleanly (flushed metrics, no traceback)
                if writer is not None:
                    writer.flush()
                self.log('Preemption: no MODEL_SAVE_PATH, exiting without '
                         'a snapshot.')
                return
            # one final snapshot (deduped against an interval save that
            # just fired on this step), made DURABLE before the fit loop
            # returns — the preemption grace window may be short, so the
            # wait happens here, not in train()'s finally
            t0 = time.time()
            _save_at(state, epoch - 1, snapshot=True)
            save_store.wait_until_finished()
            save_s = time.time() - t0
            if tele_core.enabled():
                tele_core.registry().gauge(
                    'resilience/preempt_save_s').set(save_s)
            # claim success only when a checkpoint for THIS step is
            # actually on disk: _save_at dedupes against the run's
            # starting step, so a fresh run preempted before its first
            # completed step saved nothing — telling the operator to
            # '--load' would then fail
            step = int(state.step)
            if not save_store.has_step(step):
                if writer is not None:
                    writer.flush()
                self.log('Preemption at step %d: no completed step to '
                         'snapshot (nothing newer than the run\'s start); '
                         'exiting without a resume marker.' % step)
                return
            # advisory resume marker — the snapshot itself is the resume
            # state; the marker only tells the next run (and the
            # operator) this was a clean preemption exit
            marker = os.path.join(save_store.snapshot_dir,
                                  'PREEMPTED.json')
            try:
                os.makedirs(save_store.snapshot_dir, exist_ok=True)
                with open(marker, 'w') as f:
                    json.dump({'step': int(state.step),
                               'last_complete_epoch': epoch - 1,
                               'time': time.time()}, f)
            except OSError:
                pass
            if writer is not None:
                writer.flush()
            self.log('Preemption save complete at step %d (%.2fs); '
                     'resume with --load %s'
                     % (int(state.step), save_s, config.MODEL_SAVE_PATH))

        def on_divergence(last_good_step: int) -> Optional[TrainerState]:
            """Divergence-guard rewind target: the newest restorable
            checkpoint across the epoch + step-snapshot stores, capped
            at the guard's last known-finite step (a snapshot saved
            inside the unchecked window may hold poisoned params)."""
            if save_store is None:
                return None
            # drain any in-flight async save first, so the newest
            # snapshot is durable and readable
            save_store.wait_until_finished()
            abstract_params, abstract_opt = self.trainer.abstract_state()
            try:
                restored = save_store.restore_training(
                    abstract_params, abstract_opt,
                    max_step=last_good_step)
            except Exception as exc:
                self.log('Divergence rewind: no checkpoint restorable '
                         '(%s).' % exc)
                return None
            if restored is None:
                return None
            # rewind hygiene: retained steps NEWER than the restore
            # target were saved inside the poisoned window — purge them
            # so (a) a crash-resume cannot restore them as 'newest' and
            # (b) their keys don't make orbax silently skip re-saves
            save_store.purge_steps_newer_than(restored.step)
            # re-arm the save dedupe at the restored step: the pre-rewind
            # 'last saved' value may name a just-purged key, and the
            # re-trained states at those steps must be saved again
            last_saved_step[0] = restored.step
            rewound = TrainerState(
                params=self.backend.from_canonical(restored.params),
                opt_state=restored.opt_state,
                step=jnp.asarray(restored.step, jnp.int32),
                rng=jax.random.PRNGKey(42))
            # the rewind restore is an allocation owner too: re-register
            # replaces the trainer's entries (telemetry/memory.py)
            self.trainer.register_state_memory(rewound.params,
                                               rewound.opt_state)
            return rewound

        start = getattr(self, '_start_epoch', 0)
        try:
            with (preemption if preemption is not None
                  else contextlib.nullcontext()):
                self.state = self.trainer.fit(
                    self.state, epoch_batches, start_epoch=start,
                    on_epoch_end=on_epoch_end, on_log=on_log,
                    on_eval_interval=(on_eval_interval
                                      if run_evals else None),
                    on_save_interval=(on_save_interval
                                      if save_store is not None else None),
                    on_epoch_time=on_epoch_time,
                    preemption=preemption, on_preempt=on_preempt,
                    on_divergence=on_divergence)
        finally:
            # drain in-flight async checkpoint saves even when training
            # raises: a commenced save must end up durable
            self.close_stores()
            if writer is not None:
                writer.close()
        self.params = self.state.params
        if preemption is not None and preemption.requested:
            self.log('Training stopped early by %s after a '
                     'preemption-safe snapshot; remaining epochs were '
                     'skipped.' % preemption.signal_name)

    # ---------------------------------------------------------------- save
    def save(self, model_save_path: Optional[str] = None,
             state: Optional[TrainerState] = None,
             epoch: int = 0, wait: bool = True,
             snapshot: bool = False) -> None:
        """vocab sidecar + full training state
        (reference model_base.py:102-109). Durable on return by default;
        ``wait=False`` (the in-training cadence) lets orbax finalize in the
        background — train()'s finally drains it. ``snapshot=True`` routes
        a step-interval save to the short-retention snapshot store."""
        path = model_save_path or self.config.MODEL_SAVE_PATH
        save_dir = os.path.dirname(path)
        if save_dir and not os.path.isdir(save_dir):
            os.makedirs(save_dir, exist_ok=True)
        self.vocabs.save(Config.get_vocabularies_path_from_model_path(path))
        state = state if state is not None else self.state
        store = self._store_for(path)
        # canonical {name: array} layout: loadable under either backend
        canonical = self.backend.named_params(state.params)._asdict()
        store.save_training(params=canonical, opt_state=state.opt_state,
                            step=int(state.step), epoch=epoch, wait=wait,
                            snapshot=snapshot)

    def release_model(self) -> None:
        """Strip optimizer state (reference tensorflow_model.py:132-136)."""
        assert self.config.is_loading
        store = self._store_for(self.config.MODEL_LOAD_PATH)
        store.save_release(self.backend.named_params(self.params)._asdict())
        self.close_stores()
        self.log('Released model saved under `%s__only-weights`.'
                 % self.config.MODEL_LOAD_PATH)

    # ------------------------------------------------------------ evaluate
    def evaluate(self, params=None) -> ModelEvaluationResults:
        """``params`` overrides the stored parameters for mid-training
        evaluation (the stored ``self.params`` may alias buffers the next
        donated train step will delete; callbacks pass the live state's
        params explicitly instead of mutating the model object).

        Multi-host: every process reads its line stride of the test file
        and runs a FIXED global step count (``ceil(unfiltered examples /
        global batch)`` — provably ≥ every process's local batch count, so
        it needs no communication to agree on), padding with zero-weight
        batches past its own data; mismatched jitted step counts would
        deadlock the mesh collectives.  Each process updates metric
        counters for its own rows, then one all-gather sums the counters —
        results are exact and identical on every process.
        """
        params = params if params is not None else self.params
        config = self.config
        assert config.is_testing
        process_count = jax.process_count()
        process_index = jax.process_index()
        reader = PathContextReader(self.vocabs, config,
                                   EstimatorAction.Evaluate,
                                   process_index=process_index,
                                   process_count=process_count,
                                   data_shards=(
                                       self.mesh.shape[mesh_lib.DATA_AXIS]
                                       if process_count == 1 else 1))
        wire_format = reader.wire_format()
        oov = self.vocabs.target_vocab.special_words.OOV
        topk_metric = TopKAccuracyEvaluationMetric(
            config.TOP_K_WORDS_CONSIDERED_DURING_PREDICTION, oov)
        subtoken_metric = SubtokensEvaluationMetric(oov)
        # per-example prediction log lives next to the model artifacts
        # (the reference wrote a bare 'log.txt' into the CWD,
        # tensorflow_model.py:138 — polluting wherever you ran from);
        # each process logs its own shard
        if config.is_saving:
            log_dir = os.path.dirname(config.MODEL_SAVE_PATH)
        elif config.is_loading:
            log_dir = config.model_load_dir
        else:
            log_dir = '.'
        if log_dir and log_dir != '.':
            os.makedirs(log_dir, exist_ok=True)
        shard_suffix = '' if process_index == 0 else '.proc%d' % process_index
        log_path = os.path.join(log_dir, 'log.txt' + shard_suffix)
        vectors_path = config.TEST_DATA_PATH + '.vectors' + shard_suffix
        vectors_file = (open(vectors_path, 'w')
                        if config.EXPORT_CODE_VECTORS else None)

        fixed_steps = None
        if process_count > 1:
            total_unfiltered = getattr(config, 'NUM_TEST_EXAMPLES', 0) or \
                common.count_lines_in_file(config.TEST_DATA_PATH)
            fixed_steps = -(-total_unfiltered // config.TEST_BATCH_SIZE)
        local_batch_size = config.TEST_BATCH_SIZE // process_count

        def eval_batches():
            steps = 0
            for batch in reader.iter_epoch_prefetched(
                    shuffle=False, wire_format=wire_format):
                steps += 1
                if fixed_steps is not None and steps > fixed_steps:
                    raise RuntimeError(
                        'Process %d produced more eval batches (%d) than '
                        'the agreed global step count (%d); filtering can '
                        'only shrink shards, so the test file changed '
                        'under us.' % (process_index, steps, fixed_steps))
                yield batch
            if fixed_steps is not None and steps < fixed_steps:
                pad = reader.empty_batch(local_batch_size)
                for _ in range(fixed_steps - steps):
                    yield pad

        total = 0
        loss_sum = 0.0
        weight_sum = 0.0
        start_time = time.time()
        with open(log_path, 'w') as log_file:
            def consume(out, batch) -> None:
                nonlocal total, loss_sum, weight_sum
                # loss sums are global (the jitted reduction spans all
                # processes' rows) — accumulate, don't re-merge
                loss_sum += float(out['loss_sum'])
                weight_sum += float(out['weight_sum'])
                topk_local = mesh_lib.local_rows(out['topk_indices'])
                results = decode_topk_batch(
                    topk_local, self._target_index_to_word,
                    batch.label_strings, batch.weight)
                topk_metric.update_batch(results)
                subtoken_metric.update_batch(results)
                self._log_predictions_during_evaluation(results, log_file)
                if vectors_file is not None:
                    valid = batch.weight > 0
                    vectors = mesh_lib.local_rows(out['code_vectors'])
                    for vec in vectors[valid]:
                        vectors_file.write(' '.join(map(str, vec)) + '\n')
                total += len(results)
                if total and total % (
                        config.NUM_BATCHES_TO_LOG_PROGRESS
                        * config.TEST_BATCH_SIZE) < config.TEST_BATCH_SIZE:
                    elapsed = time.time() - start_time
                    self.log('Evaluated %d examples... (%d samples/sec)'
                             % (total, int(total / max(elapsed, 1e-9))))

            # one-step pipeline: dispatch batch k+1 (async) BEFORE pulling
            # batch k's outputs to host, so per-batch decode/logging
            # overlaps device compute instead of serializing on it
            pending = None
            for arrays, batch in self.trainer.stage_batches(eval_batches()):
                out = self.trainer.eval_step_placed(params, arrays)
                if pending is not None:
                    consume(*pending)
                pending = (out, batch)
            if pending is not None:
                consume(*pending)
        if vectors_file is not None:
            vectors_file.close()
            self.log('Code vectors written to `%s`.' % vectors_path)
        if process_count > 1:
            from jax.experimental import multihost_utils
            topk_len = topk_metric.count_vector().shape[0]
            local_counts = np.concatenate([topk_metric.count_vector(),
                                           subtoken_metric.count_vector()])
            merged = np.asarray(multihost_utils.process_allgather(
                local_counts)).sum(axis=0)
            topk_metric.set_count_vector(merged[:topk_len])
            subtoken_metric.set_count_vector(merged[topk_len:])
        return ModelEvaluationResults(
            topk_acc=topk_metric.topk_correct_predictions,
            subtoken_precision=subtoken_metric.precision,
            subtoken_recall=subtoken_metric.recall,
            subtoken_f1=subtoken_metric.f1,
            loss=(loss_sum / weight_sum) if weight_sum > 0 else None)

    def _log_predictions_during_evaluation(self, results, output_file) -> None:
        """Per-example prediction log (reference
        tensorflow_model.py:411-422)."""
        oov = self.vocabs.target_vocab.special_words.OOV
        for original_name, top_words in results:
            found_match = common.get_first_match_word_from_top_predictions(
                oov, original_name, top_words)
            if found_match is not None:
                prediction_idx, predicted_word = found_match
                if prediction_idx == 0:
                    output_file.write('Original: ' + original_name
                                      + ', predicted 1st: ' + predicted_word
                                      + '\n')
                else:
                    output_file.write('\t\t predicted correctly at rank: '
                                      + str(prediction_idx + 1) + '\n')
            else:
                output_file.write('No results for predicting: '
                                  + original_name + '\n')

    # -------------------------------------------------------------- predict
    def _get_predict_reader(self) -> PathContextReader:
        """One reader for the model's lifetime — a fresh reader per
        ``predict`` call was pure construction overhead on the serving
        path (it holds no per-call state)."""
        reader = getattr(self, '_predict_reader', None)
        if reader is None:
            reader = PathContextReader(self.vocabs, self.config,
                                       EstimatorAction.Predict)
            self._predict_reader = reader
        return reader

    def predict(self, predict_data_lines: Iterable[str]
                ) -> List[ModelPredictionResults]:
        """(reference tensorflow_model.py:311-368; per-line in the
        reference, batched here — the REPL passes a handful of lines).

        Pads to the serving bucket ladder (SERVING_BATCH_BUCKETS), so
        repeated calls of varying size reuse a handful of compiled
        programs instead of compiling one per distinct size, and fetches
        only the output keys the caller needs: the tiered predict
        program already omits code vectors unless EXPORT_CODE_VECTORS.
        For sustained concurrent traffic use ``serving_engine()``; for
        whole corpora use ``serving/bulk.py``."""
        lines = list(predict_data_lines)
        if not lines:
            return []
        from code2vec_tpu.serving import engine as engine_lib
        reader = self._get_predict_reader()
        batch = reader.process_input_rows(lines)
        data_axis = self.mesh.shape[mesh_lib.DATA_AXIS]
        ladder = engine_lib.batch_ladder(
            self.config.serving_batch_buckets, data_axis)
        padded_size = engine_lib.pick_bucket(len(lines), ladder)
        if padded_size is None:
            # beyond the ladder: the old ad-hoc padding (shards evenly,
            # compiles per size — bulk_predict is the right tool there)
            padded_size = -(-len(lines) // data_axis) * data_axis
        batch = reader.pad_batch_to(batch, padded_size)
        tier = 'full' if self.config.EXPORT_CODE_VECTORS else 'attention'
        out = self.trainer.predict_step(self.params, batch, tier=tier)
        fetched = {key: np.asarray(value) for key, value in out.items()}
        return engine_lib.decode_results(fetched, batch, len(lines),
                                         self._target_index_to_word)

    def _serving_param_source(self) -> Optional['ServingParamSource']:
        """Checkpoint-backed param source for the serving engine's
        canaried rollover (``load_params`` / ``follow_checkpoints``,
        SERVING.md): steps resolve against the model's own load path
        (or the save path of a just-trained model); None when the model
        was built from neither (fresh init)."""
        path = (self.config.MODEL_LOAD_PATH if self.config.is_loading
                else self.config.MODEL_SAVE_PATH
                if self.config.is_saving else None)
        if path is None:
            return None
        return ServingParamSource(self, self._store_for(path))

    def serving_engine(self, tiers=None, warmup: bool = True, **overrides):
        """Build a ``ServingEngine`` over this model's warm params:
        dynamic micro-batching + a pre-compiled bucket ladder for
        concurrent request traffic (serving/engine.py, SERVING.md).
        ``warmup=False`` defers the eager ladder compile to the first
        ``submit``.

        The engine is armed for canaried zero-downtime checkpoint
        rollover against this model's checkpoint path; with
        ``--serve-follow-checkpoints`` (SERVE_FOLLOW_CHECKPOINTS_SECS
        > 0) it also polls that path and rolls newer steps in live."""
        from code2vec_tpu.serving.engine import ServingEngine
        if 'param_source' in overrides:
            param_source = overrides.pop('param_source')
        else:
            # only built when the caller didn't bring their own: the
            # default opens a checkpoint store (filesystem access)
            param_source = self._serving_param_source()
        if 'params_step' not in overrides:
            # baseline the follow-checkpoints poller at the step the
            # params actually came from: without it the first poll
            # re-rolls (full restore + canary) the already-serving step
            if self.state is not None:
                overrides['params_step'] = int(self.state.step)
            elif param_source is not None:
                # params-only load restores the newest retained step
                overrides['params_step'] = param_source.newest_step()
        engine = ServingEngine(
            self.config, self.trainer, self.params, self.vocabs,
            decode_table=self._target_index_to_word, tiers=tiers,
            param_source=param_source,
            log=self.log, **overrides)
        try:
            if warmup:
                engine.warmup()
            if self.config.SERVE_FOLLOW_CHECKPOINTS_SECS > 0:
                engine.follow_checkpoints()
        except BaseException:
            # never leak a running dispatcher/decode pool: the caller
            # gets the exception, not the engine, so nobody else can
            # close it
            engine.close()
            raise
        return engine

    def serving_mesh(self, replicas=None, tiers=None, warmup: bool = True,
                     **overrides):
        """Build a ``ServingMesh`` over this model: ``replicas``
        (default ``MESH_REPLICAS``) serving-engine replicas behind ONE
        shared front queue with continuous cross-tier batching and
        coordinated canaried rollover (serving/mesh.py, SERVING.md
        "Serving mesh").  With ``--serve-follow-checkpoints`` the MESH
        polls the checkpoint store and rolls the whole fleet as a unit
        — replica engines never run their own pollers.  Worker modes
        (``MESH_REPLICA_MODE='process'|'socket'``) self-heal: heartbeat
        liveness, crash-safe redispatch, and supervised restart
        (SERVING.md "Multi-host mesh")."""
        from code2vec_tpu.serving.mesh import ServingMesh
        mesh = ServingMesh(self, replicas=replicas, tiers=tiers,
                           **overrides)
        try:
            if warmup:
                mesh.warmup()
            if self.config.SERVE_FOLLOW_CHECKPOINTS_SECS > 0:
                mesh.follow_checkpoints()
        except BaseException:
            # never leak N dispatchers/decode pools: the caller gets
            # the exception, not the mesh
            mesh.close()
            raise
        return mesh

    # ----------------------------------------------------- embedding export
    def get_vocab_embedding_as_np_array(self, vocab_type: VocabType
                                        ) -> np.ndarray:
        """(reference tensorflow_model.py:379-403 — here a direct fetch)"""
        named = self.backend.named_params(self.params)
        # slice off sharding-alignment padding rows: exports carry exactly
        # vocab.size rows like the reference
        if vocab_type == VocabType.Token:
            return np.asarray(named.token_embedding)[
                :self.vocabs.token_vocab.size]
        if vocab_type == VocabType.Target:
            return np.asarray(named.target_embedding)[
                :self.vocabs.target_vocab.size]
        if vocab_type == VocabType.Path:
            return np.asarray(named.path_embedding)[
                :self.vocabs.path_vocab.size]
        raise ValueError('vocab_type must be a VocabType member.')

    def save_word2vec_format(self, dest_save_path: str,
                             vocab_type: VocabType) -> None:
        """(reference model_base.py:176-182)"""
        matrix = self.get_vocab_embedding_as_np_array(vocab_type)
        index_to_word = self.vocabs.get(vocab_type).index_to_word
        with open(dest_save_path, 'w') as words_file:
            common.save_word2vec_file(words_file, index_to_word, matrix)
        self.log('Saved %s embeddings to `%s`.'
                 % (vocab_type.name, dest_save_path))


class ServingParamSource:
    """Resolves ``ServingEngine.load_params(step|path)`` refs and
    ``newest_step()`` polls against a model's checkpoint store
    (zero-downtime rollover, SERVING.md).

    Restored params ride the SAME abstract targets (current-mesh
    shardings) as the model's own load path, so a rolled-in candidate
    matches the serving set's shapes and shardings exactly — which is
    what lets every canary shadow dispatch reuse the warm compiled
    ladder."""

    def __init__(self, model: Code2VecModel, store: CheckpointStore):
        self._model = model
        self._store = store

    def load(self, source):
        """``source``: retained step (int) of the model's own store, or
        a model path (str) — returns placed, backend-native params."""
        abstract_params, _ = self._model.trainer.abstract_state()
        if isinstance(source, int) and not isinstance(source, bool):
            params = self._store.restore_params_step(abstract_params,
                                                     source)
        else:
            store = self._model._store_for(str(source))
            params = store.restore_params(abstract_params)
            if params is None:
                raise ValueError('No checkpoint found under `%s`.'
                                 % source)
        return self._model.backend.from_canonical(params)

    def newest_step(self):
        """Newest retained step of the model's store (None when the
        path holds no checkpoints yet)."""
        return self._store.newest_step()
