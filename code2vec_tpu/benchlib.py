"""Shared harness pieces for the repo-root ``bench.py`` and the scripts
under ``benchmarks/`` — one definition of the java14m headline
configuration (reference config.py:47-70), the synthetic batch maker, and
the platform workaround, so a change to the benchmark configuration cannot
silently apply to some scripts and not others."""
from __future__ import annotations

import os
from typing import NamedTuple

V100_BASELINE_EXAMPLES_PER_SEC = 4700.0  # reference README.md:69,127


class BenchShapes(NamedTuple):
    token_vocab: int
    path_vocab: int
    target_vocab: int
    batch_size: int
    max_contexts: int


JAVA14M = BenchShapes(token_vocab=1301136, path_vocab=911417,
                      target_vocab=261245, batch_size=1024, max_contexts=200)
# Fraction of the 200 context slots a real java14m example fills —
# contexts/method p50 is 28 with a long tail (corpus_stats_r4.json), so
# ~0.25 mean is the honest shape for wire-format measurements. The
# device-compute benchmarks keep full batches (fill 1.0): masked slots
# cost the same FLOPs, and changing them would break comparability with
# every prior capture.
JAVA14M_FILL = 0.25
# Tiny shapes so a harness can be validated on CPU; metric names must be
# renamed by the caller so a smoke line is never mistaken for a real one.
SMOKE_SHAPES = BenchShapes(token_vocab=1000, path_vocab=1000,
                           target_vocab=500, batch_size=64, max_contexts=16)


def smoke_requested() -> bool:
    return os.environ.get('BENCH_SMOKE', '') not in ('', '0', 'false')


def bench_timer(name: str = 'bench', window: int = 1024):
    """A telemetry ``Timer`` for benchmark loops — the shared timer API
    (code2vec_tpu/telemetry/core.py) the timed harnesses use instead of
    hand-rolled ``time.perf_counter`` arithmetic:

        sw = benchlib.bench_timer()
        with sw.time():
            <timed region>
        seconds = sw.last          # or .total / .snapshot() for stats

    Standalone instrument, NOT registered in the process-global registry:
    benchmark timings must never leak into a live run's exported
    metrics."""
    from code2vec_tpu.telemetry.core import Timer
    return Timer(name, window=window)


def bench_steps(smoke: bool):
    """(warmup_steps, measure_steps) shared by every timed harness.
    60 measure steps keep the one amortized tunnel round-trip <2.5% at
    ~51 ms/step."""
    return (2, 5) if smoke else (10, 60)


def bench_timer_wall(fn) -> float:
    """Wall-clock one call of ``fn`` through the shared Timer (the same
    clock discipline as ``bench_timer``; returns seconds). For variants
    whose result is host numpy — already synchronized — so no extra
    device fence is needed."""
    sw = bench_timer()
    with sw.time():
        fn()
    return sw.last


def device_memory_record() -> dict:
    """Per-stage HBM footprint for the bench JSON records (ISSUE 9):
    ``peak_bytes_in_use`` / ``bytes_in_use`` summed over local devices
    from the runtime's ``memory_stats()``.  Backends without memory
    stats (CPU smoke) report None — an EXPLICIT gap on the memory axis,
    not a silently absent key, so summarize_captures.py can show that a
    round is missing its footprint numbers the same way it shows
    ``tpu_unavailable``."""
    from code2vec_tpu.telemetry.memory import backend_memory
    devices = backend_memory()['devices']  # one stats-reading code path
    if not devices:
        return {'peak_hbm_bytes': None, 'hbm_bytes_in_use': None}
    return {'peak_hbm_bytes': sum(d['peak_bytes_in_use']
                                  for d in devices),
            'hbm_bytes_in_use': sum(d['bytes_in_use'] for d in devices)}


def honor_env_platforms() -> None:
    """Honor the caller's JAX_PLATFORMS even though the sitecustomize
    preimport pins a platform list before this process's env is read (same
    guard as cli.py) — without this, CPU smoke runs hang whenever the TPU
    tunnel is wedged."""
    import jax
    env_platforms = os.environ.get('JAX_PLATFORMS')
    if env_platforms and jax.config.jax_platforms != env_platforms:
        try:
            jax.config.update('jax_platforms', env_platforms)
        except RuntimeError:
            pass  # backends already initialized


def headline_config(shapes: BenchShapes, **overrides):
    """The java14m benchmark Config (bfloat16 compute, jax backend)."""
    from code2vec_tpu.config import Config
    kwargs = dict(
        TRAIN_DATA_PATH_PREFIX='bench', DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='bfloat16', VERBOSE_MODE=0, READER_USE_NATIVE=False,
        TRAIN_BATCH_SIZE=shapes.batch_size, TEST_BATCH_SIZE=shapes.batch_size,
        MAX_CONTEXTS=shapes.max_contexts,
        MAX_TOKEN_VOCAB_SIZE=shapes.token_vocab,
        MAX_PATH_VOCAB_SIZE=shapes.path_vocab,
        MAX_TARGET_VOCAB_SIZE=shapes.target_vocab,
        # every timed harness here re-feeds the same staged arrays across
        # warmup+measure steps; donation would invalidate them after the
        # first consuming step on real devices
        DONATE_STAGED_BATCHES=False)
    kwargs.update(overrides)
    return Config(**kwargs)


def mosaic_engaged(jitted, *args) -> bool:
    """True iff the compiled program contains the Pallas (Mosaic) TPU
    custom-call. A bare 'custom-call' match would false-positive on other
    TPU custom-calls (e.g. top-k lowerings), so look for the Mosaic
    target 'tpu_custom_call' specifically. Costs one AOT compile — use
    once per A/B arm family, not per variant."""
    return 'tpu_custom_call' in jitted.lower(*args).compile().as_text()


def _make_trainer(config, shapes: BenchShapes):
    from code2vec_tpu.models.backends import create_backend
    from code2vec_tpu.training.trainer import Trainer
    from code2vec_tpu.vocab import SizeOnlyVocabs
    backend = create_backend(
        config, SizeOnlyVocabs(shapes.token_vocab, shapes.path_vocab,
                               shapes.target_vocab))
    return Trainer(config, backend)


def build_trainer(config, shapes: BenchShapes):
    """(trainer, initial training state) for the benchmark Config."""
    trainer = _make_trainer(config, shapes)
    return trainer, trainer.init_state(seed=0)


def build_eval_trainer(config, shapes: BenchShapes):
    """(trainer, sharded params) WITHOUT optimizer state — eval-only
    harnesses must not burn device memory on ~3 GB of Adam moments they
    never read."""
    import jax

    from code2vec_tpu.parallel import mesh as mesh_lib
    trainer = _make_trainer(config, shapes)
    params = mesh_lib.shard_params(trainer.backend.init(
        jax.random.PRNGKey(0)), trainer.mesh)
    return trainer, params


def random_batches(shapes: BenchShapes, n: int, seed: int = 0,
                   fill: float = 1.0):
    """``n`` synthetic host batches of uniform random indices.

    ``fill`` < 1.0 gives each example a random effective length around
    ``fill * max_contexts`` (PAD-filled tail, mask zeroed) — the realistic
    shape for wire-format measurements (JAVA14M_FILL); the default keeps
    the historical full batches the compute benchmarks are calibrated on.
    """
    import numpy as np

    from code2vec_tpu.data.reader import Batch
    rng = np.random.default_rng(seed)
    batch, contexts = shapes.batch_size, shapes.max_contexts
    out = []
    for _ in range(n):
        source = rng.integers(1, shapes.token_vocab,
                              (batch, contexts)).astype(np.int32)
        path = rng.integers(1, shapes.path_vocab,
                            (batch, contexts)).astype(np.int32)
        target = rng.integers(1, shapes.token_vocab,
                              (batch, contexts)).astype(np.int32)
        mask = np.ones((batch, contexts), np.float32)
        if fill < 1.0:
            lengths = rng.integers(
                max(1, int(fill * contexts * 0.5)),
                max(2, int(fill * contexts * 1.5)) + 1, (batch,))
            dead = np.arange(contexts)[None, :] >= lengths[:, None]
            source[dead] = 0
            path[dead] = 0
            target[dead] = 0
            mask[dead] = 0.0
        out.append(Batch(
            source=source, path=path, target=target, mask=mask,
            label=rng.integers(1, shapes.target_vocab,
                               (batch,)).astype(np.int32),
            weight=np.ones((batch,), np.float32)))
    return out


def pack_batches(batches, trainer):
    """Plane batches -> PackedBatch list for the trainer's mesh (packed
    per data shard, PAD indices from the trainer's backend). All batches
    share ONE capacity so a timed loop compiles exactly one packed
    program — per-batch capacities straddling a bucket boundary would
    bill recompiles to the measurement."""
    from code2vec_tpu.data import packed as packed_lib
    from code2vec_tpu.parallel import mesh as mesh_lib
    shards = trainer.mesh.shape[mesh_lib.DATA_AXIS]

    def pack_all(minimum):
        return [packed_lib.pack_batch(
            batch, trainer._token_pad, trainer._path_pad,
            data_shards=shards, capacity_minimum=minimum)
            for batch in batches]

    packed = pack_all(packed_lib.MIN_CAPACITY)
    caps = {p.ctx.shape[1] for p in packed}
    if len(caps) > 1:
        packed = pack_all(max(caps))
    return packed


def wire_bytes(batch) -> int:
    """Bytes/batch on the host->device wire (either format)."""
    from code2vec_tpu.data import packed as packed_lib
    return packed_lib.wire_bytes(batch)


def staged(trainer, host_batches):
    """Mesh-aware device placement via the trainer's own staging path (a
    bare jax.device_put would pin every array to device 0 and bill a
    redistribution to each timed step on multi-device meshes)."""
    return [arrays for arrays, _ in trainer.stage_batches(iter(host_batches))]
