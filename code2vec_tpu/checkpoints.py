"""Checkpoint / resume / release via orbax.

Reference parity (tensorflow_model.py:370-377, keras_model.py:230-296,
SURVEY.md §5 'Checkpoint / resume'):

- per-epoch saves, ``max_to_keep=10`` (reference config.py:57);
- the vocab sidecar ``dictionaries.bin`` lives next to the checkpoints
  (model_base.py:102-109) — written by the caller;
- **release** = params-only strip (the reference re-saves without optimizer
  state for a ~3× smaller artifact, tensorflow_model.py:132-136,
  README.md:212-219): params go under ``<path>__only-weights``;
- full state (params + Adam moments + step + epoch) goes under
  ``<path>__entire-model`` (the Keras backend's naming, config.py:196-202);
- the epoch number is stored explicitly in the checkpoint metadata — the
  reference recovered it by parsing checkpoint filenames and left a TODO
  for doing it properly (keras_model.py:274, 285-287).

Orbax writes sharded arrays natively: on a mesh, each host saves its own
shards (async-capable), and restore re-shards to the current mesh.
"""
from __future__ import annotations

import inspect
import json
import logging
import os
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

# package logger: 'code2vec_tpu.checkpoints' — propagates to the
# 'code2vec_tpu' root logger Config.get_logger configures
logger = logging.getLogger(__name__)

from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults


class CheckpointLayoutError(ValueError):
    """Permanent, store-wide restore failure (pre-canonical layout or a
    cross-framework training resume): every artifact under the store
    shares the cause, so the corruption fallback must re-raise instead
    of quarantining its way through good data."""

# orbax version split for the params-only partial restore: newer orbax
# has PyTreeRestore(partial_restore=True) dispatched through the
# manager's handler registry; 0.7.x (this image's toolchain) has neither
# — there the equivalent is a standalone PyTreeCheckpointHandler with
# the transforms={} mechanism, and registering a SECOND handler instance
# for the same item corrupts saves (each instance finalizes its own tmp
# dir onto the item path — reproduced on 0.7.0).
_PYTREE_PARTIAL_RESTORE = 'partial_restore' in inspect.signature(
    ocp.args.PyTreeRestore.__init__).parameters


class RestoredTraining(NamedTuple):
    params: Any
    opt_state: Any
    step: int
    epoch: int


# --------------------------------------------------------------------------
# Target-table row adaptation (ADVICE r3): the target table's padded row
# count folds in the fused-CE vocab tile and the mesh model-axis size
# (backends.target_row_alignment), so a checkpoint written under one
# topology/fused-CE setting allocates a different row count than a resume
# under another. The extra rows are pure padding — masked out of the
# softmax by num_valid_targets and receiving zero gradient (hence zero Adam
# moments) — so restore can pad with zeros or slice them off exactly. The
# adapted leaves are identified by keypath name: 'target_embedding' names
# the table in the canonical params dict, the optax moment NamedTuples, and
# the flax param dict alike.

_TARGET_ROWS_KEY = 'target_vocab_rows'
_TARGET_LEAF_NAME = 'target_embedding'


def _is_target_path(path) -> bool:
    last = path[-1]
    name = getattr(last, 'name', None)
    if name is None:
        name = getattr(last, 'key', None)
    return name == _TARGET_LEAF_NAME


def _with_target_rows(abstract_tree, rows: int):
    """Abstract tree with target-table leaves' leading dim set to ``rows``
    (the STORED allocation), keeping dtype and current-mesh sharding."""
    def fix(path, leaf):
        if not _is_target_path(path) or leaf.shape[0] == rows:
            return leaf
        return jax.ShapeDtypeStruct((rows,) + tuple(leaf.shape[1:]),
                                    leaf.dtype,
                                    sharding=getattr(leaf, 'sharding', None))
    return jax.tree_util.tree_map_with_path(fix, abstract_tree)


def _resize_target_rows(tree, abstract_tree, rows: int):
    """Pad (zeros) or slice restored target-table leaves to ``rows`` (the
    CURRENT allocation), re-laid-out to the abstract leaf's sharding.
    Slicing is exact because the current allocation always covers the
    valid vocabulary rows; rows beyond them are masked padding.

    The resize runs under ``jax.jit`` with an explicit ``out_shardings``:
    on a multi-process mesh the restored leaves are row-sharded and NOT
    fully addressable, where eager slicing / ``device_put`` raise — jit
    of a computation over global arrays is the legal spelling (advisor
    r4, medium)."""
    def fix(path, leaf, abstract_leaf):
        if not _is_target_path(path) or leaf.shape[0] == rows:
            return leaf
        if leaf.shape[0] > rows:
            resize = lambda x: jax.lax.slice_in_dim(x, 0, rows, axis=0)
        else:
            pad = [(0, rows - leaf.shape[0])] + [(0, 0)] * (leaf.ndim - 1)
            resize = lambda x: jax.numpy.pad(x, pad)
        sharding = getattr(abstract_leaf, 'sharding', None)
        if sharding is None or not isinstance(leaf, jax.Array):
            return resize(leaf)
        return jax.jit(resize, out_shardings=sharding)(leaf)
    return jax.tree_util.tree_map_with_path(fix, tree, abstract_tree)


def _target_rows_from_metadata(tree_meta) -> Optional[int]:
    """Target-table row count read from orbax's OWN saved array metadata,
    i.e. from the artifact being restored. The shared ``.meta.json``
    sidecar records only the NEWEST writer's row count, so after e.g. a
    ``--release`` under a reshaped config it lies about older epoch
    checkpoints (advisor r4); the per-artifact metadata cannot."""
    tree = getattr(tree_meta, 'tree', tree_meta)
    found = []

    def walk(node):
        if isinstance(node, dict):
            for key, value in node.items():
                if key == _TARGET_LEAF_NAME:
                    shape = getattr(value, 'shape', None)
                    if shape:
                        found.append(int(shape[0]))
                else:
                    walk(value)
        elif isinstance(node, (list, tuple)):
            for value in node:
                walk(value)

    walk(tree)
    return found[0] if found else None


# Adam moment subtrees subject to storage-dtype adaptation on restore:
# ADAM_MU_DTYPE's default flipped 'float32' -> 'bfloat16' (2026-07-31) and
# ADAM_NU_DTYPE is A/B-gated the same way, so a resume under either
# setting of a checkpoint written under the other must adapt instead of
# failing on a dtype mismatch. Field names follow optax.ScaleByAdamState
# (training/adam_dtypes.py keeps them for exactly this reason).
_MOMENT_FIELDS = ('mu', 'nu')


def _path_has_field(path, field: str) -> bool:
    for entry in path:
        name = getattr(entry, 'name', None)
        if name is None:
            name = getattr(entry, 'key', None)
        if name == field:
            return True
    return False


def _moment_dtype_from_metadata(tree_meta, field: str):
    """Storage dtype of the Adam moment subtree named ``field`` in the
    artifact being restored, from orbax's own saved array metadata. None
    when the artifact has no such subtree or its dtypes are
    non-uniform."""
    tree = getattr(tree_meta, 'tree', tree_meta)
    dtypes = set()

    def walk(node, under):
        if isinstance(node, dict):
            for key, value in node.items():
                walk(value, under or key == field)
        elif isinstance(node, (list, tuple)):
            for value in node:
                walk(value, under)
        elif under:
            dt = getattr(node, 'dtype', None)
            if dt is not None and jax.numpy.issubdtype(dt,
                                                       jax.numpy.floating):
                dtypes.add(np.dtype(dt))

    walk(tree, False)
    return dtypes.pop() if len(dtypes) == 1 else None


def _moment_dtype_of(abstract_tree, field: str):
    """The (uniform) floating dtype of the ``field`` moment leaves in an
    abstract optimizer-state tree, or None."""
    dtypes = set()

    def visit(path, leaf):
        if _path_has_field(path, field) and jax.numpy.issubdtype(
                leaf.dtype, jax.numpy.floating):
            dtypes.add(np.dtype(leaf.dtype))
        return leaf

    jax.tree_util.tree_map_with_path(visit, abstract_tree)
    return dtypes.pop() if len(dtypes) == 1 else None


def _with_moment_dtype(abstract_tree, dtype, field: str):
    """Abstract tree with the ``field`` moment's floating leaves set to
    ``dtype`` (the STORED moment dtype), keeping shape and sharding — the
    restore target must match what is on disk; the cast back to the
    configured dtype happens after restore (`_cast_moment`)."""
    def fix(path, leaf):
        if not _path_has_field(path, field):
            return leaf
        if not jax.numpy.issubdtype(leaf.dtype, jax.numpy.floating):
            return leaf
        if np.dtype(leaf.dtype) == np.dtype(dtype):
            return leaf
        return jax.ShapeDtypeStruct(leaf.shape, dtype,
                                    sharding=getattr(leaf, 'sharding',
                                                     None))
    return jax.tree_util.tree_map_with_path(fix, abstract_tree)


def _cast_moment(tree, abstract_tree, field: str):
    """Cast restored ``field`` moment leaves to the configured dtype from
    the abstract target (fp32 -> bf16 rounds the way the bf16-moment
    update does every step; bf16 -> fp32 is exact). Runs under ``jax.jit``
    with explicit ``out_shardings`` — the legal spelling on
    non-fully-addressable multi-process arrays (same rationale as
    `_resize_target_rows`)."""
    def fix(path, leaf, abstract_leaf):
        if not _path_has_field(path, field):
            return leaf
        if not hasattr(leaf, 'dtype') or not jax.numpy.issubdtype(
                leaf.dtype, jax.numpy.floating):
            return leaf
        want = np.dtype(abstract_leaf.dtype)
        if np.dtype(leaf.dtype) == want:
            return leaf
        cast = lambda x: x.astype(want)
        sharding = getattr(abstract_leaf, 'sharding', None)
        if sharding is None or not isinstance(leaf, jax.Array):
            return cast(leaf)
        return jax.jit(cast, out_shardings=sharding)(leaf)
    return jax.tree_util.tree_map_with_path(fix, tree, abstract_tree)


class CheckpointStore:
    """Orbax-backed store for one model path prefix."""

    def __init__(self, model_path: str, max_to_keep: int = 10,
                 metadata: Optional[Dict[str, Any]] = None,
                 snapshot_max_to_keep: int = 2):
        self.model_path = model_path
        self.entire_dir = os.path.abspath(
            Config.get_entire_model_path(model_path))
        self.weights_dir = os.path.abspath(
            Config.get_model_weights_path(model_path))
        # step-interval snapshots (preemption insurance) live in their own
        # manager with a small retention window, so frequent interval saves
        # can never evict the epoch-boundary history max_to_keep promises
        self.snapshot_dir = os.path.abspath(
            Config.get_step_snapshots_path(model_path))
        self._manager: Optional[ocp.CheckpointManager] = None
        self._snapshot_manager: Optional[ocp.CheckpointManager] = None
        self.max_to_keep = max_to_keep
        self.snapshot_max_to_keep = snapshot_max_to_keep
        # shape-determining settings (e.g. PARAM_ROW_ALIGNMENT): written at
        # save, verified before restore so a mismatch is a clear config
        # error instead of an opaque orbax shape mismatch
        self.metadata = metadata or {}
        self.meta_path = os.path.abspath(model_path) + '.meta.json'

    #: stamped into every meta file; absence marks a checkpoint written
    #: before the canonical flat {name: array} params layout
    _LAYOUT = 'canonical-v1'

    def _write_metadata(self) -> None:
        if not self.metadata:
            return
        to_write = dict(self.metadata, checkpoint_layout=self._LAYOUT)
        stored = self._stored_metadata()
        for key in self._PRESERVE_ON_WRITE:
            # the original writer wins: e.g. --release under another
            # framework must not relabel the training checkpoint's
            # framework, or the resume diagnostic below lies
            if key in stored:
                to_write[key] = stored[key]
        with open(self.meta_path, 'w') as f:
            json.dump(to_write, f)

    # identity keys where the ORIGINAL writer wins on re-save
    _PRESERVE_ON_WRITE = frozenset({'framework'})
    # metadata keys whose mismatch does not reject a restore: 'framework'
    # is informational for params-only loads (the canonical checkpoint
    # layout is backend-agnostic); target_vocab_rows differences are
    # ADAPTED on restore (pad/slice of masked padding rows), so fused-CE
    # checkpoints stay loadable across mesh reshapes. Unlike 'framework',
    # target_vocab_rows tracks the NEWEST save; since it can therefore lie
    # about OLDER artifacts sharing the sidecar, restores read the actual
    # row count per artifact from orbax's array metadata and use the
    # sidecar only as a fallback (_artifact_target_rows).
    _NON_STRICT_KEYS = frozenset({'framework', _TARGET_ROWS_KEY})

    def verify_metadata(self) -> None:
        if not self.metadata or not os.path.isfile(self.meta_path):
            return
        stored = self._stored_metadata()
        for key, value in self.metadata.items():
            if key in self._NON_STRICT_KEYS:
                continue
            if key in stored and stored[key] != value:
                raise ValueError(
                    'Checkpoint at `%s` was saved with %s=%r but the current '
                    'config has %s=%r; these settings determine parameter '
                    'shapes and must match.' % (self.model_path, key,
                                                stored[key], key, value))

    def _stored_metadata(self) -> Dict[str, Any]:
        if not os.path.isfile(self.meta_path):
            return {}
        with open(self.meta_path, 'r') as f:
            return json.load(f)

    def _stored_target_rows(self) -> Optional[int]:
        """The target-table row count the checkpoint was SAVED with, when
        recorded — restore targets must use it, then adapt to the current
        allocation (see the module-level row-adaptation note)."""
        rows = self._stored_metadata().get(_TARGET_ROWS_KEY)
        return int(rows) if rows is not None else None

    def _artifact_target_rows(self, read_metadata) -> Optional[int]:
        """Saved row count for ONE artifact: orbax's own array metadata
        first (exact per artifact), the shared sidecar as fallback for
        artifacts written before metadata was readable.  The fallback is
        LOUD: the sidecar tracks only the newest writer, so trusting it
        for an older artifact can rebuild the opaque shape mismatch this
        path exists to remove."""
        try:
            rows = _target_rows_from_metadata(read_metadata())
        except Exception as exc:
            rows = None
            fallback_reason = repr(exc)
        else:
            fallback_reason = 'no target-table leaf in artifact metadata'
        if rows is not None:
            return rows
        sidecar = self._stored_target_rows()
        if sidecar is not None:
            logger.warning(
                'checkpoint %s: per-artifact row metadata unavailable '
                '(%s); falling back to the shared sidecar value %d, which '
                'may be wrong for older artifacts', self.model_path,
                fallback_reason, sidecar)
        return sidecar

    # ------------------------------------------------------------- manager
    @staticmethod
    def _handler_registry():
        """A FRESH manager (a resuming process that never saved) cannot
        reconstruct item_metadata without knowing the handler — and the
        per-artifact row-count read depends on it.  Registering both the
        Standard handler (save / full restore / metadata) and the PyTree
        handler (the params-only partial_restore path) keeps every
        existing call pattern working."""
        from orbax.checkpoint import handlers
        registry = handlers.DefaultCheckpointHandlerRegistry()
        standard = ocp.StandardCheckpointHandler()
        registry.add('default', ocp.args.StandardSave, standard)
        registry.add('default', ocp.args.StandardRestore, standard)
        if _PYTREE_PARTIAL_RESTORE:
            # newer orbax routes the params-only partial restore through
            # this registration; on 0.7.x it goes through a standalone
            # handler instead (module comment) — and the extra handler
            # instance here would corrupt saves
            registry.add('default', ocp.args.PyTreeRestore,
                         ocp.PyTreeCheckpointHandler())
        return registry

    def manager(self) -> ocp.CheckpointManager:
        if self._manager is None:
            self._manager = ocp.CheckpointManager(
                self.entire_dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.max_to_keep, create=True),
                handler_registry=self._handler_registry())
        return self._manager

    def snapshot_manager(self) -> ocp.CheckpointManager:
        if self._snapshot_manager is None:
            self._snapshot_manager = ocp.CheckpointManager(
                self.snapshot_dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.snapshot_max_to_keep, create=True),
                handler_registry=self._handler_registry())
        return self._snapshot_manager

    def wait_until_finished(self) -> None:
        """Drain any in-flight async save on either manager WITHOUT
        closing it (preemption's final save must be durable inside the
        signal grace window; the divergence rewind reads the newest
        snapshot right after a possible interval save)."""
        if self._manager is not None:
            self._manager.wait_until_finished()
        if self._snapshot_manager is not None:
            self._snapshot_manager.wait_until_finished()

    def close(self) -> None:
        # exception-safe: a failure draining one manager must not abandon
        # the other's in-flight async save
        try:
            if self._manager is not None:
                self._manager.close()
        finally:
            self._manager = None
            try:
                if self._snapshot_manager is not None:
                    self._snapshot_manager.close()
            finally:
                self._snapshot_manager = None

    # ---------------------------------------------------------------- save
    def save_training(self, *, params, opt_state, step: int,
                      epoch: int, wait: bool = False,
                      snapshot: bool = False) -> bool:
        """Async by default: orbax copies device arrays to host
        synchronously (<1 train step of stall), then persists in the
        background while training continues (SURVEY.md §5's 'orbax async
        checkpointing'). ``close()`` and the next ``save_training`` drain
        any in-flight save.

        Checkpoints are keyed by the global *step*; ``epoch`` records the
        last fully completed epoch for resume.  ``snapshot=True`` routes
        step-interval saves (``SAVE_EVERY_N_STEPS``) to the separate
        short-retention snapshot manager."""
        state = {'params': params, 'opt_state': opt_state,
                 'step': np.asarray(step, np.int32),
                 'epoch': np.asarray(epoch, np.int32)}
        manager = self.snapshot_manager() if snapshot else self.manager()
        saved = manager.save(step, args=ocp.args.StandardSave(state))
        if saved is False:
            # orbax silently skips step <= latest_step: with rewind
            # hygiene (purge_steps_newer_than) this should not happen —
            # a skipped save the caller believes durable is lost work
            logger.warning(
                'checkpoint %s: orbax SKIPPED the save at step %d '
                '(a retained step with an equal or newer key exists) — '
                'this state was NOT persisted', self.model_path, step)
        if wait:
            manager.wait_until_finished()
        if snapshot and faults.maybe_fire('corrupt_snapshot'):
            # fault drill (ROBUSTNESS.md): finalize the async write, then
            # truncate the artifact — the exact on-disk state a disk-full
            # or killed writer leaves, which restore must fall back past
            manager.wait_until_finished()
            faults.corrupt_directory(
                os.path.join(str(manager.directory), str(step)))
        self._write_metadata()
        return saved is not False

    def save_release(self, params) -> None:
        """Params-only artifact (the reference's ``--release``)."""
        checkpointer = ocp.StandardCheckpointer()
        path = self.weights_dir
        if os.path.exists(path):
            import shutil
            shutil.rmtree(path)
        checkpointer.save(path, {'params': params})
        checkpointer.wait_until_finished()
        checkpointer.close()
        self._write_metadata()

    # ------------------------------------------------------------- restore
    @staticmethod
    def _disk_steps(directory: str) -> set:
        """Committed step directories on disk (orbax commits by rename,
        so in-flight tmp dirs carry a suffix and never match)."""
        try:
            return {int(name) for name in os.listdir(directory)
                    if name.isdigit()}
        except OSError:
            return set()

    def _restore_candidates(self) -> list:
        """Every retained (manager, step) across the epoch and snapshot
        managers, NEWEST step first — the corruption-fallback order.
        Keys are global steps (older checkpoints were keyed by epoch —
        restore handles either, the stored state carries both numbers).

        Cross-process freshness: an orbax manager caches its step list
        at open, so a step saved by ANOTHER process afterwards (a
        serving worker following a live trainer's store, a mesh worker
        asked to adopt a step the parent just wrote) would be invisible
        forever.  When the directory holds a committed step the cached
        list doesn't know, the managers are reopened to resync."""
        for directory, manager in (
                (self.entire_dir, self._manager),
                (self.snapshot_dir, self._snapshot_manager)):
            if manager is None or not os.path.isdir(directory):
                continue
            known = {int(step) for step in manager.all_steps()}
            if not self._disk_steps(directory) <= known:
                self.close()  # reopen lazily with the fresh step list
                break
        candidates = []
        if os.path.isdir(self.entire_dir):
            for step in self.manager().all_steps():
                candidates.append((self.manager(), int(step)))
        if os.path.isdir(self.snapshot_dir):
            for step in self.snapshot_manager().all_steps():
                candidates.append((self.snapshot_manager(), int(step)))
        return sorted(candidates, key=lambda c: c[1], reverse=True)

    def _newest(self) -> Optional[Tuple[ocp.CheckpointManager, int]]:
        """(manager, step) of the newest checkpoint across both stores."""
        candidates = self._restore_candidates()
        return candidates[0] if candidates else None

    def has_step(self, step: int) -> bool:
        """True when a retained checkpoint in either store holds
        ``step`` (preemption save verification)."""
        return any(s == step for _m, s in self._restore_candidates())

    def newest_step(self) -> Optional[int]:
        """Newest retained step across both stores, or None when the
        path holds no checkpoints (serving rollover polling —
        ``ServingEngine.follow_checkpoints``, SERVING.md)."""
        newest = self._newest()
        return newest[1] if newest else None

    def _quarantine(self, manager, step: int,
                    suffix: str = '.corrupt') -> None:
        """Move a step directory ASIDE (rename to ``<step><suffix>``) so
        neither retention nor the next restore trips over it again.
        Best-effort and reversible: a false positive (e.g. a transient
        read error) is recovered by renaming the directory back."""
        step_dir = os.path.join(str(manager.directory), str(step))
        try:
            if os.path.isdir(step_dir):
                # unique destination: a REPEAT rewind can quarantine the
                # same step number again (re-saved after the first
                # purge), and os.replace onto an existing non-empty dir
                # would fail, leaving the poisoned artifact in place
                dest = step_dir + suffix
                serial = 1
                while os.path.exists(dest):
                    serial += 1
                    dest = '%s%s.%d' % (step_dir, suffix, serial)
                os.replace(step_dir, dest)
                logger.warning(
                    'checkpoint %s: quarantined step %d to `%s`',
                    self.model_path, step, dest)
        except OSError as exc:
            logger.warning('checkpoint %s: could not quarantine step %d '
                           '(%s)', self.model_path, step, exc)

    def purge_steps_newer_than(self, step: int) -> None:
        """Quarantine every retained step NEWER than ``step``, across
        both stores (suffix ``.rewound``).  Divergence-rewind hygiene:
        artifacts saved inside the poisoned window (a) would shadow the
        rewound state as 'newest' for a crash-resume, and (b) hold their
        step keys, which makes orbax silently no-op any later re-save at
        or below them (``manager.save`` returns False for
        ``step <= latest_step``)."""
        for manager, retained in self._restore_candidates():
            if retained > step:
                self._quarantine(manager, retained, suffix='.rewound')
        # the managers' in-memory checkpoint lists still name the purged
        # steps; reopening on next use resyncs them with the directory
        self.close()

    def _raise_if_permanent(self, exc: Exception) -> None:
        """Re-raise a restore failure as a clear, store-wide error when
        the sidecar says it cannot be corruption: a pre-canonical layout
        or a cross-framework training resume affects EVERY retained step,
        so falling back to older artifacts cannot help."""
        stored = self._stored_metadata()
        if stored and stored.get('checkpoint_layout') != self._LAYOUT:
            raise CheckpointLayoutError(
                'Checkpoint at `%s` predates the canonical parameter '
                'layout (no checkpoint_layout marker); it cannot be '
                'restored by this version. Re-save it from the version '
                'that wrote it.' % self.model_path) from exc
        stored_fw = stored.get('framework') if stored else None
        current_fw = self.metadata.get('framework')
        if stored_fw and current_fw and stored_fw != current_fw:
            raise CheckpointLayoutError(
                'Cannot resume TRAINING from `%s` with framework=%r: '
                'the checkpoint was written by framework=%r and '
                'optimizer state is backend-specific. Params-only '
                'loads (evaluate / predict / --release) work across '
                'frameworks.' % (self.model_path, current_fw,
                                 stored_fw)) from exc

    def restore_training(self, abstract_params, abstract_opt_state,
                         max_step: Optional[int] = None
                         ) -> Optional[RestoredTraining]:
        """Restore the newest RESTORABLE full training state (epoch
        checkpoint or step-interval snapshot), re-sharded to match the
        abstract target (shapes + shardings).  ``max_step`` excludes
        newer steps (the divergence guard passes its last KNOWN-FINITE
        step so it never rewinds into a snapshot saved after the
        divergence began).

        A step that fails to restore (partial/corrupt write: disk-full,
        preemption mid-finalize) is logged and skipped in favor of the
        next-older retained step — losing one save interval beats losing
        the run.  Quarantine (rename to ``<step>.corrupt``) is DEFERRED
        until some older step actually restores: a failure shared by
        every candidate is a config/environment problem, and renaming the
        whole history aside would destroy good data — that case raises
        with the newest failure instead."""
        candidates = self._restore_candidates()
        if max_step is not None:
            candidates = [c for c in candidates if c[1] <= max_step]
        if not candidates:
            return None
        self.verify_metadata()
        return self._restore_with_fallback(
            candidates,
            lambda manager, step: self._restore_training_at(
                manager, step, abstract_params, abstract_opt_state),
            what='restore')

    def _restore_with_fallback(self, candidates, attempt, what: str):
        """The shared corruption-fallback policy (restore_training and
        restore_params): try ``attempt(manager, step)`` newest first;
        store-wide failures (CheckpointLayoutError / sidecar-permanent)
        re-raise immediately; others fall back to the next older step.
        Quarantine of failed steps is DEFERRED until some step actually
        restores — when every candidate fails the error re-raises and
        nothing is renamed (a shared failure is a config/environment
        cause, not corruption)."""
        failed: list = []   # (manager, step, exc) awaiting quarantine
        for manager, step in candidates:
            try:
                restored = attempt(manager, step)
            except CheckpointLayoutError:
                raise
            except Exception as exc:
                self._raise_if_permanent(exc)
                logger.warning(
                    'checkpoint %s: %s of step %d failed (%r); falling '
                    'back to the next older retained step',
                    self.model_path, what, step, exc)
                failed.append((manager, step, exc))
                continue
            for failed_manager, failed_step, _exc in failed:
                self._quarantine(failed_manager, failed_step)
            return restored
        last_exc = failed[-1][2]
        raise ValueError(
            'No retained checkpoint under `%s` could be restored (all %d '
            'candidate step(s) failed identically-or-worse, so nothing '
            'was quarantined — suspect a config/environment cause); '
            'newest failure: %r' % (self.model_path, len(candidates),
                                    last_exc)) from last_exc

    def _restore_training_at(self, manager, latest: int, abstract_params,
                             abstract_opt_state) -> RestoredTraining:
        """One restore attempt against one (manager, step) artifact."""
        # One metadata read serves both adaptations (it can be disk/network
        # I/O on remote checkpoint stores); the cache keeps
        # _artifact_target_rows' call-on-demand signature.
        _meta_cache = []

        def read_metadata():
            if not _meta_cache:
                _meta_cache.append(manager.item_metadata(latest))
            return _meta_cache[0]

        stored_rows = self._artifact_target_rows(read_metadata)
        # Adapt the restore target to the STORED moment dtypes: the
        # ADAM_MU_DTYPE default flip (fp32 -> bf16, 2026-07-31) — and the
        # ADAM_NU_DTYPE knob gated on the same A/B rule — must not turn a
        # default-config resume of a checkpoint written under the other
        # setting into an opaque dtype-mismatch failure. Restored moments
        # are cast back to the configured dtype below.
        moment_mismatch = {}   # field -> stored dtype
        for field in _MOMENT_FIELDS:
            try:
                stored_dt = _moment_dtype_from_metadata(read_metadata(),
                                                        field)
            except Exception:
                stored_dt = None
            configured_dt = _moment_dtype_of(abstract_opt_state, field)
            if (stored_dt is not None and configured_dt is not None
                    and stored_dt != configured_dt):
                moment_mismatch[field] = stored_dt
        current_params, current_opt = abstract_params, abstract_opt_state
        if stored_rows is not None:
            abstract_params = _with_target_rows(abstract_params, stored_rows)
            abstract_opt_state = _with_target_rows(abstract_opt_state,
                                                   stored_rows)
        for field, stored_dt in moment_mismatch.items():
            logger.warning(
                'checkpoint %s stores Adam %s as %s but the configured '
                'ADAM_%s_DTYPE differs: restoring as stored, then casting '
                '(set --adam-%s-dtype %s to resume bit-exactly)',
                self.model_path, field, stored_dt, field.upper(), field,
                stored_dt.name)
            abstract_opt_state = _with_moment_dtype(abstract_opt_state,
                                                    stored_dt, field)
        target = {'params': abstract_params, 'opt_state': abstract_opt_state,
                  'step': np.asarray(0, np.int32),
                  'epoch': np.asarray(0, np.int32)}
        # failures propagate to restore_training's candidate loop, which
        # distinguishes store-wide config errors (_raise_if_permanent)
        # from per-artifact corruption (quarantine + fall back)
        restored = manager.restore(
            latest, args=ocp.args.StandardRestore(target))
        params, opt_state = restored['params'], restored['opt_state']
        if stored_rows is not None:
            current_rows = self.metadata.get(_TARGET_ROWS_KEY)
            if current_rows is not None and current_rows != stored_rows:
                params = _resize_target_rows(params, current_params,
                                             current_rows)
                opt_state = _resize_target_rows(opt_state, current_opt,
                                                current_rows)
        for field in moment_mismatch:
            opt_state = _cast_moment(opt_state, current_opt, field)
        return RestoredTraining(
            params=params, opt_state=opt_state,
            step=int(restored['step']), epoch=int(restored['epoch']))

    def _params_adapters(self, abstract_params):
        """(with_rows, adapt) closures of the params-only restore paths:
        target the SAVED target-table row count, then pad/slice back to
        the current allocation (module-level row-adaptation note)."""
        current_params = abstract_params

        def with_rows(stored_rows):
            if stored_rows is not None:
                return _with_target_rows(current_params, stored_rows)
            return current_params

        def adapt(params, stored_rows):
            current_rows = self.metadata.get(_TARGET_ROWS_KEY)
            if (stored_rows is not None and current_rows is not None
                    and current_rows != stored_rows):
                return _resize_target_rows(params, current_params,
                                           current_rows)
            return params

        return with_rows, adapt

    def _check_restore_budget(self, abstract_tree, what: str) -> None:
        """HBM-budget precheck at the restore boundary
        (telemetry/memory.py): params-only restores bring up a NEW set
        next to whatever is already resident (the serving rollover
        candidate above all), so the predicted footprint — known
        exactly from the abstract target — is refused typed BEFORE
        orbax allocates anything.  Training resume is exempt: it
        replaces the state it restores into."""
        from code2vec_tpu.telemetry import memory as memory_lib
        memory_lib.ledger().check_budget(
            memory_lib.tree_nbytes(abstract_tree),
            '%s (`%s`)' % (what, self.model_path))

    def restore_params_step(self, abstract_params, step: int) -> Any:
        """Params-only restore pinned to ONE retained step (canaried
        serving rollover: ``ServingEngine.load_params(step)``). Unlike
        ``restore_params`` there is no older-step fallback — the caller
        asked for this step, so a missing or unrestorable artifact is an
        error, not a silent downgrade."""
        self.verify_metadata()
        self._check_restore_budget(abstract_params,
                                   'params restore at step %d' % step)
        with_rows, adapt = self._params_adapters(abstract_params)
        candidates = [(m, s) for m, s in self._restore_candidates()
                      if s == step]
        if not candidates:
            raise ValueError(
                'No retained checkpoint at step %d under `%s` (retained: '
                '%s)' % (step, self.model_path,
                         sorted({s for _m, s
                                 in self._restore_candidates()})))
        return self._restore_with_fallback(
            candidates,
            lambda manager, s: self._restore_params_at(manager, s,
                                                       with_rows, adapt),
            what='params restore at step %d' % step)

    def restore_params(self, abstract_params) -> Optional[Any]:
        """Restore params only: prefer the released weights-only artifact,
        fall back to the newest full checkpoint (reference load order:
        whatever exists under the load path)."""
        self.verify_metadata()
        self._check_restore_budget(abstract_params, 'params-only restore')
        with_rows, adapt = self._params_adapters(abstract_params)

        if os.path.isdir(self.weights_dir):
            checkpointer = ocp.StandardCheckpointer()

            def read_weights_metadata():
                # newer orbax wraps the tree in .item_metadata; 0.7.x
                # returns the metadata tree directly
                meta = checkpointer.metadata(self.weights_dir)
                return getattr(meta, 'item_metadata', meta)

            stored_rows = self._artifact_target_rows(read_weights_metadata)
            restored = checkpointer.restore(
                self.weights_dir, {'params': with_rows(stored_rows)})
            checkpointer.close()
            return adapt(restored['params'], stored_rows)
        candidates = self._restore_candidates()
        if not candidates:
            return None
        return self._restore_with_fallback(
            candidates,
            lambda manager, step: self._restore_params_at(manager, step,
                                                          with_rows, adapt),
            what='params-only restore')

    def _restore_params_at(self, manager, latest: int, with_rows, adapt):
        """One params-only restore attempt against one (manager, step)."""
        stored_rows = self._artifact_target_rows(
            lambda: manager.item_metadata(latest))
        abstract_params = with_rows(stored_rows)
        # partial restore: pull only the params subtree out of a full
        # training checkpoint (the reference's load-for-eval path similarly
        # ignores optimizer slots)
        item = {'params': abstract_params}
        restore_args = ocp.checkpoint_utils.construct_restore_args(item)
        if _PYTREE_PARTIAL_RESTORE:
            restored = manager.restore(
                latest, args=ocp.args.PyTreeRestore(
                    item=item, restore_args=restore_args,
                    partial_restore=True))
        else:
            # orbax 0.7.x: standalone handler on the step's item dir with
            # the transforms={} partial-restore mechanism (module comment)
            item_dir = os.path.join(str(manager.directory), str(latest),
                                    'default')
            checkpointer = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
            try:
                restored = checkpointer.restore(
                    item_dir, args=ocp.args.PyTreeRestore(
                        item=item, transforms={},
                        restore_args=restore_args))
            finally:
                checkpointer.close()
        self._check_materialized(restored['params'])
        return adapt(restored['params'], stored_rows)

    def _check_materialized(self, params) -> None:
        """partial_restore=True silently leaves target leaves UNRESTORED
        (as ShapeDtypeStructs) when the stored tree doesn't match — e.g. a
        checkpoint in the pre-canonical backend-native layout. Turn that
        into a clear error instead of a downstream 'not a valid JAX type'."""
        unrestored = [
            jax.tree_util.keystr(path)
            for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
            if isinstance(leaf, jax.ShapeDtypeStruct)]
        if not unrestored:
            return
        stored = self._stored_metadata()
        # CheckpointLayoutError: layout mismatches are store-wide — the
        # corruption fallback must re-raise them, not quarantine through
        # every retained step
        if stored and stored.get('checkpoint_layout') != self._LAYOUT:
            raise CheckpointLayoutError(
                'Checkpoint at `%s` predates the canonical parameter '
                'layout (no checkpoint_layout marker); it cannot be '
                'restored by this version. Re-save it from the version '
                'that wrote it.' % self.model_path)
        raise CheckpointLayoutError(
            'Checkpoint at `%s` did not contain these parameters: %s — '
            'the stored tree does not match the expected canonical '
            'layout.' % (self.model_path, ', '.join(unrestored)))


def abstract_like(tree, shardings=None):
    """ShapeDtypeStruct pytree matching ``tree`` (optionally with shardings)
    for orbax's StandardRestore target."""
    def make(leaf, sharding=None):
        return jax.ShapeDtypeStruct(np.shape(leaf), leaf.dtype,
                                    sharding=sharding)
    if shardings is None:
        return jax.tree_util.tree_map(make, tree)
    return jax.tree_util.tree_map(make, tree, shardings)
