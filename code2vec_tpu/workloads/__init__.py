"""Scenario traffic plane (WORKLOADS.md).

The serving stack below this package answers ONE request at a time;
this package is where *workloads* live — named scenarios
(``scenario.py``), durable recorded traffic (``profile.py``), and the
paced open-loop replayer that drives the ServingMesh with a mixed
stream and joins completions back to scenario labels (``replay.py``).
``blend.py`` holds the pure retrieval-augmented-naming math the mesh's
``submit_blended`` serves.

Import discipline: this package is imported BY ``serving/mesh.py``
(for the blend math), so nothing here may import the serving package
at module scope — replay/profile import mesh types lazily inside
functions.
"""
from code2vec_tpu.workloads.scenario import (  # noqa: F401
    Scenario, UnknownScenario, get_scenario, register_scenario,
    scenario_names)
