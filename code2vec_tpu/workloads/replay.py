"""Paced open-loop traffic replay against a ServingMesh
(WORKLOADS.md "Replay runbook").

``plan_replay`` turns a profile into a deterministic admission plan:
same records + same seed + same rate scale => the SAME admitted
request set in the SAME order (``admitted_fingerprint`` hashes the
plan so tests assert bit-identity).  ``replay`` drives the mesh
open-loop — submission times come from the plan, never from
completion (a slow fleet gets MORE concurrent load, as production
would) — routes each record through its scenario's entry point
(submit / submit_neighbors / submit_blended), joins completions back
to scenario labels, and aggregates per-scenario x per-language:

- quality: exact-match and subtoken-F1 vs the recorded labels
  (code2vec_tpu/metrics.py semantics);
- traffic: delivered / shed / error counts, p50/p99 latency;
- memo hit-rate per scenario (the scenario-labeled ``memo/*``
  counters, read as before/after deltas);
- SLO error-budget burn attributed per scenario
  (``serving/slo.py`` scenario tallies via ``mesh.stats()``).
"""
from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from code2vec_tpu.metrics import SubtokensEvaluationMetric
from code2vec_tpu.telemetry import catalog
from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.workloads.scenario import Scenario, get_scenario

__all__ = ['plan_replay', 'admitted_fingerprint', 'replay']

#: subtoken-metric OOV sentinel (vocab.py SPECIAL_WORDS_ONLY_OOV):
#: replay scores decoded word strings, so only the literal matters
_OOV = '<OOV>'

#: memo counters the per-scenario hit-rate is read from (scenario-
#: labeled instances; catalog.labeled)
_MEMO_COUNTERS = ('memo/hits_total', 'memo/misses_total')


def plan_replay(records: Sequence[dict], rate_scale: float = 1.0,
                seed: int = 0, limit: Optional[int] = None
                ) -> List[Tuple[float, dict]]:
    """Deterministic admission plan: ``[(t_submit, record), ...]``.

    Records are stably ordered by (t, input position) — ties keep
    profile order — and paced at ``t / rate_scale``.  ``limit``
    subsamples with the seeded rng (the ONLY seed consumer: with no
    limit the plan is seed-independent, which is what "same profile +
    seed => identical admitted set" means for full replays too)."""
    if rate_scale <= 0:
        raise ValueError('rate_scale must be > 0 (got %r)' % rate_scale)
    indexed = sorted(enumerate(records),
                     key=lambda pair: (pair[1].get('t', 0.0), pair[0]))
    if limit is not None and limit < len(indexed):
        rng = random.Random(seed)
        keep = sorted(rng.sample(range(len(indexed)), limit))
        indexed = [indexed[i] for i in keep]
    return [(float(record.get('t', 0.0)) / rate_scale, dict(record))
            for _idx, record in indexed]


def admitted_fingerprint(plan: Sequence[Tuple[float, dict]]) -> str:
    """Content hash of the admitted request set (order-sensitive):
    the replay-determinism contract is fingerprint equality."""
    digest = hashlib.sha256()
    for t_submit, record in plan:
        digest.update(('%.9f' % t_submit).encode())
        digest.update(json.dumps(record, sort_keys=True).encode())
    return digest.hexdigest()


def _scenario_counters() -> Dict[str, int]:
    """Snapshot of every scenario-labeled memo counter (name ->
    value) from the process registry; empty when telemetry is off."""
    if not tele_core.enabled():
        return {}
    out = {}
    for name, value in tele_core.registry().snapshot().items():
        if catalog.base_name(name) in _MEMO_COUNTERS \
                and name != catalog.base_name(name):
            out[name] = int(value)
    return out


def _top_words(scenario: Scenario, results) -> List[str]:
    """Ranked predicted words for one completed request (first row —
    profile records are one method per request)."""
    if not results:
        return []
    row = results[0]
    words = getattr(row, 'predicted_words', None)  # BlendResult
    if words is not None:
        return list(words)
    words = getattr(row, 'topk_predicted_words', None)  # predict rows
    if words is not None:
        return list(words)
    labels = getattr(row, 'labels', None)  # NeighborResult
    if labels is not None:
        return [str(label) for label in labels]
    return []


class _Arm:
    """One (scenario, language) aggregation cell."""

    def __init__(self):
        self.requests = 0
        self.delivered = 0
        self.shed = 0
        self.errors = 0
        self.scored = 0
        self.exact = 0
        self.latencies_ms: List[float] = []
        self.subtokens = SubtokensEvaluationMetric(_OOV)

    def report(self) -> dict:
        lat = np.asarray(sorted(self.latencies_ms), dtype=np.float64)

        def pct(q):
            if lat.size == 0:
                return 0.0
            return float(lat[min(lat.size - 1,
                                 max(0, int(q * lat.size)))])
        return {
            'requests': self.requests,
            'delivered': self.delivered,
            'shed': self.shed,
            'errors': self.errors,
            'scored': self.scored,
            'exact_match': (self.exact / self.scored
                            if self.scored else 0.0),
            'f1': self.subtokens.f1,
            'precision': self.subtokens.precision,
            'recall': self.subtokens.recall,
            'p50_ms': round(pct(0.50), 3),
            'p99_ms': round(pct(0.99), 3),
        }


def _submit_one(mesh, scenario: Scenario, record: dict):
    """Route one record through its scenario's mesh entry point."""
    kwargs = {'scenario': scenario.name,
              'language': record.get('language')}
    if scenario.kind == 'neighbors':
        payload = record.get('lines')
        if payload is None:
            payload = np.asarray(record['vector'], dtype=np.float32)
        return mesh.submit_neighbors(
            payload, k=record.get('k', scenario.k), **kwargs)
    if scenario.kind == 'blend':
        weight = record.get('weight')
        if weight is None:
            weight = scenario.blend_weight
        return mesh.submit_blended(
            record['lines'], weight=weight,
            k=record.get('k', scenario.k), **kwargs)
    return mesh.submit(record['lines'],
                       tier=record.get('tier', scenario.tier),
                       **kwargs)


def replay(mesh, records: Sequence[dict], rate_scale: float = 1.0,
           seed: int = 0, limit: Optional[int] = None,
           pace: bool = True, timeout_s: float = 60.0) -> dict:
    """Replay a profile against a live mesh; returns the joined
    per-scenario x per-language report.

    ``pace=False`` submits as fast as the callers can (the
    deterministic-result drills use it: pacing changes wall time, not
    the admitted set).  Sheds (``EngineOverloaded``) are an expected
    open-loop outcome and are aggregated, not raised.
    """
    from code2vec_tpu.serving.errors import EngineOverloaded
    plan = plan_replay(records, rate_scale=rate_scale, seed=seed,
                       limit=limit)
    fingerprint = admitted_fingerprint(plan)
    memo_before = _scenario_counters()
    arms: Dict[Tuple[str, str], _Arm] = {}
    inflight: List[tuple] = []
    t_start = time.perf_counter()
    for t_submit, record in plan:
        scenario = get_scenario(record['scenario'])
        language = record.get('language') or '-'
        arm = arms.setdefault((scenario.name, language), _Arm())
        arm.requests += 1
        if pace:
            delay = t_submit - (time.perf_counter() - t_start)
            if delay > 0:
                time.sleep(delay)
        t0 = time.perf_counter()
        try:
            future = _submit_one(mesh, scenario, record)
        except EngineOverloaded:
            arm.shed += 1
            continue
        except Exception:
            arm.errors += 1
            continue
        inflight.append((arm, scenario, record, t0, future))
        if tele_core.enabled():
            tele_core.registry().counter(
                'workloads/replayed_total').inc()
    deadline = time.monotonic() + timeout_s
    for arm, scenario, record, t0, future in inflight:
        try:
            results = future.result(
                timeout=max(0.1, deadline - time.monotonic()))
        except EngineOverloaded:
            arm.shed += 1
            continue
        except Exception:
            arm.errors += 1
            continue
        arm.delivered += 1
        arm.latencies_ms.append((time.perf_counter() - t0) * 1e3)
        label = record.get('label')
        if label:
            words = _top_words(scenario, results)
            arm.scored += 1
            if words and words[0] == label:
                arm.exact += 1
            arm.subtokens.update_batch([(label, words)])
    memo_after = _scenario_counters()
    report: dict = {'fingerprint': fingerprint,
                    'admitted': len(plan),
                    'rate_scale': rate_scale, 'seed': seed,
                    'scenarios': {}}
    for (name, language), arm in sorted(arms.items()):
        cell = arm.report()
        hits = (memo_after.get(
            catalog.labeled('memo/hits_total', 'scenario', name), 0)
            - memo_before.get(
                catalog.labeled('memo/hits_total', 'scenario', name),
                0))
        misses = (memo_after.get(
            catalog.labeled('memo/misses_total', 'scenario', name), 0)
            - memo_before.get(
                catalog.labeled('memo/misses_total', 'scenario',
                                name), 0))
        cell['memo_hit_rate'] = (hits / (hits + misses)
                                 if hits + misses else 0.0)
        report['scenarios'].setdefault(name, {})[language] = cell
    stats = mesh.stats()
    slo = stats.get('slo')
    if slo is not None:
        report['slo'] = {
            'good_total': slo.get('good_total'),
            'bad_total': slo.get('bad_total'),
            'slow_total': slo.get('slow_total'),
            'alerting': slo.get('alerting'),
            # per-scenario error-budget burn attribution
            # (serving/slo.py scenario tallies)
            'scenarios': slo.get('scenarios', {}),
        }
        for key in ('availability_burn_fast', 'availability_burn_slow',
                    'p99_burn_fast', 'p99_burn_slow'):
            if key in slo:
                report['slo'][key] = slo[key]
    return report
