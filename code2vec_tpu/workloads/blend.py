"""Retrieval-augmented naming: the pure blend math (WORKLOADS.md
"Retrieval-augmented naming").

The serve-side path (``ServingMesh.submit_blended``) fetches top-k
neighbor labels from the attached index and mixes their similarity
votes with the softmax head's top-k distribution; this module holds
the math so it is testable without a mesh, a model, or jax — and so
``serving/mesh.py`` can import it without a cycle (this module must
never import the serving package).

Semantics:

- the softmax head's ``topk_predicted_words_scores`` are already a
  distribution over its top-k candidates (``jax.nn.softmax`` over the
  top-k logits, training/trainer.py) and are used as-is;
- neighbor similarity scores become votes via a numerically-stable
  softmax over the returned neighbors, summed per label (the same
  label retrieved twice votes twice);
- the blended score of a candidate label is
  ``(1 - weight) * softmax_p + weight * neighbor_vote``, candidates
  being the union of both sources, ranked descending (ties broken by
  softmax rank, then label — deterministic across runs);
- ``weight=0`` is exact softmax parity BY CONSTRUCTION: the mesh
  short-circuits to the plain submit path and wraps the untouched
  result, so the parity test can assert bit-identical scores.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = ['BlendResult', 'blend_row', 'neighbor_votes',
           'SOURCE_BLEND', 'SOURCE_SOFTMAX', 'SOURCE_FALLBACK']

#: BlendResult.source values: a real blend, the weight<=0 passthrough,
#: and the typed no-index fallback (pure softmax because there was
#: nothing to retrieve from)
SOURCE_BLEND = 'blend'
SOURCE_SOFTMAX = 'softmax'
SOURCE_FALLBACK = 'softmax_fallback'


class BlendResult(NamedTuple):
    """One blended prediction row.  ``base`` is the untouched softmax
    ``ModelPredictionResults`` row (its scores are NOT re-ranked);
    ``predicted_words``/``predicted_scores`` are the blended ranking.
    Memoizable: ``memo.copy_results`` rebuilds NamedTuples
    generically, nested rows included."""
    original_name: str
    predicted_words: List[str]
    predicted_scores: np.ndarray
    source: str
    weight: float
    base: object            # ModelPredictionResults
    neighbors: object = None  # NeighborResult | None


def neighbor_votes(labels: Sequence[str],
                   scores: Sequence[float]) -> dict:
    """label -> vote mass: a stable softmax over the neighbor
    similarity scores, summed per label.  Empty input votes for
    nothing (the blend then degenerates to scaled softmax)."""
    if len(labels) == 0:
        return {}
    arr = np.asarray(scores, dtype=np.float64)
    with np.errstate(invalid='ignore'):  # all--inf input -> NaN -> uniform
        arr = np.exp(arr - arr.max())
    total = float(arr.sum())
    if total <= 0 or not np.isfinite(total):
        # degenerate scores (all -inf / NaN): uniform votes keep the
        # blend defined instead of propagating NaN into the ranking
        arr = np.ones_like(arr)
        total = float(arr.sum())
    votes: dict = {}
    for label, mass in zip(labels, arr / total):
        votes[str(label)] = votes.get(str(label), 0.0) + float(mass)
    return votes


def blend_row(base, neighbors, weight: float,
              top_k: Optional[int] = None) -> BlendResult:
    """Blend one softmax prediction row with one neighbor result row.

    ``base`` is a ``ModelPredictionResults``; ``neighbors`` a
    ``NeighborResult`` (``.labels``/``.scores``) or None (typed
    fallback).  ``top_k`` bounds the blended candidate list (default:
    the base row's k).
    """
    words = list(base.topk_predicted_words)
    base_scores = (np.asarray(base.topk_predicted_words_scores,
                              dtype=np.float64)
                   if base.topk_predicted_words_scores is not None
                   else np.zeros(len(words)))
    if neighbors is None:
        return BlendResult(
            original_name=base.original_name, predicted_words=words,
            predicted_scores=base_scores.astype(np.float32),
            source=SOURCE_FALLBACK, weight=float(weight), base=base,
            neighbors=None)
    votes = neighbor_votes(list(neighbors.labels),
                           list(np.asarray(neighbors.scores).ravel()))
    weight = float(min(1.0, max(0.0, weight)))
    #: softmax rank for tie-breaks; unseen-by-softmax labels rank last
    base_rank = {word: rank for rank, word in enumerate(words)}
    candidates = list(dict.fromkeys(words + sorted(votes)))
    blended: List[Tuple[float, int, str]] = []
    for label in candidates:
        rank = base_rank.get(label, len(words))
        p = float(base_scores[rank]) if rank < len(words) else 0.0
        score = (1.0 - weight) * p + weight * votes.get(label, 0.0)
        blended.append((-score, rank, label))
    blended.sort()
    k = top_k if top_k is not None else len(words)
    top = blended[:max(1, k)] if blended else []
    return BlendResult(
        original_name=base.original_name,
        predicted_words=[label for _neg, _rank, label in top],
        predicted_scores=np.asarray(
            [-neg for neg, _rank, _label in top], dtype=np.float32),
        source=SOURCE_BLEND, weight=weight, base=base,
        neighbors=neighbors)
