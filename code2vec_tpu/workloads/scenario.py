"""Scenario registry: typed workload definitions (WORKLOADS.md).

A ``Scenario`` names one kind of served traffic — which language(s) it
carries, which mesh entry point serves it (``kind``), which output
tier it rides, and the arrival process synthetic builders generate it
with.  Profiles (``profile.py``) label every record with a scenario
name; the replayer (``replay.py``) routes each record through the
mesh call its scenario's ``kind`` selects and aggregates quality and
latency per scenario.

The registry is a process-global name table so profiles recorded by
one process replay in another on names alone; ``register_scenario``
lets benchmarks and tests add their own without touching this module.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Tuple

__all__ = ['Scenario', 'UnknownScenario', 'register_scenario',
           'get_scenario', 'scenario_names', 'KINDS']

#: mesh entry point a scenario's requests ride:
#: - 'predict'   -> ServingMesh.submit(tier=...)
#: - 'neighbors' -> ServingMesh.submit_neighbors(k=...)
#: - 'blend'     -> ServingMesh.submit_blended(weight=..., k=...)
KINDS = ('predict', 'neighbors', 'blend')


class UnknownScenario(KeyError):
    """A profile or caller named a scenario the registry does not
    hold — typed so replay tooling can distinguish a stale profile
    from a generic KeyError."""


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One named workload (immutable; safe to share across threads)."""

    name: str
    #: languages this scenario's requests carry ('java', 'csharp')
    languages: Tuple[str, ...] = ('java',)
    #: mesh entry point (KINDS)
    kind: str = 'predict'
    #: output tier for 'predict' requests (ignored by other kinds)
    tier: str = 'topk'
    #: neighbors per query for 'neighbors'/'blend' (None = config k)
    k: Optional[int] = None
    #: neighbor-vs-softmax mix for 'blend' (None = config knob)
    blend_weight: Optional[float] = None
    #: default arrival rate for synthetic profile builders (req/s)
    rate_rps: float = 20.0
    description: str = ''

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError('Scenario.kind must be one of %s (got %r)'
                             % (KINDS, self.kind))
        if not self.languages:
            raise ValueError('Scenario.languages must be non-empty')


_lock = threading.Lock()
_REGISTRY: Dict[str, Scenario] = {}


def register_scenario(scenario: Scenario,
                      replace: bool = False) -> Scenario:
    """Add a scenario to the process-global registry.  Re-registering
    an identical definition is a no-op; a CONFLICTING one raises
    unless ``replace=True`` — two benchmarks silently disagreeing on
    what a name means would corrupt every per-scenario number."""
    with _lock:
        existing = _REGISTRY.get(scenario.name)
        if existing is not None and existing != scenario and not replace:
            raise ValueError(
                'scenario %r is already registered with a different '
                'definition (pass replace=True to override)'
                % scenario.name)
        _REGISTRY[scenario.name] = scenario
    return scenario


def get_scenario(name: str) -> Scenario:
    with _lock:
        scenario = _REGISTRY.get(name)
    if scenario is None:
        raise UnknownScenario(
            'unknown scenario %r (registered: %s) — profiles name '
            'scenarios by string; register it before replaying'
            % (name, sorted(_REGISTRY)))
    return scenario


def scenario_names() -> Tuple[str, ...]:
    with _lock:
        return tuple(sorted(_REGISTRY))


# ---- built-in scenarios (WORKLOADS.md "Scenario registry") ----
#: single-language method naming over the micro-batched predict path
JAVA_NAMING = register_scenario(Scenario(
    'java_naming', languages=('java',), kind='predict', tier='topk',
    description='Java method naming (softmax top-k).'))
CSHARP_NAMING = register_scenario(Scenario(
    'csharp_naming', languages=('csharp',), kind='predict', tier='topk',
    description='C# method naming (softmax top-k).'))
#: the mixed-language softmax-only arm the retrieval blend A/Bs against
SOFTMAX_NAMING = register_scenario(Scenario(
    'softmax_naming', languages=('java', 'csharp'), kind='predict',
    tier='topk',
    description='Mixed-language naming, softmax head only (the '
                'retrieval A/B baseline).'))
#: retrieval-augmented naming: softmax distribution blended with top-k
#: neighbor labels from the attached index (mesh.submit_blended)
RETRIEVAL_NAMING = register_scenario(Scenario(
    'retrieval_naming', languages=('java', 'csharp'), kind='blend',
    description='Mixed-language naming with the softmax distribution '
                'blended against attached-index neighbor labels.'))
#: raw nearest-method search over the index (code-search entry path)
NEIGHBOR_SEARCH = register_scenario(Scenario(
    'neighbor_search', languages=('java',), kind='neighbors',
    description='Nearest-method search via the vectors tier + '
                'attached index.'))
