"""Durable traffic profiles: record, read, write, synthesize
(WORKLOADS.md "Traffic profile format").

A profile is JSONL: one header line
(``{"workload_profile": 1, ...}``) then one record per request:

- ``t``        — RELATIVE timestamp (seconds since the profile's
  first request; the replayer re-paces from these, so a profile
  recorded over an hour replays at any rate scale);
- ``scenario`` — registry name (scenario.py) the request belongs to;
- ``language`` — 'java' / 'csharp' / None when unknown;
- ``lines``    — prediction-ready canonical context lines, OR
  ``vector`` — a raw code-vector ref (neighbor queries submitted as
  ndarrays record their query vector instead of source contexts);
- ``label``    — the recorded ground-truth method name ('get|square'
  form) when known: the replayer scores exact-match/F1 against it;
- ``k`` / ``weight`` — neighbors-per-query and blend weight when the
  scenario's entry point takes them.

``ProfileRecorder`` is the mesh-admission tap
(``ServingMesh.record_traffic``): thread-safe, bounded, and cheap
enough to leave on — it stores plain strings and floats, never model
objects.  ``build_synthetic_profile`` drives the corpus generators
(scripts/gen_java_corpus.py + gen_csharp_corpus.py) through the
path-context extractor to synthesize a mixed Java+C# stream with
seeded exponential arrivals.
"""
from __future__ import annotations

import importlib.util
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from code2vec_tpu.telemetry import core as tele_core
from code2vec_tpu.telemetry.core import Counter

__all__ = ['PROFILE_VERSION', 'ProfileError', 'ProfileRecorder',
           'read_profile', 'write_profile', 'build_synthetic_profile']

PROFILE_VERSION = 1

#: record keys the reader accepts (anything else is a format error —
#: profiles are durable artifacts, so drift fails loud, not silent)
_RECORD_KEYS = frozenset(
    ('t', 'scenario', 'language', 'lines', 'vector', 'label', 'k',
     'weight', 'tier'))


class ProfileError(ValueError):
    """A traffic profile that does not parse as PROFILE_VERSION."""


def _validate_record(record: dict, where: str) -> dict:
    if not isinstance(record, dict):
        raise ProfileError('%s: record is not an object' % where)
    unknown = set(record) - _RECORD_KEYS
    if unknown:
        raise ProfileError('%s: unknown record keys %s'
                           % (where, sorted(unknown)))
    if not isinstance(record.get('scenario'), str):
        raise ProfileError('%s: record needs a scenario name' % where)
    if not isinstance(record.get('t'), (int, float)) \
            or record['t'] < 0:
        raise ProfileError('%s: record needs a relative timestamp '
                           't >= 0' % where)
    has_lines = isinstance(record.get('lines'), list)
    has_vector = isinstance(record.get('vector'), list)
    if not (has_lines or has_vector):
        raise ProfileError("%s: record needs 'lines' (context lines) "
                           "or 'vector' (code-vector ref)" % where)
    return record


def write_profile(path: str, records: Sequence[dict],
                  meta: Optional[dict] = None) -> None:
    """Write a profile atomically (tmp + rename): a replayer racing a
    recorder's save can never read a torn profile."""
    header = {'workload_profile': PROFILE_VERSION,
              'records': len(records)}
    if meta:
        header.update(meta)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        f.write(json.dumps(header) + '\n')
        for i, record in enumerate(records):
            _validate_record(record, 'record %d' % i)
            f.write(json.dumps(record, sort_keys=True) + '\n')
    os.replace(tmp, path)


def read_profile(path: str) -> Tuple[dict, List[dict]]:
    """(header, records); raises ``ProfileError`` on a non-profile or
    malformed file."""
    with open(path) as f:
        first = f.readline()
        try:
            header = json.loads(first)
        except ValueError:
            raise ProfileError('%s: header is not JSON' % path)
        if not isinstance(header, dict) \
                or header.get('workload_profile') != PROFILE_VERSION:
            raise ProfileError(
                '%s: not a workload_profile v%d header'
                % (path, PROFILE_VERSION))
        records = []
        for lineno, raw in enumerate(f, start=2):
            raw = raw.strip()
            if not raw:
                continue
            try:
                record = json.loads(raw)
            except ValueError:
                raise ProfileError('%s:%d: record is not JSON'
                                   % (path, lineno))
            records.append(_validate_record(
                record, '%s:%d' % (path, lineno)))
    return header, records


class ProfileRecorder:
    """Mesh-admission traffic tap (``ServingMesh.record_traffic``).

    Timestamps are RELATIVE to the first recorded request.  Bounded:
    past ``max_records`` new traffic is counted in ``dropped`` instead
    of growing the host without limit — recording is observability,
    not a durability contract.
    """

    # submit runs on caller threads; the tap must be as cheap and as
    # safe as the counters around it (lock-discipline rule,
    # ANALYSIS.md):
    # graftlint: guard ProfileRecorder._records,_t0,dropped by _lock
    def __init__(self, max_records: int = 100_000):
        self.max_records = max(1, int(max_records))
        self._lock = threading.Lock()
        self._records: List[dict] = []
        self._t0: Optional[float] = None
        self.dropped = 0
        self.recorded_total = Counter('workloads/recorded_total')

    def record(self, scenario: str, language: Optional[str] = None,
               lines: Optional[Sequence[str]] = None,
               vector=None, label: Optional[str] = None,
               tier: Optional[str] = None, k: Optional[int] = None,
               weight: Optional[float] = None) -> None:
        now = time.monotonic()
        record: dict = {'scenario': str(scenario)}
        if lines is not None:
            record['lines'] = [str(line) for line in lines]
        if vector is not None:
            # ndarray/array-like -> plain floats (json-durable ref)
            record['vector'] = [float(v) for v in
                                getattr(vector, 'ravel', lambda: vector)()]
        if language is not None:
            record['language'] = str(language)
        if label is not None:
            record['label'] = str(label)
        if tier is not None:
            record['tier'] = str(tier)
        if k is not None:
            record['k'] = int(k)
        if weight is not None:
            record['weight'] = float(weight)
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            record['t'] = now - self._t0
            if len(self._records) >= self.max_records:
                self.dropped += 1
                return
            self._records.append(record)
        self.recorded_total.inc()
        if tele_core.enabled():
            tele_core.registry().counter(
                'workloads/recorded_total').inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def records(self) -> List[dict]:
        """A snapshot copy (the tap keeps recording)."""
        with self._lock:
            return [dict(record) for record in self._records]

    def save(self, path: str, meta: Optional[dict] = None) -> int:
        records = self.records()
        header_meta = {'source': 'recorded'}
        if meta:
            header_meta.update(meta)
        write_profile(path, records, meta=header_meta)
        return len(records)


# ------------------------------------------------- synthetic builders
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_script(name: str):
    """Import a repo script (scripts/ is not a package) — the same
    importlib idiom scripts/gen_csharp_corpus.py uses to reuse the
    Java generator."""
    path = os.path.join(_REPO_ROOT, 'scripts', name + '.py')
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _gen_sources(language: str, classes: int, seed: int,
                 out_dir: str, methods_per_class=(2, 3)) -> List[str]:
    """Generate ``classes`` synthetic source files for one language;
    returns the file paths (deterministic under seed)."""
    gjc = _load_script('gen_java_corpus')
    rng = random.Random(seed)
    noun_pairs = ([(a, n) for a in gjc.ADJS for n in gjc.NOUNS]
                  + [(n1, n2) for n1 in gjc.NOUNS for n2 in gjc.NOUNS
                     if n1 != n2])
    rng.shuffle(noun_pairs)
    paths = []
    os.makedirs(out_dir, exist_ok=True)
    for i in range(classes):
        name = 'W%05d' % i
        if language == 'csharp':
            gcs = _load_script('gen_csharp_corpus')
            src = gcs.gen_csharp_class(rng, name, noun_pairs,
                                       methods_per_class)
            path = os.path.join(out_dir, name + '.cs')
        else:
            src = gjc.gen_class(rng, name, noun_pairs,
                                methods_per_class)
            path = os.path.join(out_dir, name + '.java')
        with open(path, 'w') as f:
            f.write(src)
        paths.append(path)
    return paths


def build_synthetic_profile(
        config, workdir: str,
        classes_per_language: int = 3, seed: int = 7,
        rate_rps: float = 50.0,
        scenario_by_language: Optional[Dict[str, str]] = None,
        extractor_command: Optional[List[str]] = None,
        methods_per_class=(2, 3)) -> List[dict]:
    """Synthesize a MIXED Java+C# traffic stream: corpus-generator
    classes -> path-context extraction -> one profile record per
    method, interleaved under seeded exponential inter-arrivals.

    Deterministic under (seed, classes_per_language): the same inputs
    produce byte-identical records.  Needs the extractor binary
    (extractor/build/c2v-extract) — raises its RuntimeError when
    absent, so callers surface the gap instead of replaying an empty
    stream.
    """
    from code2vec_tpu.serving.extractor_bridge import Extractor
    scenario_by_language = dict(scenario_by_language or {
        'java': 'java_naming', 'csharp': 'csharp_naming'})
    extractor = Extractor(config, extractor_command=extractor_command)
    entries: List[dict] = []
    for language in sorted(scenario_by_language):
        paths = _gen_sources(
            language, classes_per_language, seed,
            os.path.join(workdir, language),
            methods_per_class=methods_per_class)
        for path in paths:
            try:
                lines, _hashes = extractor.extract_paths(path)
            except ValueError:
                continue  # a class whose members all failed to parse
            for line in lines:
                label = line.split(' ', 1)[0]
                entries.append({
                    'scenario': scenario_by_language[language],
                    'language': language,
                    'lines': [line],
                    'label': label,
                })
    # interleave deterministically, then pace with exponential
    # inter-arrivals — an open-loop Poisson-ish stream at rate_rps
    rng = random.Random(seed)
    rng.shuffle(entries)
    t = 0.0
    for entry in entries:
        entry['t'] = round(t, 6)
        t += rng.expovariate(rate_rps) if rate_rps > 0 else 0.0
    return entries
