"""The metric catalog: every metric name this codebase may emit, with
type, unit, and help text.

Single source of truth, three consumers:

- ``scripts/check_metrics_schema.py`` lints every emission site (telemetry
  instruments AND ``MetricsWriter.scalar`` tags) against this table, so a
  typo'd or renamed metric fails tier-1 instead of silently forking the
  time series;
- the Prometheus exporter derives the ``# HELP`` / ``# TYPE`` header from
  it;
- ``OBSERVABILITY.md`` documents it (keep in sync — the lint checks the
  doc mentions every name).

Naming: ``<subsystem>/<metric>[_<unit>]``.  Units in names: ``_ms``
(milliseconds), ``_s`` (seconds), ``_total`` (monotonic counts),
``_per_sec`` (rates).  Prometheus names are derived as
``code2vec_<name with / -> _>``.

**Instance labels.** A metric emitted by one of N coexisting instances
(serving-mesh replicas) carries a label suffix: ``serving/shed_total
{replica=r1}`` (``labeled`` / ``label_suffix`` build it;
``core.ScopedRegistry`` applies it transparently at the emission site).
The CATALOG keys stay label-free — ``base_name`` strips the suffix, and
the schema lint, the Prometheus exporter, and OBSERVABILITY.md all
resolve a labeled series to its base entry (Prometheus renders the
label natively: ``code2vec_serving_shed_total{replica="r1"}``).
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

COUNTER = 'counter'
GAUGE = 'gauge'
TIMER = 'timer'
SCALAR = 'scalar'   # MetricsWriter.scalar tags (per-step JSONL series)


def _m(mtype: str, unit: str, help_text: str) -> Dict[str, str]:
    return {'type': mtype, 'unit': unit, 'help': help_text}


CATALOG: Dict[str, Dict[str, str]] = {
    # ---- step-phase breakdown (trainer hot loop) ----
    'step/batch_wait_ms': _m(TIMER, 'ms', 'Host wait for the next staged '
                             'batch (input pipeline starvation).'),
    'step/h2d_ms': _m(TIMER, 'ms', 'Dispatch of the async host->device '
                      'placement of one batch (staging ring).'),
    'step/dispatch_ms': _m(TIMER, 'ms', 'Enqueue of the jitted train step '
                           '(async; device time only on blocking backends).'),
    'step/sync_ms': _m(TIMER, 'ms', 'Blocking device->host sync at the log '
                       'window (drains the dispatched window).'),
    'step/total_ms': _m(TIMER, 'ms', 'Full hot-loop iteration (wait + '
                        'dispatch + callbacks).'),
    'step/pack_ms': _m(TIMER, 'ms', 'Host-side packing of one batch into '
                       'the packed wire format (reader/cache thread).'),
    # ---- throughput ----
    'train/steps_total': _m(COUNTER, 'steps', 'Train steps dispatched.'),
    'train/examples_total': _m(COUNTER, 'examples', 'Valid (weight>0) '
                               'examples consumed by train steps.'),
    'train/contexts_total': _m(COUNTER, 'contexts', 'Valid path-contexts '
                               'consumed by train steps.'),
    'train/examples_per_sec': _m(GAUGE, 'examples/s', 'Windowed training '
                                 'throughput (since last telemetry flush).'),
    'train/contexts_per_sec': _m(GAUGE, 'contexts/s', 'Windowed context '
                                 'throughput (since last telemetry flush).'),
    'train/epoch_wall_time_s': _m(GAUGE, 's', 'Wall time of the last '
                                  "epoch's training loop (includes interval "
                                  'evals; excludes epoch-end eval/save).'),
    # ---- MFU / roofline (telemetry/goodput.py) ----
    'train/mfu': _m(GAUGE, 'fraction', 'Model FLOP utilization of the last '
                    'flush window: executed train-step FLOPs (AOT '
                    'cost_analysis) / (train seconds x DEVICE_PEAK_FLOPS x '
                    'mesh devices).'),
    'train/arithmetic_intensity': _m(GAUGE, 'flops/byte', 'FLOPs per byte '
                                     'accessed of the current train-step '
                                     'program (lowered-module estimate — '
                                     'the roofline x-axis).'),
    'train/step_flops': _m(GAUGE, 'flops', 'Logical FLOPs of one train '
                           'step at the current dispatch shape (AOT '
                           'cost_analysis, pre-partitioning).'),
    'train/step_bytes': _m(GAUGE, 'bytes', 'Bytes accessed by one train '
                           'step at the current dispatch shape '
                           '(lowered-module estimate).'),
    # ---- staging ring ----
    'staging/ring_occupancy': _m(GAUGE, 'batches', 'Batches currently held '
                                 'in the device staging ring.'),
    'staging/ring_depth': _m(GAUGE, 'batches', 'Configured staging-ring '
                             'depth (DEVICE_PREFETCH_BATCHES, after the '
                             'platform clamp).'),
    # ---- jit compilation ----
    'jit/compiles_total': _m(COUNTER, 'compiles', 'XLA backend compiles in '
                             'this process (jax.monitoring).'),
    'jit/compile_ms': _m(TIMER, 'ms', 'XLA backend compile durations.'),
    'jit/respecializations_total': _m(COUNTER, 'compiles', 'Packed-capacity '
                                      're-specializations of the step '
                                      'program observed by the trainer.'),
    'jit/packed_capacity': _m(GAUGE, 'slots', 'Current packed-wire context '
                              'capacity bucket feeding the step.'),
    # ---- input pipeline ----
    'input/cache_hit_total': _m(COUNTER, 'caches', 'Token-cache opens that '
                                'found a fresh on-disk cache.'),
    'input/cache_miss_total': _m(COUNTER, 'caches', 'Token-cache opens that '
                                 'had to (re)build the cache.'),
    'input/batches_total': _m(COUNTER, 'batches', 'Batches emitted by the '
                              'host input pipeline.'),
    'input/packed_fill_rate': _m(GAUGE, 'fraction', 'Retained context slots '
                                 '/ packed wire capacity of the last packed '
                                 'batch (padding waste = 1 - this).'),
    # ---- serving engine (code2vec_tpu/serving/, SERVING.md) ----
    'serving/requests_total': _m(COUNTER, 'requests', 'Prediction requests '
                                 'submitted to the serving engine.'),
    'serving/batches_total': _m(COUNTER, 'batches', 'Coalesced '
                                'micro-batches dispatched to the device.'),
    'serving/queue_depth': _m(GAUGE, 'requests', 'Requests waiting in the '
                              'micro-batcher queue.'),
    'serving/batch_fill_rate': _m(GAUGE, 'fraction', 'Valid rows / bucket '
                                  'size of the last dispatched '
                                  'micro-batch.'),
    'serving/latency_ms': _m(TIMER, 'ms', 'Request latency: submit -> '
                             'decoded results (windowed percentiles).'),
    'serving/dispatch_ms': _m(TIMER, 'ms', 'Coalesce + pack + place + '
                              'async device dispatch of one '
                              'micro-batch.'),
    'serving/decode_ms': _m(TIMER, 'ms', 'Host-side device fetch + '
                            'top-k/attention decode of one micro-batch '
                            '(worker pool).'),
    'serving/warmup_s': _m(GAUGE, 's', 'Wall time of the eager '
                           'bucket-ladder compile at engine load.'),
    'serving/programs_warm': _m(GAUGE, 'programs', 'Pre-compiled (bucket '
                                'x capacity x tier) programs resident '
                                'after warmup.'),
    'serving/bulk_examples_per_sec': _m(GAUGE, 'examples/s', 'Streaming '
                                        'bulk predict / embedding-export '
                                        'throughput.'),
    # ---- serving resilience (admission control / rollover / breaker) ----
    'serving/shed_total': _m(COUNTER, 'requests', 'Requests rejected at '
                             'admission (queue bound, drain-estimate vs '
                             'deadline, or a reject_all drill).'),
    'serving/expired_total': _m(COUNTER, 'requests', 'Admitted requests '
                                'expired past their SLO deadline while '
                                'queued (never dispatched).'),
    'serving/degraded_total': _m(COUNTER, 'requests', 'Requests admitted '
                                 'at a downgraded output tier by the '
                                 'overload degradation ladder.'),
    'serving/overload_level': _m(GAUGE, 'level', 'Degradation ladder '
                                 'state: 0 normal, 1 full->attention, '
                                 '2 everything->topk.'),
    'serving/queue_peak_rows': _m(GAUGE, 'rows', 'High-water mark of '
                                  'admitted rows queued (vs the '
                                  'admission bound).'),
    'serving/rollover_total': _m(COUNTER, 'rollovers', 'Live checkpoint '
                                 'rollovers swapped in (canary passed '
                                 'or canary disabled).'),
    'serving/rollover_rollbacks_total': _m(COUNTER, 'rollovers',
                                           'Canaried rollovers rolled '
                                           'back (agreement below the '
                                           'floor).'),
    'serving/rollover_agreement': _m(GAUGE, 'fraction', 'Top-1 agreement '
                                     '(candidate vs serving params) '
                                     'measured by the last canary.'),
    'serving/breaker_state': _m(GAUGE, 'state', 'Extractor circuit '
                                'breaker: 0 closed, 1 half-open, '
                                '2 open.'),
    'serving/breaker_open_total': _m(COUNTER, 'trips', 'Extractor '
                                     'circuit-breaker open transitions.'),
    'serving/extractor_retries_total': _m(COUNTER, 'retries', 'Extractor '
                                          'pool calls retried after a '
                                          'crash-class failure.'),
    # ---- serving mesh (code2vec_tpu/serving/mesh.py, SERVING.md) ----
    'mesh/requests_total': _m(COUNTER, 'requests', 'Requests submitted '
                              'to the serving mesh front queue.'),
    'mesh/queue_depth': _m(GAUGE, 'requests', 'Requests waiting in the '
                           'shared mesh front queue (all tiers).'),
    'mesh/queue_rows': _m(GAUGE, 'rows', 'Rows admitted to the shared '
                          'front queue (the admission-bound basis).'),
    'mesh/shed_total': _m(COUNTER, 'requests', 'Requests shed at mesh '
                          'admission (all reasons).'),
    'mesh/shed_bound_total': _m(COUNTER, 'requests', 'Mesh sheds caused '
                                'by the shared queue bound.'),
    'mesh/shed_deadline_total': _m(COUNTER, 'requests', 'Mesh sheds '
                                   'caused by the fleet drain estimate '
                                   'exceeding the request deadline.'),
    'mesh/expired_total': _m(COUNTER, 'requests', 'Admitted mesh '
                             'requests expired past their SLO deadline '
                             'in the shared queue (never dispatched).'),
    'mesh/degraded_total': _m(COUNTER, 'requests', 'Mesh requests '
                              'admitted at a downgraded tier by the '
                              'shared-queue overload ladder.'),
    'mesh/replicas': _m(GAUGE, 'replicas', 'Replicas registered in the '
                        'mesh replica table.'),
    'mesh/replicas_serving': _m(GAUGE, 'replicas', 'Replicas currently '
                                'weighted INTO dispatch (not breaker-'
                                'open, not retired, not closed).'),
    'mesh/dispatch_share': _m(GAUGE, 'fraction', 'Per-replica share of '
                              'all rows the mesh has dispatched '
                              '(replica-labeled series).'),
    'mesh/replica_breaker_open_total': _m(COUNTER, 'trips', 'Replica '
                                          'dispatch-breaker open '
                                          'transitions (consecutive '
                                          'dispatch failures).'),
    'mesh/rollover_total': _m(COUNTER, 'rollovers', 'Coordinated fleet '
                              'rollovers: canary passed on one replica, '
                              'every replica swapped.'),
    'mesh/rollover_rollbacks_total': _m(COUNTER, 'rollovers',
                                        'Coordinated rollovers rolled '
                                        'back by the canary replica '
                                        '(fleet kept the old params).'),
    'mesh/replicas_live': _m(GAUGE, 'replicas', 'Replicas currently '
                             'LIVE by the heartbeat verdict (not dead, '
                             'not retired) — distinct from dispatch '
                             'health: a breaker-open replica still '
                             'counts, a hung one does not.'),
    'mesh/restarts_total': _m(COUNTER, 'restarts', 'Supervised worker '
                              'restarts that rejoined the fleet '
                              '(re-adopted onto the current params '
                              'step before pulling).'),
    'mesh/redispatched_total': _m(COUNTER, 'requests', 'Requests '
                                  're-admitted at the queue FRONT '
                                  'after their batch died with its '
                                  'worker (once per request; a second '
                                  'crash fails typed).'),
    'mesh/heartbeat_misses_total': _m(COUNTER, 'intervals', 'Heartbeat '
                                      'intervals worker replicas were '
                                      'observed past due (budget '
                                      'MESH_HEARTBEAT_MISSES marks the '
                                      'replica dead).'),
    'mesh/clock_offset_ms': _m(GAUGE, 'ms', 'Estimated monotonic-clock '
                               'offset of one worker incarnation vs '
                               'the mesh (replica-labeled; min-filter '
                               'over heartbeat samples — remote span '
                               'stamps shift by this at stitching).'),
    'mesh/worker_snapshots_total': _m(COUNTER, 'snapshots', 'Worker '
                                      'telemetry/ledger snapshots '
                                      'merged replica-labeled into the '
                                      'fleet registry off heartbeats.'),
    # ---- elastic fleet (mesh placement / adoption / autoscaler) ----
    'mesh/retired_total': _m(COUNTER, 'replicas', 'Replicas permanently '
                             'retired from the fleet, plus a '
                             'reason-labeled series: {reason=drain|'
                             'autoscale|restart_budget|adopted_worker_'
                             'exit} — a post-mortem can tell a planned '
                             'drain from a budget exhaustion from an '
                             'orchestrator-owned worker exiting.'),
    'mesh/adopted_total': _m(COUNTER, 'workers', 'Externally-spawned '
                             'workers ADOPTED into the fleet off an '
                             'unclaimed dial-in (capability handshake '
                             'passed, re-adopted onto the fleet params '
                             'step; restart supervision stays with '
                             'their orchestrator).'),
    'mesh/adoption_rejected_total': _m(COUNTER, 'workers', 'Adoption '
                                       'dial-ins rejected after the '
                                       'hello: duplicate rid, ready '
                                       'timeout, or capability '
                                       'mismatch (tiers/wire); the '
                                       'worker gets a typed '
                                       'adopt_rejected frame.'),
    'autoscale/replicas_target': _m(GAUGE, 'replicas', 'Fleet size the '
                                    'SLO-driven autoscaler currently '
                                    'wants (clamped to AUTOSCALE_MIN/'
                                    'MAX_REPLICAS).'),
    'autoscale/scale_up_total': _m(COUNTER, 'transitions', 'Autoscaler '
                                   'scale-up transitions that seated a '
                                   'new replica (queue drain estimate '
                                   'over AUTOSCALE_UP_QUEUE_SECS, or '
                                   'SLO burn over AUTOSCALE_UP_BURN).'),
    'autoscale/scale_up_failed_total': _m(COUNTER, 'transitions',
                                          'Scale-up attempts whose '
                                          'spawn/seat failed (counted, '
                                          'not fatal; the up-cooldown '
                                          'applies before the retry).'),
    'autoscale/scale_down_total': _m(COUNTER, 'transitions',
                                     'Autoscaler scale-down '
                                     'transitions: newest eligible '
                                     'replica drained and retired '
                                     '{reason=autoscale} after the '
                                     'sustained-idle window.'),
    'autoscale/flap_freezes_total': _m(COUNTER, 'freezes', 'Flap-guard '
                                       'trips: too many direction '
                                       'reversals inside '
                                       'AUTOSCALE_FLAP_WINDOW_SECS — '
                                       'all scaling frozen for one '
                                       'window instead of thrashing '
                                       'warm compile ladders.'),
    # ---- memoization tier (code2vec_tpu/serving/memo.py, SERVING.md) ----
    'memo/hits_total': _m(COUNTER, 'requests', 'Requests served from '
                          'the exact memo tier at mesh admission (zero '
                          'device-seconds, no queue slot). Scenario-'
                          'labeled mirrors (memo/hits_total{scenario=s})'
                          ' give per-workload hit rates (WORKLOADS.md).'),
    'memo/misses_total': _m(COUNTER, 'requests', 'Memo lookups that '
                            'missed and went to the live serving '
                            'path. Scenario-labeled mirrors as for '
                            'memo/hits_total.'),
    'memo/inserts_total': _m(COUNTER, 'results', 'Delivered-good '
                             'results inserted into the exact memo '
                             'tier.'),
    'memo/evictions_total': _m(COUNTER, 'entries', 'LRU entries evicted '
                               'under the MEMO_CACHE_BYTES budget '
                               '(generation bumps invalidate without '
                               'counting here).'),
    'memo/bytes': _m(GAUGE, 'bytes', 'Host bytes held by cached memo '
                     'results (exact + semantic tiers; mirrors the '
                     'ledger memo bucket).'),
    'memo/entries': _m(GAUGE, 'entries', 'Entries resident in the exact '
                       'memo tier.'),
    'memo/semantic_hits_total': _m(COUNTER, 'requests', 'Neighbor '
                                   'queries served by the semantic '
                                   'tier from a within-epsilon cached '
                                   'query.'),
    'memo/semantic_agreement': _m(GAUGE, 'fraction', 'Running top-1 '
                                  'agreement of shadow-sampled '
                                  'semantic hits vs their live '
                                  'results — the epsilon-'
                                  'aggressiveness dial (SERVING.md '
                                  'rollout runbook).'),
    # ---- embedding index (code2vec_tpu/index/, INDEX.md) ----
    'index/build_s': _m(GAUGE, 's', 'Wall time of the last store / IVF '
                        'build.'),
    'index/vectors_total': _m(GAUGE, 'vectors', 'Vectors resident in the '
                              'loaded index store.'),
    'index/shard_rows': _m(GAUGE, 'rows', 'Store rows per mesh data '
                           'shard after padding (device-resident exact '
                           'tier).'),
    'index/warmup_s': _m(GAUGE, 's', 'Wall time of the eager '
                         'query-bucket ladder compile at index load.'),
    'index/queries_total': _m(COUNTER, 'queries', 'Neighbor queries '
                              'answered by the index.'),
    'index/query_latency_ms': _m(TIMER, 'ms', 'Index search latency per '
                                 'query batch (dispatch + fetch + '
                                 'merge).'),
    'index/queries_per_sec': _m(GAUGE, 'queries/s', 'Streaming batch '
                                'neighbor-query throughput '
                                '(--query-neighbors).'),
    'index/probe_fanout': _m(GAUGE, 'candidates', 'Mean candidate rows '
                             'scanned per query by the IVF probe '
                             '(nprobe lists, pre-padding).'),
    'index/recall_at10': _m(GAUGE, 'fraction', 'Measured IVF recall@10 '
                            'vs the exact tier on a held-out query '
                            'sample.'),
    'index/segments': _m(GAUGE, 'segments', 'Uncompacted append '
                         'segments live in the quantized tier.'),
    'index/append_rows': _m(GAUGE, 'rows', 'Inserted vectors queryable '
                            'from the append buffer, not yet folded '
                            'into the base lists.'),
    'index/inserts_total': _m(COUNTER, 'vectors', 'Vectors inserted '
                              'live into the quantized tier since '
                              'load.'),
    'index/compactions_total': _m(COUNTER, 'compactions', 'Append-'
                                  'segment compactions folded into the '
                                  'base CSR (no k-means rebuild).'),
    'index/compact_s': _m(GAUGE, 's', 'Wall time of the last '
                          'compaction (lock held: inserts/searches '
                          'block for this long).'),
    'index/rollover_agreement': _m(GAUGE, 'fraction', 'Running top-k '
                                   'id agreement of the candidate '
                                   'index vs live results during a '
                                   'canaried index rollover.'),
    'index/rollovers_total': _m(COUNTER, 'rollovers', 'Index rollovers '
                                'that concluded with a swap (new index '
                                'version; memo neighbor entries '
                                'invalidated).'),
    'index/rollover_rollbacks_total': _m(COUNTER, 'rollbacks',
                                         'Index rollovers rolled back '
                                         'below the agreement floor or '
                                         'on candidate error.'),
    # ---- training goodput plane (telemetry/goodput.py) ----
    'goodput/productive_s': _m(GAUGE, 's', 'Cumulative wall seconds of '
                               'productive train-step time this run '
                               '(fit wall minus typed badput).'),
    'goodput/badput_s': _m(GAUGE, 's', 'Cumulative badput seconds, '
                           'kind-labeled: {kind=compile|input_wait|'
                           'checkpoint|eval|rewind|rewind_replay|preempt|'
                           'warmup}.'),
    'goodput/fraction': _m(GAUGE, 'fraction', 'Goodput: productive '
                           'seconds / fit wall seconds so far (the '
                           'primary training fleet metric).'),
    'goodput/anomalies_total': _m(COUNTER, 'anomalies', 'Step-time anomaly '
                                  'watchdog fires: sustained regression '
                                  'past GOODPUT_ANOMALY_SIGMA robust '
                                  'deviations of the dispatch shape\'s '
                                  'rolling median (dumps '
                                  'flight_step_anomaly.jsonl).'),
    'goodput/autocaptures_total': _m(COUNTER, 'captures', 'Anomaly-'
                                     'triggered profiler captures armed '
                                     '(rate-limited to one per '
                                     'GOODPUT_AUTOCAPTURE_COOLDOWN_SECS).'),
    # ---- profiler capture ----
    'trace/captures_total': _m(COUNTER, 'captures', 'On-demand jax.profiler '
                               'trace captures completed.'),
    # ---- per-request serving traces (telemetry/tracing.py) ----
    'tracing/traces_total': _m(COUNTER, 'traces', 'Per-request serving '
                               'traces completed (sampled or not).'),
    'tracing/retained_total': _m(COUNTER, 'traces', 'Traces written to '
                                 'the span log: head-sampled, or '
                                 'tail-retained (shed/expired/degraded/'
                                 'split/closed/slow).'),
    'tracing/flight_dumps_total': _m(COUNTER, 'dumps', 'Flight-recorder '
                                     'ring dumps (flight_<event>.jsonl, '
                                     'replica-namespaced '
                                     'flight_<event>_r<N>.jsonl in '
                                     'worker processes: overload burst, '
                                     'canary rollback, breaker open, '
                                     'SLO burn, close).'),
    'tracing/adopted_spans_total': _m(COUNTER, 'spans', 'Remote worker '
                                      'span records grafted into live '
                                      'parent traces by adopt_spans '
                                      '(cross-process stitching).'),
    'tracing/remote_spans_dropped_total': _m(COUNTER, 'spans', 'Remote '
                                             'span records that could '
                                             'not be stitched: their '
                                             'dispatch was no longer '
                                             'pending or the trace had '
                                             'already finished.'),
    # ---- SLO burn-rate monitor (serving/slo.py, SERVING.md) ----
    'slo/availability_burn_fast': _m(GAUGE, 'ratio', 'Availability '
                                     'error-budget burn rate over the '
                                     'fast window (1.0 = burning '
                                     'exactly the budget).'),
    'slo/availability_burn_slow': _m(GAUGE, 'ratio', 'Availability '
                                     'error-budget burn rate over the '
                                     'slow window.'),
    'slo/p99_burn_fast': _m(GAUGE, 'ratio', 'p99-latency error-budget '
                            'burn rate over the fast window (share of '
                            'requests slower than SERVING_SLO_P99_MS '
                            'vs the 1% budget).'),
    'slo/p99_burn_slow': _m(GAUGE, 'ratio', 'p99-latency error-budget '
                            'burn rate over the slow window.'),
    'slo/good_total': _m(COUNTER, 'requests', 'Requests counted good '
                         'by the SLO monitor (delivered, within the '
                         'latency target when one is set). Scenario-'
                         'labeled mirrors (slo/good_total{scenario=s}) '
                         'attribute budget burn per workload '
                         '(WORKLOADS.md).'),
    'slo/bad_total': _m(COUNTER, 'requests', 'Requests counted against '
                        'the availability budget (shed, expired, '
                        'failed). Scenario-labeled mirrors as for '
                        'slo/good_total.'),
    'slo/slow_total': _m(COUNTER, 'requests', 'Delivered requests '
                         'slower than SERVING_SLO_P99_MS (counted '
                         'against the latency budget). Scenario-'
                         'labeled mirrors as for slo/good_total.'),
    'slo/alerts_total': _m(COUNTER, 'alerts', 'SLO burn alerts fired '
                           '(both windows over '
                           'SERVING_SLO_BURN_THRESHOLD; dumps '
                           'flight_slo_burn.jsonl).'),
    # ---- scenario traffic plane (code2vec_tpu/workloads/, WORKLOADS.md) ----
    'workloads/recorded_total': _m(COUNTER, 'requests', 'Requests seen '
                                   'by the admission traffic tap '
                                   '(ProfileRecorder.record) for later '
                                   'durable save + replay.'),
    'workloads/replayed_total': _m(COUNTER, 'requests', 'Recorded '
                                   'requests re-submitted against a '
                                   'live mesh by the replay engine '
                                   '(workloads/replay.py).'),
    'mesh/blend_requests_total': _m(COUNTER, 'requests', 'Retrieval-'
                                    'augmented naming requests '
                                    '(ServingMesh.submit_blended): '
                                    'softmax top-k blended with '
                                    'neighbor-label votes at '
                                    'BLEND_NEIGHBOR_WEIGHT.'),
    'mesh/blend_fallback_total': _m(COUNTER, 'requests', 'Blend '
                                    'requests served as pure softmax '
                                    'because no index was attached '
                                    '(typed source=softmax_fallback '
                                    'degradation, not an error).'),
    # ---- device-memory ledger (telemetry/memory.py) ----
    'mem/params_bytes': _m(GAUGE, 'bytes', 'Ledger-attributed device '
                           'bytes held by model parameter sets (one '
                           'entry per set — a canary candidate is a '
                           'second entry).'),
    'mem/opt_state_bytes': _m(GAUGE, 'bytes', 'Ledger-attributed '
                              'optimizer-state (Adam moment) bytes.'),
    'mem/staging_bytes': _m(GAUGE, 'bytes', 'Bytes held by batches '
                            'resident in the device staging ring.'),
    'mem/index_bytes': _m(GAUGE, 'bytes', 'Bytes held by embedding-'
                          'index residents (exact store shards, IVF '
                          'rows + centroids).'),
    'mem/executables_bytes': _m(GAUGE, 'bytes', 'Measured footprint of '
                                'the warm serving compilation ladder '
                                '(code + temp, AOT memory_analysis; '
                                'excluded from array reconciliation).'),
    'mem/memo_bytes': _m(GAUGE, 'bytes', 'Host bytes held by the '
                         'serving memoization tier (bucket memo, '
                         'kind=host; excluded from array '
                         'reconciliation — nothing on a device).'),
    'mem/attributed_bytes': _m(GAUGE, 'bytes', 'Sum of all array-kind '
                               'ledger entries (the reconciliation '
                               'numerator).'),
    'mem/unattributed_bytes': _m(GAUGE, 'bytes', 'Backend live bytes '
                                 'minus attributed — the residual the '
                                 'reconciliation keeps honest.'),
    'mem/backend_live_bytes': _m(GAUGE, 'bytes', 'Backend-reported '
                                 'live device bytes (live_arrays '
                                 'logical basis; memory_stats rides '
                                 'in snapshots).'),
    'mem/watermark_bytes': _m(GAUGE, 'bytes', 'High-water mark of '
                              'attributed bytes since process start.'),
    'mem/budget_bytes': _m(GAUGE, 'bytes', 'Effective HBM_BUDGET_BYTES '
                           '(0 = unlimited).'),
    'mem/oom_dumps_total': _m(COUNTER, 'dumps', 'oom_ledger.json '
                              'forensic dumps written on '
                              'RESOURCE_EXHAUSTED or a budget-exceeded '
                              'refusal.'),
    'mem/snapshots_total': _m(COUNTER, 'snapshots', 'Ledger snapshots '
                              'written (MEM_NOW, --memory-report, '
                              'forensic dumps).'),
    # ---- resilience (code2vec_tpu/resilience/, ROBUSTNESS.md) ----
    'resilience/rewinds_total': _m(COUNTER, 'rewinds', 'Divergence-guard '
                                   'rewinds: non-finite loss windows that '
                                   'triggered a checkpoint restore.'),
    'resilience/faults_fired_total': _m(COUNTER, 'faults', 'Injected faults '
                                        'fired by the FAULT_INJECT plan '
                                        '(nonzero only in fault drills).'),
    'resilience/preempt_save_s': _m(GAUGE, 's', 'Duration of the final '
                                    'snapshot save after a preemption '
                                    'signal (SIGTERM/SIGINT).'),
    'watchdog/armed': _m(GAUGE, 'bool', 'Hang watchdog state: 1 while the '
                         'hot loop is inside a watched blocking wait.'),
    'watchdog/expired_total': _m(COUNTER, 'expiries', 'Watchdog deadline '
                                 'expiries (stack dump + hard abort; >0 '
                                 'at most once per process).'),
    # ---- MetricsWriter scalar tags (per-step JSONL series) ----
    'train/loss': _m(SCALAR, 'nats', 'Windowed average training loss.'),
    'eval/top1_acc': _m(SCALAR, 'fraction', 'Top-1 exact-match accuracy.'),
    'eval/subtoken_f1': _m(SCALAR, 'fraction', 'Subtoken F1.'),
    'eval/subtoken_precision': _m(SCALAR, 'fraction', 'Subtoken precision.'),
    'eval/subtoken_recall': _m(SCALAR, 'fraction', 'Subtoken recall.'),
    'eval/wall_time_s': _m(SCALAR, 's', 'Wall time of one full evaluation '
                           'pass.'),
}
# train/examples_per_sec and train/epoch_wall_time_s double as
# MetricsWriter scalar tags (model_api.train's on_log / on_epoch_time);
# the lint accepts either emission form for any cataloged name.


#: instance-label suffix: one {key=value} trailer on a catalog name
_LABEL_RE = re.compile(r'^(?P<base>[^{]+)\{(?P<key>\w+)=(?P<val>[^}]*)\}$')


def label_suffix(key: str, value: str) -> str:
    """The ``{key=value}`` trailer a labeled series appends to its
    catalog name (``core.ScopedRegistry`` applies it)."""
    return '{%s=%s}' % (key, value)


def labeled(name: str, key: str, value: str) -> str:
    """``('serving/shed_total', 'replica', 'r1')`` ->
    ``'serving/shed_total{replica=r1}'``."""
    return name + label_suffix(key, value)


def base_name(name: str) -> str:
    """Catalog key for a possibly-labeled metric name (the schema lint
    and the exporters resolve labeled series through this)."""
    match = _LABEL_RE.match(name)
    return match.group('base') if match else name


def split_label(name: str) -> Tuple[str, Optional[Tuple[str, str]]]:
    """``'m{replica=r1}'`` -> ``('m', ('replica', 'r1'))``;
    label-free names return ``(name, None)``."""
    match = _LABEL_RE.match(name)
    if match is None:
        return name, None
    return match.group('base'), (match.group('key'), match.group('val'))


def prometheus_name(name: str) -> str:
    """Catalog name -> Prometheus metric name (labels render as
    Prometheus labels: ``m{replica=r1}`` ->
    ``code2vec_m{replica="r1"}``)."""
    base, label = split_label(name)
    prom = 'code2vec_' + base.replace('/', '_').replace('.', '_')
    if label is not None:
        prom += '{%s="%s"}' % label
    return prom
