"""``StepTelemetry`` — the trainer-facing telemetry bundle.

Owns the hot-loop instruments (phase timers, throughput counters), the
exporters, the jit trackers, and the on-demand trace controller, so the
trainer's integration is: create one of these when ``Config.TELEMETRY``
is on, record into its attributes, call ``after_step``/``flush_now``.
With telemetry off the trainer holds ``None`` and every instrumented
site is a single ``is None`` check.
"""
from __future__ import annotations

import os
import time
from typing import Optional

from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry import goodput as goodput_lib
from code2vec_tpu.telemetry import memory as memory_lib
from code2vec_tpu.telemetry.exporters import (ConsoleExporter, JsonlExporter,
                                              PrometheusExporter)
from code2vec_tpu.telemetry.jit_tracker import (CapacityTracker,
                                                install_compile_listener)
from code2vec_tpu.telemetry.trace import TraceController


def telemetry_dir(config) -> str:
    """Where telemetry artifacts live: ``TELEMETRY_DIR`` if set, else next
    to the model artifacts (the ``summaries/`` convention of
    metrics_writer.maybe_create), else the CWD."""
    if getattr(config, 'TELEMETRY_DIR', None):
        return config.TELEMETRY_DIR
    if config.is_saving:
        return os.path.join(os.path.dirname(config.MODEL_SAVE_PATH),
                            'telemetry')
    if config.is_loading:
        return os.path.join(config.model_load_dir, 'telemetry')
    return 'telemetry'


class StepTelemetry:
    def __init__(self, config, log=None, process_index: int = 0):
        core.enable()
        install_compile_listener()
        self.log = log or (lambda msg: None)
        self.dir = telemetry_dir(config)
        # multi-host: each process exports its own files, like log.txt
        suffix = '' if process_index == 0 else '.proc%d' % process_index
        reg = core.registry()
        self.registry = reg
        self.batch_wait = reg.timer('step/batch_wait_ms')
        self.h2d = reg.timer('step/h2d_ms')
        self.dispatch = reg.timer('step/dispatch_ms')
        self.sync = reg.timer('step/sync_ms')
        self.step_total = reg.timer('step/total_ms')
        self.steps = reg.counter('train/steps_total')
        self.examples = reg.counter('train/examples_total')
        self.contexts = reg.counter('train/contexts_total')
        self.ring_occupancy = reg.gauge('staging/ring_occupancy')
        self.capacity = CapacityTracker(log=self.log)
        self.trace = TraceController(
            self.dir,
            trace_at_step=getattr(config, 'TELEMETRY_TRACE_AT_STEP', -1),
            num_steps=getattr(config, 'TELEMETRY_TRACE_NUM_STEPS', 5),
            log=self.log)
        # MEM_NOW touch-file ledger snapshots (telemetry/memory.py),
        # polled at the flush cadence like the exporters — and route
        # the ledger's forensic dumps next to the other artifacts
        self.memwatch = memory_lib.MemoryReportController(self.dir,
                                                          log=self.log)
        memory_lib.configure(dump_dir=self.dir)
        self.flush_every = max(1, getattr(config,
                                          'TELEMETRY_FLUSH_EVERY_STEPS', 50))
        # ---- training goodput plane (telemetry/goodput.py) ----
        self.goodput = goodput_lib.GoodputLedger(
            os.path.join(self.dir, 'intervals%s.jsonl' % suffix),
            log=self.log)
        try:
            import jax
            device_kind = jax.local_devices()[0].device_kind
            self._num_devices = jax.device_count()
        except Exception:  # jax-less construction (unit tests)
            device_kind = None
            self._num_devices = 1
        self.peak_flops = goodput_lib.resolve_peak_flops(
            getattr(config, 'DEVICE_PEAK_FLOPS', -1.0), device_kind)
        sigma = getattr(config, 'GOODPUT_ANOMALY_SIGMA', 6.0)
        cooldown = getattr(config, 'GOODPUT_AUTOCAPTURE_COOLDOWN_SECS',
                           600.0)
        self.anomaly = goodput_lib.StepAnomalyWatchdog(
            sigma, cooldown, dump_dir=self.dir,
            on_capture=self.trace.request,
            on_record=self.goodput.note_anomaly,
            suffix=suffix, log=self.log)
        self._window_excluded = 0.0
        self.exporters = [
            JsonlExporter(self.dir, filename='metrics%s.jsonl' % suffix),
            PrometheusExporter(self.dir, filename='metrics%s.prom' % suffix),
            ConsoleExporter(self.log, min_interval_s=getattr(
                config, 'TELEMETRY_CONSOLE_EVERY_SECS', 30.0)),
        ]
        # rate window state: rates are computed per flush interval
        self._window_t0 = time.monotonic()
        self._window_examples = 0
        self._window_contexts = 0

    # ------------------------------------------------------------ recording
    def count_batch(self, num_examples: int, num_contexts: int) -> None:
        self.steps.inc()
        self.examples.inc(num_examples)
        self.contexts.inc(num_contexts)
        self._window_examples += num_examples
        self._window_contexts += num_contexts

    def after_step(self, step: int) -> None:
        """Periodic work at the bottom of each hot-loop iteration: rate
        gauges + exporter flush, every ``flush_every`` steps."""
        if step % self.flush_every:
            return
        self.flush_now(step)

    def flush_now(self, step: int) -> None:
        now = time.monotonic()
        elapsed = max(now - self._window_t0, 1e-9)
        reg = self.registry
        # train/examples_per_sec measures TRAIN steps: subtract the
        # window's eval/checkpoint/rewind/preempt interval seconds (the
        # goodput ledger marks them) from the wall window, so a slow
        # eval no longer dilutes the exported throughput gauge
        excluded = self.goodput.rate_excluded_total()
        excluded_delta = min(max(excluded - self._window_excluded, 0.0),
                             elapsed - 1e-9)
        self._window_excluded = excluded
        train_elapsed = max(elapsed - excluded_delta, 1e-9)
        reg.gauge('train/examples_per_sec').set(
            self._window_examples / train_elapsed)
        reg.gauge('train/contexts_per_sec').set(
            self._window_contexts / train_elapsed)
        self._window_t0 = now
        self._window_examples = 0
        self._window_contexts = 0
        # goodput/* totals + the window's MFU off the harvested FLOPs
        self.goodput.export_gauges(reg)
        window = self.goodput.harvest_window()
        mfu_value = None
        if window['flops'] > 0:
            mfu_value = goodput_lib.mfu(window['flops'], train_elapsed,
                                        self.peak_flops, self._num_devices)
            reg.gauge('train/mfu').set(mfu_value)
            flops, byts = self.goodput.current_cost()
            reg.gauge('train/step_flops').set(flops)
            reg.gauge('train/step_bytes').set(byts)
            intensity = self.goodput.arithmetic_intensity()
            if intensity is not None:
                reg.gauge('train/arithmetic_intensity').set(intensity)
        if window['steps'] or window['productive_s'] > 0:
            self.goodput.write_window(step, window, train_elapsed, mfu_value)
        # refresh the mem/* gauges so every flush exports the current
        # ledger attribution alongside the phase timers
        memory_lib.ledger().export_gauges()
        for exporter in self.exporters:
            exporter.flush(reg, step)
        self.memwatch.poll(step)

    def resume(self) -> None:
        """Re-arm recording (fit entry) — the counterpart of shutdown()'s
        disable, so fit can be called repeatedly on one trainer."""
        core.enable()
        goodput_lib.activate(self.goodput)
        self.goodput.run_start()

    def shutdown(self, step: int) -> None:
        """Final flush + stop any live capture (fit teardown), then drop
        the process-global enable flag: a finished telemetry run must not
        leave later non-telemetry trainers/readers in this process paying
        the pipeline-recording cost into an unexported registry."""
        self.trace.shutdown()
        # final window BEFORE run_end so every window record sits inside
        # its run span (goodput_report.split_spans closes a span at the
        # run_end line; a trailing window would read as a crashed span)
        self.flush_now(step)
        self.goodput.run_end(step)
        goodput_lib.deactivate(self.goodput)
        core.disable()
