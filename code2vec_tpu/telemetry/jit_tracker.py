"""jit-compilation accounting.

Two complementary signals:

- ``install_compile_listener()`` hooks ``jax.monitoring`` (the duration
  stream every backend compile reports,
  ``/jax/core/compile/backend_compile_duration``) into the telemetry
  registry — compile COUNT and TIME, including compiles the trainer
  never sees (eval twins, checkpoint init, collective warmup).
- ``CapacityTracker`` watches the packed-wire context capacity feeding
  the step: every NEW capacity is one more specialization of the whole
  train-step program (data/packed.py buckets capacities precisely to
  bound these), so each first sight is counted AND logged with its
  bucket — the "silent jit re-specialization" PR 1 made possible and
  this PR makes visible.

The monitoring listener is installed once per process and kept — jax has
no unregister API stable across versions — but it forwards through
``core.enabled()``, so with telemetry off its cost is one bool read per
compile (compiles are seconds-scale; this is nothing).
"""
from __future__ import annotations

from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry import goodput

_LISTENER_INSTALLED = False

# Event-name suffixes across jax versions (0.4.x uses *_duration; older
# releases used *_time_sec).
_COMPILE_EVENT_SUFFIXES = ('backend_compile_duration',
                           'backend_compile_time_sec')


def _on_event_duration(name: str, secs: float, **_kwargs) -> None:
    if not core.enabled():
        return
    if name.endswith(_COMPILE_EVENT_SUFFIXES):
        reg = core.registry()
        reg.counter('jit/compiles_total').inc()
        reg.timer('jit/compile_ms').record(secs)
        # compile wall is badput: feed the active goodput ledger (a
        # single attribute read when no trainer has one armed)
        goodput.on_compile(secs)


def install_compile_listener() -> bool:
    """Idempotently register the jax.monitoring compile listener.
    Returns False when jax (or its monitoring API) is unavailable."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception:
        return False
    _LISTENER_INSTALLED = True
    return True


class CapacityTracker:
    """Counts and logs packed-capacity re-specializations of the step
    program.  One instance per trainer; single-threaded (hot loop only)."""

    def __init__(self, log=None):
        self._log = log
        self._seen = set()

    def observe(self, capacity: int, step: int) -> None:
        reg = core.registry()
        reg.gauge('jit/packed_capacity').set(capacity)
        if capacity in self._seen:
            return
        first = not self._seen
        self._seen.add(capacity)
        if not first:
            # the first capacity is the program's initial specialization,
            # already billed by the compile listener — only GROWTH beyond
            # it is a re-specialization
            reg.counter('jit/respecializations_total').inc()
        if self._log is not None:
            self._log('telemetry: packed-capacity %s at step %d '
                      '(bucket %d; %d seen) — new step-program '
                      'specialization'
                      % ('re-specialization' if not first else
                         'specialization', step, capacity, len(self._seen)))
