"""Unified telemetry layer: counters, gauges, windowed timers, exporters,
and the trainer's step-phase instrumentation.

The reference's only observability was a Keras TensorBoard callback
(config.py:42-43, keras_model.py:158-163); this package is the
MLPerf-style telemetry layer the north-star workloads need — continuous
throughput/latency accounting in the hot loop, not one-off bench scripts.

Layout:

- ``core``        — Counter / Gauge / Timer instruments + the process-global
                    thread-safe Registry.  Dependency-free (stdlib only).
- ``catalog``     — the metric catalog (names, units, help); the single
                    source of truth ``scripts/check_metrics_schema.py``
                    lints emission sites against.
- ``exporters``   — JSONL sink, rate-limited console line, Prometheus
                    textfile.
- ``jit_tracker`` — jax.monitoring compile listener + the packed-capacity
                    re-specialization tracker.
- ``trace``       — on-demand ``jax.profiler`` capture (config step or
                    touch-file trigger).
- ``stepwatch``   — ``StepTelemetry``, the trainer-facing bundle wiring
                    the above together.

Everything imports jax lazily (same policy as ``data/packed.py``) so the
core stays importable — and testable — without an accelerator stack.

Cost model: one process-global ``enabled()`` flag.  When off (the
default), instrumented call sites reduce to a single ``is None`` /
``enabled()`` check — no clocks are read, no instruments are touched, no
files are opened.
"""
from __future__ import annotations

from code2vec_tpu.telemetry.core import (Counter, Gauge, Registry, Timer,
                                         disable, enable, enabled, registry,
                                         reset)
from code2vec_tpu.telemetry.stepwatch import StepTelemetry

__all__ = ['Counter', 'Gauge', 'Registry', 'Timer', 'StepTelemetry',
           'disable', 'enable', 'enabled', 'registry', 'reset']
