"""Training goodput plane: badput ledger, MFU accounting, step-time
anomaly watchdog (OBSERVABILITY.md "Training goodput").

Goodput — the fraction of wall time spent on productive train steps —
is the fleet-level training metric (the Ads training-infrastructure
paper, PAPERS.md).  This module classifies every second of wall time
inside ``Trainer.fit`` into productive step time vs typed badput:

- ``compile``      XLA backend compiles (fed by the jit_tracker
                   monitoring listener through :func:`on_compile`);
- ``input_wait``   host blocked on the input pipeline beyond
                   ``INPUT_WAIT_THRESHOLD_S`` (the unavoidable
                   per-step poll under it is not badput);
- ``checkpoint``   snapshot saves (interval, epoch-end, preemption);
- ``eval``         in-training evaluation passes;
- ``rewind``       divergence-guard checkpoint restores;
- ``rewind_replay``the steps re-trained after a rewind to regain the
                   lost progress (real work, but work done twice);
- ``preempt``      the preemption-exit snapshot path;
- ``warmup``       the first hot-loop iteration's non-compile remainder
                   (tracing, staging fill, donation warmup).

Everything else a step pays (dispatch, device execute, the log-window
sync that drains real device work) is productive.  Totals are exported
as ``goodput/*`` gauges at the telemetry flush AND appended durably to
``intervals.jsonl`` (``intervals.procN.jsonl`` per extra process, the
metrics.jsonl convention) so a run's goodput is reconstructable
post-hoc by the jax-free ``scripts/goodput_report.py``.

**MFU.**  Per dispatch-shape train-step FLOPs/bytes come from the AOT
``Lowered.cost_analysis()`` (captured once per shape by the trainer —
analysis of the lowered module, no extra backend compile, so a
telemetry run still makes zero post-warmup compiles).  The lowered
module is pre-partitioning, so its flop count is the LOGICAL total:

    MFU = window_flops / (window_seconds * peak_flops_per_device
                          * mesh_devices)

``peak_flops_per_device`` resolves from ``Config.DEVICE_PEAK_FLOPS`` /
``--device-peak-flops``, the ``DEVICE_PEAK_FLOPS`` environment
variable, or :data:`KNOWN_DEVICE_PEAK_FLOPS` by device kind.

**Anomaly watchdog.**  :class:`StepAnomalyWatchdog` keeps a rolling
median/MAD of clean step seconds per dispatch shape; a sustained
regression past ``GOODPUT_ANOMALY_SIGMA`` robust deviations fires
``goodput/anomalies_total``, dumps ``flight_step_anomaly.jsonl``, and
— at most once per ``GOODPUT_AUTOCAPTURE_COOLDOWN_SECS`` — arms the
on-demand ``TraceController`` profiler capture, so "training got slow"
self-documents with a trace and zero operator action.

Dependency-free (stdlib only): the report script and tests import this
without jax.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

from code2vec_tpu.telemetry import core

# ---------------------------------------------------------------- taxonomy
KIND_COMPILE = 'compile'
KIND_INPUT_WAIT = 'input_wait'
KIND_CHECKPOINT = 'checkpoint'
KIND_EVAL = 'eval'
KIND_REWIND = 'rewind'
KIND_REWIND_REPLAY = 'rewind_replay'
KIND_PREEMPT = 'preempt'
KIND_WARMUP = 'warmup'

BADPUT_KINDS = (KIND_COMPILE, KIND_INPUT_WAIT, KIND_CHECKPOINT, KIND_EVAL,
                KIND_REWIND, KIND_REWIND_REPLAY, KIND_PREEMPT, KIND_WARMUP)

#: interval kinds excluded from the stepwatch throughput window
#: (train/examples_per_sec measures train steps, not eval/save wall):
RATE_EXCLUDED_KINDS = frozenset({KIND_CHECKPOINT, KIND_EVAL, KIND_REWIND,
                                 KIND_PREEMPT})

#: per-step input wait under this is the pipeline's steady poll cost,
#: not starvation — only the excess is badput
INPUT_WAIT_THRESHOLD_S = 0.005

#: flight-recorder dump the anomaly watchdog writes (telemetry dir,
#: process-suffixed like the other flight_<event>.jsonl dumps)
FLIGHT_DUMP_NAME = 'flight_step_anomaly'

#: per-chip dense peak FLOP/s by jax ``device_kind`` prefix (bf16/int8
#: mixes vary per generation; these are the dense bf16 figures the MFU
#: literature normalizes against).  The CPU row is a nominal figure so
#: smoke runs report a finite, comparable-across-runs MFU — absolute
#: CPU MFU is not meaningful.
KNOWN_DEVICE_PEAK_FLOPS: Dict[str, float] = {
    'TPU v2': 45e12,
    'TPU v3': 123e12,
    'TPU v4': 275e12,
    'TPU v5 lite': 197e12,
    'TPU v5e': 197e12,
    'TPU v5p': 459e12,
    'TPU v6 lite': 918e12,
    'TPU v6e': 918e12,
    'cpu': 50e9,
}

#: fallback when the device kind is unknown and no knob is set
DEFAULT_PEAK_FLOPS = 50e9

ENV_DEVICE_PEAK_FLOPS = 'DEVICE_PEAK_FLOPS'


def resolve_peak_flops(configured: float = -1.0,
                       device_kind: Optional[str] = None) -> float:
    """Per-device peak FLOP/s: ``Config.DEVICE_PEAK_FLOPS`` when set
    (> 0), else the ``DEVICE_PEAK_FLOPS`` environment variable (the
    TELEMETRY_TRACE_AT_STEP unset-field convention), else the
    known-device table by ``device_kind`` prefix match, else
    :data:`DEFAULT_PEAK_FLOPS`."""
    if configured and configured > 0:
        return float(configured)
    env = os.environ.get(ENV_DEVICE_PEAK_FLOPS)
    if env:
        try:
            value = float(env)
            if value > 0:
                return value
        except ValueError:
            pass
    if device_kind:
        kind = device_kind.lower()
        for known, peak in KNOWN_DEVICE_PEAK_FLOPS.items():
            if kind.startswith(known.lower()):
                return peak
    return DEFAULT_PEAK_FLOPS


def mfu(window_flops: float, window_seconds: float,
        peak_flops_per_device: float, num_devices: int = 1) -> float:
    """Model FLOP utilization of one window: logical FLOPs executed /
    (seconds * aggregate peak).  Pure math, unit-testable against
    hand-computed FLOPs."""
    denom = (max(window_seconds, 1e-9) * max(peak_flops_per_device, 1e-9)
             * max(num_devices, 1))
    return window_flops / denom


class GoodputLedger:
    """The badput ledger: typed wall-time accounting for one trainer.

    The hot loop reports iterations (:meth:`note_input_wait`,
    :meth:`step_done`); slow-path sites mark typed intervals
    (:meth:`interval`); the jit_tracker compile listener feeds
    :meth:`on_compile` — possibly from whatever thread jax compiles on,
    hence the lock.  Nested ``interval`` marks absorb into the
    outermost (model_api's eval funnel runs inside the trainer's eval
    callback wrap; the wall seconds must count once).
    """

    # hot-loop thread + the jax.monitoring compile-listener thread +
    # model_api callback marks (lock-discipline rule, ANALYSIS.md):
    # graftlint: guard GoodputLedger._badput_s,_productive_s,_rate_excluded_s,_accrued_s,_interval_depth,_interval_kind,_interval_t0,_compile_in_step,_replay_left,_steps,_first_step_done,_window_flops,_window_bytes,_window_steps,_harvested,_step_cost,_current_cost,_run_open,_t0 by _lock
    def __init__(self, path: Optional[str] = None, log=None,
                 input_wait_threshold_s: float = INPUT_WAIT_THRESHOLD_S,
                 clock: Callable[[], float] = time.monotonic):
        self._path = path
        self._log = log or (lambda msg: None)
        self._clock = clock
        self._threshold = input_wait_threshold_s
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._badput_s: Dict[str, float] = {k: 0.0 for k in BADPUT_KINDS}
        self._productive_s = 0.0
        self._rate_excluded_s = 0.0
        self._accrued_s = 0.0        # badput accrued inside the current
        self._interval_depth = 0     # hot-loop iteration (subtracted in
        self._interval_kind = None   # step_done so seconds count once)
        self._interval_t0 = 0.0
        self._compile_in_step = False
        self._replay_left = 0
        self._steps = 0
        self._first_step_done = False
        # MFU window state, harvested at each telemetry flush
        self._window_flops = 0.0
        self._window_bytes = 0.0
        self._window_steps = 0
        self._harvested: Dict[str, float] = {}
        self._step_cost: Dict[str, Tuple[float, float]] = {}
        self._current_cost: Tuple[float, float] = (0.0, 0.0)
        self._run_open = False

    # ------------------------------------------------------------- run span
    def run_start(self, step: int = 0) -> None:
        """Fit entry: open the wall-time span.  Repeated fits on one
        trainer keep accumulating (totals are per-ledger, spans per
        run record)."""
        with self._lock:
            if self._t0 is None:
                self._t0 = self._clock()
            self._run_open = True
        self._append({'kind': 'run_start', 'wall': time.time(),
                      'step': int(step)})

    def run_end(self, step: int = 0, reason: str = 'done') -> None:
        """Fit teardown: durable totals record (the report's primary
        source — windows/intervals reconstruct the same numbers when a
        crash loses this line).  Idempotent per run span: the preempt
        exit writes it with its reason, the fit-finally shutdown must
        not write a second."""
        with self._lock:
            if not self._run_open:
                return
            self._run_open = False
            wall = self._wall_locked()
            totals = dict(self._badput_s)
            productive = self._productive_s
            steps = self._steps
        self._append({'kind': 'run_end', 'wall': time.time(),
                      'step': int(step), 'reason': reason,
                      'wall_s': wall, 'productive_s': productive,
                      'steps': steps, 'badput_s': totals})

    def _wall_locked(self) -> float:
        return 0.0 if self._t0 is None else max(0.0,
                                                self._clock() - self._t0)

    # --------------------------------------------------------- hot loop
    def note_input_wait(self, seconds: float) -> None:
        """Top of a hot-loop iteration: host wait for the staged batch.
        Doubles as the iteration-start mark — badput accrued between
        iterations (epoch-end eval/save) is wall time OUTSIDE any
        iteration and must not be subtracted from one."""
        excess = max(0.0, seconds - self._threshold)
        with self._lock:
            self._accrued_s = 0.0
            self._compile_in_step = False
            if excess > 0.0:
                self._badput_s[KIND_INPUT_WAIT] += excess
                self._accrued_s += excess

    def on_compile(self, seconds: float) -> None:
        """A backend compile completed (jit_tracker's monitoring
        listener) — compile wall is badput, and the step it landed in
        is excluded from the anomaly baseline.  A compile that lands
        inside an open typed interval (the eval program compiling
        during an eval mark) is absorbed by that interval: its wall is
        already being accrued under the interval's kind, and billing it
        twice would push the badput sum past wall time."""
        with self._lock:
            self._compile_in_step = True
            if self._interval_depth > 0:
                return
            self._badput_s[KIND_COMPILE] += seconds
            self._accrued_s += seconds

    def step_done(self, step: int, seconds: float,
                  shape: Optional[str] = None) -> Tuple[float, bool]:
        """Bottom of a hot-loop iteration: classify its wall time.
        ``seconds`` minus the badput accrued inside the iteration is the
        clean step time — billed to warmup (first iteration), to
        rewind_replay (re-trained steps after a rewind), else counted
        productive.  Returns ``(clean_seconds, had_compile)`` so the
        caller can feed the anomaly watchdog with compile-free samples.
        """
        with self._lock:
            clean = max(0.0, seconds - self._accrued_s)
            self._accrued_s = 0.0
            had_compile = self._compile_in_step
            self._compile_in_step = False
            if not self._first_step_done:
                self._first_step_done = True
                self._badput_s[KIND_WARMUP] += clean
            elif self._replay_left > 0:
                self._replay_left -= 1
                self._badput_s[KIND_REWIND_REPLAY] += clean
            else:
                self._productive_s += clean
            self._steps += 1
            if shape is not None:
                self._current_cost = self._step_cost.get(shape,
                                                         self._current_cost)
            flops, byts = self._current_cost
            self._window_flops += flops
            self._window_bytes += byts
            self._window_steps += 1
            return clean, had_compile

    def note_productive(self, seconds: float) -> None:
        """Wall time outside iterations that drains real device work
        (the epoch-end window sync)."""
        with self._lock:
            self._productive_s += seconds

    # --------------------------------------------------------- intervals
    @contextlib.contextmanager
    def interval(self, kind: str):
        """Mark a typed badput interval.  Re-entrant: only the OUTERMOST
        mark accrues seconds and writes a durable record (nested marks —
        model_api's eval funnel inside the trainer's eval-callback wrap
        — are absorbed)."""
        assert kind in BADPUT_KINDS, kind
        t0 = self._clock()
        with self._lock:
            self._interval_depth += 1
            outermost = self._interval_depth == 1
            if outermost:
                self._interval_kind = kind
                self._interval_t0 = t0
        wall0 = time.time()
        try:
            yield
        finally:
            with self._lock:
                self._interval_depth -= 1
                record = None
                if outermost:
                    now = self._clock()
                    # accrue from the (possibly harvest-rebased) start —
                    # the pre-rebase portion was billed by the flush that
                    # crossed this interval; the record keeps the full span
                    dur = max(0.0, now - self._interval_t0)
                    self._badput_s[kind] += dur
                    self._accrued_s += dur
                    if kind in RATE_EXCLUDED_KINDS:
                        self._rate_excluded_s += dur
                    self._interval_kind = None
                    record = {'kind': 'interval', 'type': kind,
                              'wall': wall0,
                              'dur_s': max(0.0, now - t0)}
            if record is not None:
                self._append(record)

    def mark_replay(self, n_steps: int) -> None:
        """After a divergence rewind: the next ``n_steps`` clean steps
        re-train lost progress — work done twice, billed to
        ``rewind_replay``."""
        if n_steps > 0:
            with self._lock:
                self._replay_left += int(n_steps)

    # ---------------------------------------------------------- MFU costs
    def set_step_cost(self, shape: str, flops: float, bytes_accessed: float
                      ) -> None:
        """AOT cost of the train-step program for one dispatch shape
        (trainer captures it at first sight, alongside the capacity
        tracker's specialization accounting)."""
        with self._lock:
            self._step_cost[shape] = (float(flops), float(bytes_accessed))
            self._current_cost = self._step_cost[shape]

    def arithmetic_intensity(self) -> Optional[float]:
        """FLOPs per byte accessed of the current step program (from the
        lowered module — an unoptimized-HLO estimate)."""
        with self._lock:
            flops, byts = self._current_cost
        if flops <= 0 or byts <= 0:
            return None
        return flops / byts

    def current_cost(self) -> Tuple[float, float]:
        with self._lock:
            return self._current_cost

    # ------------------------------------------------------------- flush
    def rate_excluded_total(self) -> float:
        """Cumulative seconds of eval/checkpoint/rewind/preempt
        intervals — the stepwatch subtracts the per-window delta from
        its throughput window (train/examples_per_sec measures train
        steps, not the flush window's wall clock)."""
        with self._lock:
            return self._rate_excluded_s

    def harvest_window(self) -> Dict[str, float]:
        """Per-flush-window deltas: productive/badput seconds since the
        last harvest, plus the window's executed FLOPs.  Resets the
        window accumulators."""
        with self._lock:
            # an interval open across the flush boundary: bill what has
            # elapsed so far to THIS window (and rebase its start), so a
            # long eval cannot hide a whole window's badput
            if self._interval_depth > 0 and self._interval_kind is not None:
                now = self._clock()
                dur = max(0.0, now - self._interval_t0)
                self._badput_s[self._interval_kind] += dur
                self._accrued_s += dur
                if self._interval_kind in RATE_EXCLUDED_KINDS:
                    self._rate_excluded_s += dur
                self._interval_t0 = now
            out = {'productive_s': self._productive_s
                   - self._harvested.get('productive_s', 0.0),
                   'flops': self._window_flops,
                   'bytes': self._window_bytes,
                   'steps': self._window_steps}
            for kind in BADPUT_KINDS:
                key = 'badput/' + kind
                out[key] = self._badput_s[kind] \
                    - self._harvested.get(key, 0.0)
            self._harvested = {'productive_s': self._productive_s}
            for kind in BADPUT_KINDS:
                self._harvested['badput/' + kind] = self._badput_s[kind]
            self._window_flops = 0.0
            self._window_bytes = 0.0
            self._window_steps = 0
            return out

    def export_gauges(self, registry=None) -> None:
        """Cumulative totals -> ``goodput/*`` gauges (flush cadence)."""
        reg = registry if registry is not None else core.registry()
        with self._lock:
            wall = self._wall_locked()
            productive = self._productive_s
            badput = dict(self._badput_s)
        reg.gauge('goodput/productive_s').set(productive)
        for kind, secs in badput.items():
            reg.gauge('goodput/badput_s{kind=%s}' % kind).set(secs)
        if wall > 0:
            reg.gauge('goodput/fraction').set(
                max(0.0, min(1.0, productive / wall)))

    def write_window(self, step: int, window: Dict[str, float],
                     window_seconds: float, mfu_value: Optional[float]
                     ) -> None:
        """Durable per-flush-window record (the report's MFU timeline
        and the crash-safe basis of the totals)."""
        badput = {kind: round(window['badput/' + kind], 6)
                  for kind in BADPUT_KINDS if window['badput/' + kind] > 0}
        self._append({'kind': 'window', 'wall': time.time(),
                      'step': int(step),
                      'elapsed_s': round(window_seconds, 6),
                      'productive_s': round(window['productive_s'], 6),
                      'steps': int(window['steps']),
                      'flops': window['flops'],
                      'mfu': mfu_value, 'badput_s': badput})

    def note_anomaly(self, record: Dict) -> None:
        """Anomaly watchdog fire -> durable record in intervals.jsonl
        (the report's anomaly list)."""
        rec = {'kind': 'anomaly', 'wall': time.time()}
        rec.update(record)
        self._append(rec)

    # ------------------------------------------------------------ plumbing
    def _append(self, record: Dict) -> None:
        """Best-effort durable append; ledger accounting must survive an
        unwritable telemetry dir."""
        if self._path is None:
            return
        try:
            os.makedirs(os.path.dirname(self._path) or '.', exist_ok=True)
            with open(self._path, 'a') as f:
                f.write(json.dumps(record) + '\n')
        except (OSError, ValueError) as exc:
            self._log('goodput: could not append to `%s`: %s'
                      % (self._path, exc))

    def snapshot(self) -> Dict:
        """Current totals (tests, report drills)."""
        with self._lock:
            return {'wall_s': self._wall_locked(),
                    'productive_s': self._productive_s,
                    'steps': self._steps,
                    'badput_s': dict(self._badput_s)}


class StepAnomalyWatchdog:
    """Rolling median/MAD step-time regression detector per dispatch
    shape.  Single-threaded by design (hot loop only, like
    CapacityTracker); the monkeypatchable ``clock`` drives the
    auto-capture cooldown.

    A sample past ``median + sigma * 1.4826 * MAD`` (MAD floored at 5%
    of the median so a perfectly flat window cannot hair-trigger)
    extends the current streak; ``sustain`` consecutive outliers fire:
    ``goodput/anomalies_total``, a ``flight_step_anomaly.jsonl`` dump,
    and — at most once per ``cooldown_s`` — the on-demand profiler
    capture via ``on_capture(step)``.
    """

    def __init__(self, sigma: float, cooldown_s: float,
                 dump_dir: Optional[str] = None,
                 on_capture: Optional[Callable[[int], None]] = None,
                 on_record: Optional[Callable[[Dict], None]] = None,
                 window: int = 64, min_samples: int = 16, sustain: int = 3,
                 suffix: str = '', log=None,
                 clock: Callable[[], float] = time.monotonic):
        self.sigma = float(sigma)
        self.cooldown_s = float(cooldown_s)
        self.dump_dir = dump_dir
        self.on_capture = on_capture
        self.on_record = on_record
        self.window = max(8, window)
        self.min_samples = max(4, min_samples)
        self.sustain = max(1, sustain)
        self.suffix = suffix
        self._log = log or (lambda msg: None)
        self._clock = clock
        self._samples: Dict[str, Deque[float]] = {}
        self._streaks: Dict[str, int] = {}
        self._last_capture = float('-inf')

    @property
    def enabled(self) -> bool:
        return self.sigma > 0

    def observe(self, shape: str, seconds: float, step: int) -> bool:
        """Feed one clean (compile-free) step sample; True iff an
        anomaly fired."""
        if not self.enabled:
            return False
        window = self._samples.setdefault(
            shape, collections.deque(maxlen=self.window))
        fired = False
        if len(window) >= self.min_samples:
            ordered = sorted(window)
            median = ordered[len(ordered) // 2]
            mad = sorted(abs(x - median) for x in ordered)[len(ordered) // 2]
            scale = max(1.4826 * mad, 0.05 * median, 1e-5)
            if seconds > median + self.sigma * scale:
                streak = self._streaks.get(shape, 0) + 1
                self._streaks[shape] = streak
                if streak >= self.sustain:
                    self._fire(shape, seconds, median, scale, step, window)
                    self._streaks[shape] = 0
                    fired = True
            else:
                self._streaks[shape] = 0
        window.append(seconds)
        return fired

    def _fire(self, shape: str, seconds: float, median: float, scale: float,
              step: int, window) -> None:
        reg = core.registry()
        reg.counter('goodput/anomalies_total').inc()
        deviation = (seconds - median) / scale
        captured = False
        now = self._clock()
        if self.on_capture is not None and self.cooldown_s > 0 and \
                now - self._last_capture >= self.cooldown_s:
            self._last_capture = now
            self.on_capture(step)
            reg.counter('goodput/autocaptures_total').inc()
            captured = True
        record = {'step': int(step), 'shape': shape,
                  'step_ms': seconds * 1e3, 'median_ms': median * 1e3,
                  'mad_scale_ms': scale * 1e3,
                  'sigma': round(deviation, 2), 'autocapture': captured}
        self._dump_flight(record, window)
        if self.on_record is not None:
            self.on_record(record)
        self._log('goodput: step-time anomaly at step %d (shape %s): '
                  '%.1fms vs median %.1fms (%.1f robust sigmas)%s — see '
                  'flight_step_anomaly%s.jsonl'
                  % (step, shape, seconds * 1e3, median * 1e3, deviation,
                     '; profiler auto-capture armed' if captured else '',
                     self.suffix))

    def _dump_flight(self, record: Dict, window) -> None:
        """``flight_step_anomaly.jsonl``: the fire record + the shape's
        recent step-time window, the forensic context the runbook
        starts from.  Overwritten per fire (latest anomaly wins), like
        the tracing flight dumps."""
        if self.dump_dir is None:
            return
        path = os.path.join(self.dump_dir, '%s%s.jsonl'
                            % (FLIGHT_DUMP_NAME, self.suffix))
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(json.dumps(dict(record, kind='anomaly',
                                        wall=time.time())) + '\n')
                for sample in window:
                    f.write(json.dumps({'kind': 'sample',
                                        'step_ms': sample * 1e3}) + '\n')
            os.replace(tmp, path)
        except OSError as exc:
            self._log('goodput: could not write %s: %s' % (path, exc))


# Process-global active ledger, like the fault plan (resilience/faults.py):
# interval marks live in layers with no trainer handle (model_api's
# eval/save funnels).  None (telemetry off) keeps every mark site at a
# single attribute read — the zero-overhead guarantee.
_ACTIVE: Optional[GoodputLedger] = None


def activate(ledger: GoodputLedger) -> None:
    global _ACTIVE
    _ACTIVE = ledger


def deactivate(ledger: Optional[GoodputLedger] = None) -> None:
    global _ACTIVE
    if ledger is None or _ACTIVE is ledger:
        _ACTIVE = None


def active() -> Optional[GoodputLedger]:
    return _ACTIVE


def on_compile(seconds: float) -> None:
    """jit_tracker's monitoring listener forwards backend-compile
    durations here; no-op with no active ledger."""
    ledger = _ACTIVE
    if ledger is not None:
        ledger.on_compile(seconds)


@contextlib.contextmanager
def interval(kind: str):
    """Module-level typed-interval mark against the active ledger
    (model_api's eval/save/preempt funnels) — a no-op nullcontext when
    telemetry is off."""
    ledger = _ACTIVE
    if ledger is None:
        yield
        return
    with ledger.interval(kind):
        yield
