"""Telemetry exporters: JSONL sink, rate-limited console line, and
Prometheus textfile.

All three consume ``Registry.snapshot()`` — one walk of the instruments
per flush, not per record.  Flushing is periodic (every
``TELEMETRY_FLUSH_EVERY_STEPS`` steps from the trainer), so the hot loop
never touches a file.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from code2vec_tpu.telemetry import catalog
from code2vec_tpu.telemetry.core import Counter, Gauge, Registry, Timer


class JsonlExporter:
    """Append registry snapshots to ``<dir>/metrics.jsonl`` — the same
    ``{tag, value, step, time}`` schema as ``MetricsWriter`` (timers add
    their stat fields), so one plotting script reads both streams.

    Opens the file per flush (append mode): no long-lived handle to leak,
    and flushes are infrequent by design.  The append itself is
    serialized: the trainer flushes from the hot loop while a serving
    engine (or a test harness) may flush the same exporter concurrently,
    and interleaved buffered writes would tear records mid-line.
    """

    def __init__(self, logdir: str, filename: str = 'metrics.jsonl'):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._lock = threading.Lock()
        self.path = os.path.join(logdir, filename)

    def flush(self, registry: Registry, step: int) -> None:
        now = time.time()
        lines = []
        for name, inst in registry.items():
            record = {'tag': name, 'step': int(step), 'time': now}
            if isinstance(inst, Timer):
                stats = inst.snapshot()
                if not stats['count']:
                    continue
                record['value'] = stats['mean_ms']
                record.update(stats)
            else:
                record['value'] = inst.snapshot()
            lines.append(json.dumps(record))
        if not lines:
            return
        payload = '\n'.join(lines) + '\n'
        with self._lock:
            with open(self.path, 'a') as f:
                f.write(payload)


class PrometheusExporter:
    """Textfile export for scraping (node_exporter textfile collector or a
    sidecar): the CURRENT state, rewritten atomically each flush."""

    def __init__(self, logdir: str, filename: str = 'metrics.prom'):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(logdir, filename)

    def flush(self, registry: Registry, step: int) -> None:
        out = []
        for name, inst in registry.items():
            prom = catalog.prometheus_name(name)
            meta = catalog.CATALOG.get(name)
            if meta is not None:
                out.append('# HELP %s %s' % (prom, meta['help']))
            if isinstance(inst, Counter):
                out.append('# TYPE %s counter' % prom)
                out.append('%s %d' % (prom, inst.snapshot()))
            elif isinstance(inst, Gauge):
                out.append('# TYPE %s gauge' % prom)
                out.append('%s %.17g' % (prom, inst.snapshot()))
            elif isinstance(inst, Timer):
                # per-stat gauge families, NOT a 'summary': the summary
                # exposition requires {quantile=...} + _sum series, and
                # strict expfmt parsers drop the whole file on violation
                stats = inst.snapshot()
                for stat in ('mean_ms', 'p50_ms', 'p95_ms', 'max_ms'):
                    out.append('# TYPE %s_%s gauge' % (prom, stat))
                    out.append('%s_%s %.17g' % (prom, stat, stats[stat]))
                out.append('# TYPE %s_count counter' % prom)
                out.append('%s_count %d' % (prom, stats['count']))
        tmp = self.path + '.tmp'
        with open(tmp, 'w') as f:
            f.write('\n'.join(out) + '\n')
        os.replace(tmp, self.path)  # scrapers never see a torn file


class ConsoleExporter:
    """One compact progress line through the run logger, rate-limited so a
    fast step loop cannot flood the console."""

    def __init__(self, log, min_interval_s: float = 30.0):
        self.log = log
        self.min_interval_s = min_interval_s
        # None, not 0.0: time.monotonic() starts near zero on a fresh
        # boot, so a 0.0 sentinel would suppress the FIRST emit for the
        # whole first min_interval_s of machine uptime
        self._last_emit = None

    @staticmethod
    def _ms(registry: Registry, name: str) -> float:
        inst = registry.get(name)
        return inst.snapshot()['mean_ms'] if isinstance(inst, Timer) else 0.0

    def flush(self, registry: Registry, step: int) -> None:
        now = time.monotonic()
        if self._last_emit is not None \
                and now - self._last_emit < self.min_interval_s:
            return
        self._last_emit = now

        def gauge(name: str) -> float:
            inst = registry.get(name)
            return inst.snapshot() if isinstance(inst, Gauge) else 0.0

        def count(name: str) -> int:
            inst = registry.get(name)
            return inst.snapshot() if isinstance(inst, Counter) else 0

        self.log('telemetry step %d | %.0f ex/s | wait %.1f h2d %.1f '
                 'dispatch %.1f sync %.1f ms | ring %d | %d compiles'
                 % (step, gauge('train/examples_per_sec'),
                    self._ms(registry, 'step/batch_wait_ms'),
                    self._ms(registry, 'step/h2d_ms'),
                    self._ms(registry, 'step/dispatch_ms'),
                    self._ms(registry, 'step/sync_ms'),
                    int(gauge('staging/ring_occupancy')),
                    count('jit/compiles_total')))
