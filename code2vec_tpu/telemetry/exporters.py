"""Telemetry exporters: JSONL sink, rate-limited console line, and
Prometheus textfile.

All three consume ``Registry.snapshot()`` — one walk of the instruments
per flush, not per record.  Flushing is periodic (every
``TELEMETRY_FLUSH_EVERY_STEPS`` steps from the trainer), so the hot loop
never touches a file.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from code2vec_tpu.telemetry import catalog
from code2vec_tpu.telemetry.core import Counter, Gauge, Registry, Timer


class JsonlExporter:
    """Append registry snapshots to ``<dir>/metrics.jsonl`` — the same
    ``{tag, value, step, time}`` schema as ``MetricsWriter`` (timers add
    their stat fields), so one plotting script reads both streams.

    Opens the file per flush (append mode): no long-lived handle to leak,
    and flushes are infrequent by design.  The append itself is
    serialized: the trainer flushes from the hot loop while a serving
    engine (or a test harness) may flush the same exporter concurrently,
    and interleaved buffered writes would tear records mid-line.
    """

    def __init__(self, logdir: str, filename: str = 'metrics.jsonl'):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._lock = threading.Lock()
        self.path = os.path.join(logdir, filename)

    def flush(self, registry: Registry, step: int) -> None:
        now = time.time()
        lines = []
        for name, inst in registry.items():
            record = {'tag': name, 'step': int(step), 'time': now}
            if isinstance(inst, Timer):
                stats = inst.snapshot()
                if not stats['count']:
                    continue
                record['value'] = stats['mean_ms']
                record.update(stats)
            else:
                record['value'] = inst.snapshot()
            lines.append(json.dumps(record))
        if not lines:
            return
        payload = '\n'.join(lines) + '\n'
        with self._lock:
            with open(self.path, 'a') as f:
                f.write(payload)


class PrometheusExporter:
    """Textfile export for scraping (node_exporter textfile collector or a
    sidecar): the CURRENT state, rewritten atomically each flush."""

    def __init__(self, logdir: str, filename: str = 'metrics.prom'):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(logdir, filename)

    def flush(self, registry: Registry, step: int) -> None:
        # grouped by FAMILY, emitted one family at a time: expfmt
        # requires all samples of a metric family in one contiguous
        # group under a single HELP/TYPE header.  Replica-labeled
        # series make that nontrivial for Timers — the full-name sort
        # interleaves r0's five stat families with r1's — so samples
        # are collected per family first, then written family-by-family
        # (strict parsers drop the whole file on a split family).
        families: Dict[str, dict] = {}

        def fam(prom: str, mtype: Optional[str],
                help_text: Optional[str] = None) -> list:
            entry = families.get(prom)
            if entry is None:
                entry = families[prom] = {'type': mtype,
                                          'help': help_text,
                                          'samples': []}
            return entry['samples']

        for name, inst in registry.items():
            # instance-labeled series (replica-scoped serving metrics):
            # headers carry the label-FREE family name, the sample line
            # carries the label — the expfmt contract
            base, label = catalog.split_label(name)
            prom = catalog.prometheus_name(base)
            labels = '' if label is None else '{%s="%s"}' % label
            meta = catalog.CATALOG.get(base)
            help_text = meta['help'] if meta is not None else None
            if isinstance(inst, Counter):
                fam(prom, 'counter', help_text).append(
                    '%s%s %d' % (prom, labels, inst.snapshot()))
            elif isinstance(inst, Gauge):
                fam(prom, 'gauge', help_text).append(
                    '%s%s %.17g' % (prom, labels, inst.snapshot()))
            elif isinstance(inst, Timer):
                # per-stat gauge families, NOT a 'summary': the summary
                # exposition requires {quantile=...} + _sum series, and
                # strict expfmt parsers drop the whole file on violation
                stats = inst.snapshot()
                if help_text is not None:
                    fam(prom, None, help_text)  # HELP-only family line
                for stat in ('mean_ms', 'p50_ms', 'p95_ms', 'max_ms'):
                    fam('%s_%s' % (prom, stat), 'gauge').append(
                        '%s_%s%s %.17g'
                        % (prom, stat, labels, stats[stat]))
                fam('%s_count' % prom, 'counter').append(
                    '%s_count%s %d' % (prom, labels, stats['count']))
        out = []
        for prom, entry in families.items():  # first-seen (name) order
            if entry['help'] is not None:
                out.append('# HELP %s %s' % (prom, entry['help']))
            if entry['type'] is not None:
                out.append('# TYPE %s %s' % (prom, entry['type']))
            out.extend(entry['samples'])
        tmp = self.path + '.tmp'
        with open(tmp, 'w') as f:
            f.write('\n'.join(out) + '\n')
        os.replace(tmp, self.path)  # scrapers never see a torn file


class ConsoleExporter:
    """One compact progress line through the run logger, rate-limited so a
    fast step loop cannot flood the console."""

    def __init__(self, log, min_interval_s: float = 30.0):
        self.log = log
        self.min_interval_s = min_interval_s
        # None, not 0.0: time.monotonic() starts near zero on a fresh
        # boot, so a 0.0 sentinel would suppress the FIRST emit for the
        # whole first min_interval_s of machine uptime
        self._last_emit = None

    @staticmethod
    def _ms(registry: Registry, name: str) -> float:
        inst = registry.get(name)
        return inst.snapshot()['mean_ms'] if isinstance(inst, Timer) else 0.0

    def flush(self, registry: Registry, step: int) -> None:
        now = time.monotonic()
        if self._last_emit is not None \
                and now - self._last_emit < self.min_interval_s:
            return
        self._last_emit = now

        def gauge(name: str) -> float:
            inst = registry.get(name)
            return inst.snapshot() if isinstance(inst, Gauge) else 0.0

        def count(name: str) -> int:
            inst = registry.get(name)
            return inst.snapshot() if isinstance(inst, Counter) else 0

        self.log('telemetry step %d | %.0f ex/s | wait %.1f h2d %.1f '
                 'dispatch %.1f sync %.1f ms | ring %d | %d compiles'
                 % (step, gauge('train/examples_per_sec'),
                    self._ms(registry, 'step/batch_wait_ms'),
                    self._ms(registry, 'step/h2d_ms'),
                    self._ms(registry, 'step/dispatch_ms'),
                    self._ms(registry, 'step/sync_ms'),
                    int(gauge('staging/ring_occupancy')),
                    count('jit/compiles_total')))
