"""On-demand ``jax.profiler`` trace capture from a live run.

``Config.PROFILE_DIR`` (the pre-existing knob) captures one fixed window
near the start of a run; this controller adds captures that need no
restart:

- ``TELEMETRY_TRACE_AT_STEP`` (config field, CLI ``--trace-at-step``, or
  the environment variable of the same name): capture
  ``TELEMETRY_TRACE_NUM_STEPS`` steps once that global step is reached.
- Touch-file trigger: ``touch <telemetry_dir>/TRACE_NOW`` in a live run;
  the trainer polls for it every ``poll_every`` steps (one ``stat`` call
  per poll — nothing per step), consumes the file, and captures the next
  window.  Repeatable: touch again for another capture.

Each capture lands in its own ``<telemetry_dir>/traces/step<N>`` dir
(viewable with TensorBoard/Perfetto; decomposable offline with
``benchmarks/analyze_trace.py --trace <dir>``).  jax.profiler cannot nest
captures, so the controller is inert while ``Config.PROFILE_DIR``'s
window is active — the trainer gates on that.
"""
from __future__ import annotations

import os
from typing import Optional

from code2vec_tpu.telemetry import core

ENV_TRACE_AT_STEP = 'TELEMETRY_TRACE_AT_STEP'
TOUCH_FILE_NAME = 'TRACE_NOW'


class TraceController:
    def __init__(self, trace_root: str, trace_at_step: int = -1,
                 num_steps: int = 5, poll_every: int = 25,
                 log=None):
        self.trace_root = trace_root
        # config < 0 means unset; the env var then takes over, so a live
        # run launched without the flag can still be told where to look
        if trace_at_step < 0:
            trace_at_step = int(os.environ.get(ENV_TRACE_AT_STEP, -1))
        self.trace_at_step = trace_at_step
        self.num_steps = max(1, num_steps)
        self.poll_every = max(1, poll_every)
        self.touch_path = os.path.join(trace_root, TOUCH_FILE_NAME)
        self._log = log or (lambda msg: None)
        self._active_dir: Optional[str] = None
        self._stop_at = -1
        self._armed_at = -1   # step the touch trigger armed for (-1: none)

    @property
    def active(self) -> bool:
        return self._active_dir is not None

    def request(self, step: int) -> None:
        """Arm a one-shot capture to start at (or after — ``_should_start``
        matches exactly, so pass the next step the trainer will offer)
        step ``step``.  The anomaly watchdog's auto-capture entry; same
        arming as the touch-file trigger.  No-op while a capture is
        already active or armed."""
        if self._active_dir is None and self._armed_at < 0:
            self._armed_at = step

    def _should_start(self, step: int) -> bool:
        if step == self.trace_at_step or step == self._armed_at:
            return True
        if step % self.poll_every == 0 and os.path.exists(self.touch_path):
            try:
                os.remove(self.touch_path)  # consume: one capture per touch
            except OSError:
                pass
            self._armed_at = step  # start on THIS step
            return True
        return False

    def maybe_update(self, step: int, sync_tree=None) -> None:
        """Advance the capture state machine at the top of step ``step``.
        ``sync_tree`` (typically the train state's params) is blocked on
        before stopping so the traced window contains completed device
        work, not just dispatches."""
        if self._active_dir is None:
            if not self._should_start(step):
                return
            import jax
            trace_dir = os.path.join(self.trace_root, 'traces',
                                     'step%d' % step)
            os.makedirs(trace_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(trace_dir)
            except Exception as exc:  # another trace active, backend quirk
                self._log('telemetry: trace capture at step %d failed to '
                          'start: %s' % (step, exc))
                self._armed_at = -1
                return
            self._active_dir = trace_dir
            self._stop_at = step + self.num_steps
            self._armed_at = -1
            self._log('telemetry: profiler capture started at step %d '
                      '(%d steps) -> %s' % (step, self.num_steps, trace_dir))
        elif step >= self._stop_at:
            import jax
            if sync_tree is not None:
                jax.block_until_ready(sync_tree)
            jax.profiler.stop_trace()
            core.registry().counter('trace/captures_total').inc()
            self._log('telemetry: profiler capture written to `%s` '
                      '(analyze: python benchmarks/analyze_trace.py '
                      '--trace %s --steps %d)'
                      % (self._active_dir, self._active_dir, self.num_steps))
            self._active_dir = None
            self._stop_at = -1

    def shutdown(self) -> None:
        """Stop a capture left active (fit teardown/exception path)."""
        if self._active_dir is not None:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active_dir = None
            self._stop_at = -1
