"""Device-memory ledger: per-subsystem attribution of live device bytes.

The telemetry layer (OBSERVABILITY.md) made *time* observable; this
module does the same for *space*.  Every subsystem that owns device
memory — trainer state, checkpoint restores, the staging ring, the
serving engine's param sets and warm compilation ladder, index shards —
registers its allocations here, so at any moment the process can answer
"who holds how many device bytes", diff two moments for leaks, refuse
an allocation that would blow the HBM budget BEFORE it happens, and
dump a full forensic ledger when the backend reports
``RESOURCE_EXHAUSTED``.

Design constraints (mirroring ``telemetry/core.py``):

- **Dependency-free at import** — jax is imported lazily inside the
  functions that need it, so the graftlint engine (and any other
  jax-free consumer) can import the catalogs below in a bare
  interpreter.
- **Thread-safe** — the staging ring registers from the input thread
  while the serving engine's dispatcher swaps param sets; one lock
  guards the ledger state.
- **Zero host syncs** — bookkeeping reads only array METADATA
  (``.nbytes``); reconciliation enumerates ``jax.live_arrays()`` /
  ``device.memory_stats()``, neither of which blocks on device work.
  Nothing here ever calls ``device_get`` / ``block_until_ready`` or
  fetches a device value (guarded in tests/test_memory_ledger.py).

Accounting is in LOGICAL bytes (one count per array, replication along
mesh axes NOT multiplied) — the same basis as ``jax.Array.nbytes`` and
therefore directly reconcilable against ``jax.live_arrays()`` on every
backend, including the CPU test mesh.  ``memory_stats()`` per-device
physical numbers ride along in snapshots when the backend provides
them (TPU), so the physical view is never lost — it is just not the
reconciliation basis.

Bucket taxonomy (OBSERVABILITY.md "Device memory ledger"):

- ``params``       — model parameter sets, one entry per SET: the
                     training/serving state plus, during a canaried
                     rollover, the candidate copy (so the second copy
                     an armed canary holds is visible, not mystery
                     bytes).
- ``opt_state``    — optimizer moments (Adam mu/nu + scalars).
- ``staging``      — batches resident in the device staging ring
                     (``Trainer.stage_batches``).
- ``index``        — embedding-index residents: exact-tier store
                     shards, IVF cluster-sorted rows + centroids.
- ``executables``  — the serving compilation ladder's programs
                     (bucket × capacity × tier), measured at warmup
                     via AOT ``memory_analysis``.  kind='executable':
                     reported, but excluded from the array
                     reconciliation (an executable is not a
                     ``jax.Array``).
- ``memo``         — the serving memoization tier's cached results
                     (``serving/memo.py``) — HOST bytes, the one
                     host-resident bucket in the taxonomy.
                     kind='host': reported so the cache budget is
                     visible next to the device residents it spares,
                     but excluded from the live-array reconciliation
                     (nothing here lives on a device).

Everything live on the backend but in no bucket is the residual
"unattributed" — reconciliation keeps it honest: nothing hides.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from code2vec_tpu.telemetry import core as tele_core

ENV_BUDGET = 'HBM_BUDGET_BYTES'
TOUCH_FILE_NAME = 'MEM_NOW'
OOM_DUMP_NAME = 'oom_ledger.json'

#: the ledger's bucket taxonomy — registration validates against it so
#: a typo'd bucket cannot silently fork the accounting
BUCKETS = ('params', 'opt_state', 'staging', 'index', 'executables',
           'memo')

#: bucket -> catalog gauge mirrored into the telemetry registry
#: (names cataloged in telemetry/catalog.py; OBSERVABILITY.md)
_BUCKET_GAUGE = {
    'params': 'mem/params_bytes',
    'opt_state': 'mem/opt_state_bytes',
    'staging': 'mem/staging_bytes',
    'index': 'mem/index_bytes',
    'executables': 'mem/executables_bytes',
    'memo': 'mem/memo_bytes',
}

_EVENT_RING = 128


class MemoryBudgetExceeded(RuntimeError):
    """An allocation would cross ``HBM_BUDGET_BYTES`` — raised BEFORE
    the allocation happens (index attach, serving ``load_params``), so
    the caller fails typed instead of the backend failing with an
    undiagnosable ``RESOURCE_EXHAUSTED`` mid-dispatch."""


# ------------------------------------------------------- alloc catalog
# Cataloged allocation owners (graftlint rule ``alloc-catalog``,
# ANALYSIS.md): every device-allocation site — ``device_put``,
# batch/param placement (``shard_batch``/``shard_params``), and
# host-initiated ``jnp.zeros/empty/full/asarray`` — inside these owner
# modules must belong to a function cataloged here (meaning: its
# allocations are ledger-registered, or deliberately exempt with the
# reason recorded) or carry an inline graftlint suppression.  ``count``
# pins the number of sites in the function, so a NEW allocation slipped
# into an already-cataloged owner still fails the lint; an entry whose
# function no longer allocates is stale and fails too.
ALLOC_OWNER_FILES = (
    'code2vec_tpu/training/trainer.py',
    'code2vec_tpu/serving/engine.py',
    'code2vec_tpu/index/exact.py',
    'code2vec_tpu/index/ivf.py',
    'code2vec_tpu/index/quant.py',
)

ALLOC_CATALOG = (
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer.init_state', 'count': 2,
     'reason': 'fresh params placement + step scalar — registered as '
               'params/opt_state via register_state_memory'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer.state_from_params', 'count': 2,
     'reason': 'params placement + step scalar — registered via '
               'register_state_memory'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer.train_step', 'count': 1,
     'reason': 'unstaged one-shot batch placement (tests/REPL); the '
               'staged path accounts in stage_batches, and a one-shot '
               'batch is consumed (and donated) within the call'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer.stage_batches', 'count': 2,
     'reason': 'THE staging ring: both placement branches register '
               'into the staging bucket (telemetry on) and release at '
               'pop'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer._build_steps.<locals>.packed_rows', 'count': 2,
     'reason': 'traced INSIDE the jitted packed train step (the PAD-row '
               'append that completes lazy Adam\'s touched-row set off '
               'the packed ctx stream, ISSUE 12) — two 4-byte '
               'compile-time constants in the XLA program, never a '
               'host-initiated device allocation; nothing to ledger'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer.eval_step', 'count': 1,
     'reason': 'one-shot eval batch placement, consumed within the '
               'call (the eval loop goes through stage_batches)'},
    {'file': 'code2vec_tpu/training/trainer.py',
     'func': 'Trainer.predict_step', 'count': 1,
     'reason': 'REPL-path predict batch placement, consumed within '
               'the call; serving traffic accounts in the engine'},
    {'file': 'code2vec_tpu/serving/engine.py',
     'func': 'ServingEngine.warmup', 'count': 1,
     'reason': 'warmup ladder batches: transient compile fodder, dead '
               'after the eager compile; the EXECUTABLES they produce '
               'are what registers (bucket executables)'},
    {'file': 'code2vec_tpu/serving/engine.py',
     'func': 'ServingEngine._dispatch_batch', 'count': 1,
     'reason': 'micro-batch placement: in flight only between dispatch '
               'and decode, bounded by the bucket ladder; per-request '
               'accounting would put ledger ops on the hot path'},
    {'file': 'code2vec_tpu/index/exact.py',
     'func': 'ExactIndex.__init__', 'count': 4,
     'reason': 'store matrix + -inf row mask (sharded and single-'
               'device branches) — budget-checked before allocation, '
               'registered as index/exact'},
    {'file': 'code2vec_tpu/index/ivf.py',
     'func': 'IVFIndex.__init__', 'count': 2,
     'reason': 'cluster-sorted rows + centroids — registered as '
               'index/ivf'},
    {'file': 'code2vec_tpu/index/ivf.py',
     'func': 'kmeans', 'count': 2,
     'reason': 'build-path device copies of the store + init '
               'centroids, freed when the build returns (transient; '
               'the persistent residents register in IVFIndex '
               '__init__)'},
    {'file': 'code2vec_tpu/index/quant.py',
     'func': 'QuantizedIVFIndex._install_base_locked', 'count': 2,
     'reason': 'cluster-sorted quantized codes + codec constants '
               '(scales / codebooks / centroids) — budget-checked at '
               'attach, registered as index quant:<fp>:base'},
    {'file': 'code2vec_tpu/index/quant.py',
     'func': 'QuantizedIVFIndex._refresh_append_device_locked',
     'count': 1,
     'reason': 'capacity-rung padded append-segment buffer — the '
               'delta to the next rung is budget-gated before '
               'placement, re-registered per segment as '
               'quant:<fp>:seg%05d + quant:<fp>:segslack'},
)


# ------------------------------------------------------------- helpers
def tree_nbytes(tree) -> int:
    """Total LOGICAL bytes of a pytree of arrays (jax arrays, numpy
    arrays, or abstract ``ShapeDtypeStruct``s — anything with
    ``.nbytes`` or ``shape``+``dtype``).  Metadata only: never blocks
    on device values."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, 'nbytes', None)
        if nbytes is not None:
            total += int(nbytes)
            continue
        shape = getattr(leaf, 'shape', None)
        dtype = getattr(leaf, 'dtype', None)
        if shape is not None and dtype is not None:
            size = 1
            for dim in shape:
                size *= int(dim)
            total += size * np.dtype(dtype).itemsize
    return total


def backend_memory() -> Dict[str, Any]:
    """Backend-reported memory: LOGICAL live-array bytes (every backend;
    the reconciliation basis) plus per-device physical ``memory_stats``
    when the runtime provides them (TPU/GPU; CPU returns None)."""
    import jax

    live = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue  # donated-away buffers linger as husks
        except Exception:
            pass
        live += int(arr.nbytes)
        count += 1
    devices = []
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            devices.append({
                'id': int(dev.id),
                'bytes_in_use': int(stats.get('bytes_in_use', 0)),
                'peak_bytes_in_use': int(stats.get('peak_bytes_in_use',
                                                   0)),
            })
    return {'live_bytes': live, 'live_arrays': count,
            'source': 'live_arrays', 'devices': devices}


def is_oom_error(exc: BaseException) -> bool:
    """Does this exception look like a device out-of-memory?  XLA
    surfaces them as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...`` (the
    jit-dispatch boundary) or allocation failures mentioning
    out-of-memory (the ``device_put`` attach boundary)."""
    text = str(exc)
    return ('RESOURCE_EXHAUSTED' in text
            or 'out of memory' in text.lower())


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


class _Entry:
    __slots__ = ('bucket', 'key', 'nbytes', 'kind', 'attrs', 't',
                 'finalizer')

    def __init__(self, bucket: str, key: str, nbytes: int, kind: str,
                 attrs: Optional[dict]):
        self.bucket = bucket
        self.key = key
        self.nbytes = int(nbytes)
        self.kind = kind
        self.attrs = attrs or {}
        self.t = time.time()
        self.finalizer = None

    def record(self) -> Dict[str, Any]:
        out = {'key': self.key, 'bytes': self.nbytes, 'kind': self.kind}
        if self.attrs:
            out['attrs'] = self.attrs
        return out


# --------------------------------------------------------------- ledger
class MemoryLedger:
    """The process-global device-memory ledger.

    ``register`` replaces any existing entry under the same
    ``(bucket, key)`` — owners re-registering across restores/rollovers
    therefore never double-count, and replacing IS the release of the
    previous generation.  ``owner=`` attaches a ``weakref.finalize`` so
    an owner that is garbage-collected auto-releases its entry instead
    of leaving the ledger stale.
    """

    # registration races between the input thread, the serving
    # dispatcher/decode workers, and snapshot readers (lock-discipline
    # rule, ANALYSIS.md):
    # graftlint: guard MemoryLedger._entries,_events,_watermarks,_budget,_dump_dir,_oom_dumps by _lock
    def __init__(self):
        # RLock, deliberately: a weakref.finalize callback (owner
        # collected) calls release(), and cyclic GC can fire it on THIS
        # thread while it already holds the lock inside register() (the
        # locked region allocates). A plain Lock would self-deadlock the
        # staging/dispatcher thread; re-entering is safe — release
        # mutates before the watermark/export reads run.
        self._lock = threading.RLock()
        self._entries: Dict[Tuple[str, str], _Entry] = {}
        self._events: collections.deque = collections.deque(
            maxlen=_EVENT_RING)
        self._watermarks: Dict[str, int] = {}
        self._budget: Optional[int] = None  # None = env var decides
        self._dump_dir: Optional[str] = None
        self._oom_dumps = 0

    # ------------------------------------------------------ configure
    def configure(self, budget_bytes: Optional[int] = None,
                  dump_dir: Optional[str] = None) -> None:
        """Pin the budget (overriding the ``HBM_BUDGET_BYTES`` env var;
        0 = unlimited) and/or the directory forensic dumps land in
        (default: the current working directory)."""
        with self._lock:
            if budget_bytes is not None:
                self._budget = int(budget_bytes)
            if dump_dir is not None:
                self._dump_dir = dump_dir

    def budget_bytes(self) -> int:
        """The effective budget: the configured value, else the
        ``HBM_BUDGET_BYTES`` environment variable, else 0 (unlimited)."""
        with self._lock:
            budget = self._budget
        if budget is not None:
            return budget
        try:
            return int(os.environ.get(ENV_BUDGET, '0') or 0)
        except ValueError:
            raise ValueError('%s must be an integer byte count, got %r'
                             % (ENV_BUDGET, os.environ.get(ENV_BUDGET)))

    def dump_dir(self) -> str:
        with self._lock:
            return self._dump_dir or '.'

    # ------------------------------------------------------- mutation
    def register(self, bucket: str, key: str, source,
                 kind: str = 'array', owner=None,
                 attrs: Optional[dict] = None) -> int:
        """Attribute ``source`` (a pytree of arrays, or an int byte
        count) to ``(bucket, key)``.  Returns the registered bytes."""
        if bucket not in BUCKETS:
            raise ValueError('unknown ledger bucket %r (taxonomy: %s)'
                             % (bucket, list(BUCKETS)))
        nbytes = (int(source) if isinstance(source, (int, float))
                  else tree_nbytes(source))
        entry = _Entry(bucket, key, nbytes, kind, attrs)
        if owner is not None:
            entry.finalizer = weakref.finalize(
                owner, self.release, bucket, key)
        with self._lock:
            old = self._entries.get((bucket, key))
            if old is not None and old.finalizer is not None:
                old.finalizer.detach()
            self._entries[(bucket, key)] = entry
            self._events.append({'t': entry.t, 'op': 'register',
                                 'bucket': bucket, 'key': key,
                                 'bytes': nbytes})
            self._update_watermarks_locked()
            self._export_locked()
        return nbytes

    def release(self, bucket: str, key: str) -> int:
        """Drop an entry (no-op when absent — finalizers may race an
        explicit release).  Returns the released bytes."""
        with self._lock:
            entry = self._entries.pop((bucket, key), None)
            if entry is None:
                return 0
            if entry.finalizer is not None:
                entry.finalizer.detach()
            self._events.append({'t': time.time(), 'op': 'release',
                                 'bucket': bucket, 'key': key,
                                 'bytes': entry.nbytes})
            self._export_locked()
            return entry.nbytes

    # ------------------------------------------------------- accounting
    def _totals_locked(self) -> Dict[str, int]:
        totals = {bucket: 0 for bucket in BUCKETS}
        for entry in self._entries.values():
            totals[entry.bucket] += entry.nbytes
        return totals

    def _attributed_locked(self) -> int:
        """Array-kind bytes only: executables are not ``jax.Array``s and
        must not count against the live-array reconciliation."""
        return sum(entry.nbytes for entry in self._entries.values()
                   if entry.kind == 'array')

    def _update_watermarks_locked(self) -> None:
        totals = self._totals_locked()
        for bucket, value in totals.items():
            if value > self._watermarks.get(bucket, 0):
                self._watermarks[bucket] = value
        attributed = self._attributed_locked()
        if attributed > self._watermarks.get('total', 0):
            self._watermarks['total'] = attributed

    def _export_locked(self) -> None:
        """Mirror bucket totals into the telemetry registry (one gauge
        set per bucket; a no-op bool check when telemetry is off)."""
        if not tele_core.enabled():
            return
        reg = tele_core.registry()
        totals = self._totals_locked()
        for bucket, metric in _BUCKET_GAUGE.items():
            reg.gauge(metric).set(totals[bucket])
        reg.gauge('mem/attributed_bytes').set(self._attributed_locked())
        reg.gauge('mem/watermark_bytes').set(
            self._watermarks.get('total', 0))

    def attributed_bytes(self) -> int:
        with self._lock:
            return self._attributed_locked()

    def bucket_bytes(self, bucket: str) -> int:
        with self._lock:
            return self._totals_locked().get(bucket, 0)

    def export_gauges(self) -> None:
        """Refresh the ``mem/*`` gauges (telemetry flush cadence)."""
        budget = self.budget_bytes()  # env read outside the lock
        with self._lock:
            self._export_locked()
        if tele_core.enabled():
            tele_core.registry().gauge('mem/budget_bytes').set(budget)

    # -------------------------------------------------------- snapshot
    def snapshot(self, reconcile: bool = True,
                 reason: str = 'snapshot') -> Dict[str, Any]:
        """Full ledger state; with ``reconcile`` (the default) also the
        backend's live bytes and the unattributed residual.  Pure
        metadata — zero host syncs, zero compiles."""
        backend = backend_memory() if reconcile else None
        budget = self.budget_bytes()
        with self._lock:
            totals = self._totals_locked()
            attributed = self._attributed_locked()
            buckets = {}
            for bucket in BUCKETS:
                entries = sorted(
                    (e.record() for e in self._entries.values()
                     if e.bucket == bucket),
                    key=lambda r: -r['bytes'])
                buckets[bucket] = {'bytes': totals[bucket],
                                   'entries': entries}
            snap = {
                'time': time.time(),
                'reason': reason,
                'budget_bytes': budget,
                'attributed_bytes': attributed,
                'executables_bytes': totals['executables'],
                'buckets': buckets,
                'watermarks': dict(self._watermarks),
                'events': list(self._events),
            }
        if backend is not None:
            snap['backend'] = backend
            snap['unattributed_bytes'] = (backend['live_bytes']
                                          - attributed)
            if tele_core.enabled():
                reg = tele_core.registry()
                reg.gauge('mem/backend_live_bytes').set(
                    backend['live_bytes'])
                reg.gauge('mem/unattributed_bytes').set(
                    snap['unattributed_bytes'])
        return snap

    @staticmethod
    def diff(before: Dict[str, Any], after: Dict[str, Any]
             ) -> Dict[str, Any]:
        """Delta view of two snapshots — the leak-detection primitive:
        per-bucket byte deltas, per-entry added/removed/grown, and the
        attributed/backend/unattributed deltas."""
        out: Dict[str, Any] = {
            'attributed_delta': (after['attributed_bytes']
                                 - before['attributed_bytes']),
            'buckets': {},
        }
        if 'backend' in before and 'backend' in after:
            out['backend_live_delta'] = (
                after['backend']['live_bytes']
                - before['backend']['live_bytes'])
            out['unattributed_delta'] = (
                after['unattributed_bytes']
                - before['unattributed_bytes'])
        for bucket in BUCKETS:
            b_entries = {e['key']: e['bytes'] for e in
                         before['buckets'][bucket]['entries']}
            a_entries = {e['key']: e['bytes'] for e in
                         after['buckets'][bucket]['entries']}
            changed = {}
            for key in sorted(set(b_entries) | set(a_entries)):
                delta = a_entries.get(key, 0) - b_entries.get(key, 0)
                if delta:
                    changed[key] = delta
            out['buckets'][bucket] = {
                'bytes_delta': (after['buckets'][bucket]['bytes']
                                - before['buckets'][bucket]['bytes']),
                'entries': changed,
            }
        return out

    # ------------------------------------------------ budget/forensics
    def check_budget(self, incoming_bytes: int, what: str) -> None:
        """Refuse an allocation that would cross the budget: dumps the
        forensic ledger and raises ``MemoryBudgetExceeded`` BEFORE any
        device memory moves.  A budget of 0 (the default) admits
        everything."""
        budget = self.budget_bytes()
        if budget <= 0:
            return
        attributed = self.attributed_bytes()
        if attributed + incoming_bytes <= budget:
            return
        path = self.dump(reason='budget: %s' % what)
        raise MemoryBudgetExceeded(
            '%s needs %d bytes but only %d of the %d-byte HBM budget '
            'remain (%d attributed; %s). Nothing was allocated. Ledger '
            'dumped to `%s` — render with scripts/memory_report.py.'
            % (what, incoming_bytes, max(0, budget - attributed),
               budget, attributed, ENV_BUDGET, path))

    def note_oom(self, exc: BaseException, context: str
                 ) -> Optional[str]:
        """OOM forensics hook for the jit-dispatch / attach boundaries:
        when ``exc`` is a backend out-of-memory, dump ``oom_ledger.json``
        (full ledger + watermarks + recent allocation events) so the
        postmortem starts with attribution instead of a bare
        ``RESOURCE_EXHAUSTED``.  Callers re-raise either way."""
        if not is_oom_error(exc):
            return None
        with self._lock:
            self._oom_dumps += 1
        if tele_core.enabled():
            tele_core.registry().counter('mem/oom_dumps_total').inc()
        return self.dump(
            path=os.path.join(self.dump_dir(), OOM_DUMP_NAME),
            reason='oom: %s: %s' % (context, exc))

    def dump(self, path: Optional[str] = None,
             reason: str = 'dump') -> str:
        """Write a reconciled snapshot as JSON (atomic), default
        ``<dump_dir>/oom_ledger.json`` for forensic reasons and
        ``memory_*.json`` for the report paths."""
        if path is None:
            path = os.path.join(self.dump_dir(), OOM_DUMP_NAME)
        out_dir = os.path.dirname(path)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        try:
            snap = self.snapshot(reason=reason)
        except Exception:
            # forensics must not mask the original failure: fall back
            # to the unreconciled ledger if the backend query dies
            snap = self.snapshot(reconcile=False, reason=reason)
        _atomic_write_json(path, snap)
        if tele_core.enabled():
            tele_core.registry().counter('mem/snapshots_total').inc()
        return path

    def reset(self) -> None:
        """Drop every entry and watermark (test isolation)."""
        with self._lock:
            for entry in self._entries.values():
                if entry.finalizer is not None:
                    entry.finalizer.detach()
            self._entries.clear()
            self._events.clear()
            self._watermarks.clear()
            self._budget = None
            self._dump_dir = None
            self._oom_dumps = 0


_LEDGER = MemoryLedger()


def ledger() -> MemoryLedger:
    """The process-global ledger."""
    return _LEDGER


def configure(budget_bytes: Optional[int] = None,
              dump_dir: Optional[str] = None) -> None:
    _LEDGER.configure(budget_bytes=budget_bytes, dump_dir=dump_dir)


def reset() -> None:
    _LEDGER.reset()


# ------------------------------------------------------- MEM_NOW trigger
class MemoryReportController:
    """Touch-file ledger snapshots from a live run, mirroring
    ``TRACE_NOW`` (telemetry/trace.py): ``touch <telemetry_dir>/MEM_NOW``
    and the next telemetry flush consumes it and writes
    ``memory_step<N>.json``.  Repeatable — touch again for another
    snapshot."""

    def __init__(self, out_dir: str, log=None):
        self.out_dir = out_dir
        self.touch_path = os.path.join(out_dir, TOUCH_FILE_NAME)
        self._log = log or (lambda msg: None)

    def poll(self, step: int) -> Optional[str]:
        """Called at the telemetry flush cadence: one ``stat`` per
        flush, nothing per step."""
        if not os.path.exists(self.touch_path):
            return None
        try:
            os.remove(self.touch_path)  # consume: one snapshot per touch
        except OSError:
            pass
        path = os.path.join(self.out_dir, 'memory_step%d.json' % step)
        _LEDGER.dump(path, reason='MEM_NOW at step %d' % step)
        self._log('memory: ledger snapshot written to `%s` (render: '
                  'python scripts/memory_report.py %s)' % (path, path))
        return path


def write_report(config) -> str:
    """``--memory-report``: write a reconciled ledger snapshot next to
    the run's telemetry artifacts and log where it landed."""
    from code2vec_tpu.telemetry.stepwatch import telemetry_dir
    out_dir = telemetry_dir(config)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, 'memory_report.json')
    _LEDGER.dump(path, reason='--memory-report')
    config.log('memory: ledger report written to `%s` (render: python '
               'scripts/memory_report.py %s)' % (path, path))
    return path
